#!/usr/bin/env python
"""COMMBENCH sweep driver: capture the committed comm-subsystem record.

Runs ``bench.py --mode comm`` with the full variant sweep (int8, int8 +
backward overlap, bf16, 1 MB buckets) on the forced virtual CPU mesh and
writes the committed ``COMMBENCH.json`` artifact — bytes-on-wire vs
exact, step-time delta, and parity drift at N steps per variant.  The
regression tripwire is ``make commbench-check`` (``BENCH_CHECK=1
bench.py --mode comm``), which enforces the <= 0.65 bytes claim and the
parity-drift band against this artifact.

A SUBPROCESS per invocation, not an import: the comm bench must force
its virtual mesh before any jax backend initializes, which only a fresh
interpreter guarantees (the __graft_entry__ constraint).
"""

from __future__ import annotations

import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    out = os.path.join(_REPO, "COMMBENCH.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["BENCH_SWEEP"] = "1"
    env["COMMBENCH_OUT"] = out
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--mode", "comm"],
        env=env, cwd=_REPO,
    )
    if r.returncode == 0:
        print(f"commbench sweep complete: {out}")
    return r.returncode


if __name__ == "__main__":
    sys.exit(main())
