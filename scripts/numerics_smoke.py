#!/usr/bin/env python
"""numerics-smoke: CPU train smoke with an injected NaN (ISSUE 10).

The CI leg of the numerics flight recorder (``make numerics-smoke``,
part of ``check-static``): run a real ``run_training`` loop — obs trace,
events JSONL sink, telemetry registry, and SLO monitor all live — inject
a NaN into one mid-run batch, and assert the acceptance contract WITHOUT
any rerun:

- the abort lands ONE ``NUMERICS_DUMP.json`` naming the first
  non-finite layer (the provenance pass replaced ``--debug-nans``);
- the built-in nonfinite SLO rule fires EXACTLY ONCE, visible as one
  ``slo_violation`` record in metrics.jsonl AND one instant on the
  trace timeline, plus the ``numerics_trip`` marker;
- the auto-emitted PERF_REPORT.json is schema-valid, its numerics
  section is populated, and the ``numerics:divergence`` verdict ranks
  #1 — above every SLO and inferred bottleneck;
- the disabled-path contract holds structurally: with numerics off, the
  step's metrics carry no summary keys.

Exit 0 on success; any failed check prints one ``numerics-smoke FAIL:``
line and exits 1.  Stdout ends with one machine-readable JSON summary.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # direct `python scripts/numerics_smoke.py` runs
    sys.path.insert(0, _REPO)

_failures: list[str] = []


def check(ok: bool, what: str) -> None:
    if not ok:
        _failures.append(what)
        print(f"numerics-smoke FAIL: {what}", flush=True)


def main() -> int:
    import jax
    import jax.numpy as jnp
    import optax

    from batchai_retinanet_horovod_coco_tpu import obs
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry
    from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
        auto_emit,
        validate_report,
    )
    from batchai_retinanet_horovod_coco_tpu.obs.events import (
        EventSink,
        split_runs,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.train.loop import (
        LoopConfig,
        run_training,
    )

    hw, batch_size = (64, 64), 4
    obs_dir = tempfile.mkdtemp(prefix="numerics_smoke_")
    try:
        obs.enable(obs_dir, process_label="numerics-smoke")
        logger = EventSink(obs_dir, stdout=False)
        telemetry.reset()
        telemetry.enable()
        monitor = slo.SloMonitor(
            telemetry.default(),
            [slo.nonfinite_rule(), slo.grad_norm_spike()],
            sink=logger,
            poll_interval=0.2,
        ).start()

        model = build_retinanet(
            RetinaNetConfig(
                num_classes=3, backbone="resnet_test", fpn_channels=16,
                head_width=16, head_depth=1, dtype=jnp.float32,
            )
        )
        state = create_train_state(
            model, optax.sgd(1e-3, momentum=0.9), (1, *hw, 3),
            jax.random.key(0),
        )

        def stream(nan_at_step: int = 3):
            rng = np.random.default_rng(0)
            i = 0
            while True:
                i += 1
                images = rng.normal(0, 1, (batch_size, *hw, 3)).astype(
                    np.float32
                )
                if i == nan_at_step:
                    images[0, 0, 0, 0] = np.nan  # the injected poison
                yield Batch(
                    images=images,
                    gt_boxes=np.tile(
                        np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                        (batch_size, 1, 1),
                    ),
                    gt_labels=np.ones((batch_size, 1), np.int32),
                    gt_mask=np.ones((batch_size, 1), bool),
                    image_ids=np.arange(batch_size, dtype=np.int64)
                    + i * 100,
                    scales=np.ones((batch_size,), np.float32),
                    valid=np.ones((batch_size,), bool),
                )

        aborted = False
        try:
            run_training(
                model, state, stream(), 3,
                LoopConfig(
                    total_steps=8, log_every=1, numerics=True,
                    numerics_dump_dir=obs_dir, rng_seed=0,
                ),
                logger=logger,
            )
        except FloatingPointError as e:
            aborted = True
            print(f"# abort (expected): {e}", flush=True)
        check(aborted, "injected NaN did not abort the loop")

        # Drain: the monitor's stop() runs one final evaluation, so the
        # end-of-run breach fires even on a sub-poll-interval run; the
        # fired latch guarantees it fired EXACTLY once overall.
        monitor.stop()
        monitor.check_once()  # must NOT re-fire (latched breach)
        logger.close()
        obs.finalize()

        # 1. ONE provenance dump naming the first non-finite layer.
        dump_path = os.path.join(obs_dir, "NUMERICS_DUMP.json")
        check(os.path.exists(dump_path), "NUMERICS_DUMP.json missing")
        dumps = [
            f for f in os.listdir(obs_dir) if f.startswith("NUMERICS_DUMP")
        ]
        check(len(dumps) == 1, f"expected ONE dump, found {dumps}")
        first = None
        if os.path.exists(dump_path):
            with open(dump_path) as f:
                dump = json.load(f)
            first = dump.get("first_nonfinite")
            check(bool(first), "dump names no first non-finite layer")
            check(
                "backbone" in str(first),
                f"NaN images should localize to the backbone, got {first!r}",
            )
            check(
                bool(dump.get("batch_image_ids")),
                "dump carries no batch source ids",
            )

        # 2. EXACTLY ONE nonfinite slo_violation in metrics.jsonl.
        runs = split_runs(os.path.join(obs_dir, "metrics.jsonl"))
        records = runs[-1]["records"] if runs else []
        violations = [
            r
            for r in records
            if r.get("event") == "slo_violation"
            and r.get("rule") == "train-nonfinite"
        ]
        check(
            len(violations) == 1,
            f"expected exactly one train-nonfinite slo_violation, "
            f"got {len(violations)}",
        )
        trips = [r for r in records if r.get("event") == "numerics_trip"]
        check(len(trips) == 1, f"expected one numerics_trip, got {len(trips)}")
        numerics_records = [
            r for r in records if r.get("event") == "numerics"
        ]
        check(
            len(numerics_records) >= 1,
            "no structured numerics records reached metrics.jsonl",
        )

        # 3. The trip + violation sit ON the trace timeline.
        with open(os.path.join(obs_dir, "trace.json")) as f:
            trace_doc = json.load(f)
        instants = [
            e
            for e in trace_doc.get("traceEvents", [])
            if e.get("ph") == "i"
        ]
        slo_markers = [
            e
            for e in instants
            if e.get("name") == "slo_violation"
            and (e.get("args") or {}).get("rule") == "train-nonfinite"
        ]
        check(
            len(slo_markers) == 1,
            f"expected one slo_violation trace instant, got "
            f"{len(slo_markers)}",
        )
        check(
            any(e.get("name") == "numerics_trip" for e in instants),
            "no numerics_trip instant on the trace timeline",
        )

        # 4. PERF_REPORT: schema-valid, numerics populated, divergence #1.
        report_path = auto_emit(obs_dir)
        check(bool(report_path), "auto_emit produced no PERF_REPORT")
        if report_path:
            with open(report_path) as f:
                report = json.load(f)
            problems = validate_report(report)
            check(not problems, f"report schema problems: {problems}")
            num = report.get("numerics") or {}
            check(num.get("available"), "report numerics section empty")
            check(
                (num.get("trips") or {}).get("count", 0) >= 1,
                "report numerics section saw no trip",
            )
            bn = report.get("bottlenecks") or [{}]
            check(
                bn[0].get("name") == "numerics:divergence",
                f"divergence verdict not ranked #1 (got {bn[0].get('name')})",
            )

        # 5. Disabled-path contract: numerics off adds no summary keys.
        from batchai_retinanet_horovod_coco_tpu.train.step import (
            make_train_step,
        )

        step_off = make_train_step(model, hw, 3, donate_state=False)
        batch0 = next(iter(stream(nan_at_step=0)))
        # Fresh state: the loop's step donated the original one.
        fresh = create_train_state(
            model, optax.sgd(1e-3, momentum=0.9), (1, *hw, 3),
            jax.random.key(1),
        )
        _, metrics_off = step_off(
            fresh,
            {
                "images": jnp.asarray(batch0.images),
                "gt_boxes": jnp.asarray(batch0.gt_boxes),
                "gt_labels": jnp.asarray(batch0.gt_labels),
                "gt_mask": jnp.asarray(batch0.gt_mask),
            },
        )
        check(
            "update_ratio" not in metrics_off
            and not any(k.startswith("gnorm/") for k in metrics_off),
            "numerics-off step leaked summary keys",
        )

        print(
            json.dumps(
                {
                    "numerics_smoke": "ok" if not _failures else "FAIL",
                    "failures": _failures,
                    "first_nonfinite": first,
                    "slo_violations": len(violations),
                    "obs_dir": obs_dir,
                }
            ),
            flush=True,
        )
        return 1 if _failures else 0
    finally:
        telemetry.reset()
        if not _failures:
            shutil.rmtree(obs_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
