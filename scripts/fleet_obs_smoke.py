"""Fleet observability smoke (ISSUE 15, `make fleet-obs-smoke`).

The REAL fleet CLI over 2 stub-engine replica subprocesses with the obs
plane on (`--obs-trace --obs-dir`), proving the acceptance chain end to
end on CPU:

1. **kill leg** — SIGKILL replica-0 mid-run: the breaker opens, the
   built-in fleet availability SLO fires EXACTLY ONCE, the supervisor
   respawns the replica in place and the half-open probe readmits it;
2. **re-dispatch leg** — with both replicas alive again, bursts against
   tiny bucket queues force a replica-level shed that re-dispatches to
   the sibling: one trace id, ``serve_request`` spans on BOTH replicas;
3. **trace-id echo** — every /detect response (200 and 503) carries the
   ``X-Retinanet-Trace`` header + ``trace_id`` field;
4. **federated metrics consistency** — after quiescing, the fleet
   ``/metrics`` replica-labeled series EQUAL each replica's own
   exposition (counters are frozen, so equality is exact);
5. **artifacts** — one merged ``trace.json`` with fleet + both replica
   process tracks and a re-dispatched trace id spanning two replicas;
   ``FLEET_METRICS.json``; ``metrics.jsonl`` with exactly one
   ``slo_violation`` for ``fleet-availability``; and an
   ``obs/analyze --fleet`` report whose verdict NAMES the killed
   replica.

CPU-only, no dataset, no device work — wired into `make check-static`.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"fleet-obs-smoke {tag}: {what}", flush=True)
    if not ok:
        FAILURES.append(what)


def _png_bytes() -> bytes:
    import io

    import numpy as np
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.zeros((64, 64, 3), np.uint8)).save(buf, "PNG")
    return buf.getvalue()


def _http(url: str, data: bytes | None = None, headers: dict | None = None,
          timeout: float = 30.0):
    """(status, headers dict, body bytes); 4xx/5xx are data."""
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET"
    )
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _wait_until(predicate, timeout: float, what: str) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if predicate():
                return True
        except Exception:
            pass
        time.sleep(0.2)
    check(False, what)
    return False


class Fleet:
    """The fleet CLI under test + structured stdout/stderr readers."""

    def __init__(self, obs_dir: str):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "batchai_retinanet_horovod_coco_tpu.serve.fleet",
                "--http", "0", "--spawn", "2", "--stub-engine",
                "--stub-delay-ms", "120",
                "--poll-interval", "0.2", "--respawn-delay-s", "0.5",
                "--fleet-timeout-s", "20",
                # Sheds stay LOAD signals in this harness (the re-dispatch
                # leg sheds on purpose): only the SIGKILL may open a
                # breaker, so availability fires exactly once.
                "--shed-trip", "1000000",
                "--spawn-serve-args",
                "--serve-bucket-queue 1 --serve-workers 1 "
                "--serve-max-delay-ms 20",
                "--obs-trace", "--obs-dir", obs_dir,
                "--slo-poll-s", "0.2",
            ],
            env=env, cwd=_REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        self.stdout_lines: list[str] = []
        self.stderr_lines: list[str] = []

        def reader(stream, into):
            try:
                for line in stream:
                    into.append(line.rstrip("\n"))
            except Exception as e:
                into.append(f"__reader_error__ {e!r}")

        # watchdog: harness-local pipe readers; liveness is witnessed by
        # the driver's own bounded waits, not the obs watchdog.
        for stream, into in (
            (self.proc.stdout, self.stdout_lines),
            (self.proc.stderr, self.stderr_lines),
        ):
            threading.Thread(
                target=reader, args=(stream, into), daemon=True
            ).start()
        try:
            self.base_url = self._wait_for_url()
        except Exception:
            self.stop()
            raise

    def _wait_for_url(self, timeout: float = 180.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet CLI died rc={self.proc.returncode}: "
                    f"{self.stderr_lines[-5:]}"
                )
            for line in self.stdout_lines:
                if line.startswith("fleet serving on "):
                    return line.split("fleet serving on ", 1)[1].split()[0]
            time.sleep(0.1)
        raise RuntimeError("fleet CLI never started serving")

    def events(self, kind: str) -> list[dict]:
        out = []
        for line in self.stdout_lines + self.stderr_lines:
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict) and rec.get("event") == kind:
                out.append(rec)
        return out

    def status(self) -> dict:
        code, _h, body = _http(f"{self.base_url}/fleet")
        return json.loads(body.decode()) if code == 200 else {}

    def metric(self, key: str) -> float:
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            parse_exposition,
        )

        code, _h, body = _http(f"{self.base_url}/metrics")
        if code != 200:
            return float("nan")
        _types, samples = parse_exposition(body.decode())
        return samples.get(key, 0.0)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def _burst(base_url: str, payload: bytes, n: int, clients: int) -> dict:
    """n concurrent-ish requests; every response must echo a trace id."""
    counts = {"ok": 0, "shed": 0, "other": 0, "no_echo": 0}
    lock = threading.Lock()
    issued = [0]

    def client():
        try:
            while True:
                with lock:
                    if issued[0] >= n:
                        return
                    issued[0] += 1
                code, headers, body = _http(
                    f"{base_url}/detect", data=payload
                )
                try:
                    doc = json.loads(body.decode())
                except ValueError:
                    doc = {}
                echoed = bool(doc.get("trace_id")) and bool(
                    headers.get("X-Retinanet-Trace")
                )
                with lock:
                    if not echoed:
                        counts["no_echo"] += 1
                    if code == 200:
                        counts["ok"] += 1
                    elif code == 503:
                        counts["shed"] += 1
                    else:
                        counts["other"] += 1
        except Exception as e:
            # Crash channel: a dead client must fail the burst loudly,
            # not leave the driver waiting on requests never issued.
            with lock:
                counts["other"] += 1
            print(f"fleet-obs-smoke FAIL: burst client crashed: {e!r}",
                  flush=True)
            raise

    # watchdog: harness-local load generators, bounded by the joins below.
    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    return counts


def main() -> int:
    obs_dir = tempfile.mkdtemp(prefix="fleet_obs_smoke_")
    print(f"fleet-obs-smoke: obs dir {obs_dir}", flush=True)
    payload = _png_bytes()
    fleet = Fleet(obs_dir)
    victim_rid = None
    try:
        spawned = fleet.events("fleet_replica_spawned")
        check(len(spawned) == 2, f"2 replicas spawned (saw {len(spawned)})")

        # Explicit header round-trip at the fleet edge.
        code, headers, body = _http(
            f"{fleet.base_url}/detect", data=payload,
            headers={"X-Retinanet-Trace": "smoke-trace-1"},
        )
        doc = json.loads(body.decode())
        check(
            code == 200 and doc.get("trace_id") == "smoke-trace-1"
            and headers.get("X-Retinanet-Trace") == "smoke-trace-1",
            "client trace id echoed on header + JSON field",
        )

        # ---- kill leg: exactly-one availability SLO violation ----------
        victim = spawned[0]
        victim_rid = victim["replica_id"]
        os.kill(victim["pid"], signal.SIGKILL)
        _wait_until(
            lambda: fleet.metric(
                'slo_violations_total{rule="fleet-availability"}'
            ) == 1.0,
            30, "availability SLO fired after the kill",
        )
        _wait_until(
            lambda: len(fleet.events("fleet_replica_respawned")) >= 1,
            60, "victim respawned",
        )
        _wait_until(
            lambda: all(
                r["state"] == "closed"
                for r in fleet.status().get("replicas", [])
            ),
            60, "breaker readmitted the respawned victim",
        )

        # ---- re-dispatch leg: both replicas ALIVE (so both export their
        # trace fragments), tiny bucket queues force replica-level sheds
        # that re-dispatch onto the sibling under one trace id.
        before = fleet.metric("fleet_redispatch_total")
        for _ in range(20):
            counts = _burst(fleet.base_url, payload, n=24, clients=12)
            check(counts["no_echo"] == 0,
                  f"every response echoed a trace id: {counts}")
            if counts["other"]:
                check(False, f"unexpected response codes: {counts}")
            if fleet.metric("fleet_redispatch_total") > before:
                break
        check(
            fleet.metric("fleet_redispatch_total") > before,
            "a replica-level shed re-dispatched onto the sibling",
        )

        # ---- quiesce, then federated-vs-local consistency --------------
        time.sleep(1.5)  # a few scrape cycles with zero traffic
        ports = {
            e["replica_id"]: e["port"]
            for e in fleet.events("fleet_replica_spawned")
            + fleet.events("fleet_replica_respawned")
        }
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            parse_exposition,
        )

        for rid, port in sorted(ports.items()):
            code, _h, body = _http(f"http://127.0.0.1:{port}/metrics")
            check(code == 200, f"{rid} /metrics scrapeable")
            _t, local = parse_exposition(body.decode())
            local_done = local.get("serve_requests_completed_total", 0.0)
            fed_done = fleet.metric(
                f'serve_requests_completed_total{{replica="{rid}"}}'
            )
            check(
                fed_done == local_done and local_done > 0,
                f"federated completed_total == {rid}'s own "
                f"({fed_done} vs {local_done})",
            )

        check(
            fleet.metric(
                'slo_violations_total{rule="fleet-availability"}'
            ) == 1.0,
            "availability SLO fired EXACTLY once end-to-end",
        )
    finally:
        fleet.stop()

    # ---- merged artifacts --------------------------------------------
    trace_path = os.path.join(obs_dir, "trace.json")
    check(os.path.exists(trace_path), "merged trace.json written")
    with open(trace_path) as f:
        merged = json.load(f)
    events = merged.get("traceEvents") or []
    check(len(merged.get("otherData", {}).get("merged_from", [])) >= 3,
          "merge stitched >= 3 process fragments (fleet + 2 replicas)")
    labels = {
        str((e.get("args") or {}).get("name"))
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    for rid in ("replica-0", "replica-1"):
        check(any(rid in lb for lb in labels),
              f"{rid} has its own process track in the merged trace")
    by_trace: dict[str, set] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("name") == "serve_request":
            args = e.get("args") or {}
            if args.get("trace"):
                by_trace.setdefault(str(args["trace"]), set()).add(
                    str(args.get("replica"))
                )
    multi = [t for t, rids in by_trace.items() if len(rids) > 1]
    check(
        bool(multi),
        "a re-dispatched request's serve_request spans appear on BOTH "
        f"replicas' tracks under one trace id ({len(multi)} such ids)",
    )
    check(
        os.path.exists(os.path.join(obs_dir, "FLEET_METRICS.json")),
        "FLEET_METRICS.json written",
    )
    metrics_jsonl = os.path.join(obs_dir, "metrics.jsonl")
    violations = []
    if os.path.exists(metrics_jsonl):
        with open(metrics_jsonl) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (
                    rec.get("event") == "slo_violation"
                    and rec.get("rule") == "fleet-availability"
                ):
                    violations.append(rec)
    check(
        len(violations) == 1,
        f"metrics.jsonl carries exactly one availability slo_violation "
        f"(saw {len(violations)})",
    )

    # ---- the fleet perf report ----------------------------------------
    rc = subprocess.run(
        [
            sys.executable, "-m",
            "batchai_retinanet_horovod_coco_tpu.obs.analyze",
            obs_dir, "--fleet",
        ],
        cwd=_REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ).returncode
    check(rc == 0, f"obs.analyze --fleet exits 0 (rc={rc})")
    report_path = os.path.join(obs_dir, "PERF_REPORT.json")
    check(os.path.exists(report_path), "fleet PERF_REPORT.json written")
    if os.path.exists(report_path):
        with open(report_path) as f:
            report = json.load(f)
        names = [b.get("name") for b in report.get("bottlenecks", [])]
        check(
            f"fleet:unavailable_replica:{victim_rid}" in names,
            f"the verdict names the killed replica ({names[:4]})",
        )
        rules = (report.get("violations") or {}).get("rules") or {}
        check(
            rules.get("fleet-availability", {}).get("count") == 1,
            "the report's violations section pins the one availability "
            "breach",
        )
        fleet_sec = report.get("fleet") or {}
        check(
            fleet_sec.get("redispatched_traces", {}).get("count", 0) >= 1,
            "the report counts the re-dispatched trace id(s)",
        )

    if FAILURES:
        print(
            f"fleet-obs-smoke: {len(FAILURES)} FAILURE(S): {FAILURES}",
            flush=True,
        )
        return 1
    print(f"fleet-obs-smoke: all checks green ({obs_dir})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
