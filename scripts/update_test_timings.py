"""Write TEST_TIMINGS.md from a `pytest --durations=N` log.

The committed snapshot is the fast tier's time ledger (tests/conftest.py
documents the budget mechanism): when a new capability lands, regenerate
with `make test-timings` so its test-time cost is visible in the diff.
"""

import re
import sys
from datetime import date


def main(log_path: str) -> None:
    with open(log_path) as f:
        log = f.read()
    rows = re.findall(r"^\s*([0-9.]+)s\s+(call|setup|teardown)\s+(\S+)", log, re.M)
    # Final summary line: matches "N passed ..." AND "M failed, N passed ..."
    tail = re.search(
        r"^((?:\d+ \w+, )*\d+ (?:passed|failed|error\w*).* in [0-9.]+s.*)$",
        log,
        re.M,
    )
    # Wall time from the matched summary line itself (an earlier log line
    # like "retried in 0.5s" must not win).
    total = (
        re.search(r" in ([0-9.]+)s", tail.group(1)) if tail else None
    )
    wall = f"{float(total.group(1)):.0f} s wall" if total else "wall unknown"
    lines = [
        "# Fast-tier test timings (`pytest -m \"not slow\"`, per-session compile cache)",
        "",
        f"Snapshot: {date.today().isoformat()} — regenerate with `make test-timings`.",
        f"Result: {tail.group(1) if tail else 'unknown'} ({wall}; budget 1200 s)",
        "",
        "Budget: 1200 s per session (tests/conftest.py warns, listing offenders,",
        "when a fast-tier session exceeds it; every session pays each unique",
        "program's compile once — the machine-persistent cache is gone, see",
        "conftest.py). A capability that adds a slower test than these either",
        "earns its seconds or takes a `slow` mark.",
        "",
        "| seconds | phase | test |",
        "|---|---|---|",
    ]
    for secs, phase, nodeid in rows:
        lines.append(f"| {secs} | {phase} | `{nodeid}` |")
    with open("TEST_TIMINGS.md", "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote TEST_TIMINGS.md ({len(rows)} rows)")


if __name__ == "__main__":
    main(sys.argv[1])
