#!/usr/bin/env python
"""Static watchdog-coverage audit: every thread/process spawn site in the
package must register with the obs watchdog or say why it doesn't.

The sibling of ``audit_collectives.py`` (which makes the scaling premise
checkable, this makes the OBSERVABILITY premise checkable): the stall
watchdog (obs/watchdog.py) only diagnoses components that heartbeat, so a
new ``threading.Thread``/``mp.Process`` spawned without registering is a
future "it hung and nothing says why" — exactly the hole ISSUE 3 closes.
This audit walks the package AST and, for every spawn call, requires one
of, within ``WINDOW`` lines of the spawn:

- a ``watchdog.register(`` call (registration at the spawn site), or
- a ``# watchdog:`` / ``# watchdog-exempt:`` comment with a non-empty
  rationale (e.g. "registers in feeder() at thread start", "workers
  heartbeat implicitly via the result queue").

Run:
    python scripts/audit_threads.py            # audit the package, exit 1
    python scripts/audit_threads.py --json     # machine-readable report

Wired into ``make lint-obs`` and run in tier-1
(tests/unit/test_obs.py::test_audit_threads_clean).
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys

# Constructors whose call sites spawn (or pool) concurrent execution.
SPAWN_NAMES = frozenset(
    {"Thread", "Timer", "Process", "ThreadPoolExecutor", "ProcessPoolExecutor"}
)

# Lines around the spawn call searched for a registration or a rationale.
WINDOW = 8

_MARKER_RE = re.compile(r"#\s*watchdog(?:-exempt)?\s*(?:\((?P<scope>[^)]*)\))?:\s*(?P<why>\S.*)")
_REGISTER_RE = re.compile(r"\bwatchdog\.register\(")


def _spawn_calls(tree: ast.AST):
    """Yield (lineno, callee_name) for every spawn-constructor call."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = None
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        if name in SPAWN_NAMES:
            yield node.lineno, name


def audit_file(path: str) -> list[dict]:
    """Violations in one file: spawn sites with neither a nearby
    ``watchdog.register(`` nor a ``# watchdog...:`` rationale comment."""
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [{"path": path, "line": e.lineno or 0,
                 "callee": "?", "reason": f"unparseable: {e.msg}"}]
    lines = src.splitlines()
    violations = []
    for lineno, callee in _spawn_calls(tree):
        lo = max(0, lineno - 1 - WINDOW)
        hi = min(len(lines), lineno + WINDOW)
        window = "\n".join(lines[lo:hi])
        if _REGISTER_RE.search(window) or _MARKER_RE.search(window):
            continue
        violations.append(
            {
                "path": path,
                "line": lineno,
                "callee": callee,
                "reason": (
                    f"{callee}() spawn without watchdog.register( or a "
                    "'# watchdog: <why>' rationale within "
                    f"{WINDOW} lines"
                ),
            }
        )
    return violations


def audit_package(root: str) -> list[dict]:
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                violations.extend(audit_file(os.path.join(dirpath, fn)))
    return violations


def default_root() -> str:
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "..",
        "batchai_retinanet_horovod_coco_tpu",
    )


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", default=None,
                    help="directory to audit (default: the package)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    root = args.root or default_root()
    violations = audit_package(root)
    if args.json:
        print(json.dumps({"root": root, "violations": violations}))
    elif violations:
        for v in violations:
            print(f"{v['path']}:{v['line']}: {v['reason']}")
        print(f"{len(violations)} unwatched spawn site(s)")
    else:
        print("audit_threads: every spawn site is watchdog-covered")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
