#!/usr/bin/env python
"""Static watchdog-coverage audit — now a shim over the lint engine.

The original bespoke AST walk moved into the invariant lint engine as the
``watchdog-coverage`` rule
(``batchai_retinanet_horovod_coco_tpu/analysis/rules/watchdog_coverage.py``);
this entry point survives so ``make lint-obs`` and the tier-1 wiring
(tests/unit/test_obs.py, tests/unit/test_serve.py) keep their exact CLI and
API: every thread/process spawn site in the package must register with the
obs watchdog or say why it doesn't (a ``# watchdog: <why>`` rationale
within ``WINDOW`` lines, or the engine's uniform
``# lint: watchdog-coverage: <why>`` suppression).

Run:
    python scripts/audit_threads.py            # audit the package, exit 1
    python scripts/audit_threads.py --json     # machine-readable report

The full rule set (bounded queues, thread error contracts, jit purity,
clock discipline, collective safety, this audit) runs via ``make lint`` /
``python -m batchai_retinanet_horovod_coco_tpu.analysis``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # direct `python scripts/audit_threads.py` runs
    sys.path.insert(0, _REPO)

from batchai_retinanet_horovod_coco_tpu.analysis import engine  # noqa: E402
from batchai_retinanet_horovod_coco_tpu.analysis.rules import (  # noqa: E402
    watchdog_coverage as _rule,
)

# Legacy API surface, re-exported from the engine rule.
SPAWN_NAMES = _rule.SPAWN_NAMES
WINDOW = _rule.WINDOW
_MARKER_RE = _rule.MARKER_RE
_REGISTER_RE = _rule.REGISTER_RE
_spawn_calls = _rule.spawn_calls


def _to_legacy(finding: engine.Finding, path: str) -> dict:
    if finding.rule == engine.SUPPRESSION_RULE:
        return {"path": path, "line": finding.line, "callee": "?",
                "reason": finding.message.replace("unparseable file: ",
                                                  "unparseable: ")}
    return {
        "path": path,
        "line": finding.line,
        "callee": finding.message.split("(", 1)[0],
        "reason": finding.message,
    }


def audit_file(path: str) -> list[dict]:
    """Violations in one file: spawn sites with neither a nearby
    ``watchdog.register(`` nor a rationale (legacy ``# watchdog...:``
    marker or engine ``# lint: watchdog-coverage: <why>``)."""
    with open(path) as f:
        src = f.read()
    res = engine.lint_source(path, path, src, rule_names=[_rule.NAME])
    out = [_to_legacy(f, path) for f in res.findings]
    # Suppression-grammar errors in the file still surface here so a typo'd
    # rationale can't silently waive the audit.
    out.extend(_to_legacy(f, path) for f in res.grammar_findings)
    return out


def audit_package(root: str) -> list[dict]:
    violations = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                violations.extend(audit_file(os.path.join(dirpath, fn)))
    return violations


def default_root() -> str:
    return os.path.join(_REPO, "batchai_retinanet_horovod_coco_tpu")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("root", nargs="?", default=None,
                    help="directory to audit (default: the package)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    root = args.root or default_root()
    violations = audit_package(root)
    if args.json:
        print(json.dumps({"root": root, "violations": violations}))
    elif violations:
        for v in violations:
            print(f"{v['path']}:{v['line']}: {v['reason']}")
        print(f"{len(violations)} unwatched spawn site(s)")
    else:
        print("audit_threads: every spawn site is watchdog-covered")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
