"""Streaming detection smoke (ISSUE 18, `make stream-smoke`).

The REAL fleet CLI over 2 stub-video replica subprocesses, driving the
full streaming surface over HTTP end to end on CPU:

1. **mixed traffic** — 3 seeded drift streams (/stream/open + ordered
   /stream/frame posts) race single-image /detect traffic through the
   same fleet edge; every class completes;
2. **frame-delta cache** — the drift plateaus between scene cuts return
   ``cache_hit: true`` responses (hits > 0), and the fleet's federated
   /metrics carries the replica-side cache counters;
3. **track stitching** — every detection carries a ``track_id``, and ids
   hold stable across the frames between cuts;
4. **replica kill** — SIGKILL the replica pinned by stream 0 mid-stream:
   each stream pinned there re-pins to the survivor with exactly one
   structured ``stream_repinned`` event, and ZERO frames drop — every
   admitted frame still returns 200 detections (the fleet edge retries
   the in-flight frame on the new pin);
5. **close** — /stream/close returns the per-session stats snapshot.

CPU-only, no dataset, no device work — wired into `make check-static`.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_STREAMS = 3
FRAMES = 36
CUT_EVERY = 12
KILL_AT_FRAME = 15  # kill once every stream has passed this frame
N_SINGLES = 30

FAILURES: list[str] = []


def check(ok: bool, what: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"stream-smoke {tag}: {what}", flush=True)
    if not ok:
        FAILURES.append(what)


def _png(arr) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "PNG")
    return buf.getvalue()


def _http(url: str, data: bytes | None = None, headers: dict | None = None,
          timeout: float = 30.0):
    """(status, headers dict, body bytes); 4xx/5xx are data."""
    req = urllib.request.Request(
        url, data=data, method="POST" if data is not None else "GET"
    )
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class Fleet:
    """The fleet CLI under test + structured stdout/stderr readers."""

    def __init__(self):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "batchai_retinanet_horovod_coco_tpu.serve.fleet",
                "--http", "0", "--spawn", "2", "--stub-engine",
                "--poll-interval", "0.2", "--respawn-delay-s", "1.0",
                "--fleet-timeout-s", "20",
                "--spawn-serve-args=--stub-video",
            ],
            env=env, cwd=_REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        self.stdout_lines: list[str] = []
        self.stderr_lines: list[str] = []

        def reader(stream, into):
            try:
                for line in stream:
                    into.append(line.rstrip("\n"))
            except Exception as e:
                into.append(f"__reader_error__ {e!r}")

        # watchdog: harness-local pipe readers; liveness is witnessed by
        # the driver's own bounded waits, not the obs watchdog.
        for stream, into in (
            (self.proc.stdout, self.stdout_lines),
            (self.proc.stderr, self.stderr_lines),
        ):
            threading.Thread(
                target=reader, args=(stream, into), daemon=True
            ).start()
        try:
            self.base_url = self._wait_for_url()
        except Exception:
            self.stop()
            raise

    def _wait_for_url(self, timeout: float = 180.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet CLI died rc={self.proc.returncode}: "
                    f"{self.stderr_lines[-5:]}"
                )
            for line in self.stdout_lines:
                if line.startswith("fleet serving on "):
                    return line.split("fleet serving on ", 1)[1].split()[0]
            time.sleep(0.1)
        raise RuntimeError("fleet CLI never started serving")

    def events(self, kind: str) -> list[dict]:
        out = []
        for line in self.stdout_lines + self.stderr_lines:
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict) and rec.get("event") == kind:
                out.append(rec)
        return out

    def metric(self, key: str) -> float:
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            parse_exposition,
        )

        code, _h, body = _http(f"{self.base_url}/metrics")
        if code != 200:
            return float("nan")
        _types, samples = parse_exposition(body.decode())
        return samples.get(key, 0.0)

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


class StreamClient:
    """One video session: ordered frame posts, per-frame bookkeeping."""

    def __init__(self, k: int, base_url: str):
        from batchai_retinanet_horovod_coco_tpu.serve.stub import (
            drift_frames,
        )

        self.k = k
        self.base_url = base_url
        self.frames = [
            _png(fr)
            for fr in drift_frames(
                seed=42 + k, n=FRAMES, step=1.0, cut_every=CUT_EVERY
            )
        ]
        self.sid = ""
        self.replica_id = ""
        self.sent = 0
        self.responses: list[dict] = []  # per-frame response docs
        self.bad: list[tuple[int, int, str]] = []  # (seq, code, body)
        self.stats: dict = {}
        self.error: str | None = None

    def open(self) -> None:
        code, _h, body = _http(
            f"{self.base_url}/stream/open",
            data=json.dumps({"width": 64, "height": 64}).encode(),
            headers={"Content-Type": "application/json"},
        )
        if code != 200:
            raise RuntimeError(f"stream {self.k} open -> {code}: {body!r}")
        doc = json.loads(body.decode())
        self.sid = doc["session"]
        self.replica_id = doc.get("replica_id", "")

    def run(self) -> None:
        try:
            for seq, payload in enumerate(self.frames):
                code, _h, body = _http(
                    f"{self.base_url}/stream/frame", data=payload,
                    headers={
                        "X-Retinanet-Stream": self.sid,
                        "X-Retinanet-Frame": str(seq),
                    },
                )
                if code == 200:
                    self.responses.append(json.loads(body.decode()))
                else:
                    # Any non-200 is a DROPPED frame: the fleet edge
                    # consumed the seq, so there is no legal retry.
                    self.bad.append((seq, code, body.decode()[:200]))
                self.sent = seq + 1
                time.sleep(0.02)  # ~50 fps offered — gentle pacing
            code, _h, body = _http(
                f"{self.base_url}/stream/close", data=b"",
                headers={"X-Retinanet-Stream": self.sid},
            )
            if code == 200:
                self.stats = json.loads(body.decode()).get("stats", {})
        except Exception as e:  # crash channel: fail loudly, not silently
            self.error = repr(e)


def main() -> int:
    fleet = Fleet()
    try:
        spawned = fleet.events("fleet_replica_spawned")
        check(len(spawned) == 2, f"2 replicas spawned (saw {len(spawned)})")
        pid_by_rid = {e["replica_id"]: e["pid"] for e in spawned}

        clients = [StreamClient(k, fleet.base_url) for k in range(N_STREAMS)]
        for c in clients:
            c.open()
        check(
            all(c.sid for c in clients),
            f"{N_STREAMS} streams opened, each pinned to a replica "
            f"({[c.replica_id for c in clients]})",
        )

        # watchdog: harness-local load generators, bounded by the joins
        # below.
        threads = [
            threading.Thread(target=c.run, daemon=True) for c in clients
        ]
        for t in threads:
            t.start()

        # Single-image traffic mixed through the same edge.
        import numpy as np

        rng = np.random.default_rng(7)
        singles_png = _png(
            rng.integers(0, 255, size=(64, 64, 3)).astype(np.uint8)
        )
        single_codes: list[int] = []

        def singles():
            try:
                for _ in range(N_SINGLES):
                    code, _h, _b = _http(
                        f"{fleet.base_url}/detect", data=singles_png
                    )
                    single_codes.append(code)
                    time.sleep(0.03)
            except Exception as exc:  # forward into the FAILURES ledger
                check(False, f"singles generator crashed: {exc!r}")

        # watchdog: harness-local load generator, joined below.
        single_thread = threading.Thread(target=singles, daemon=True)
        single_thread.start()

        # Kill stream 0's pinned replica once every stream is mid-flight.
        deadline = time.monotonic() + 60
        while any(c.sent < KILL_AT_FRAME for c in clients):
            if time.monotonic() > deadline:
                check(False, "streams never reached the kill point")
                break
            time.sleep(0.05)
        victim_rid = clients[0].replica_id
        pinned_to_victim = [c for c in clients if c.replica_id == victim_rid]
        os.kill(pid_by_rid[victim_rid], signal.SIGKILL)
        print(f"stream-smoke: killed {victim_rid} "
              f"(pinned: {[c.k for c in pinned_to_victim]})", flush=True)

        for t in threads:
            t.join(timeout=120)
        single_thread.join(timeout=120)
        check(
            not any(t.is_alive() for t in threads)
            and not single_thread.is_alive(),
            "all load generators finished",
        )
        for c in clients:
            check(c.error is None, f"stream {c.k} client clean ({c.error})")

        # ---- zero dropped frames across the kill ----------------------
        for c in clients:
            check(
                not c.bad and len(c.responses) == FRAMES,
                f"stream {c.k}: {len(c.responses)}/{FRAMES} frames served, "
                f"dropped {c.bad[:3]}",
            )
            check(
                all(
                    d.get("frame") == i
                    for i, d in enumerate(c.responses)
                ),
                f"stream {c.k}: responses arrived in frame order",
            )

        # ---- cache hits on the drift plateaus --------------------------
        hits = sum(
            1 for c in clients for d in c.responses if d.get("cache_hit")
        )
        check(hits > 0, f"frame-delta cache hits > 0 (saw {hits})")

        # ---- track ids present and stable between cuts ------------------
        for c in clients:
            dets = [d.get("detections", []) for d in c.responses]
            check(
                all(
                    all("track_id" in dd for dd in frame_dets)
                    for frame_dets in dets
                ),
                f"stream {c.k}: every detection carries track_id",
            )
            # Frames 1..KILL_AT_FRAME-1 sit inside drift plateaus before
            # the kill on the FIRST pin; ids must hold within a cut
            # segment (the stitcher resets on re-pin, so stop early).
            seg_end = min(CUT_EVERY, KILL_AT_FRAME)
            ids = [
                sorted(dd["track_id"] for dd in frame_dets)
                for frame_dets in dets[1:seg_end]
            ]
            check(
                all(x == ids[0] for x in ids),
                f"stream {c.k}: track ids stable across frames "
                f"1..{seg_end - 1} ({ids[:3]}...)",
            )

        # ---- exactly one re-pin per stream pinned to the victim --------
        # (bounded wait: the stderr reader thread can lag the pipe by a
        # beat, so poll until the expected lines land)
        deadline = time.monotonic() + 15
        repins = fleet.events("stream_repinned")
        while (
            len(repins) < len(pinned_to_victim)
            and time.monotonic() < deadline
        ):
            time.sleep(0.2)
            repins = fleet.events("stream_repinned")
        by_stream: dict[str, int] = {}
        for e in repins:
            by_stream[e["stream"]] = by_stream.get(e["stream"], 0) + 1
        expected = {c.sid for c in pinned_to_victim}
        check(
            set(by_stream) == expected
            and all(v == 1 for v in by_stream.values()),
            f"exactly one stream_repinned per victim-pinned stream "
            f"({by_stream} vs expected {sorted(expected)})",
        )
        check(
            fleet.metric("fleet_stream_repinned_total")
            == float(len(expected)),
            "fleet_stream_repinned_total matches the events",
        )

        # ---- singles were never starved by the streams ------------------
        ok = sum(1 for c in single_codes if c == 200)
        odd = [c for c in single_codes if c not in (200, 503)]
        check(
            len(single_codes) == N_SINGLES and not odd
            and ok >= 0.9 * N_SINGLES,
            f"single-image traffic served through the kill "
            f"({ok}/{N_SINGLES} ok, odd codes {odd[:5]})",
        )

        # ---- close returned per-session stats ---------------------------
        closable = [c for c in clients if c.replica_id != victim_rid]
        check(
            all(c.stats.get("frames", 0) > 0 for c in closable),
            f"/stream/close returned per-session stats "
            f"({[c.stats.get('frames') for c in clients]})",
        )
    finally:
        fleet.stop()

    if FAILURES:
        print(f"stream-smoke: {len(FAILURES)} FAILURE(S): {FAILURES}",
              flush=True)
        return 1
    print("stream-smoke: all checks green", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
