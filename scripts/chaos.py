#!/usr/bin/env python
"""chaos: fault-injection harness for the durability subsystem (ISSUE 11).

Drives a REAL ``train.py`` CPU training subprocess through a kill
schedule and asserts the crash-safety contract the checkpoint protocol
promises (utils/checkpoint.py):

- **Save-phase kills** — ``RETINANET_CHAOS_KILL=<phase>@<n>`` makes the
  subprocess SIGKILL itself at the n-th crossing of a named protocol
  phase (snapshot, tmp_write, manifest_commit, rename, finalize).  After
  EVERY kill: no published ``ckpt-*`` dir may be torn (manifest present
  and consistent), and a plain resume run must complete and produce
  losses BIT-IDENTICAL to an uninterrupted baseline at every step —
  ``--resume-elastic`` re-derives the stream position, so step k sees
  the same batch in both runs.
- **Mid-step kills** — the driver SIGKILLs the subprocess from outside
  once the log shows a target step, covering the window between saves.
- **Torn-dir triage** — manufactured damage (deleted manifest,
  truncated leaf, stray .tmp dir) must be skipped to the previous
  complete checkpoint, and the resume still completes.
- **NaN auto-resume** — ``--inject-nan-step`` poisons one mid-run batch;
  with ``--auto-resume`` the run must complete to the target step with
  EXACTLY ONE structured ``auto_resume`` event, a NUMERICS_DUMP.json,
  and the poison batch's image ids excluded from the healed stream.
- **Comm leg** (``--comm`` / ``make chaos-comm``, ISSUE 13) — SIGKILL a
  ``--comm-compress int8`` run (2 virtual devices) mid-save; the
  surviving checkpoint must carry the EF residual leaves, the resume
  must restore them (or cleanly zero them with ONE structured
  ``ef_reset`` event), and the resumed losses must rejoin the
  uninterrupted compressed baseline's envelope.  The hierarchical
  sub-leg (ISSUE 16) repeats the schedule at ``--comm-slices 2`` on 4
  devices and additionally requires the surviving residual leaves to be
  keyed per hop (``@dcn``) — proving the per-hop EF state survives
  SIGKILL + resume.
- **CKPTBENCH** (``--bench``) — measures the two durability numbers the
  ROADMAP asks for: save overhead (wall time of N checkpointed steps vs
  the same N without) and time-to-first-step on resume; writes
  CKPTBENCH.json.  ``--check`` re-measures against the committed
  artifact with bench-check's device-class guard, and a non-CPU target
  (CKPTBENCH_PLATFORM) gets the probe + exit-75 outage contract.

Modes: ``--smoke`` (one mid-save kill + one NaN leg; the check-static
CI leg), default full schedule (>= 20 kills), ``--bench``/``--check``.
Exit 0 = contract held; 1 = violation (each printed as one
``chaos FAIL:`` line); 75 = accelerator unreachable (bench only).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time  # lint-exempt scripts/: subprocess wall timing only

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

EXIT_UNREACHABLE = 75
_failures: list[str] = []

# Every save-protocol phase, in write order (utils/checkpoint.py).
PHASES = ("snapshot", "tmp_write", "manifest_commit", "rename", "finalize")


def check(ok: bool, what: str) -> None:
    if not ok:
        _failures.append(what)
        print(f"chaos FAIL: {what}", flush=True)


def _base_cmd(work: str, steps: int, extra: list[str] | None = None) -> list[str]:
    return [
        sys.executable, os.path.join(_REPO, "train.py"), "synthetic",
        "--platform", "cpu", "--backbone", "resnet_test", "--f32",
        "--image-min-side", "64", "--image-max-side", "64",
        "--synthetic-size", "64", "--synthetic-images", "16",
        "--synthetic-classes", "3",
        "--synthetic-root", os.path.join(work, "data"),
        "--batch-size", "4", "--num-devices", "1", "--workers", "2",
        "--max-gt", "8", "--seed", "0", "--log-every", "1",
        "--steps", str(steps),
        "--snapshot-path", os.path.join(work, "ckpt"),
        "--checkpoint-every", "2",
        "--log-dir", os.path.join(work, "logs"),
    ] + (extra or [])


def _run(cmd: list[str], env_extra: dict | None = None,
         timeout: float = 900.0) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout,
    )


def _run_until_step_then_kill(
    cmd: list[str], work: str, kill_at_step: int, timeout: float = 900.0
) -> int:
    """Launch and SIGKILL from OUTSIDE once metrics.jsonl shows the step
    — the mid-step half of the schedule (between-save windows)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
    )
    metrics = os.path.join(work, "logs", "metrics.jsonl")
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return proc.returncode  # died early — caller flags it
            for rec in _records(metrics):
                if rec.get("step", -1) >= kill_at_step:
                    proc.kill()
                    proc.wait(timeout=30)
                    return -signal.SIGKILL
            time.sleep(0.2)
        proc.kill()
        proc.wait(timeout=30)
        return -999  # timed out waiting for the step
    finally:
        if proc.poll() is None:
            proc.kill()


def _records(metrics_path: str) -> list[dict]:
    out = []
    try:
        with open(metrics_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # a killed run may leave one torn tail line
    except OSError:
        pass
    return out


def _losses_by_step(metrics_path: str) -> dict[int, float]:
    """step -> train/loss over ALL runs appended to the file; a later run
    overwrites (resume re-logs nothing, so collisions only happen when a
    killed step re-runs after resume — and then bit-equality is exactly
    the claim under test)."""
    out: dict[int, float] = {}
    for rec in _records(metrics_path):
        if "step" in rec and "train/loss" in rec and "event" not in rec:
            out[int(rec["step"])] = rec["train/loss"]
    return out


def _events(metrics_path: str, kind: str) -> list[dict]:
    return [r for r in _records(metrics_path) if r.get("event") == kind]


def _validate_ckpt_dir(work: str, context: str) -> None:
    """No PUBLISHED checkpoint may be torn, ever — the core protocol
    claim.  (Dirs without a manifest cannot exist under the protocol;
    .tmp-* leftovers are expected and invisible to restore.)"""
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        _load_manifest,
    )

    d = os.path.join(work, "ckpt")
    if not os.path.isdir(d):
        return
    for name in sorted(os.listdir(d)):
        if not name.startswith("ckpt-"):
            continue
        manifest = _load_manifest(os.path.join(d, name))
        check(
            manifest is not None,
            f"{context}: published {name} is torn (protocol violation)",
        )


def _fresh_workdir(tag: str) -> str:
    work = tempfile.mkdtemp(prefix=f"chaos_{tag}_")
    return work


def _baseline(steps: int) -> tuple[str, dict[int, float]]:
    work = _fresh_workdir("baseline")
    r = _run(_base_cmd(work, steps))
    check(r.returncode == 0, f"baseline run failed rc={r.returncode}: "
                             f"{r.stderr[-500:]}")
    losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
    check(
        set(losses) == set(range(1, steps + 1)),
        f"baseline logged steps {sorted(losses)} != 1..{steps}",
    )
    return work, losses


def _kill_leg(
    tag: str, kill_env: str | None, baseline: dict[int, float], steps: int,
    kill_at_step: int | None = None,
) -> None:
    """One scheduled kill: run with the kill armed, assert it fired and
    the checkpoint dir survived; resume; assert completion + bit-identical
    losses vs the baseline."""
    work = _fresh_workdir(tag)
    cmd = _base_cmd(work, steps, ["--resume-elastic"])
    if kill_env is not None:
        r = _run(cmd, env_extra={"RETINANET_CHAOS_KILL": kill_env})
        check(
            r.returncode != 0,
            f"{tag}: kill {kill_env} never fired (rc 0 — schedule vacuous)",
        )
    else:
        rc = _run_until_step_then_kill(cmd, work, kill_at_step)
        check(rc == -signal.SIGKILL, f"{tag}: external kill failed rc={rc}")
    _validate_ckpt_dir(work, tag)
    resume = _run(cmd)
    check(
        resume.returncode == 0,
        f"{tag}: resume failed rc={resume.returncode}: "
        f"{resume.stderr[-500:]}",
    )
    _validate_ckpt_dir(work, f"{tag}/post-resume")
    losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
    check(
        losses.get(steps) is not None,
        f"{tag}: resumed run never reached step {steps}",
    )
    mismatches = {
        s: (losses[s], baseline[s])
        for s in losses
        if s in baseline and losses[s] != baseline[s]
    }
    check(
        not mismatches,
        f"{tag}: losses not bit-identical to baseline: {mismatches}",
    )
    if not _failures:
        shutil.rmtree(work, ignore_errors=True)


def _torn_dir_legs(baseline: dict[int, float], steps: int) -> None:
    """Manufactured damage: restore must skip to the previous complete
    checkpoint and the run must still finish."""
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        latest_step,
    )

    src = _fresh_workdir("torn_src")
    r = _run(_base_cmd(src, steps, ["--resume-elastic"]))
    check(r.returncode == 0, f"torn-src run failed rc={r.returncode}")
    ckpt = os.path.join(src, "ckpt")
    newest = latest_step(ckpt)
    check(newest == steps, f"torn-src latest {newest} != {steps}")

    def damage_and_resume(tag: str, damage) -> None:
        work = _fresh_workdir(tag)
        shutil.rmtree(work)
        shutil.copytree(src, work)
        damage(os.path.join(work, "ckpt"))
        got = latest_step(os.path.join(work, "ckpt"))
        check(
            got is not None and got < steps,
            f"{tag}: damaged newest not skipped (latest={got})",
        )
        resume = _run(_base_cmd(work, steps + 2, ["--resume-elastic"]))
        check(
            resume.returncode == 0,
            f"{tag}: resume after damage failed rc={resume.returncode}: "
            f"{resume.stderr[-500:]}",
        )
        losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
        mism = {
            s: (losses[s], baseline[s])
            for s in losses
            if s in baseline and losses[s] != baseline[s]
        }
        check(not mism, f"{tag}: post-damage losses diverged: {mism}")
        if not _failures:
            shutil.rmtree(work, ignore_errors=True)

    damage_and_resume(
        "torn_manifest",
        lambda d: os.unlink(os.path.join(d, f"ckpt-{newest}", "manifest.json")),
    )

    def truncate(d):
        leaf = os.path.join(d, f"ckpt-{newest}", "leaf_00001.npy")
        with open(leaf, "r+b") as f:
            f.truncate(max(1, os.path.getsize(leaf) // 2))

    damage_and_resume("torn_leaf", truncate)
    damage_and_resume(
        "stray_tmp",
        lambda d: (
            os.makedirs(os.path.join(d, ".tmp-99-1"), exist_ok=True),
            os.unlink(os.path.join(d, f"ckpt-{newest}", "manifest.json")),
        ),
    )
    if not _failures:
        shutil.rmtree(src, ignore_errors=True)


def _nan_leg(steps: int = 12, inject_at: int = 7) -> None:
    """Injected NaN + --auto-resume: completes to target with exactly one
    auto_resume event, a provenance dump, and the poison ids excluded."""
    work = _fresh_workdir("nan")
    cmd = _base_cmd(
        work, steps,
        ["--auto-resume", "--inject-nan-step", str(inject_at)],
    )
    r = _run(cmd)
    check(
        r.returncode == 0,
        f"nan: auto-resume run failed rc={r.returncode}: {r.stderr[-800:]}",
    )
    metrics = os.path.join(work, "logs", "metrics.jsonl")
    resumes = _events(metrics, "auto_resume")
    check(
        len(resumes) == 1,
        f"nan: expected exactly one auto_resume event, got {len(resumes)}",
    )
    losses = _losses_by_step(metrics)
    check(
        losses.get(steps) is not None,
        f"nan: healed run never reached step {steps}",
    )
    dump = os.path.join(work, "logs", "NUMERICS_DUMP.json")
    check(os.path.exists(dump), "nan: no NUMERICS_DUMP.json landed")
    if resumes:
        ev = resumes[0]
        check(
            bool(ev.get("exclude_ids")),
            "nan: auto_resume event carries no excluded poison ids",
        )
        check(
            ev.get("restored_step", -1) < inject_at,
            f"nan: restored step {ev.get('restored_step')} not before the "
            f"poison step {inject_at}",
        )
    if not _failures:
        shutil.rmtree(work, ignore_errors=True)


# ---------------------------------------------------------------------------
# Comm leg (ISSUE 13): SIGKILL under gradient compression + error feedback
# ---------------------------------------------------------------------------
#
# The EF residual is TRAINING STATE: it carries the quantization error the
# next step must add back, so a crash/restore cycle that silently dropped
# it would re-bias the compressed gradients with nothing in the logs.
# This leg kills a real --comm-compress int8 CPU run (2 virtual devices —
# compression rides the mesh collectives) mid-save and asserts the
# durability contract: the checkpoint carries ['comm_state'] leaves, the
# resume either restores them or cleanly zeros them with EXACTLY ONE
# structured ef_reset event, and the resumed losses rejoin the
# uninterrupted compressed baseline's envelope.


def _comm_cmd(work: str, steps: int, hier: bool = False) -> list[str]:
    cmd = _base_cmd(
        work, steps, ["--resume-elastic", "--comm-compress", "int8"]
    )
    # Compression needs a mesh: virtual CPU devices (train.py forces
    # xla_force_host_platform_device_count in the subprocess).  The
    # hierarchical leg (ISSUE 16) emulates 2 slices x 2 devices via
    # --comm-slices, which moves the EF residuals to the DCN hop.
    i = cmd.index("--num-devices")
    cmd[i + 1] = "4" if hier else "2"
    if hier:
        cmd += ["--comm-slices", "2"]
    return cmd


def _comm_leg(steps: int = 8, hier: bool = False) -> None:
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        read_manifest,
    )

    tag = "comm-hier" if hier else "comm"
    # Uninterrupted compressed baseline (its own losses — int8+EF drifts
    # from the exact run by design, so the envelope is compressed-vs-
    # compressed).
    base = _fresh_workdir(f"{tag}_base".replace("-", "_"))
    r = _run(_comm_cmd(base, steps, hier))
    check(
        r.returncode == 0,
        f"{tag}: baseline failed rc={r.returncode}: {r.stderr[-500:]}",
    )
    baseline = _losses_by_step(os.path.join(base, "logs", "metrics.jsonl"))
    check(
        baseline.get(steps) is not None,
        f"{tag}: baseline never reached step {steps}",
    )

    work = _fresh_workdir(f"{tag}_kill".replace("-", "_"))
    cmd = _comm_cmd(work, steps, hier)
    r = _run(cmd, env_extra={"RETINANET_CHAOS_KILL": "tmp_write@2"})
    check(
        r.returncode != 0,
        f"{tag}: mid-save kill never fired (rc 0 — schedule vacuous)",
    )
    _validate_ckpt_dir(work, tag)
    manifest = read_manifest(os.path.join(work, "ckpt"))
    check(manifest is not None, f"{tag}: no restorable checkpoint survived")
    if manifest is not None:
        ef_paths = [
            e["path"]
            for e in manifest.get("leaves", [])
            if e["path"].startswith("['comm_state']")
        ]
        check(
            bool(ef_paths),
            f"{tag}: surviving checkpoint carries no EF residual leaves "
            "(comm_state was not checkpointed)",
        )
        if hier:
            # The hierarchical tree keys its residuals per hop — the
            # checkpoint must carry the @dcn layout, or a resume would
            # silently zero them (layout mismatch -> ef_reset).
            check(
                any("@dcn" in p for p in ef_paths),
                f"{tag}: EF residual leaves are not keyed per hop "
                f"(no @dcn in {ef_paths})",
            )
    resume = _run(cmd)
    check(
        resume.returncode == 0,
        f"{tag}: resume failed rc={resume.returncode}: "
        f"{resume.stderr[-500:]}",
    )
    metrics = os.path.join(work, "logs", "metrics.jsonl")
    ef_resets = _events(metrics, "ef_reset")
    check(
        len(ef_resets) <= 1,
        f"{tag}: expected 0 (restored) or 1 (cleanly zeroed) ef_reset "
        f"events, got {len(ef_resets)}",
    )
    losses = _losses_by_step(metrics)
    check(
        losses.get(steps) is not None,
        f"{tag}: resumed run never reached step {steps}",
    )
    # Same world size + --resume-elastic: a restore that carried the EF
    # state replays the baseline essentially exactly (tight envelope);
    # the announced zero-and-continue path perturbs the first resumed
    # steps at quantization-error scale, so its envelope is the loose
    # one — either way the trajectory must rejoin the uninterrupted
    # compressed baseline.
    rtol = 5e-2 if ef_resets else 1e-5
    bad = {
        s: (losses[s], baseline[s])
        for s in losses
        if s in baseline
        and abs(losses[s] - baseline[s]) > rtol * max(abs(baseline[s]), 1e-9)
    }
    check(
        not bad,
        f"{tag}: resumed losses left the baseline envelope: {bad}",
    )
    if not _failures:
        shutil.rmtree(base, ignore_errors=True)
        shutil.rmtree(work, ignore_errors=True)


# ---------------------------------------------------------------------------
# Serve fleet leg (ISSUE 12): kill-a-replica + SLO-gated canary rollback
# ---------------------------------------------------------------------------
#
# System under test: the REAL fleet CLI (python -m …serve.fleet) over
# stub-engine replica subprocesses — the serve-side twin of the training
# kill schedule above.  Two legs:
#
# - kill: SIGKILL one replica subprocess mid-load; every accepted request
#   must complete or shed WITH A REASON (zero hung clients, zero silent
#   drops), the router's /healthz must stay 200 throughout, and after the
#   supervisor respawns the replica the breaker must readmit it (traffic
#   lands on it again).
# - canary: a deliberately slow stub canary joins behind the canary gate;
#   the p99 regression must produce EXACTLY ONE canary_rollback event and
#   leave the fleet at baseline weights, with traffic unharmed.


def _fleet_payload() -> bytes:
    import io

    import numpy as np
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.zeros((64, 64, 3), np.uint8)).save(buf, "PNG")
    return buf.getvalue()


def _http_get(url: str, timeout: float = 10.0):
    """(status, body_bytes); 4xx/5xx are data, socket errors raise."""
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class _FleetUnderTest:
    """One fleet-CLI subprocess + line-readers for its structured stdout
    (spawn/respawn events) and stderr (breaker/canary events)."""

    def __init__(self, tag: str, extra_args: list[str]):
        import threading

        self.tag = tag
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.serve.fleet",
             "--http", "0"] + extra_args,
            env=env, cwd=_REPO, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        self.stdout_lines: list[str] = []
        self.stderr_lines: list[str] = []

        def reader(stream, into):
            try:
                for line in stream:
                    into.append(line.rstrip("\n"))
            except Exception as e:  # crash channel: visible in the report
                into.append(f"__reader_error__ {e!r}")

        # watchdog: harness-local pipe readers; liveness is witnessed by
        # the driver's own bounded waits, not the obs watchdog.
        self._readers = [
            threading.Thread(
                target=reader, args=(self.proc.stdout, self.stdout_lines),
                daemon=True,
            ),
            threading.Thread(
                target=reader, args=(self.proc.stderr, self.stderr_lines),
                daemon=True,
            ),
        ]
        for t in self._readers:
            t.start()
        try:
            self.base_url = self._wait_for_url()
        except Exception:
            # Constructor failure = no handle for the caller's finally:
            # kill the fleet CLI here (its own teardown reaps the
            # replica children) so a wedged bring-up can't leak
            # processes holding pinned ports into the next CI run.
            self.stop()
            raise

    def _wait_for_url(self, timeout: float = 180.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"{self.tag}: fleet CLI died rc={self.proc.returncode}: "
                    f"{self.stderr_lines[-5:]}"
                )
            for line in self.stdout_lines:
                if line.startswith("fleet serving on "):
                    return line.split("fleet serving on ", 1)[1].split()[0]
            time.sleep(0.1)
        raise RuntimeError(f"{self.tag}: fleet CLI never started serving")

    def events(self, kind: str) -> list[dict]:
        out = []
        for line in self.stdout_lines + self.stderr_lines:
            try:
                rec = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(rec, dict) and rec.get("event") == kind:
                out.append(rec)
        return out

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


def _fleet_storm(
    base_url: str, payload: bytes, total: int, clients: int,
    mid_action=None, request_timeout: float = 30.0,
) -> dict:
    """Drive ``total`` requests from ``clients`` threads; every request
    must RESOLVE (2xx/4xx/5xx all count — a hang or router socket error
    does not).  ``mid_action()`` runs once, halfway through."""
    import threading
    import urllib.error
    import urllib.request

    lock = threading.Lock()
    counts = {"ok": 0, "shed": 0, "timeout": 0, "server_error": 0,
              "router_unreachable": 0, "hung": 0, "other": 0}
    issued = [0]
    acted = [False]

    def one_request():
        req = urllib.request.Request(
            f"{base_url}/detect", data=payload, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=request_timeout) as r:
                json.loads(r.read().decode())
                return "ok"
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read().decode())
            except Exception:
                pass
            if e.code == 503:
                # A shed MUST carry a machine-readable reason.
                return "shed" if body.get("reason") else "other"
            if e.code == 504:
                return "timeout"
            return "server_error"
        except TimeoutError:
            return "hung"  # the contract violation this leg exists for
        except Exception as e:
            if "timed out" in str(e).lower():
                return "hung"
            return "router_unreachable"

    def client():
        try:
            while True:
                with lock:
                    if issued[0] >= total:
                        return
                    issued[0] += 1
                    n = issued[0]
                    fire = n == max(1, total // 2) and not acted[0]
                    if fire:
                        acted[0] = True
                if fire and mid_action is not None:
                    mid_action()
                outcome = one_request()
                with lock:
                    counts[outcome] += 1
        except Exception as e:  # crash channel: a dead client = hung reqs
            with lock:
                counts["other"] += 1
            print(f"chaos FAIL: storm client crashed: {e!r}", flush=True)

    # watchdog: harness-local load generators; every request is bounded
    # by its own urlopen timeout, the driver joins with a budget below.
    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=request_timeout * total / max(1, clients) + 60)
    counts["submitted"] = issued[0]
    counts["resolved"] = sum(
        counts[k] for k in ("ok", "shed", "timeout", "server_error")
    )
    return counts


def _wait_until(predicate, timeout: float, what: str) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if predicate():
                return True
        except Exception:
            pass
        time.sleep(0.25)
    check(False, what)
    return False


def _fleet_status(base_url: str) -> dict:
    code, body = _http_get(f"{base_url}/fleet")
    return json.loads(body.decode()) if code == 200 else {}


def _metric_value(base_url: str, name: str) -> float:
    sys.path.insert(0, _REPO)
    try:
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            parse_exposition,
        )
    finally:
        sys.path.pop(0)
    code, body = _http_get(f"{base_url}/metrics")
    if code != 200:
        return float("nan")
    _types, samples = parse_exposition(body.decode())
    return samples.get(name, 0.0)


def _serve_kill_leg() -> None:
    """SIGKILL one replica mid-load: zero hangs, zero silent drops,
    router 200 throughout, breaker reopens after the respawn."""
    import threading

    fleet = _FleetUnderTest("serve_kill", [
        "--spawn", "2", "--stub-engine", "--stub-delay-ms", "30",
        "--poll-interval", "0.2", "--respawn-delay-s", "0.5",
        "--fleet-timeout-s", "20",
    ])
    try:
        spawned = fleet.events("fleet_replica_spawned")
        check(len(spawned) == 2, f"expected 2 spawns, saw {len(spawned)}")
        victim = spawned[0]

        # Router-liveness watcher: /healthz must be 200 THROUGHOUT.
        bad_healthz: list[tuple] = []
        stop_watch = threading.Event()

        def watch_healthz():
            try:
                while not stop_watch.wait(0.1):
                    code, _ = _http_get(
                        f"{fleet.base_url}/healthz", timeout=5
                    )
                    if code != 200:
                        bad_healthz.append((time.monotonic(), code))
            except Exception as e:  # crash channel → leg fails loudly
                bad_healthz.append((time.monotonic(), repr(e)))

        # watchdog: harness-local probe loop, bounded by stop_watch below.
        watcher = threading.Thread(target=watch_healthz, daemon=True)
        watcher.start()

        counts = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=60, clients=4,
            mid_action=lambda: os.kill(victim["pid"], signal.SIGKILL),
        )
        stop_watch.set()
        watcher.join(timeout=10)

        check(counts["hung"] == 0, f"kill leg: hung clients: {counts}")
        check(
            counts["router_unreachable"] == 0 and counts["other"] == 0,
            f"kill leg: router dropped/garbled requests: {counts}",
        )
        check(
            counts["resolved"] == counts["submitted"],
            f"kill leg: silent drops: {counts}",
        )
        check(counts["ok"] > 0, f"kill leg: nothing completed: {counts}")
        check(
            not bad_healthz,
            f"kill leg: router /healthz flapped: {bad_healthz[:5]}",
        )
        check(
            _metric_value(fleet.base_url, "fleet_breaker_open_total") >= 1,
            "kill leg: breaker never opened on the killed replica",
        )

        # The supervisor respawns the victim in place; the half-open
        # probe must readmit it (breaker re-closes).
        _wait_until(
            lambda: len(fleet.events("fleet_replica_respawned")) >= 1,
            60, "kill leg: victim was never respawned",
        )
        rid = victim["replica_id"]
        _wait_until(
            lambda: any(
                r["replica_id"] == rid and r["state"] == "closed"
                for r in _fleet_status(fleet.base_url).get("replicas", [])
            ),
            60, "kill leg: breaker never readmitted the respawned replica",
        )
        post = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=8, clients=2
        )
        check(
            post["ok"] == post["submitted"],
            f"kill leg: post-respawn traffic unhealthy: {post}",
        )
        # Fleet metric families exist on the scrape surface.
        _code, metrics_body = _http_get(f"{fleet.base_url}/metrics")
        for fam in ("fleet_requests_completed_total", "fleet_replica_weight",
                    "fleet_request_latency_ms", "fleet_breaker_state"):
            check(
                fam.encode() in metrics_body,
                f"kill leg: {fam} missing from fleet /metrics",
            )
        # ISSUE 14: the leg runs under CONTINUOUS in-flight batching (the
        # serve default) — the replicas must advertise the slot-pool load
        # fields the router's weight formula consumes.
        loads = [
            r.get("load", {})
            for r in _fleet_status(fleet.base_url).get("replicas", [])
        ]
        check(
            any(
                "free_slots" in ld and "slot_capacity" in ld for ld in loads
            ),
            "kill leg: no replica advertises the continuous slot-pool "
            f"load fields (free_slots/slot_capacity): {loads}",
        )
    finally:
        fleet.stop()


def _serve_canary_leg() -> None:
    """An injected-slow canary behind the gate: exactly one
    canary_rollback, fleet back to baseline weights, traffic unharmed."""
    fleet = _FleetUnderTest("serve_canary", [
        "--spawn", "2", "--stub-engine", "--stub-delay-ms", "2",
        "--canary-stub-delay-ms", "250", "--canary-weight", "0.5",
        "--canary-p99-factor", "3", "--canary-for-s", "0.5",
        "--canary-poll-s", "0.2", "--poll-interval", "0.2",
        "--fleet-timeout-s", "20",
    ])
    try:
        counts = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=60, clients=4
        )
        check(
            counts["resolved"] == counts["submitted"]
            and counts["hung"] == 0,
            f"canary leg: requests lost during rollout: {counts}",
        )
        _wait_until(
            lambda: _metric_value(
                fleet.base_url, "fleet_canary_rollback_total"
            ) == 1.0,
            60, "canary leg: rollback never fired",
        )
        # More traffic — the gate must NOT fire again (exactly once).
        post = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=20, clients=2
        )
        check(
            post["resolved"] == post["submitted"] and post["hung"] == 0,
            f"canary leg: post-rollback traffic lost: {post}",
        )
        check(
            _metric_value(
                fleet.base_url, "fleet_canary_rollback_total"
            ) == 1.0,
            "canary leg: canary_rollback fired more than once",
        )
        rollbacks = fleet.events("canary_rollback")
        check(
            len(rollbacks) == 1,
            f"canary leg: expected 1 canary_rollback event, saw "
            f"{len(rollbacks)}",
        )
        status = _fleet_status(fleet.base_url)
        by_id = {r["replica_id"]: r for r in status.get("replicas", [])}
        check(
            status.get("canary_outcome") == "rolled_back",
            f"canary leg: outcome {status.get('canary_outcome')!r}",
        )
        check(
            by_id.get("canary", {}).get("state") == "drained"
            and by_id.get("canary", {}).get("weight") == 0,
            f"canary leg: canary not drained: {by_id.get('canary')}",
        )
        baseline_ok = all(
            by_id.get(rid, {}).get("state") == "closed"
            and by_id.get(rid, {}).get("weight", 0) > 0
            for rid in ("replica-0", "replica-1")
        )
        check(
            baseline_ok,
            f"canary leg: fleet not back at baseline weights: {by_id}",
        )
    finally:
        fleet.stop()


def _paced_storm(
    base_url: str, payload: bytes, times: list[float], clients: int,
    mid_action=None, request_timeout: float = 30.0,
) -> dict:
    """Open-loop load: fire one request per entry of ``times`` (absolute
    seconds from leg start — the seeded arrival schedule), bounded by a
    worker pool so a lagging fleet backs pressure up into occupancy
    instead of unbounded client threads.  ``mid_action()`` runs once,
    as the halfway arrival is claimed.  Same outcome taxonomy as
    ``_fleet_storm``: every request must RESOLVE."""
    import threading
    import urllib.error
    import urllib.request

    lock = threading.Lock()
    counts = {"ok": 0, "shed": 0, "timeout": 0, "server_error": 0,
              "router_unreachable": 0, "hung": 0, "other": 0}
    idx = [0]
    acted = [False]
    t0 = time.monotonic()

    def one_request():
        req = urllib.request.Request(
            f"{base_url}/detect", data=payload, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=request_timeout) as r:
                json.loads(r.read().decode())
                return "ok"
        except urllib.error.HTTPError as e:
            body = {}
            try:
                body = json.loads(e.read().decode())
            except Exception:
                pass
            if e.code == 503:
                return "shed" if body.get("reason") else "other"
            if e.code == 504:
                return "timeout"
            return "server_error"
        except TimeoutError:
            return "hung"
        except Exception as e:
            if "timed out" in str(e).lower():
                return "hung"
            return "router_unreachable"

    def client():
        try:
            while True:
                with lock:
                    if idx[0] >= len(times):
                        return
                    i = idx[0]
                    idx[0] += 1
                    fire = i == len(times) // 2 and not acted[0]
                    if fire:
                        acted[0] = True
                delay = times[i] - (time.monotonic() - t0)
                if delay > 0:
                    time.sleep(delay)
                if fire and mid_action is not None:
                    mid_action()
                outcome = one_request()
                with lock:
                    counts[outcome] += 1
        except Exception as e:  # crash channel: a dead client = hung reqs
            with lock:
                counts["other"] += 1
            print(f"chaos FAIL: storm client crashed: {e!r}", flush=True)

    # watchdog: harness-local load generators; every request is bounded
    # by its own urlopen timeout, the driver joins with a budget below.
    threads = [
        threading.Thread(target=client, daemon=True)
        for _ in range(clients)
    ]
    for t in threads:
        t.start()
    budget = (times[-1] if times else 0.0) + request_timeout * 4 + 60
    for t in threads:
        t.join(timeout=budget)
    counts["submitted"] = idx[0]
    counts["resolved"] = sum(
        counts[k] for k in ("ok", "shed", "timeout", "server_error")
    )
    return counts


def _closed_replicas(base_url: str) -> list[str]:
    return [
        r["replica_id"]
        for r in _fleet_status(base_url).get("replicas", [])
        if r["state"] == "closed"
    ]


def _serve_autoscale_leg() -> None:
    """The seeded diurnal/spike day against a 1..3 autoscaling stub
    fleet, with a mid-spike SIGKILL of the seed replica: the fleet must
    scale 1→N under the spike, lose nothing (every request resolves,
    zero hangs), repair the preempted replica, and come back down to
    one replica once the day goes quiet."""
    sys.path.insert(0, _REPO)
    try:
        from batchai_retinanet_horovod_coco_tpu.utils.arrivals import (
            diurnal_spike_schedule,
        )
    finally:
        sys.path.pop(0)

    fleet = _FleetUnderTest("serve_autoscale", [
        "--spawn", "1", "--stub-engine", "--stub-delay-ms", "60",
        "--poll-interval", "0.2", "--respawn-delay-s", "0.3",
        "--fleet-timeout-s", "20",
        "--autoscale", "--min-replicas", "1", "--max-replicas", "3",
        "--target-occupancy", "0.15:0.5", "--autoscale-for-s", "0.4",
        "--autoscale-up-cooldown-s", "1",
        "--autoscale-down-cooldown-s", "2",
        "--autoscale-interval-s", "0.2",
    ])
    try:
        check(
            len(fleet.events("autoscaler_armed")) == 1,
            "autoscale leg: autoscaler_armed never emitted",
        )
        spawned = fleet.events("fleet_replica_spawned")
        check(
            len(spawned) == 1, f"expected 1 seed spawn, saw {len(spawned)}"
        )
        killed: list[str] = []

        def preempt():
            """SIGKILL a replica that is ROUTABLE at kill time — the
            autoscaler may have already scaled the seed replica away
            during the pre-spike lull, so the victim is chosen live."""
            pids: dict[str, int] = {}
            for e in (fleet.events("fleet_replica_spawned")
                      + fleet.events("fleet_replica_respawned")):
                pids[e["replica_id"]] = e["pid"]  # latest pid wins
            for rid in _closed_replicas(fleet.base_url):
                if rid not in pids:
                    continue
                try:
                    os.kill(pids[rid], signal.SIGKILL)
                except ProcessLookupError:
                    continue
                killed.append(rid)
                return
        # One compressed "day": sinusoidal base with a 4x burst window —
        # the ~55 rps spike saturates one 60ms-stub replica (≈16 rps)
        # and MUST force a scale-up; the window is wide enough (~6 s of
        # arrivals) that the breach re-sustains after the mid-spike
        # SIGKILL resets it.
        times = diurnal_spike_schedule(
            450, base_rate=12.0, seed=5, period_s=20.0, amplitude=0.5,
            spikes=((0.55, 0.5, 4.0),),
        )
        counts = _paced_storm(
            fleet.base_url, _fleet_payload(), times, clients=10,
            mid_action=preempt,
        )
        check(bool(killed), "autoscale leg: found no routable replica "
                            "to SIGKILL mid-spike")
        check(counts["hung"] == 0, f"autoscale leg: hung clients: {counts}")
        check(
            counts["router_unreachable"] == 0 and counts["other"] == 0,
            f"autoscale leg: dropped/garbled requests: {counts}",
        )
        check(
            counts["resolved"] == counts["submitted"],
            f"autoscale leg: silent drops: {counts}",
        )
        check(counts["ok"] > 0, f"autoscale leg: nothing completed: {counts}")
        # The spike forced at least one scale-up...
        ups = [
            e for e in fleet.events("autoscale_decision")
            if e.get("decision") == "scale_up"
        ]
        check(bool(ups), "autoscale leg: no scale_up decision under spike")
        check(
            _metric_value(fleet.base_url, "fleet_scale_up_total") >= 1,
            "autoscale leg: fleet_scale_up_total never incremented",
        )
        check(
            len(fleet.events("fleet_replica_joined")) >= 1,
            "autoscale leg: no replica joined the router",
        )
        # ... the SIGKILLed seed replica was repaired (respawn budget) ...
        _wait_until(
            lambda: len(fleet.events("fleet_replica_respawned")) >= 1,
            60, "autoscale leg: preempted replica never respawned",
        )
        # ... and the quiet tail of the day scales back down to min.
        _wait_until(
            lambda: len(_closed_replicas(fleet.base_url)) == 1
            and _metric_value(
                fleet.base_url, "fleet_scale_down_total"
            ) >= 1,
            90, "autoscale leg: fleet never scaled back down to 1",
        )
        # Post-scale-down traffic still serves (zero-drop drain).
        post = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=8, clients=2
        )
        check(
            post["ok"] == post["submitted"],
            f"autoscale leg: post-scale-down traffic unhealthy: {post}",
        )
        # The decision surface is on the scrape.
        _code, metrics_body = _http_get(f"{fleet.base_url}/metrics")
        for fam in ("fleet_replicas_desired", "fleet_replicas_active",
                    "fleet_occupancy", "fleet_scale_up_total",
                    "fleet_scale_down_total"):
            check(
                fam.encode() in metrics_body,
                f"autoscale leg: {fam} missing from fleet /metrics",
            )
    finally:
        fleet.stop()


def _serve_scale_to_zero_leg() -> None:
    """A cold tier (min_replicas=0): strict idleness takes the fleet to
    ZERO replicas; the first request sheds at the edge and that demand
    signal respawns capacity — the client's retry loop lands."""
    fleet = _FleetUnderTest("serve_scale_zero", [
        "--spawn", "1", "--stub-engine", "--stub-delay-ms", "5",
        "--poll-interval", "0.2", "--fleet-timeout-s", "20",
        "--autoscale", "--min-replicas", "0", "--max-replicas", "2",
        "--target-occupancy", "0.15:0.6", "--autoscale-for-s", "0.4",
        "--autoscale-up-cooldown-s", "0.5",
        "--autoscale-down-cooldown-s", "1",
        "--autoscale-interval-s", "0.2",
    ])
    try:
        warm = _fleet_storm(
            fleet.base_url, _fleet_payload(), total=4, clients=2
        )
        check(
            warm["ok"] == warm["submitted"],
            f"scale-to-zero leg: warm traffic unhealthy: {warm}",
        )
        # Idle → the last replica drains away: an EMPTY fleet.
        _wait_until(
            lambda: not _fleet_status(fleet.base_url).get("replicas"),
            90, "scale-to-zero leg: idle fleet never reached 0 replicas",
        )
        downs = [
            e for e in fleet.events("autoscale_decision")
            if e.get("decision") == "scale_down"
        ]
        check(
            bool(downs) and downs[-1].get("reason") == "idle",
            f"scale-to-zero leg: expected an idle scale_down: {downs}",
        )
        # First request hits the empty fleet: a REASONED shed, then the
        # demand signal scales from zero and a bounded retry loop lands.
        payload = _fleet_payload()
        deadline = time.monotonic() + 90
        outcomes = []
        recovered = False
        while time.monotonic() < deadline:
            code, body = 0, b""
            try:
                import urllib.request
                req = urllib.request.Request(
                    f"{fleet.base_url}/detect", data=payload,
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=20) as r:
                    code, body = r.status, r.read()
            except Exception as e:
                import urllib.error
                if isinstance(e, urllib.error.HTTPError):
                    code, body = e.code, e.read()
            outcomes.append(code)
            if code == 200:
                recovered = True
                break
            time.sleep(0.5)
        check(
            recovered,
            f"scale-to-zero leg: fleet never recovered from zero "
            f"(outcomes {outcomes[-10:]})",
        )
        wakes = [
            e for e in fleet.events("autoscale_decision")
            if e.get("reason") == "demand_scale_from_zero"
        ]
        check(
            len(wakes) >= 1,
            "scale-to-zero leg: no demand_scale_from_zero decision",
        )
    finally:
        fleet.stop()


def run_serve_legs() -> None:
    """The fleet serve schedule (``make fleet-smoke`` / ``--serve``).
    Since ISSUE 14 the replicas run CONTINUOUS in-flight batching (the
    serve default; the kill leg pins the advertised slot-pool fields),
    so the chaos contracts are proven against the slot-pool path."""
    _serve_kill_leg()
    _serve_canary_leg()


def run_autoscale_legs() -> None:
    """The autoscaling schedule (``make scale-smoke`` / ``--autoscale``,
    ISSUE 19): the diurnal/spike 1→N→1 leg with a mid-spike SIGKILL,
    then the scale-to-zero cold-tier leg."""
    _serve_autoscale_leg()
    if not _failures:
        _serve_scale_to_zero_leg()


# ---------------------------------------------------------------------------
# CKPTBENCH
# ---------------------------------------------------------------------------


def _wall_of_steps(metrics_path: str, first: int, last: int) -> float | None:
    """Wall seconds from step ``first`` to ``last`` via the records'
    sink-relative wall_s stamps (one clock per run)."""
    recs = {
        int(r["step"]): r.get("wall_s")
        for r in _records(metrics_path)
        if "step" in r and "event" not in r
    }
    if recs.get(first) is None or recs.get(last) is None:
        return None
    return float(recs[last]) - float(recs[first])


def _last_run_segment(metrics_path: str) -> list[dict]:
    runs: list[list[dict]] = []
    for rec in _records(metrics_path):
        if rec.get("event") == "run_header":
            runs.append([])
        if runs:
            runs[-1].append(rec)
    return runs[-1] if runs else []


def run_bench(check_mode: bool, out_path: str) -> int:
    platform = os.environ.get("CKPTBENCH_PLATFORM", "cpu")
    if platform != "cpu":
        # The outage contract (bench.py's): probe in a subprocess (init
        # can HANG), classify unreachable as exit 75 with the committed
        # last-known-good attached.
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('probe_ok', jax.devices()[0].device_kind)"],
            capture_output=True, text=True,
            timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120")),
        )
        if probe.returncode != 0 or "probe_ok" not in probe.stdout:
            committed = None
            if os.path.exists(out_path):
                with open(out_path) as f:
                    committed = json.load(f)
            print(json.dumps({
                "event": "ckptbench_outage",
                "error": (probe.stderr or probe.stdout)[-800:],
                "last_known_good": committed,
            }), flush=True)
            return EXIT_UNREACHABLE
    steps = int(os.environ.get("CKPTBENCH_STEPS", "10"))

    # Leg A: save overhead — same stream, with and without checkpointing.
    plain = _fresh_workdir("bench_plain")
    cmd = _base_cmd(plain, steps)
    cmd.remove("--snapshot-path")
    cmd.remove(os.path.join(plain, "ckpt"))
    r = _run(cmd)
    check(r.returncode == 0, f"bench plain run failed rc={r.returncode}")
    wall_plain = _wall_of_steps(
        os.path.join(plain, "logs", "metrics.jsonl"), 1, steps
    )

    ck = _fresh_workdir("bench_ckpt")
    r = _run(_base_cmd(ck, steps) + ["--checkpoint-every", "1"])
    check(r.returncode == 0, f"bench ckpt run failed rc={r.returncode}")
    ck_metrics = os.path.join(ck, "logs", "metrics.jsonl")
    wall_ckpt = _wall_of_steps(ck_metrics, 1, steps)
    saves = _events(ck_metrics, "ckpt_saved")
    write_s = [float(e["write_s"]) for e in saves if "write_s" in e]
    ckpt_bytes = saves[-1].get("bytes") if saves else None

    # Leg B: resume time-to-first-step (restore + compile + first step),
    # measured from the resumed run's own clock (run_header at 0).
    r = _run(_base_cmd(ck, steps + 2, ["--resume-elastic"]))
    check(r.returncode == 0, f"bench resume run failed rc={r.returncode}")
    seg = _last_run_segment(ck_metrics)
    first_step = next(
        (rec for rec in seg if "step" in rec and "event" not in rec), None
    )
    restored = [rec for rec in seg if rec.get("event") == "ckpt_restored"]
    time_to_first_step = (
        float(first_step["wall_s"]) if first_step else None
    )
    restore_s = float(restored[0]["restore_s"]) if restored else None

    overhead_pct = None
    if wall_plain and wall_ckpt:
        overhead_pct = round((wall_ckpt - wall_plain) / wall_plain * 100, 2)
    record = {
        "bench": "ckptbench",
        "schema_version": 1,
        "device_kind": platform,
        "steps": steps,
        "save": {
            "saves": len(saves),
            "mean_write_s": round(sum(write_s) / len(write_s), 4)
            if write_s else None,
            "bytes": ckpt_bytes,
            "wall_plain_s": round(wall_plain, 3) if wall_plain else None,
            "wall_ckpt_s": round(wall_ckpt, 3) if wall_ckpt else None,
            "overhead_pct": overhead_pct,
        },
        "resume": {
            "time_to_first_step_s": round(time_to_first_step, 3)
            if time_to_first_step is not None else None,
            "restore_s": restore_s,
        },
        "note": (
            "CPU capture at the WORST-CASE cadence (checkpoint_every=1): "
            "on a small shared box the writer competes with the step for "
            "the same cores and the per-save write exceeds the tiny step "
            "time, so the one-behind contract serializes on the disk "
            "write and overhead_pct is an upper bound, not the "
            "production expectation (chip runs save every O(1000) steps; "
            "steady-state overhead ~= one device->host snapshot per "
            "save, amortized).  Wall numbers are host-noise-dominated; "
            "the check band is wide (CKPTBENCH_BAND) and the "
            "device-class guard refuses cross-class comparisons"
        ),
    }
    check(bool(write_s), "bench: no ckpt_saved events recorded")
    check(
        time_to_first_step is not None,
        "bench: resume leg produced no first-step record",
    )

    if not check_mode:
        from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
            atomic_write_text,
        )

        atomic_write_text(
            out_path, json.dumps(record, indent=2, sort_keys=True) + "\n"
        )
        print(f"# ckptbench record written to {out_path}")
        print(json.dumps(record), flush=True)
    else:
        if not os.path.exists(out_path):
            check(False, f"--check: no committed {out_path}")
        else:
            with open(out_path) as f:
                committed = json.load(f)
            if committed.get("device_kind") != record["device_kind"]:
                print(
                    f"# ckptbench-check: committed artifact is for "
                    f"{committed.get('device_kind')!r}, this run is "
                    f"{record['device_kind']!r} — PASSING with a loud "
                    "note; re-capture on this device class",
                    flush=True,
                )
            else:
                band = float(os.environ.get("CKPTBENCH_BAND", "0.75"))
                for leg, key in (("save", "mean_write_s"),
                                 ("resume", "time_to_first_step_s")):
                    was = (committed.get(leg) or {}).get(key)
                    now = (record.get(leg) or {}).get(key)
                    if was is None or now is None:
                        continue
                    check(
                        now <= was * (1 + band),
                        f"--check: {leg}.{key} regressed {was} -> {now} "
                        f"(> +{band:.0%} band)",
                    )
        print(json.dumps({"ckptbench_check": record}), flush=True)
    if not _failures:
        shutil.rmtree(plain, ignore_errors=True)
        shutil.rmtree(ck, ignore_errors=True)
    return 1 if _failures else 0


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="bounded CI leg: one mid-save SIGKILL + one NaN "
                        "auto-resume (make chaos-smoke)")
    p.add_argument("--serve", action="store_true",
                   help="serve fleet legs only (make fleet-smoke): "
                        "SIGKILL one stub replica mid-load behind the "
                        "fleet router (zero hangs/silent drops, router "
                        "200s throughout, breaker reopens after respawn) "
                        "+ the slow-canary rollback leg (exactly one "
                        "canary_rollback, fleet back to baseline)")
    p.add_argument("--autoscale", action="store_true",
                   help="autoscale legs only (make scale-smoke): the "
                        "seeded diurnal/spike day against a 1..3 "
                        "autoscaling stub fleet with a mid-spike "
                        "SIGKILL (1→N on the spike, preemption "
                        "repaired, back to 1 when quiet, zero "
                        "hangs/drops), then the scale-to-zero cold "
                        "tier (idle fleet reaches 0 replicas and "
                        "recovers on the first request)")
    p.add_argument("--comm", action="store_true",
                   help="comm leg only (make chaos-comm): SIGKILL a "
                        "--comm-compress int8 run mid-save; the resume "
                        "must restore the EF residual state (or cleanly "
                        "zero it with one structured ef_reset event) and "
                        "rejoin the uninterrupted compressed baseline")
    p.add_argument("--bench", action="store_true",
                   help="CKPTBENCH: save overhead + time-to-first-step")
    p.add_argument("--check", action="store_true",
                   help="with --bench: enforce the committed CKPTBENCH.json")
    p.add_argument("--out", default=os.path.join(_REPO, "CKPTBENCH.json"))
    p.add_argument("--steps", type=int, default=10,
                   help="target step count for kill legs")
    p.add_argument("--kills-per-phase", type=int, default=4,
                   help="full mode: occurrences per save phase "
                        "(5 phases x 4 = the >= 20-kill schedule)")
    args = p.parse_args(argv)

    if args.bench:
        rc = run_bench(args.check, args.out)
        print(json.dumps({
            "chaos": "ok" if not _failures else "FAIL",
            "failures": _failures,
        }), flush=True)
        return rc

    if args.serve:
        run_serve_legs()
        print(json.dumps({
            "chaos": "ok" if not _failures else "FAIL",
            "failures": _failures,
        }), flush=True)
        return 1 if _failures else 0

    if args.autoscale:
        run_autoscale_legs()
        print(json.dumps({
            "chaos": "ok" if not _failures else "FAIL",
            "failures": _failures,
        }), flush=True)
        return 1 if _failures else 0

    if args.comm:
        _comm_leg()
        if not _failures:
            _comm_leg(hier=True)  # per-hop EF durability (ISSUE 16)
        print(json.dumps({
            "chaos": "ok" if not _failures else "FAIL",
            "failures": _failures,
        }), flush=True)
        return 1 if _failures else 0

    steps = args.steps
    baseline_dir, baseline = _baseline(steps)
    if _failures:
        return 1

    if args.smoke:
        _kill_leg("smoke_midsave", "tmp_write@1", baseline, steps)
        _nan_leg()
    else:
        kills = 0
        for n in range(1, args.kills_per_phase + 1):
            for phase in PHASES:
                _kill_leg(f"{phase}@{n}", f"{phase}@{n}", baseline, steps)
                kills += 1
                if _failures:
                    break
            if _failures:
                break
        # Mid-step (between saves) external kills.
        if not _failures:
            for at in (3, 5):
                _kill_leg(
                    f"midstep_{at}", None, baseline, steps, kill_at_step=at
                )
                kills += 2 - 1
        if not _failures:
            _torn_dir_legs(baseline, steps)
            _nan_leg()
        if not _failures:
            _comm_leg()  # compression+EF durability (ISSUE 13)
        if not _failures:
            _comm_leg(hier=True)  # per-hop EF durability (ISSUE 16)
        if not _failures:
            run_serve_legs()  # the serve-side half of the full schedule
        if not _failures:
            run_autoscale_legs()  # elasticity contracts (ISSUE 19)
        print(f"# chaos: {kills} scheduled kills executed", flush=True)

    if not _failures:
        shutil.rmtree(baseline_dir, ignore_errors=True)
    print(json.dumps({
        "chaos": "ok" if not _failures else "FAIL",
        "failures": _failures,
    }), flush=True)
    return 1 if _failures else 0


if __name__ == "__main__":
    sys.exit(main())
