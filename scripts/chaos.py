#!/usr/bin/env python
"""chaos: fault-injection harness for the durability subsystem (ISSUE 11).

Drives a REAL ``train.py`` CPU training subprocess through a kill
schedule and asserts the crash-safety contract the checkpoint protocol
promises (utils/checkpoint.py):

- **Save-phase kills** — ``RETINANET_CHAOS_KILL=<phase>@<n>`` makes the
  subprocess SIGKILL itself at the n-th crossing of a named protocol
  phase (snapshot, tmp_write, manifest_commit, rename, finalize).  After
  EVERY kill: no published ``ckpt-*`` dir may be torn (manifest present
  and consistent), and a plain resume run must complete and produce
  losses BIT-IDENTICAL to an uninterrupted baseline at every step —
  ``--resume-elastic`` re-derives the stream position, so step k sees
  the same batch in both runs.
- **Mid-step kills** — the driver SIGKILLs the subprocess from outside
  once the log shows a target step, covering the window between saves.
- **Torn-dir triage** — manufactured damage (deleted manifest,
  truncated leaf, stray .tmp dir) must be skipped to the previous
  complete checkpoint, and the resume still completes.
- **NaN auto-resume** — ``--inject-nan-step`` poisons one mid-run batch;
  with ``--auto-resume`` the run must complete to the target step with
  EXACTLY ONE structured ``auto_resume`` event, a NUMERICS_DUMP.json,
  and the poison batch's image ids excluded from the healed stream.
- **CKPTBENCH** (``--bench``) — measures the two durability numbers the
  ROADMAP asks for: save overhead (wall time of N checkpointed steps vs
  the same N without) and time-to-first-step on resume; writes
  CKPTBENCH.json.  ``--check`` re-measures against the committed
  artifact with bench-check's device-class guard, and a non-CPU target
  (CKPTBENCH_PLATFORM) gets the probe + exit-75 outage contract.

Modes: ``--smoke`` (one mid-save kill + one NaN leg; the check-static
CI leg), default full schedule (>= 20 kills), ``--bench``/``--check``.
Exit 0 = contract held; 1 = violation (each printed as one
``chaos FAIL:`` line); 75 = accelerator unreachable (bench only).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time  # lint-exempt scripts/: subprocess wall timing only

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

EXIT_UNREACHABLE = 75
_failures: list[str] = []

# Every save-protocol phase, in write order (utils/checkpoint.py).
PHASES = ("snapshot", "tmp_write", "manifest_commit", "rename", "finalize")


def check(ok: bool, what: str) -> None:
    if not ok:
        _failures.append(what)
        print(f"chaos FAIL: {what}", flush=True)


def _base_cmd(work: str, steps: int, extra: list[str] | None = None) -> list[str]:
    return [
        sys.executable, os.path.join(_REPO, "train.py"), "synthetic",
        "--platform", "cpu", "--backbone", "resnet_test", "--f32",
        "--image-min-side", "64", "--image-max-side", "64",
        "--synthetic-size", "64", "--synthetic-images", "16",
        "--synthetic-classes", "3",
        "--synthetic-root", os.path.join(work, "data"),
        "--batch-size", "4", "--num-devices", "1", "--workers", "2",
        "--max-gt", "8", "--seed", "0", "--log-every", "1",
        "--steps", str(steps),
        "--snapshot-path", os.path.join(work, "ckpt"),
        "--checkpoint-every", "2",
        "--log-dir", os.path.join(work, "logs"),
    ] + (extra or [])


def _run(cmd: list[str], env_extra: dict | None = None,
         timeout: float = 900.0) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=timeout,
    )


def _run_until_step_then_kill(
    cmd: list[str], work: str, kill_at_step: int, timeout: float = 900.0
) -> int:
    """Launch and SIGKILL from OUTSIDE once metrics.jsonl shows the step
    — the mid-step half of the schedule (between-save windows)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL
    )
    metrics = os.path.join(work, "logs", "metrics.jsonl")
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                return proc.returncode  # died early — caller flags it
            for rec in _records(metrics):
                if rec.get("step", -1) >= kill_at_step:
                    proc.kill()
                    proc.wait(timeout=30)
                    return -signal.SIGKILL
            time.sleep(0.2)
        proc.kill()
        proc.wait(timeout=30)
        return -999  # timed out waiting for the step
    finally:
        if proc.poll() is None:
            proc.kill()


def _records(metrics_path: str) -> list[dict]:
    out = []
    try:
        with open(metrics_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # a killed run may leave one torn tail line
    except OSError:
        pass
    return out


def _losses_by_step(metrics_path: str) -> dict[int, float]:
    """step -> train/loss over ALL runs appended to the file; a later run
    overwrites (resume re-logs nothing, so collisions only happen when a
    killed step re-runs after resume — and then bit-equality is exactly
    the claim under test)."""
    out: dict[int, float] = {}
    for rec in _records(metrics_path):
        if "step" in rec and "train/loss" in rec and "event" not in rec:
            out[int(rec["step"])] = rec["train/loss"]
    return out


def _events(metrics_path: str, kind: str) -> list[dict]:
    return [r for r in _records(metrics_path) if r.get("event") == kind]


def _validate_ckpt_dir(work: str, context: str) -> None:
    """No PUBLISHED checkpoint may be torn, ever — the core protocol
    claim.  (Dirs without a manifest cannot exist under the protocol;
    .tmp-* leftovers are expected and invisible to restore.)"""
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        _load_manifest,
    )

    d = os.path.join(work, "ckpt")
    if not os.path.isdir(d):
        return
    for name in sorted(os.listdir(d)):
        if not name.startswith("ckpt-"):
            continue
        manifest = _load_manifest(os.path.join(d, name))
        check(
            manifest is not None,
            f"{context}: published {name} is torn (protocol violation)",
        )


def _fresh_workdir(tag: str) -> str:
    work = tempfile.mkdtemp(prefix=f"chaos_{tag}_")
    return work


def _baseline(steps: int) -> tuple[str, dict[int, float]]:
    work = _fresh_workdir("baseline")
    r = _run(_base_cmd(work, steps))
    check(r.returncode == 0, f"baseline run failed rc={r.returncode}: "
                             f"{r.stderr[-500:]}")
    losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
    check(
        set(losses) == set(range(1, steps + 1)),
        f"baseline logged steps {sorted(losses)} != 1..{steps}",
    )
    return work, losses


def _kill_leg(
    tag: str, kill_env: str | None, baseline: dict[int, float], steps: int,
    kill_at_step: int | None = None,
) -> None:
    """One scheduled kill: run with the kill armed, assert it fired and
    the checkpoint dir survived; resume; assert completion + bit-identical
    losses vs the baseline."""
    work = _fresh_workdir(tag)
    cmd = _base_cmd(work, steps, ["--resume-elastic"])
    if kill_env is not None:
        r = _run(cmd, env_extra={"RETINANET_CHAOS_KILL": kill_env})
        check(
            r.returncode != 0,
            f"{tag}: kill {kill_env} never fired (rc 0 — schedule vacuous)",
        )
    else:
        rc = _run_until_step_then_kill(cmd, work, kill_at_step)
        check(rc == -signal.SIGKILL, f"{tag}: external kill failed rc={rc}")
    _validate_ckpt_dir(work, tag)
    resume = _run(cmd)
    check(
        resume.returncode == 0,
        f"{tag}: resume failed rc={resume.returncode}: "
        f"{resume.stderr[-500:]}",
    )
    _validate_ckpt_dir(work, f"{tag}/post-resume")
    losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
    check(
        losses.get(steps) is not None,
        f"{tag}: resumed run never reached step {steps}",
    )
    mismatches = {
        s: (losses[s], baseline[s])
        for s in losses
        if s in baseline and losses[s] != baseline[s]
    }
    check(
        not mismatches,
        f"{tag}: losses not bit-identical to baseline: {mismatches}",
    )
    if not _failures:
        shutil.rmtree(work, ignore_errors=True)


def _torn_dir_legs(baseline: dict[int, float], steps: int) -> None:
    """Manufactured damage: restore must skip to the previous complete
    checkpoint and the run must still finish."""
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        latest_step,
    )

    src = _fresh_workdir("torn_src")
    r = _run(_base_cmd(src, steps, ["--resume-elastic"]))
    check(r.returncode == 0, f"torn-src run failed rc={r.returncode}")
    ckpt = os.path.join(src, "ckpt")
    newest = latest_step(ckpt)
    check(newest == steps, f"torn-src latest {newest} != {steps}")

    def damage_and_resume(tag: str, damage) -> None:
        work = _fresh_workdir(tag)
        shutil.rmtree(work)
        shutil.copytree(src, work)
        damage(os.path.join(work, "ckpt"))
        got = latest_step(os.path.join(work, "ckpt"))
        check(
            got is not None and got < steps,
            f"{tag}: damaged newest not skipped (latest={got})",
        )
        resume = _run(_base_cmd(work, steps + 2, ["--resume-elastic"]))
        check(
            resume.returncode == 0,
            f"{tag}: resume after damage failed rc={resume.returncode}: "
            f"{resume.stderr[-500:]}",
        )
        losses = _losses_by_step(os.path.join(work, "logs", "metrics.jsonl"))
        mism = {
            s: (losses[s], baseline[s])
            for s in losses
            if s in baseline and losses[s] != baseline[s]
        }
        check(not mism, f"{tag}: post-damage losses diverged: {mism}")
        if not _failures:
            shutil.rmtree(work, ignore_errors=True)

    damage_and_resume(
        "torn_manifest",
        lambda d: os.unlink(os.path.join(d, f"ckpt-{newest}", "manifest.json")),
    )

    def truncate(d):
        leaf = os.path.join(d, f"ckpt-{newest}", "leaf_00001.npy")
        with open(leaf, "r+b") as f:
            f.truncate(max(1, os.path.getsize(leaf) // 2))

    damage_and_resume("torn_leaf", truncate)
    damage_and_resume(
        "stray_tmp",
        lambda d: (
            os.makedirs(os.path.join(d, ".tmp-99-1"), exist_ok=True),
            os.unlink(os.path.join(d, f"ckpt-{newest}", "manifest.json")),
        ),
    )
    if not _failures:
        shutil.rmtree(src, ignore_errors=True)


def _nan_leg(steps: int = 12, inject_at: int = 7) -> None:
    """Injected NaN + --auto-resume: completes to target with exactly one
    auto_resume event, a provenance dump, and the poison ids excluded."""
    work = _fresh_workdir("nan")
    cmd = _base_cmd(
        work, steps,
        ["--auto-resume", "--inject-nan-step", str(inject_at)],
    )
    r = _run(cmd)
    check(
        r.returncode == 0,
        f"nan: auto-resume run failed rc={r.returncode}: {r.stderr[-800:]}",
    )
    metrics = os.path.join(work, "logs", "metrics.jsonl")
    resumes = _events(metrics, "auto_resume")
    check(
        len(resumes) == 1,
        f"nan: expected exactly one auto_resume event, got {len(resumes)}",
    )
    losses = _losses_by_step(metrics)
    check(
        losses.get(steps) is not None,
        f"nan: healed run never reached step {steps}",
    )
    dump = os.path.join(work, "logs", "NUMERICS_DUMP.json")
    check(os.path.exists(dump), "nan: no NUMERICS_DUMP.json landed")
    if resumes:
        ev = resumes[0]
        check(
            bool(ev.get("exclude_ids")),
            "nan: auto_resume event carries no excluded poison ids",
        )
        check(
            ev.get("restored_step", -1) < inject_at,
            f"nan: restored step {ev.get('restored_step')} not before the "
            f"poison step {inject_at}",
        )
    if not _failures:
        shutil.rmtree(work, ignore_errors=True)


# ---------------------------------------------------------------------------
# CKPTBENCH
# ---------------------------------------------------------------------------


def _wall_of_steps(metrics_path: str, first: int, last: int) -> float | None:
    """Wall seconds from step ``first`` to ``last`` via the records'
    sink-relative wall_s stamps (one clock per run)."""
    recs = {
        int(r["step"]): r.get("wall_s")
        for r in _records(metrics_path)
        if "step" in r and "event" not in r
    }
    if recs.get(first) is None or recs.get(last) is None:
        return None
    return float(recs[last]) - float(recs[first])


def _last_run_segment(metrics_path: str) -> list[dict]:
    runs: list[list[dict]] = []
    for rec in _records(metrics_path):
        if rec.get("event") == "run_header":
            runs.append([])
        if runs:
            runs[-1].append(rec)
    return runs[-1] if runs else []


def run_bench(check_mode: bool, out_path: str) -> int:
    platform = os.environ.get("CKPTBENCH_PLATFORM", "cpu")
    if platform != "cpu":
        # The outage contract (bench.py's): probe in a subprocess (init
        # can HANG), classify unreachable as exit 75 with the committed
        # last-known-good attached.
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('probe_ok', jax.devices()[0].device_kind)"],
            capture_output=True, text=True,
            timeout=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120")),
        )
        if probe.returncode != 0 or "probe_ok" not in probe.stdout:
            committed = None
            if os.path.exists(out_path):
                with open(out_path) as f:
                    committed = json.load(f)
            print(json.dumps({
                "event": "ckptbench_outage",
                "error": (probe.stderr or probe.stdout)[-800:],
                "last_known_good": committed,
            }), flush=True)
            return EXIT_UNREACHABLE
    steps = int(os.environ.get("CKPTBENCH_STEPS", "10"))

    # Leg A: save overhead — same stream, with and without checkpointing.
    plain = _fresh_workdir("bench_plain")
    cmd = _base_cmd(plain, steps)
    cmd.remove("--snapshot-path")
    cmd.remove(os.path.join(plain, "ckpt"))
    r = _run(cmd)
    check(r.returncode == 0, f"bench plain run failed rc={r.returncode}")
    wall_plain = _wall_of_steps(
        os.path.join(plain, "logs", "metrics.jsonl"), 1, steps
    )

    ck = _fresh_workdir("bench_ckpt")
    r = _run(_base_cmd(ck, steps) + ["--checkpoint-every", "1"])
    check(r.returncode == 0, f"bench ckpt run failed rc={r.returncode}")
    ck_metrics = os.path.join(ck, "logs", "metrics.jsonl")
    wall_ckpt = _wall_of_steps(ck_metrics, 1, steps)
    saves = _events(ck_metrics, "ckpt_saved")
    write_s = [float(e["write_s"]) for e in saves if "write_s" in e]
    ckpt_bytes = saves[-1].get("bytes") if saves else None

    # Leg B: resume time-to-first-step (restore + compile + first step),
    # measured from the resumed run's own clock (run_header at 0).
    r = _run(_base_cmd(ck, steps + 2, ["--resume-elastic"]))
    check(r.returncode == 0, f"bench resume run failed rc={r.returncode}")
    seg = _last_run_segment(ck_metrics)
    first_step = next(
        (rec for rec in seg if "step" in rec and "event" not in rec), None
    )
    restored = [rec for rec in seg if rec.get("event") == "ckpt_restored"]
    time_to_first_step = (
        float(first_step["wall_s"]) if first_step else None
    )
    restore_s = float(restored[0]["restore_s"]) if restored else None

    overhead_pct = None
    if wall_plain and wall_ckpt:
        overhead_pct = round((wall_ckpt - wall_plain) / wall_plain * 100, 2)
    record = {
        "bench": "ckptbench",
        "schema_version": 1,
        "device_kind": platform,
        "steps": steps,
        "save": {
            "saves": len(saves),
            "mean_write_s": round(sum(write_s) / len(write_s), 4)
            if write_s else None,
            "bytes": ckpt_bytes,
            "wall_plain_s": round(wall_plain, 3) if wall_plain else None,
            "wall_ckpt_s": round(wall_ckpt, 3) if wall_ckpt else None,
            "overhead_pct": overhead_pct,
        },
        "resume": {
            "time_to_first_step_s": round(time_to_first_step, 3)
            if time_to_first_step is not None else None,
            "restore_s": restore_s,
        },
        "note": (
            "CPU capture at the WORST-CASE cadence (checkpoint_every=1): "
            "on a small shared box the writer competes with the step for "
            "the same cores and the per-save write exceeds the tiny step "
            "time, so the one-behind contract serializes on the disk "
            "write and overhead_pct is an upper bound, not the "
            "production expectation (chip runs save every O(1000) steps; "
            "steady-state overhead ~= one device->host snapshot per "
            "save, amortized).  Wall numbers are host-noise-dominated; "
            "the check band is wide (CKPTBENCH_BAND) and the "
            "device-class guard refuses cross-class comparisons"
        ),
    }
    check(bool(write_s), "bench: no ckpt_saved events recorded")
    check(
        time_to_first_step is not None,
        "bench: resume leg produced no first-step record",
    )

    if not check_mode:
        from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
            atomic_write_text,
        )

        atomic_write_text(
            out_path, json.dumps(record, indent=2, sort_keys=True) + "\n"
        )
        print(f"# ckptbench record written to {out_path}")
        print(json.dumps(record), flush=True)
    else:
        if not os.path.exists(out_path):
            check(False, f"--check: no committed {out_path}")
        else:
            with open(out_path) as f:
                committed = json.load(f)
            if committed.get("device_kind") != record["device_kind"]:
                print(
                    f"# ckptbench-check: committed artifact is for "
                    f"{committed.get('device_kind')!r}, this run is "
                    f"{record['device_kind']!r} — PASSING with a loud "
                    "note; re-capture on this device class",
                    flush=True,
                )
            else:
                band = float(os.environ.get("CKPTBENCH_BAND", "0.75"))
                for leg, key in (("save", "mean_write_s"),
                                 ("resume", "time_to_first_step_s")):
                    was = (committed.get(leg) or {}).get(key)
                    now = (record.get(leg) or {}).get(key)
                    if was is None or now is None:
                        continue
                    check(
                        now <= was * (1 + band),
                        f"--check: {leg}.{key} regressed {was} -> {now} "
                        f"(> +{band:.0%} band)",
                    )
        print(json.dumps({"ckptbench_check": record}), flush=True)
    if not _failures:
        shutil.rmtree(plain, ignore_errors=True)
        shutil.rmtree(ck, ignore_errors=True)
    return 1 if _failures else 0


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="bounded CI leg: one mid-save SIGKILL + one NaN "
                        "auto-resume (make chaos-smoke)")
    p.add_argument("--bench", action="store_true",
                   help="CKPTBENCH: save overhead + time-to-first-step")
    p.add_argument("--check", action="store_true",
                   help="with --bench: enforce the committed CKPTBENCH.json")
    p.add_argument("--out", default=os.path.join(_REPO, "CKPTBENCH.json"))
    p.add_argument("--steps", type=int, default=10,
                   help="target step count for kill legs")
    p.add_argument("--kills-per-phase", type=int, default=4,
                   help="full mode: occurrences per save phase "
                        "(5 phases x 4 = the >= 20-kill schedule)")
    args = p.parse_args(argv)

    if args.bench:
        rc = run_bench(args.check, args.out)
        print(json.dumps({
            "chaos": "ok" if not _failures else "FAIL",
            "failures": _failures,
        }), flush=True)
        return rc

    steps = args.steps
    baseline_dir, baseline = _baseline(steps)
    if _failures:
        return 1

    if args.smoke:
        _kill_leg("smoke_midsave", "tmp_write@1", baseline, steps)
        _nan_leg()
    else:
        kills = 0
        for n in range(1, args.kills_per_phase + 1):
            for phase in PHASES:
                _kill_leg(f"{phase}@{n}", f"{phase}@{n}", baseline, steps)
                kills += 1
                if _failures:
                    break
            if _failures:
                break
        # Mid-step (between saves) external kills.
        if not _failures:
            for at in (3, 5):
                _kill_leg(
                    f"midstep_{at}", None, baseline, steps, kill_at_step=at
                )
                kills += 2 - 1
        if not _failures:
            _torn_dir_legs(baseline, steps)
            _nan_leg()
        print(f"# chaos: {kills} scheduled kills executed", flush=True)

    if not _failures:
        shutil.rmtree(baseline_dir, ignore_errors=True)
    print(json.dumps({
        "chaos": "ok" if not _failures else "FAIL",
        "failures": _failures,
    }), flush=True)
    return 1 if _failures else 0


if __name__ == "__main__":
    sys.exit(main())
