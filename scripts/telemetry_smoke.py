#!/usr/bin/env python
"""telemetry-smoke: CPU serve smoke → scrape + schema-check (ISSUE 9).

The CI leg of the live telemetry plane (``make telemetry-smoke``, part of
``check-static``): bring up a real ``DetectionServer`` + HTTP frontend
over a stub engine (no device work — the serve machinery, queues,
watchdog heartbeats, and telemetry registry are all real), drive real
traffic INCLUDING sheds, then assert the acceptance contract:

- ``GET /metrics`` is valid Prometheus text exposition carrying the
  request-latency summary (quantile series), per-reason shed counters,
  and queue-depth gauges;
- ``GET /healthz`` returns 200 while live, flips to 503 NAMING the
  stalled component under an injected watchdog stall, and recovers;
- the registry-derived completed/shed/p99 numbers agree with the
  server's own ``/stats`` snapshot (the bench consistency check's
  logic, run here without a chip).

Exit 0 on success; any failed check prints one ``telemetry-smoke
FAIL:`` line and exits 1.  Stdout ends with one machine-readable JSON
summary line.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # direct `python scripts/telemetry_smoke.py` runs
    sys.path.insert(0, _REPO)

from batchai_retinanet_horovod_coco_tpu.obs import telemetry, watchdog  # noqa: E402
from batchai_retinanet_horovod_coco_tpu.serve import (  # noqa: E402
    DetectionServer,
    RequestRejected,
    ServeConfig,
    serve_http,
)
# The canonical no-device stub engine (serve/stub.py — ISSUE 12 unified
# the private copies): a small dispatch delay so an open-loop flood
# overwhelms the tiny queues and SHEDS (the smoke must see nonzero shed
# counters, not just latency).
from batchai_retinanet_horovod_coco_tpu.serve.stub import (  # noqa: E402
    StubDetectEngine,
)


def _get(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:  # 503 is data here, not an error
        return e.code, e.read()


def main() -> int:
    failures: list[str] = []

    def check(ok: bool, what: str) -> None:
        if not ok:
            failures.append(what)
            print(f"telemetry-smoke FAIL: {what}", flush=True)

    img = np.zeros((64, 64, 3), np.uint8)
    server = DetectionServer(
        StubDetectEngine(delay_s=0.02),
        ServeConfig(
            max_delay_ms=5.0, admission_queue=2, bucket_queue=2,
            preprocess_workers=1,
        ),
    )
    httpd = serve_http(server, port=0)
    hb_scrape = watchdog.register("telemetry-smoke-http")
    thread = threading.Thread(
        # Stdlib target: a crash surfaces as the scrape's urlopen failure.
        target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True, name="telemetry-smoke-http",
    )
    thread.start()
    host, port = httpd.server_address[:2]
    base = f"http://{host}:{port}"
    try:
        # Real traffic: closed-loop completions (one in flight — the
        # queues are sized 2 precisely so the burst below sheds), then an
        # open-loop burst against those tiny bounds.
        for _ in range(3):
            server.submit(img).result(timeout=30)
        futures = []
        shed = 0
        for _ in range(64):
            try:
                futures.append(server.submit(img))
            except RequestRejected:
                shed += 1
        for f in futures:
            try:
                f.result(timeout=60)
            except RequestRejected:
                pass
        check(shed > 0, "open-loop burst produced no sheds")

        # /metrics schema.
        code, body = _get(f"{base}/metrics")
        check(code == 200, f"/metrics returned {code}")
        types, samples = telemetry.parse_exposition(body.decode())
        check(
            types.get("serve_request_latency_ms") == "summary"
            and 'serve_request_latency_ms{quantile="0.99"}' in samples
            and samples.get("serve_request_latency_ms_count", 0) > 0,
            "request-latency summary missing from /metrics",
        )
        check(
            types.get("serve_shed_total") == "counter"
            and sum(
                v for k, v in samples.items()
                if k.startswith("serve_shed_total")
            ) > 0,
            "shed counters missing/zero in /metrics",
        )
        check(
            types.get("serve_queue_depth") == "gauge"
            and any(k.startswith("serve_queue_depth{") for k in samples),
            "queue-depth gauges missing from /metrics",
        )
        check(
            types.get("watchdog_beat_age_seconds") == "gauge",
            "watchdog beat-age gauges missing from /metrics",
        )

        # Registry vs snapshot consistency (same window, two paths).
        snap = server.snapshot()
        check(
            samples.get("serve_requests_completed_total")
            == snap["completed"],
            "completed_total disagrees with /stats snapshot",
        )
        check(
            sum(
                v for k, v in samples.items()
                if k.startswith("serve_shed_total")
            )
            == snap["shed_total"],
            "shed_total disagrees with /stats snapshot",
        )

        # /healthz: live, stalled (named), recovered.
        code, body = _get(f"{base}/healthz")
        payload = json.loads(body.decode())
        check(
            code == 200 and payload["status"] == "ok",
            f"/healthz not live: {code} {payload}",
        )
        check(
            "inflight" in payload.get("load", {})
            and "p99_ms" in payload.get("load", {}),
            "/healthz lacks per-replica load fields",
        )
        # Identity (ISSUE 12): the fleet router attributes health by
        # these — an anonymous payload is a regression.
        check(
            bool(payload.get("load", {}).get("replica_id"))
            and bool(payload.get("load", {}).get("version")),
            "/healthz load fields lack replica_id/version identity",
        )
        wedge = watchdog.register("smoke-wedged", stall_after=0.01)
        time.sleep(0.05)
        code, body = _get(f"{base}/healthz")
        payload = json.loads(body.decode())
        check(
            code == 503 and payload.get("component") == "smoke-wedged",
            f"stalled /healthz wrong: {code} {payload}",
        )
        wedge.close()
        code, _body = _get(f"{base}/healthz")
        check(code == 200, f"/healthz did not recover: {code}")
    finally:
        httpd.shutdown()
        httpd.server_close()
        thread.join(timeout=10)
        hb_scrape.close()
        server.close(drain=False)

    print(
        json.dumps(
            {
                "telemetry_smoke": "ok" if not failures else "fail",
                "failures": failures,
            }
        ),
        flush=True,
    )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
