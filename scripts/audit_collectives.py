#!/usr/bin/env python
"""HLO collective audit: verify the sharded step's communication schedule.

PARITY.md's scaling argument (BASELINE.json ≥90% efficiency at 8→256
chips) rests on one premise: the DP step's gradient synchronization
compiles to a SMALL number of fused all-reduce ops moving ≈152 MB of
f32 gradients (37.97M flagship params × 4 B), which at ~100 GB/s ICI
ring bandwidth costs ≈3 ms against a 135 ms step.  This script makes
that premise checkable: it compiles the real flagship-width train step
over an ``--devices N`` virtual CPU mesh, parses the OPTIMIZED HLO, and
reports every collective with its result-shape payload.

Measured (jax 0.9.0, CPU backend, f32 flagship width, SGD+momentum):
the whole module contains exactly ONE variadic all-reduce — XLA's
combiner fuses the entire gradient tree AND the pmean'd metrics/num_pos
scalars into a single add-reduction collective — with payload
152.0 MB, independent of N (verified n=8 and n=32; pinned by
tests/distributed/test_scale_evidence.py).  The ZeRO flavor
(``--zero``) replaces it with reduce-scatter(grads)/all-gather(params)
whose payloads shrink as 1/N per shard.

Run:
    python scripts/audit_collectives.py --devices 32 --json
    python scripts/audit_collectives.py --devices 8 --zero
"""

import argparse
import json
import os
import re
import sys

# Base collective op names; the parser also matches each one's async
# "-start" form (emitted on backends/flags with async collectives) and
# folds it into the base name so a schedule audits uniformly.  Async
# "-start" results are (operand, result, ...) tuples; the operand half is
# an aliased copy of the input, so only the RESULT elements are counted
# (``_async_result_bytes``) — payloads match the sync form exactly, and
# the matching "-done" halves are never separately counted.
_COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "s64": 8, "u64": 8,
    "s32": 4, "u32": 4, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
}


def _shape_bytes(shape_text: str) -> int:
    """Total bytes of every typed array in an HLO result-shape string
    (handles tuples: '(f32[3,3,64,64]{3,2,1,0}, f32[64]{0}, ...)')."""
    total = 0
    for m in re.finditer(r"(\w+)\[([0-9,]*)\]", shape_text):
        dt = _DTYPE_BYTES.get(m.group(1))
        if dt is None:
            continue
        n = 1
        for d in filter(None, m.group(2).split(",")):
            n *= int(d)
        total += n * dt
    return total


def _split_top_level(shape_text: str) -> list[str]:
    """Top-level elements of a tuple shape string: ``(f32[3,3]{1,0},
    (f32[4]{0}, f32[4]{0}))`` -> ['f32[3,3]{1,0}', '(f32[4]{0}, f32[4]{0})'].
    Returns [] when the text is not a tuple."""
    s = shape_text.strip()
    if not s.startswith("("):
        return []
    depth = 0
    elems, start = [], 1
    for i, c in enumerate(s):
        if c in "([{":
            depth += 1
        elif c in ")]}":
            depth -= 1
            if depth == 0:
                elems.append(s[start:i].strip())
                break
        elif c == "," and depth == 1:
            elems.append(s[start:i].strip())
            start = i + 1
    return [e for e in elems if e]


def _async_result_bytes(shape_text: str) -> int:
    """Payload of an async ``op-start`` result WITHOUT the operand alias:
    start ops return ``(operand, result, ...context)`` tuples, so counting
    the whole tuple over-counts ~2x vs. the sync form.  Drop the first
    element (the aliased input) and count the rest; a non-tuple start
    result (bufferized forms) is counted whole."""
    elems = _split_top_level(shape_text)
    if len(elems) < 2:
        return _shape_bytes(shape_text)
    return sum(_shape_bytes(e) for e in elems[1:])


def audit_hlo_text(txt: str) -> dict:
    """Parse optimized HLO, return {op: {count, payload_bytes}} with
    async ``op-start`` instructions folded into their base op name:
    payload from the start's RESULT elements only (operand-alias halves
    dropped), and the matching ``op-done`` instructions never separately
    counted."""
    out: dict[str, dict[str, int]] = {}
    # `%name = SHAPE op-name(operands...)`; SHAPE may be a long tuple, so
    # split the line at the op-name rather than regexing the whole shape.
    for line in txt.splitlines():
        for op in _COLLECTIVES:
            for marker, is_start in ((f" {op}-start(", True),
                                     (f" {op}(", False)):
                if marker in line and "=" in line.split(marker)[0]:
                    lhs = line.split(marker)[0].split("=", 1)[1]
                    rec = out.setdefault(op, {"count": 0, "payload_bytes": 0})
                    rec["count"] += 1
                    rec["payload_bytes"] += (
                        _async_result_bytes(lhs) if is_start
                        else _shape_bytes(lhs)
                    )
                    break
            else:
                continue
            break
    return out


def compile_and_audit(
    n_devices: int, reduced: bool, zero: bool
) -> dict:
    # Must run before any other jax use in this process (the container's
    # sitecustomize registers a TPU backend; see __graft_entry__).
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    # Virtual-device fallback for jax builds without the
    # ``jax_num_cpu_devices`` config option (e.g. 0.4.x): the XLA flag
    # must be in the env BEFORE the backend initializes.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        pass  # older jax: the XLA_FLAGS fallback above did the job
    assert jax.device_count() == n_devices, (
        f"virtual CPU mesh came up with {jax.device_count()} devices, "
        f"wanted {n_devices}"
    )

    import jax.numpy as jnp
    import numpy as np
    import optax

    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
    from batchai_retinanet_horovod_coco_tpu.train import (
        create_train_state,
        make_train_step,
    )

    width = {"fpn_channels": 64, "head_width": 64} if reduced else {}
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=80, backbone="resnet50", dtype=jnp.float32, **width
        )
    )
    hw = (64, 64)  # fully-conv: the GRADIENT payload is width-set, not hw-set
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *hw, 3), jax.random.key(0)
    )
    num_params = int(sum(x.size for x in jax.tree.leaves(state.params)))
    mesh = make_mesh(n_devices)
    step = make_train_step(
        model, hw, 80, mesh=mesh, donate_state=False,
        shard_weight_update=zero,
    )
    if zero:
        from batchai_retinanet_horovod_coco_tpu.parallel import (
            init_sharded_opt_state,
        )

        state = state.replace(
            opt_state=init_sharded_opt_state(state.tx, state.params, mesh)
        )
    batch = {
        "images": jnp.zeros((n_devices, *hw, 3), jnp.float32),
        "gt_boxes": jnp.tile(
            jnp.asarray([[8.0, 8.0, 40.0, 40.0]]), (n_devices, 1, 1)
        ),
        "gt_labels": jnp.zeros((n_devices, 1), jnp.int32),
        "gt_mask": jnp.ones((n_devices, 1), bool),
    }
    compiled = step.lower(state, batch).compile()
    collectives = audit_hlo_text(compiled.as_text())
    return {
        "devices": n_devices,
        "flavor": "zero" if zero else "dp",
        "width": "reduced" if reduced else "flagship",
        "num_params": num_params,
        "grad_bytes_f32": num_params * 4,
        "collectives": collectives,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--reduced", action="store_true",
                    help="audit the reduced-width model (default: flagship)")
    ap.add_argument("--zero", action="store_true",
                    help="audit the ZeRO (weight-update-sharded) flavor")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    sys.path.insert(
        0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    )
    result = compile_and_audit(args.devices, args.reduced, args.zero)
    if args.json:
        print(json.dumps(result))
        return
    print(
        f"{result['flavor']} step, {result['width']} width, "
        f"{result['devices']} devices: {result['num_params'] / 1e6:.2f}M "
        f"params -> {result['grad_bytes_f32'] / 1e6:.1f} MB f32 grads"
    )
    if not result["collectives"]:
        print("  NO collectives found (single-device module?)")
    for op, rec in sorted(result["collectives"].items()):
        print(
            f"  {op:20s} x{rec['count']:3d}  payload "
            f"{rec['payload_bytes'] / 1e6:8.1f} MB"
        )


if __name__ == "__main__":
    main()
