#!/usr/bin/env python
"""Standalone repro: XLA SPMD miscompiles the backward of a residual
conv chain on tiny H-sharded maps over a 2-D (data, space) mesh.

THE MINIMAL TRIGGER (~25 lines, pure lax, f64 so rounding is ruled
out): >= 2 chained residual blocks ``x + conv2(relu(conv1(x)))`` of 3x3
stride-1 SAME convs on an H=2 feature map, input sharded
P('data', 'space') with space=2 (one H row per shard) and data >= 2.
The weight gradients under the partitioner then diverge from the
unsharded gradients CATASTROPHICALLY, exploding with both chain length
and data-axis width (relative L2 error, jax 0.9.0 CPU backend, f64):

    blocks:      1        2        4
    (8,2) H=2    exact    1.9      6.7e3

    data:        2        4        8        16        (4 blocks, H=2)
    (d,2) H=2    3.0      1.5e2    6.7e3    4.1e5

    neighbours measured EXACT (<=1e-15): H=1 (0.5 rows/shard), H=3
    (1.5 rows), H=4 (2 rows); space=4 at H=4 (1 row/shard!); data=1
    at any probed H; the chain without the residual add; a single
    block; every single-conv probe (see strided_conv_weight_grad.py).

Finite-difference proof that the BACKWARD (not the forward) is wrong —
run on the full-depth ResNet variant of this trigger, differencing
through the sharded executable's own forward:

    fd (through SHARDED forward)  +6.875e+01
    unsharded autodiff gradient   +6.898e+01
    SHARDED autodiff gradient     +1.641e+06      (~24,000x too large)

Model-level impact (what led here, round 5): the spatially partitioned
RetinaNet train step on DEEP backbones (stacked residual blocks at the
H/16, H/32 stages, which hit these tiny-map geometries on small CI
images) computes wrong gradients whenever the mesh has data >= 2 —
measured per-step param L2 error 2.8e-4 (data=2) to 7.2e-3 (data=16)
at hw 64, f64-persistent — while the 1-block-per-stage CI backbone,
(data, 1) meshes, and (1, space) pure-spatial meshes measure exact.
The composed model diverges in MORE configs than this minimal trigger
(e.g. space=4 at hw 64), so the framework guards on the measured
model-level envelope, not just this op pattern
(train/step.py::make_train_step_spatial "Data-axis envelope").

Canary: tests/distributed/test_spatial_train.py::
test_xla_spatial_data_axis_grad_canary (asserts the bug is PRESENT —
its failure after a jax upgrade is the signal to re-measure and relax
the guards).

Run:  python scripts/xla_repros/spatial_residual_chain_grad.py [--json]
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=32"
).strip()
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def rel_diff(
    data: int, space: int, H: int, blocks: int, residual: bool = True
) -> float:
    """Relative L2 error of the sharded weight grads vs unsharded."""
    mesh = Mesh(
        np.array(jax.devices()[: data * space]).reshape(data, space),
        axis_names=("data", "space"),
    )
    rng = np.random.default_rng(0)
    C = 16
    B = max(2, data)
    x = rng.normal(0, 1, (B, H, H, C))
    ws = [rng.normal(0, 0.1, (3, 3, C, C)) for _ in range(2 * blocks)]
    cot = rng.normal(0, 1, (B, H, H, C))
    xsh = NamedSharding(mesh, P("data", "space"))
    rep = NamedSharding(mesh, P())

    def net(ws, x):
        for i in range(blocks):
            h = _conv(jax.nn.relu(_conv(x, ws[2 * i])), ws[2 * i + 1])
            x = x + h if residual else h
        return jnp.sum(x * jnp.asarray(cot))

    def net_sharded(ws, x):
        return net(ws, jax.lax.with_sharding_constraint(x, xsh))

    args = [jnp.asarray(w) for w in ws]
    g_ref = jax.grad(net)(args, jnp.asarray(x))
    g_sp = jax.jit(jax.grad(net_sharded), out_shardings=rep)(
        args, jnp.asarray(x)
    )
    num = sum(
        float(np.sum((np.asarray(p) - np.asarray(q)) ** 2))
        for p, q in zip(g_sp, g_ref)
    )
    den = sum(float(np.sum(np.asarray(p) ** 2)) for p in g_ref)
    return (num / den) ** 0.5


if __name__ == "__main__":
    rows = []
    print(f"jax {jax.__version__}; 32 virtual CPU devices; f64")
    for data, space, H, blocks, label in [
        (8, 2, 2, 2, "THE TRIGGER: 2 residual blocks, 1 row/shard"),
        (8, 2, 2, 4, "4 blocks (explodes with depth)"),
        (2, 2, 2, 4, "data=2 (minimum data width)"),
        (8, 2, 2, 1, "1 block: exact"),
        (8, 2, 4, 4, "2 rows/shard: exact"),
        (8, 2, 3, 4, "1.5 rows/shard: exact"),
        (8, 4, 4, 4, "space=4 at 1 row/shard: exact"),
        (1, 2, 2, 4, "data=1: exact"),
    ]:
        r = rel_diff(data, space, H, blocks)
        rows.append({"data": data, "space": space, "H": H,
                     "blocks": blocks, "rel": r})
        flag = "  <== WRONG" if r > 1e-6 else ""
        print(f"({data},{space}) H={H} blocks={blocks} [{label}]: "
              f"rel {r:.3e}{flag}")
    no_res = rel_diff(8, 2, 2, 4, residual=False)
    rows.append({"data": 8, "space": 2, "H": 2, "blocks": 4,
                 "residual": False, "rel": no_res})
    print(f"(8,2) H=2 blocks=4 WITHOUT residual add: rel {no_res:.3e}")
    if "--json" in sys.argv[1:]:
        print(json.dumps(rows))
