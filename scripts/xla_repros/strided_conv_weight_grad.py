#!/usr/bin/env python
"""Standalone repro: XLA SPMD mis-computes a strided-conv WEIGHT gradient.

One `lax.conv_general_dilated` (3x3, stride 2, SAME-style (1,1) padding),
input H-sharded over 8 devices with exactly ONE input row per shard:
the weight gradient under the partitioner differs from the unsharded
gradient by ~45% RELATIVE, in float64 (so it is a different sum, not
rounding), with both the GSPMD and Shardy partitioners (jax 0.9.0,
CPU backend with --xla_force_host_platform_device_count=8).

Neighbouring configs are exact (<=1e-15 relative): kernel 1x1 or 5x5,
stride 1, >=2 rows per shard, and 4 shards at one row per shard — the
boundary is shard-count-dependent.  Forward values and the grad-input
are exact in every probed config; only grad-weight is wrong.

Run:  python scripts/xla_repros/strided_conv_weight_grad.py [shardy]

This is the bug behind `make_train_step_spatial`'s sharding-envelope
guard (batchai_retinanet_horovod_coco_tpu/train/step.py) and is pinned
by tests/distributed/test_spatial_train.py::test_xla_strided_conv_grad_canary.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
if "shardy" in sys.argv[1:]:
    jax.config.update("jax_use_shardy_partitioner", True)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def rel_diff(shards: int, H: int, k: int = 3, stride: int = 2) -> float:
    mesh = Mesh(
        np.array(jax.devices()[:shards]).reshape(1, shards),
        axis_names=("data", "space"),
    )
    rng = np.random.default_rng(0)
    C = 16
    x = rng.normal(0, 1, (2, H, H, C))
    w = rng.normal(0, 0.1, (k, k, C, C))
    Ho = (H + stride - 1) // stride
    cot = rng.normal(0, 1, (2, Ho, Ho, C))
    pad = ((k // 2, k // 2), (k // 2, k // 2))

    def loss(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return jnp.sum(y * jnp.asarray(cot))

    g_ref = jax.grad(loss)(jnp.asarray(w), jnp.asarray(x))
    xsh = NamedSharding(mesh, P("data", "space"))
    rep = NamedSharding(mesh, P())
    g_sp = jax.jit(
        jax.grad(loss), in_shardings=(rep, xsh), out_shardings=rep
    )(jnp.asarray(w), jax.device_put(jnp.asarray(x), xsh))
    d = float(np.max(np.abs(np.asarray(g_ref) - np.asarray(g_sp))))
    return d / float(np.max(np.abs(np.asarray(g_ref))))


if __name__ == "__main__":
    print(f"jax {jax.__version__}; shardy={'shardy' in sys.argv[1:]}")
    bad = rel_diff(shards=8, H=8)
    print(f"8 shards, H=8 (1 row/shard), k=3 s=2: rel diff {bad:.3e}  "
          f"{'<== WRONG' if bad > 1e-6 else '(fixed?)'}")
    for shards, H, k, stride, label in [
        (8, 16, 3, 2, "2 rows/shard"),
        (8, 8, 1, 2, "k=1"),
        (8, 8, 5, 2, "k=5"),
        (8, 8, 3, 1, "stride 1"),
        (4, 4, 3, 2, "4 shards, 1 row/shard"),
    ]:
        r = rel_diff(shards=shards, H=H, k=k, stride=stride)
        print(f"{shards} shards, H={H} ({label}): rel diff {r:.3e}")
