#!/usr/bin/env python
"""Standalone repro: XLA SPMD mis-computes a strided-conv WEIGHT gradient.

One `lax.conv_general_dilated` (3x3, stride 2, SAME-style (1,1) padding),
input H-sharded over 8 devices with exactly ONE input row per shard:
the weight gradient under the partitioner differs from the unsharded
gradient by ~45% RELATIVE, in float64 (so it is a different sum, not
rounding), with both the GSPMD and Shardy partitioners (jax 0.9.0,
CPU backend with --xla_force_host_platform_device_count=8).

Neighbouring configs are exact (<=1e-15 relative): kernel 1x1 or 5x5,
stride 1, >=2 rows per shard, and 4 shards at one row per shard — the
boundary is shard-count-dependent.  Forward values and the grad-input
are exact in every probed config; only grad-weight is wrong.

16-shard sweep (round 5, run via ``--probe``, pinned by
tests/distributed/test_spatial_train.py::test_xla_strided_conv_grad_canary_16shard):

    rows/shard   0.25    0.5     1.0     1.5     2.0     4.0
    16 shards    exact   44%     41%     exact   exact   exact
     8 shards    —       exact*  44%     exact   exact   exact
     4 shards    —       exact   exact   exact   exact   exact

(*) single-op repro only: round-4 MODEL-level probes measured 1e-4-class
parameter error at 0.5 rows/shard on 8 shards, so the model guard's
[0.5, 2)-rows zone is kept as the conservative union of both probes.
Every layout the single-op sweep finds broken lies inside that zone at
both 8 and 16 shards — the zone generalizes as a superset, with the
1.5-rows row measured exact (over-refusal, accepted: the cost is only a
smaller --spatial-shards).  Sub-half-row layouts (H < shards/2) are
handled by replication and exact.

Run:  python scripts/xla_repros/strided_conv_weight_grad.py [shardy]
      # custom sweep (shards:H pairs; device count auto-raised):
      python scripts/xla_repros/strided_conv_weight_grad.py \\
          --json --probe 16:8 16:16 16:24 16:32

This is the bug behind `make_train_step_spatial`'s sharding-envelope
guard (batchai_retinanet_horovod_coco_tpu/train/step.py) and is pinned
by tests/distributed/test_spatial_train.py::test_xla_strided_conv_grad_canary.
"""

import json
import os
import sys

# Device count must be fixed BEFORE importing jax: parse --probe first so
# a 16-shard sweep gets a 16-device host platform.
_probes = []
_args = sys.argv[1:]
if "--probe" in _args:
    for a in _args[_args.index("--probe") + 1 :]:
        if ":" not in a:
            break
        s, h = a.split(":")
        _probes.append((int(s), int(h)))
_ndev = max([8] + [s for s, _ in _probes])

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_ndev}"
).strip()
# A shared compilation cache may hold entries from a differently-flagged
# interpreter; this script is tiny, always compile fresh.
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
if "shardy" in sys.argv[1:]:
    jax.config.update("jax_use_shardy_partitioner", True)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def rel_diff(shards: int, H: int, k: int = 3, stride: int = 2) -> float:
    mesh = Mesh(
        np.array(jax.devices()[:shards]).reshape(1, shards),
        axis_names=("data", "space"),
    )
    rng = np.random.default_rng(0)
    C = 16
    x = rng.normal(0, 1, (2, H, H, C))
    w = rng.normal(0, 0.1, (k, k, C, C))
    Ho = (H + stride - 1) // stride
    cot = rng.normal(0, 1, (2, Ho, Ho, C))
    pad = ((k // 2, k // 2), (k // 2, k // 2))
    xsh = NamedSharding(mesh, P("data", "space"))
    rep = NamedSharding(mesh, P())

    def loss_ref(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (stride, stride), pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return jnp.sum(y * jnp.asarray(cot))

    def loss(w, x):
        # The shard layout comes from an in-jit constraint (GSPMD pads
        # non-divisible extents), matching how the model's intermediate
        # maps are sharded — a device_put would refuse H % shards != 0.
        return loss_ref(w, jax.lax.with_sharding_constraint(x, xsh))

    g_ref = jax.grad(loss_ref)(jnp.asarray(w), jnp.asarray(x))
    g_sp = jax.jit(jax.grad(loss), out_shardings=rep)(
        jnp.asarray(w), jnp.asarray(x)
    )
    d = float(np.max(np.abs(np.asarray(g_ref) - np.asarray(g_sp))))
    return d / float(np.max(np.abs(np.asarray(g_ref))))


if __name__ == "__main__":
    if _probes:
        results = [
            {"shards": s, "H": h, "rows_per_shard": h / s,
             "rel": rel_diff(shards=s, H=h)}
            for s, h in _probes
        ]
        if "--json" in sys.argv[1:]:
            print(json.dumps(results))
        else:
            for r in results:
                print(f"{r['shards']} shards, H={r['H']} "
                      f"({r['rows_per_shard']:.2f} rows/shard): "
                      f"rel diff {r['rel']:.3e}")
        sys.exit(0)

    print(f"jax {jax.__version__}; shardy={'shardy' in sys.argv[1:]}")
    bad = rel_diff(shards=8, H=8)
    print(f"8 shards, H=8 (1 row/shard), k=3 s=2: rel diff {bad:.3e}  "
          f"{'<== WRONG' if bad > 1e-6 else '(fixed?)'}")
    for shards, H, k, stride, label in [
        (8, 16, 3, 2, "2 rows/shard"),
        (8, 8, 1, 2, "k=1"),
        (8, 8, 5, 2, "k=5"),
        (8, 8, 3, 1, "stride 1"),
        (4, 4, 3, 2, "4 shards, 1 row/shard"),
    ]:
        r = rel_diff(shards=shards, H=H, k=k, stride=stride)
        print(f"{shards} shards, H={H} ({label}): rel diff {r:.3e}")
