#!/usr/bin/env python
"""Standalone repro: wrong bf16 forward loss under GSPMD spatial sharding.

A bf16 RetinaNet at flagship head width (256) with its images H-sharded
over a 2-D (data, space) mesh returns a WRONG forward cls_loss value —
1.128 single-device vs 1.420 sharded (gn norm; 2.82 with frozen_bn) with
gradients 14-60x off — deterministically, once the box-regression
gradient is part of the program.  Signatures of a partitioner
miscompilation rather than arithmetic noise (round-4 bisection,
PARITY.md "A second partitioner miscompilation"):

- f32 at the same width is exact; bf16 at head width 64 is exact.
- The wrong value CHANGES when unrelated graph consumers are added
  (loss-only jit: correct; + `optax.global_norm(grads)`: wrong).
- Swapping the focal mask construction, the focal custom-VJP, and the
  box-target memory layout all reproduce the same wrong bits.
- Shardy produces bit-identical wrong values.
- Constraining the head outputs to space-replicated before the loss
  fixes the forward everywhere but frozen_bn gradients stay 3-13% off,
  so part of the miscompilation is in the partitioned model backward.

Round-5 minimization (``--minimal``): the wrong VALUE does NOT need
the matching, the targets, or the box loss — the same model with the
loss replaced by ``sum(focal_elementwise(cls_levels, targets=0)) +
0.1*sum(box_levels**2)`` (zero-target focal + plain L2, no data
plumbing at all) still returns a value ~3.7e-3 relative off under the
(4, 2) sharding, while the identical program with ``softplus`` in
place of the focal term matches to 2.9e-6 — so the trigger is the
focal expression's backward interacting with the partitioned model,
not the detection pipeline.  Bottom-up reconstructions below the real
model stay clean (round 4: a 3-conv two-branch net; a depth-4 shared
head over 5 levels; an FPN with lateral adds; f32 master params cast
per conv).  Two leads for upstream triage: (a) during these probes
XLA's partitioner logs "[SPMD] Involuntary full rematerialization …
cannot go from sharding {devices=[4,1,1,1,2]} to
{devices=[1,2,1,1,4]T(1,0)} efficiently for
transpose(jvp(RetinaNet))/fpn/fpn/add_any on bf16[2,1,1,256]"
(tracked upstream as b/433785288) — the backward of the FPN lateral
add on TINY maps hits a resharding fallback, the same tiny-map
backward territory as the round-5 residual-chain bug
(spatial_residual_chain_grad.py); (b) gradient NORMS diverge ~1e-2
relative even in the softplus control, so the value-wrongness
threshold and the grad-wrongness threshold differ.  Run on the
8-virtual-device CPU backend (jax 0.9.0):

    python scripts/xla_repros/bf16_spatial_cls_loss.py [--minimal]

This is the bug behind `make_train_step_spatial`'s f32-only gate
(batchai_retinanet_horovod_coco_tpu/train/step.py) and is pinned by
tests/distributed/test_spatial_train.py::test_xla_bf16_spatial_step_canary.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax.numpy as jnp
import numpy as np
import optax

from batchai_retinanet_horovod_coco_tpu.models import (
    RetinaNetConfig,
    build_retinanet,
)
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import make_mesh_2d
from batchai_retinanet_horovod_coco_tpu.train import (
    create_train_state,
    make_train_step,
)
from batchai_retinanet_horovod_coco_tpu.train.step import (
    make_train_step_spatial,
)


def minimal() -> None:
    """Round-5 strip: model + zero-target focal + L2 — no matching, no
    targets, no box codec.  The focal variant returns a WRONG value
    under the (4, 2) sharding; the softplus control matches."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from batchai_retinanet_horovod_coco_tpu.losses import (
        LossConfig,
        _focal_elementwise,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
        spatial_batch_shardings,
    )

    hw = (64, 64)
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=4, backbone="resnet_test", norm_kind="gn",
            dtype=jnp.bfloat16,
        )
    )
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(0, 1, (8, *hw, 3)).astype(np.float32))
    params = jax.jit(model.init)(jax.random.key(0), images[:1])["params"]

    def heads(p, im):
        o = model.apply({"params": p}, im, train=True, return_levels="nhwc")
        return o["cls_levels"], o["box_levels"]

    def make_value(cls_term):
        def value(p, im):
            c, b = heads(p, im)
            cls = sum(jnp.sum(cls_term(x.astype(jnp.float32))) for x in c)
            return cls + 0.1 * sum(
                jnp.sum(x.astype(jnp.float32) ** 2) for x in b
            )

        def vg(p, im):
            v, g = jax.value_and_grad(value)(p, im)
            return v, optax.global_norm(g)

        return vg

    mesh = make_mesh_2d(4, 2)
    rep = NamedSharding(mesh, P())
    imsh = spatial_batch_shardings(mesh)["images"]
    print(f"jax {jax.__version__} (minimal mode)")
    for name, term in (
        ("focal(t=0)+L2", lambda x: _focal_elementwise(
            x, jnp.zeros_like(x), LossConfig())),
        ("softplus+L2  ", jax.nn.softplus),
    ):
        vg = make_value(term)
        vr, gr = (float(x) for x in jax.jit(vg)(params, images))
        vs, gs = (float(x) for x in jax.jit(
            vg, in_shardings=(rep, imsh), out_shardings=(rep, rep)
        )(params, images))
        rel = abs(vs - vr) / max(1e-12, abs(vr))
        print(
            f"{name}: value {vr:.6g} single vs {vs:.6g} spatial "
            f"(rel {rel:.2e}) {'<== WRONG' if rel > 1e-4 else '(match)'}; "
            f"grad_norm {gr:.4g} vs {gs:.4g}"
        )


def main() -> None:
    hw, k = (64, 64), 3
    rng = np.random.default_rng(0)
    batch = 8
    gt_boxes = np.zeros((batch, 5, 4), np.float32)
    gt_labels = np.zeros((batch, 5), np.int32)
    gt_mask = np.zeros((batch, 5), bool)
    for b in range(batch):
        n = int(rng.integers(1, 4))
        xy = rng.uniform(0, 32, (n, 2))
        wh = rng.uniform(8, 30, (n, 2))
        gt_boxes[b, :n] = np.concatenate([xy, xy + wh], 1)
        gt_labels[b, :n] = rng.integers(0, k, n)
        gt_mask[b, :n] = True
    B = {
        "images": jnp.asarray(
            rng.integers(0, 255, (batch, *hw, 3)).astype(np.uint8)
        ),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }
    print(f"jax {jax.__version__}")
    for dtype, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
        model = build_retinanet(
            RetinaNetConfig(
                num_classes=k, backbone="resnet_test", norm_kind="gn",
                dtype=dtype,
            )
        )
        state = create_train_state(
            model, optax.sgd(1e-2, momentum=0.9), (1, *hw, 3),
            jax.random.key(0),
        )
        _, m1 = make_train_step(
            model, hw, k, mesh=None, donate_state=False
        )(state, B)
        _, m2 = make_train_step_spatial(
            model, hw, k, mesh=make_mesh_2d(4, 2), donate_state=False,
            allow_unvalidated_bf16=True,
        )(state, B)
        cls1, cls2 = float(m1["cls_loss"]), float(m2["cls_loss"])
        gn1, gn2 = float(m1["grad_norm"]), float(m2["grad_norm"])
        wrong = abs(cls2 - cls1) / abs(cls1) > 0.01
        print(
            f"{name}: cls_loss {cls1:.5f} single vs {cls2:.5f} spatial; "
            f"grad_norm {gn1:.3f} vs {gn2:.3f}  "
            f"{'<== WRONG' if wrong else '(match)'}"
        )


if __name__ == "__main__":
    if "--minimal" in sys.argv[1:]:
        minimal()
    else:
        main()
