#!/usr/bin/env python
"""Standalone repro: 40x conv-backward cliff at small batch on TPU.

A single bf16 3x3 stride-1 NHWC conv at ResNet stage2 geometry
(200x336 spatial, 64 channels — the C3 level of an 800x1344 detection
input) takes ~120-210 ms run-to-run for its gradient at batch 4 but
~5 ms at batch 8 on a v5e chip (jax 0.9.0): a 20-40x non-monotonic
cliff in XLA:TPU's lowering of the backward conv.  Neighbouring
geometries (100x168x128, 50x84x256) scale sanely.

End-to-end effect (BUCKETBENCH.json batch_scaling): the full RetinaNet
train step is ABSOLUTELY slower at per-chip batch 4 than at batch 8
(146 vs 119 ms/step), and per-image throughput plateaus at ~35 ms/image
for batch <= 4 vs ~15 at batch 8 — so the framework's RUNBOOK recommends
per-chip batch 8 and the linear-scaling LR rule instead of spreading a
small global batch one-image-per-chip.

Requires a real TPU (the cliff is in the TPU lowering; CPU is fine).
Run:  python scripts/xla_repros/smallbatch_conv_grad_tpu.py
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n: int = 30) -> float:
    compiled = jax.jit(fn).lower(*args).compile()
    out = None
    for _ in range(3):
        out = compiled(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = compiled(*args)
    # Hard host sync (tunneled backends can return from block_until_ready
    # before the device finishes).
    np.asarray(jax.device_get(jax.tree.leaves(out)[0])).ravel()[0]
    return (time.perf_counter() - t0) / n * 1e3


def main() -> None:
    print(f"jax {jax.__version__}; device {jax.devices()[0].device_kind}")
    rng = np.random.default_rng(0)
    for (H, W, C) in [(200, 336, 64), (100, 168, 128), (50, 84, 256)]:
        w = jnp.asarray(rng.normal(0, 0.05, (3, 3, C, C)), jnp.bfloat16)

        def loss(w, x):
            y = jax.lax.conv_general_dilated(
                x, w, (1, 1), ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            return jnp.sum(y.astype(jnp.float32))

        g = jax.grad(loss)
        times = {}
        for b in (4, 8):
            x = jnp.asarray(rng.normal(0, 1, (b, H, W, C)), jnp.bfloat16)
            times[b] = timeit(g, w, x)
        flag = "  <== CLIFF" if times[4] > 3 * times[8] else ""
        print(
            f"conv {H}x{W}x{C}: grad b4 {times[4]:7.2f} ms vs "
            f"b8 {times[8]:6.2f} ms{flag}"
        )


if __name__ == "__main__":
    sys.exit(main())
