"""Test env: force an 8-virtual-device CPU mesh regardless of TPU presence.

This gives every test the real SPMD code path (shard_map/psum over an 8-device
mesh) without TPU hardware, per SURVEY.md §4.3.

Note: this container's sitecustomize registers an 'axon' TPU PJRT backend at
interpreter start and prepends it to jax_platforms, so setting the
JAX_PLATFORMS env var here is NOT sufficient — we must override the config
after importing jax (backend selection is lazy, so this is still early
enough).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
