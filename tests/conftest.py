"""Test env: force an 8-virtual-device CPU mesh regardless of TPU presence.

This gives every test the real SPMD code path (shard_map/psum over an 8-device
mesh) without TPU hardware, per SURVEY.md §4.3.

Note: this container's sitecustomize registers an 'axon' TPU PJRT backend at
interpreter start and prepends it to jax_platforms, so setting the
JAX_PLATFORMS env var here is NOT sufficient — we must override the config
after importing jax (backend selection is lazy, so this is still early
enough).
"""

import os

# ISSUE 20: arm the runtime lock-order witness for the whole tier — every
# utils.locks.make_lock() site returns a debug wrapper that raises on any
# inversion of the committed analysis/lock_order.json order, so tier-1
# validates the static lock order on every run.  setdefault: an explicit
# RETINANET_LOCK_DEBUG=0 still wins (bisection escape hatch).  Subprocess
# legs (chaos, fleet smokes) inherit it through the environment.
os.environ.setdefault("RETINANET_LOCK_DEBUG", "1")

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Compilation cache: the suite's dominant cost is XLA recompiling the SAME
# tiny train/detect programs in every test (make_train_step builds a fresh
# closure per call, so the in-process trace cache never hits).  The on-disk
# cache is keyed on the HLO hash, so identical programs compile once per
# SESSION, not once per test — measured: test_loop.py 649 s cold → ~5 min
# warm.  The dir is per-session (a fresh temp dir), NOT machine-persistent:
# this container's XLA:CPU segfaults when EXECUTING an executable
# deserialized from a cache written by another process (reproduced
# deterministically on test_loop's step programs; same-process reuse is
# fine), so a machine-shared dir turns one poisoned entry into a suite-
# killing crash on every later run.  Per-session keeps the intra-suite
# dedup win and rules the cross-process reload path out entirely.
import tempfile as _tempfile

_CACHE_DIR = os.environ.get("RETINANET_TEST_CACHE_DIR")
if not _CACHE_DIR:
    _CACHE_DIR = _tempfile.mkdtemp(prefix="jax_cache_")
    # Our temp dir, our mess: reclaim the serialized executables (tens of
    # MB per session) when the session ends.  An explicit
    # RETINANET_TEST_CACHE_DIR is the caller's to manage (and to keep
    # single-process — see the segfault note above).
    import atexit as _atexit
    import shutil as _shutil

    _atexit.register(_shutil.rmtree, _CACHE_DIR, ignore_errors=True)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
# Deliberately NOT exported via JAX_COMPILATION_CACHE_DIR: a subprocess
# inheriting this session's dir could deserialize an executable another
# process wrote — the segfault mode above.  The pod tests that spawn
# worker ranks (test_pod_launch / test_fault_injection) each set their own
# per-test cache dir explicitly.

# Checkpointing runs ASYNC under test, like production: the native
# writer (utils/checkpoint.py, ISSUE 11) is plain stdlib threading, so
# the orbax async-finalize segfault class (cross-thread asyncio wakeups
# + grpc under this container's sandboxed kernel) that once forced
# RETINANET_ASYNC_CKPT=0 here is gone.  The env var survives as an
# escape hatch selecting the synchronous path; tests that want it set it
# explicitly.

import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running integration test")


@pytest.fixture(scope="session")
def tiny_model_and_state():
    """A 3-class resnet_test RetinaNet + fresh TrainState (fully conv: any HW)."""
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3,
            backbone="resnet_test",
            fpn_channels=32,
            head_width=32,
            head_depth=1,
            dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2), (1, 64, 64, 3), jax.random.key(0)
    )
    return model, state


# ---- Fast-tier time budget (VERDICT r3 weak #1) -----------------------------
# Every new capability adds compiled programs, and nothing structurally
# stopped the "not slow" tier from drifting 10 -> 15 -> 30 min.  The budget
# makes the drift VISIBLE in every run: when a fast-tier session exceeds it,
# a prominent warning names the worst offenders so the capability that blew
# the budget pays its test-time cost in review.  (A hard fail would flake on
# loaded boxes; visibility is the mechanism.)  The committed per-test
# snapshot lives in TEST_TIMINGS.md (`make test-timings`).
# 600 -> 1200: the 600 s figure assumed the machine-persistent compile
# cache ("warm" runs); with the cache per-session (see above) every run
# pays each unique program's compile once, measured ~16 min for the full
# tier before the PR-1 diet.
_FAST_TIER_BUDGET_S = 1200.0
_session_start = None


def pytest_sessionstart(session):
    global _session_start
    import time

    _session_start = time.perf_counter()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    import time

    if _session_start is None:
        return
    # Only police the fast tier: a run that deselects `slow` tests.
    markexpr = getattr(config.option, "markexpr", "") or ""
    if "not slow" not in markexpr.replace("'", "").replace('"', ""):
        return
    elapsed = time.perf_counter() - _session_start
    if elapsed <= _FAST_TIER_BUDGET_S:
        return
    tr = terminalreporter
    tr.write_sep("=", "FAST TIER OVER BUDGET", red=True, bold=True)
    tr.write_line(
        f"fast tier took {elapsed:.0f}s > {_FAST_TIER_BUDGET_S:.0f}s budget "
        "(cold compilation caches can exceed it once; a WARM run over "
        "budget means a recently added test owes a diet or a `slow` mark "
        "— see TEST_TIMINGS.md / `make test-timings`)."
    )
    durations = []
    for reports in terminalreporter.stats.values():
        for rep in reports:
            if getattr(rep, "when", None) == "call":
                durations.append((rep.duration, rep.nodeid))
    for dur, nodeid in sorted(durations, reverse=True)[:10]:
        tr.write_line(f"  {dur:7.1f}s  {nodeid}")
