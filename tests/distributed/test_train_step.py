"""Distributed-correctness tests on the 8-device virtual CPU mesh.

The core SURVEY.md §4.3 requirement the reference never had: prove the
data-parallel step (shard_map + pmean over the `data` axis) produces the SAME
result as a single-device step on the same global batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step

HW = (64, 64)
NUM_CLASSES = 4
GLOBAL_BATCH = 8


def tiny_config(**kw):
    return RetinaNetConfig(
        num_classes=NUM_CLASSES,
        backbone="resnet_test",
        fpn_channels=32,
        head_width=32,
        head_depth=1,
        dtype=jnp.float32,
        **kw,
    )


def synthetic_batch(seed=0):
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 1, (GLOBAL_BATCH, *HW, 3)).astype(np.float32)
    gt_boxes = np.zeros((GLOBAL_BATCH, 5, 4), np.float32)
    gt_labels = np.zeros((GLOBAL_BATCH, 5), np.int32)
    gt_mask = np.zeros((GLOBAL_BATCH, 5), bool)
    for b in range(GLOBAL_BATCH):
        n = int(rng.integers(1, 4))
        xy = rng.uniform(0, 32, (n, 2))
        wh = rng.uniform(8, 30, (n, 2))
        gt_boxes[b, :n] = np.concatenate([xy, xy + wh], 1)
        gt_labels[b, :n] = rng.integers(0, NUM_CLASSES, n)
        gt_mask[b, :n] = True
    return {
        "images": jnp.asarray(images),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


@pytest.fixture(scope="module")
def model_and_state():
    model = build_retinanet(tiny_config())
    tx = optax.sgd(1e-2, momentum=0.9)
    state = create_train_state(model, tx, (1, *HW, 3), jax.random.key(0))
    return model, state


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_dp_grads_equal_single_device(model_and_state):
    """Allreduce-correctness: sharded step == single-device step, same batch."""
    model, state0 = model_and_state
    batch = synthetic_batch()

    single_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )
    s_single, m_single = single_step(state0, batch)

    mesh = make_mesh(8)
    dp_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    s_dp, m_dp = dp_step(state0, batch)

    np.testing.assert_allclose(
        float(m_single["loss"]), float(m_dp["loss"]), rtol=1e-5
    )
    flat_single = jax.tree.leaves(s_single.params)
    flat_dp = jax.tree.leaves(s_dp.params)
    for a, b in zip(flat_single, flat_dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


@pytest.mark.slow
def test_loss_decreases_overfit():
    """Fixed batch, 12 sharded steps: loss must go down (integration smoke).

    Slow tier: 35 s (round-4 timing report), and the CLI test suite's
    end-to-end synthetic train covers the same learn-something contract."""
    model = build_retinanet(tiny_config())
    state = create_train_state(
        model, optax.adam(1e-3), (1, *HW, 3), jax.random.key(0)
    )
    batch = synthetic_batch(seed=3)
    mesh = make_mesh(8)
    step = make_train_step(model, HW, NUM_CLASSES, mesh=mesh, donate_state=False)
    first = None
    for _ in range(12):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert np.isfinite(first) and np.isfinite(last)
    assert last < first


def test_metrics_keys_and_step_counter(model_and_state):
    model, state = model_and_state
    batch = synthetic_batch(seed=5)
    mesh = make_mesh(8)
    step = make_train_step(model, HW, NUM_CLASSES, mesh=mesh, donate_state=False)
    new_state, metrics = step(state, batch)
    assert set(metrics) >= {"loss", "cls_loss", "box_loss", "num_pos"}
    assert int(new_state.step) == int(state.step) + 1


def test_mesh_subset_sizes():
    """Mesh over fewer devices than available also works (2-way DP)."""
    model = build_retinanet(tiny_config())
    tx = optax.sgd(1e-2)
    state = create_train_state(model, tx, (1, *HW, 3), jax.random.key(0))
    mesh = make_mesh(2)
    step = make_train_step(model, HW, NUM_CLASSES, mesh=mesh, donate_state=False)
    _, metrics = step(state, synthetic_batch(seed=7))
    assert np.isfinite(float(metrics["loss"]))


def test_grad_norm_metric(model_and_state):
    """SURVEY.md §5.5: grad-norm is reported per step, sharded == single."""
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh

    model, state = model_and_state
    batch = synthetic_batch(0)
    single = make_train_step(model, HW, NUM_CLASSES, donate_state=False)
    _, m1 = single(state, batch)
    mesh = make_mesh(8)
    sharded = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    _, m8 = sharded(state, batch)
    assert float(m1["grad_norm"]) > 0
    np.testing.assert_allclose(
        float(m8["grad_norm"]), float(m1["grad_norm"]), rtol=1e-4
    )
