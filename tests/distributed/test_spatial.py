"""Spatially-partitioned detection on the 8-device CPU mesh.

The long-axis stretch of SURVEY.md §2.4: the image's H axis sharded over the
mesh, GSPMD inserting conv halo exchanges. Correctness contract: identical
detections to the unsharded path on the same image.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
    make_detect_fn_spatial,
)
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh

HW = (128, 64)  # H=128 shards 16 rows/device over 8 devices


def test_spatial_matches_unsharded(tiny_model_and_state):
    model, state = tiny_model_and_state
    config = DetectConfig(pre_nms_size=64, max_detections=10)
    rng = np.random.default_rng(0)
    images = jnp.asarray(
        rng.normal(0, 1, (2, *HW, 3)).astype(np.float32)
    )

    plain = make_detect_fn(model, HW, config)
    spatial = make_detect_fn_spatial(model, HW, config, mesh=make_mesh(8))

    a = jax.device_get(plain(state, images))
    b = jax.device_get(spatial(state, images))
    np.testing.assert_array_equal(a.valid, b.valid)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_allclose(a.scores, b.scores, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(a.boxes, b.boxes, rtol=1e-4, atol=1e-3)


def test_spatial_non_divisible_height(tiny_model_and_state):
    """H=96 over 8 devices → P3 level has 12 rows, P7 has 1: GSPMD pads."""
    model, state = tiny_model_and_state
    config = DetectConfig(pre_nms_size=32, max_detections=5)
    hw = (96, 64)
    rng = np.random.default_rng(1)
    images = jnp.asarray(rng.normal(0, 1, (1, *hw, 3)).astype(np.float32))
    plain = make_detect_fn(model, hw, config)
    spatial = make_detect_fn_spatial(model, hw, config, mesh=make_mesh(8))
    a = jax.device_get(plain(state, images))
    b = jax.device_get(spatial(state, images))
    np.testing.assert_array_equal(a.valid, b.valid)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_allclose(a.scores, b.scores, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(a.boxes, b.boxes, rtol=1e-4, atol=1e-3)


@pytest.mark.slow
def test_spatial_eval_bf16_flagship_width_matches():
    """bf16 at flagship head width is exactly the regime where the spatial
    TRAIN step is miscompiled (train/step.py f32 gate, round 4) — pin that
    the forward-only EVAL program is clean there: detections from the
    H-sharded program are IDENTICAL to the unsharded ones (measured
    bitwise-equal on the CPU mesh; asserted with zero tolerance so any
    future partitioner drift in the inference path is loud)."""
    import optax

    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", norm_kind="gn",
            dtype=jnp.bfloat16,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2), (1, *HW, 3), jax.random.key(0)
    )
    config = DetectConfig(pre_nms_size=64, max_detections=10)
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(0, 1, (2, *HW, 3)).astype(np.float32))
    a = jax.device_get(make_detect_fn(model, HW, config)(state, images))
    b = jax.device_get(
        make_detect_fn_spatial(model, HW, config, mesh=make_mesh(8))(
            state, images
        )
    )
    np.testing.assert_array_equal(a.valid, b.valid)
    np.testing.assert_array_equal(a.labels, b.labels)
    np.testing.assert_array_equal(a.scores, b.scores)
    np.testing.assert_array_equal(a.boxes, b.boxes)
