"""Worker for the multi-process pod bring-up test (one OS process per 'host').

Run by test_pod_launch.py:  python pod_worker.py <coordinator> <num_procs>
<proc_id> <out_dir>.  Each process owns 4 virtual CPU devices, joins the
world via launch/pod.py (the hvd.init/mpirun replacement, SURVEY.md H4),
feeds ITS shard of a deterministic global batch through the shard_map'd
train step, and writes final loss + param checksum for cross-process
comparison.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402


def main(
    coordinator: str,
    num_processes: int,
    process_id: int,
    out_dir: str,
    flavor: str = "plain",
):
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
    from batchai_retinanet_horovod_coco_tpu.launch import (
        DistributedConfig,
        initialize_distributed,
        shard_info,
    )
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import make_mesh_2d
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.train.loop import (
        LoopConfig,
        run_training,
    )

    initialize_distributed(
        DistributedConfig(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    )
    assert jax.process_count() == num_processes
    assert len(jax.devices()) == 4 * num_processes
    shard_index, shard_count = shard_info()
    assert (shard_index, shard_count) == (process_id, num_processes)

    hw = (64, 64)
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=np.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *hw, 3), jax.random.key(0)
    )

    # One image per (virtual) device, whatever the world size: 8 at the
    # 2-process world, 16 at the 4-process one.
    global_batch = 4 * num_processes
    local = global_batch // num_processes

    def stream():
        # Deterministic GLOBAL batch; each process slices its contiguous
        # shard (make_array_from_process_local_data concatenates in process
        # order, matching a global array sharded over the device axis).
        rng = np.random.default_rng(0)
        images = rng.normal(0, 1, (global_batch, *hw, 3)).astype(np.float32)
        boxes = np.tile(
            np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (global_batch, 1, 1)
        )
        sl = slice(process_id * local, (process_id + 1) * local)
        while True:
            yield Batch(
                images=images[sl],
                gt_boxes=boxes[sl],
                gt_labels=np.ones((local, 1), np.int32),
                gt_mask=np.ones((local, 1), bool),
                image_ids=np.arange(local, dtype=np.int64),
                scales=np.ones((local,), np.float32),
                valid=np.ones((local,), bool),
            )

    if flavor == "spatial":
        # 2-D data x space mesh SPANNING all processes (VERDICT r3
        # missing #2: --spatial-shards had only ever run single-process).
        # space=2 stays within each host's 4 devices (the make_mesh_2d
        # guard) and inside the supported sharding envelope
        # (train/step.py::make_train_step_spatial): each host's 2x2 device
        # block holds 2 data rows x 2 H-halves of its own images.  Sized
        # from the world so any nprocs works, not just 2.
        mesh = make_mesh_2d(2 * num_processes, 2)
    else:
        mesh = make_mesh()  # all 4*nprocs global devices, 1-D data
    state = run_training(
        model, state, stream(), 3,
        LoopConfig(total_steps=3, log_every=0), mesh=mesh,
        # "quantized": the int8-gather allreduce flavor in a REAL 2-process
        # world (VERDICT r2 missing #3 — it only ever ran single-process).
        quantized_allreduce=(flavor == "quantized"),
    )

    loss_like = float(
        sum(float(np.sum(np.asarray(x))) for x in jax.tree.leaves(state.params))
    )
    with open(os.path.join(out_dir, f"result_{process_id}.json"), "w") as f:
        json.dump({"param_sum": loss_like, "step": int(state.step)}, f)


if __name__ == "__main__":
    main(
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5] if len(sys.argv) > 5 else "plain",
    )
