"""Worker for the fault-injection test (SURVEY.md §5.3).

Run by test_fault_injection.py:
    python fault_worker.py <out_dir> <total_steps> <die_before_step>

Trains a tiny RetinaNet on a 4-virtual-device CPU mesh with checkpointing
every 2 steps and per-step JSONL loss logging.  ``die_before_step > 0``
injects the fault: the process SIGKILLs itself (no cleanup, no atexit — the
same abrupt death as a preempted/failed host) right before fetching the
batch for that step.  The relaunch (same command, die_before_step=0)
auto-resumes from the latest complete checkpoint; batches are a pure
function of the step index, so the post-resume loss trajectory must be
bitwise identical to an uninterrupted golden run — which is exactly the
fail-stop + job-retry recovery model of the reference stack (Batch AI
restarts the mpirun job from the last snapshot), minus the lost work.
"""

import json
import os
import signal
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402


def batch_for_step(step: int, hw, batch_size: int):
    """Deterministic batch for a given global step (resume-safe stream)."""
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch

    rng = np.random.default_rng(1000 + step)
    images = rng.normal(0, 1, (batch_size, *hw, 3)).astype(np.float32)
    boxes = np.tile(
        np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (batch_size, 1, 1)
    )
    return Batch(
        images=images,
        gt_boxes=boxes,
        gt_labels=np.ones((batch_size, 1), np.int32),
        gt_mask=np.ones((batch_size, 1), bool),
        image_ids=np.arange(batch_size, dtype=np.int64),
        scales=np.ones((batch_size,), np.float32),
        valid=np.ones((batch_size,), bool),
    )


def main(out_dir: str, total_steps: int, die_before_step: int):
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.train.loop import (
        LoopConfig,
        run_training,
    )
    from batchai_retinanet_horovod_coco_tpu.utils import checkpoint as ckpt_lib
    from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger

    hw = (64, 64)
    batch_size = 4
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=np.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *hw, 3), jax.random.key(0)
    )

    ckpt_dir = os.path.join(out_dir, "ckpt")
    start = ckpt_lib.latest_step(ckpt_dir) or 0

    def stream():
        step = start
        while True:
            step += 1
            if step == die_before_step:
                os.kill(os.getpid(), signal.SIGKILL)  # abrupt host death
            yield batch_for_step(step, hw, batch_size)

    state = run_training(
        model, state, stream(), 3,
        LoopConfig(
            total_steps=total_steps,
            log_every=1,
            checkpoint_every=2,
            checkpoint_dir=ckpt_dir,
            resume=True,
        ),
        mesh=make_mesh(),
        logger=MetricLogger(os.path.join(out_dir, "logs"), stdout=False),
    )

    param_sum = float(
        sum(float(np.sum(np.asarray(x))) for x in jax.tree.leaves(state.params))
    )
    with open(os.path.join(out_dir, "result.json"), "w") as f:
        json.dump({"param_sum": param_sum, "step": int(state.step)}, f)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
