"""Scale evidence toward the 256-chip claim (VERDICT r4 missing #1).

Multi-chip TPU hardware is unavailable to this rig, so BASELINE.json's
≥90% 8→256 scaling target is argued from measured single-chip numbers
plus one structural premise: the DP step's gradient synchronization
compiles to a handful of fused collectives moving ≈152 MB (PARITY.md
"Scaling-efficiency analysis").  These tests make the premise — and the
sharding structure at a 32-device world — executable facts instead of
prose:

- the HLO collective audit (scripts/audit_collectives.py) pins that the
  flagship-width DP step contains exactly ONE variadic all-reduce whose
  payload is the full f32 gradient tree, independent of device count
  (n=8 and n=32), and that the ZeRO flavor replaces it with
  reduce-scatter/all-gather;
- the driver's dryrun (parity asserted in-process against the
  single-device step) runs green at n=32, 4x the artifact's width;
- the DRYRUN_FULL_WIDTH=1 opt-in (VERDICT r4 weak #2) cannot rot:
  the full-parameter-count parity flavor is exercised here.

All subprocess-based: each needs its own device count / env, and the
test session is pinned to an 8-device CPU platform.
"""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_AUDIT = os.path.join(_REPO, "scripts", "audit_collectives.py")
_ENTRY = os.path.join(_REPO, "__graft_entry__.py")


def _run(cmd, env_extra=None, timeout=1500):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env.update(env_extra or {})
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_REPO,
    )
    assert proc.returncode == 0, (
        f"{' '.join(cmd)} failed (exit {proc.returncode}):\n"
        f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-3000:]}"
    )
    return proc.stdout


def _audit(devices: int, *flags: str) -> dict:
    out = _run([sys.executable, _AUDIT, "--devices", str(devices),
                "--json", *flags])
    return json.loads(out.strip().splitlines()[-1])


@pytest.mark.slow
def test_dp_gradient_allreduce_is_one_fused_collective():
    """The premise of PARITY's 3.0 ms scaling arithmetic, pinned: at
    flagship width the optimized DP step contains exactly ONE all-reduce
    (XLA's combiner fuses the whole gradient tree plus the pmean'd
    metrics into a single variadic add-reduction), its payload is the
    full 152 MB f32 gradient size (+ the dozen metric scalars), and
    BOTH facts are independent of device count (n=8 vs n=32) — i.e.
    scaling to more chips changes the collective's group size, never the
    schedule or the bytes."""
    results = [_audit(8), _audit(32)]
    for r in results:
        assert r["width"] == "flagship"
        # The reference-parity headline number: 37.97M flagship params.
        assert abs(r["num_params"] - 37.97e6) < 0.3e6, r["num_params"]
        ar = r["collectives"].get("all-reduce", {"count": 0})
        assert ar["count"] == 1, (
            f"expected ONE fused gradient all-reduce at n={r['devices']}, "
            f"found {r['collectives']}"
        )
        # Payload ≈ the gradient byte count: measured 0.9999x (a ~15 kB
        # sliver of the tree — O(1e-4) of the payload — is optimized out
        # of the combined op) to at most 1.01x (fused metric scalars).
        ratio = ar["payload_bytes"] / r["grad_bytes_f32"]
        assert 0.99 <= ratio < 1.01, (
            f"all-reduce payload {ar['payload_bytes'] / 1e6:.1f} MB vs "
            f"gradient {r['grad_bytes_f32'] / 1e6:.1f} MB (ratio {ratio:.4f})"
        )
        # Nothing else moves data between chips in the DP step.
        others = {k: v for k, v in r["collectives"].items()
                  if k != "all-reduce"}
        assert not others, f"unexpected collectives: {others}"
    # Identical schedule and payload at 8 and 32 devices.
    a8, a32 = (r["collectives"]["all-reduce"] for r in results)
    assert a8 == a32, (a8, a32)


@pytest.mark.slow
def test_zero_flavor_uses_reduce_scatter_and_all_gather():
    """The ZeRO flavor's schedule: gradients reduce-scattered (1/N per
    shard), updated params all-gathered, and NO full-size gradient
    all-reduce (the remaining all-reduce carries only the pmean'd metric
    scalars).  Audited at reduced width: the schedule shape is
    width-independent and the flagship-width audit above covers the
    payload arithmetic."""
    r = _audit(8, "--zero", "--reduced")
    cols = r["collectives"]
    assert cols.get("reduce-scatter", {}).get("count", 0) >= 1, cols
    assert cols.get("all-gather", {}).get("count", 0) >= 1, cols
    # reduce-scatter results are the 1/N gradient shards.
    rs = cols["reduce-scatter"]["payload_bytes"]
    expected_shard = r["grad_bytes_f32"] / r["devices"]
    assert rs < 1.2 * expected_shard, (rs, expected_shard)
    # all-gather reassembles the full f32 param tree.
    ag = cols["all-gather"]["payload_bytes"]
    assert 0.95 * r["grad_bytes_f32"] < ag < 1.35 * r["grad_bytes_f32"], (
        ag, r["grad_bytes_f32"]
    )
    # Any residual all-reduce is metric scalars only (< 1 kB).
    ar = cols.get("all-reduce", {"payload_bytes": 0})
    assert ar["payload_bytes"] < 1024, cols


@pytest.mark.slow
def test_dryrun_multichip_32():
    """The driver artifact's dryrun — all four flavors with parity
    asserted in-process against the single-device step — green at a
    32-device world, 4x the artifact's n=8 (VERDICT r4 missing #1's
    locally executable remainder)."""
    out = _run([sys.executable, _ENTRY, "32"], timeout=2400)
    assert "dryrun_multichip(32): sharded == single-device parity ok" in out
    assert "zero ok" in out
    assert "quantized-allreduce ok" in out
    assert "spatial pure (1 x 2) ok" in out
    assert "spatial combined (data x space = 16 x 2) ok" in out


@pytest.mark.slow
def test_dryrun_full_width():
    """DRYRUN_FULL_WIDTH=1 coverage (VERDICT r4 weak #2): the only
    flavor whose parity claim holds at the FULL flagship parameter count
    must not rot as an untested env opt-in.  n=2 keeps the CPU compile
    tractable; the parity asserts run inside dryrun_multichip."""
    out = _run([sys.executable, _ENTRY, "2"],
               env_extra={"DRYRUN_FULL_WIDTH": "1"}, timeout=2400)
    assert "FULL flagship width (DRYRUN_FULL_WIDTH=1)" in out
    assert "dryrun_multichip(2): sharded == single-device parity ok" in out
