"""Multi-process pod bring-up tests: N 'hosts' x 4 virtual devices each.

The reference stack could not test its launch layer without an Azure
cluster (SURVEY.md §4 'Distributed testing: none'); here the
jax.distributed coordinator path — the mpirun/MPI replacement — runs as
real OS processes on CPU (2-rank worlds for every step flavor, plus a
4-rank / 16-device world), and every rank must finish training with
IDENTICAL replicated params (the correctness claim behind 'no broadcast
callback needed').
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "pod_worker.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _world_env(work_dir) -> dict:
    """Worker env: repo on PYTHONPATH, PRIVATE per-world compilation cache.

    The shared session cache must be excluded — it can hold XLA:CPU AOT
    entries whose target-machine features don't match what a Gloo-enabled
    process expects (each mismatched entry costs a failed-load + recompile,
    widening inter-process skew against Gloo's ~30 s collective timeout).
    """
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_COMPILATION_CACHE_DIR")
    }
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(str(work_dir), "jax_cache")
    return env


def _communicate_all(procs, timeout: int = 600) -> list[str]:
    """communicate() every rank against ONE shared deadline (a per-rank
    timeout would let a multi-rank hang stall nprocs*timeout before
    failing); on expiry, kill AND REAP all survivors (no zombies, no
    leaked collectives) and re-raise with the ranks' output tails
    attached — the Gloo/XLA stall signature lives in the merged stdout
    and would otherwise be discarded."""
    import time

    deadline = time.monotonic() + timeout
    outs = []
    try:
        for p in procs:
            remaining = max(0.0, deadline - time.monotonic())
            outs.append(p.communicate(timeout=remaining)[0].decode())
    except subprocess.TimeoutExpired as e:
        tails = []
        for i, p in enumerate(procs):
            if p.poll() is None:
                p.kill()
            out = p.communicate()[0].decode()  # reaps; collects the tail
            tails.append(f"--- rank {i} tail ---\n{out[-1500:]}")
        raise AssertionError(
            f"world timed out after {timeout}s; rank outputs:\n"
            + "\n".join(tails)
        ) from e
    return outs


def _run_bringup_world(tmp_path, flavor: str, nprocs: int) -> list[dict]:
    """Launch ``nprocs`` OS-process ranks of pod_worker; return results."""
    coordinator = f"127.0.0.1:{free_port()}"
    env = _world_env(tmp_path)
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coordinator, str(nprocs), str(i),
             str(tmp_path), flavor],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(nprocs)
    ]
    outs = _communicate_all(procs)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = []
    for i in range(nprocs):
        with open(tmp_path / f"result_{i}.json") as f:
            results.append(json.load(f))
    assert all(r["step"] == 3 for r in results)
    # Replicated state must be identical across hosts (psum'd grads, same
    # init PRNG) — the property Horovod needed broadcast callbacks for.
    # Quantized flavor included: every process dequantizes the same
    # gathered bytes, so bitwise cross-host equality must still hold.
    assert len({r["param_sum"] for r in results}) == 1
    return results


@pytest.mark.slow
@pytest.mark.parametrize("flavor", ["plain", "quantized", "spatial"])
def test_two_process_pod(tmp_path, flavor):
    """2-host bring-up for the plain, int8-quantized-allreduce, AND
    spatially partitioned step flavors (VERDICT r2 missing #3 /
    r3 missing #2: each had only ever run single-process).  "spatial"
    trains on a 2-D data x space mesh spanning both processes' devices —
    with ZeRO's own ckpt/resume world below, all FOUR flavors now have
    real multi-process coverage."""
    _run_bringup_world(tmp_path, flavor, nprocs=2)


@pytest.mark.slow
def test_four_process_pod(tmp_path):
    """4-host bring-up (16 virtual devices): the collective schedule over
    >2 ranks is a genuinely different Gloo/XLA code path from the
    pairwise 2-rank ring, and the compile barrier must hold FOUR
    processes through their cold compiles.  Same bitwise cross-host
    param-equality contract."""
    _run_bringup_world(tmp_path, "plain", nprocs=4)


_CKPT_WORKER = os.path.join(os.path.dirname(__file__), "pod_ckpt_eval_worker.py")


class _GlooSkewError(AssertionError):
    """A world died on Gloo's hardcoded ~30 s collective read timeout.

    Not a correctness failure: the CPU-collective timeout has no jaxlib
    knob, while the checkpoint/resume phases sequentially compile several
    long-running programs per process — OS-scheduling skew between the two
    processes occasionally exceeds 30 s and the first collective one side
    reaches alone dies (observed round 3 on the ZeRO resume phase, which
    compiles the most programs)."""


def _run_world(worker, work_dir, phase, flavor="plain", nprocs=2):
    env = _world_env(work_dir)  # private per-attempt compilation cache
    coordinator = f"127.0.0.1:{free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, str(nprocs), str(i),
             str(work_dir), phase, flavor],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(nprocs)
    ]
    outs = _communicate_all(procs)
    failing = [out for p, out in zip(procs, outs) if p.returncode]
    # Classify as benign skew only when EVERY failing worker shows the
    # Gloo signature: a real crash on one rank also kills its peer with
    # "Connection closed by peer", but the crashing rank's own output
    # then carries a non-Gloo traceback and must fail the test normally.
    if failing and all(
        "Gloo" in out
        and ("Read timeout" in out or "Connection closed by peer" in out)
        for out in failing
    ):
        raise _GlooSkewError(outs[0][-1500:] + outs[1][-1500:])
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker ({phase}) failed:\n{out[-3000:]}"


def _run_ckpt_eval_phases(tmp_path, flavor, nprocs=2, resume_phase="resume"):
    """Run the train -> kill -> resume sequence; returns the work dir.

    Retries ONCE, in a FRESH work dir, if a phase dies on the Gloo
    collective-timeout signature (_GlooSkewError): every correctness
    assertion lives inside the workers and re-runs from scratch, so the
    retry cannot mask a real failure — it only tolerates the
    environment's unconfigurable 30 s collective timeout.  The phases
    share one per-attempt compilation cache, so the resume phase (the
    skew-prone one: most programs) cache-hits what train compiled.
    """
    for attempt in (0, 1):
        work_dir = tmp_path / f"attempt{attempt}"
        work_dir.mkdir()
        os.symlink(tmp_path / "data", work_dir / "data")
        try:
            _run_world(
                _CKPT_WORKER, work_dir, "train", flavor=flavor,
                nprocs=nprocs,
            )
            assert (work_dir / "ckpt").exists()
            _run_world(
                _CKPT_WORKER, work_dir, resume_phase, flavor=flavor,
                nprocs=nprocs,
            )
            return work_dir
        except _GlooSkewError:
            if attempt:
                raise


@pytest.mark.slow
def test_two_process_checkpoint_resume_and_sharded_eval(tmp_path):
    """VERDICT r1 weak #7: multi-host orbax save → kill → resume → sharded
    eval, with sharded == unsharded metric parity asserted in-worker."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    # Dataset created ONCE here; both worker processes only read it.
    make_synthetic_coco(
        str(tmp_path / "data"), num_images=6, num_classes=3,
        image_size=(64, 64), seed=5, split="val",
    )
    work_dir = _run_ckpt_eval_phases(tmp_path, flavor="plain")

    results = []
    for i in range(2):
        with open(work_dir / f"eval_{i}.json") as f:
            results.append(json.load(f))
    assert results[0]["step"] == results[1]["step"] == 5
    # Post-gather metrics identical on every process (same merged dt list).
    assert results[0]["metrics"] == results[1]["metrics"]
    # Process 0's in-worker parity assert ran (full_metrics recorded).
    assert "full_metrics" in results[0]


@pytest.mark.slow
def test_four_process_checkpoint_resume(tmp_path):
    """VERDICT r4 stretch #9: carry the §5.4 checkpoint/resume evidence
    to the widest world the box supports — 4 hosts x 4 devices.  Orbax
    save fan-in from FOUR processes (PARITY's stated residual risk),
    kill, restore into a fresh 4-process world, train on, and every
    rank's replicated params must be identical.  Eval-free resume phase:
    the per-rank eval tails would serialize on this box's single core
    and blow the coordination service's ~30 s shutdown barrier at 4
    ranks — the sharded-eval parity claim keeps its 2-process
    coverage in the tests below."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    make_synthetic_coco(
        str(tmp_path / "data"), num_images=8, num_classes=3,
        image_size=(64, 64), seed=5, split="val",
    )
    work_dir = _run_ckpt_eval_phases(
        tmp_path, flavor="plain", nprocs=4, resume_phase="resume_noeval"
    )

    results = []
    for i in range(4):
        with open(work_dir / f"eval_{i}.json") as f:
            results.append(json.load(f))
    assert all(r["step"] == 5 for r in results)
    assert len({r["param_sum"] for r in results}) == 1


@pytest.mark.slow
def test_two_process_zero_checkpoint_resume_and_sharded_eval(tmp_path):
    """VERDICT r2 missing #3: the --shard-weight-update flavor in a REAL
    2-process world — train with the sharded optimizer state, checkpoint,
    kill, resume in a fresh world (the multi-host ZeRO restore branch),
    then run the sharded eval (which must drop the non-addressable
    opt_state before pulling state to host, ADVICE r2).  The worker also
    asserts bitwise parity of the resumed run against an uninterrupted one
    — a wrong momentum restore cannot hide."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    make_synthetic_coco(
        str(tmp_path / "data"), num_images=6, num_classes=3,
        image_size=(64, 64), seed=5, split="val",
    )
    work_dir = _run_ckpt_eval_phases(tmp_path, flavor="zero")

    results = []
    for i in range(2):
        with open(work_dir / f"eval_{i}.json") as f:
            results.append(json.load(f))
    assert results[0]["step"] == results[1]["step"] == 5
    assert results[0]["metrics"] == results[1]["metrics"]
    assert "full_metrics" in results[0]
