"""Multi-process pod bring-up test: 2 'hosts' x 4 virtual devices.

The reference stack could not test its launch layer without an Azure
cluster (SURVEY.md §4 'Distributed testing: none'); here the
jax.distributed coordinator path — the mpirun/MPI replacement — runs as two
real OS processes on CPU, and both must finish training with IDENTICAL
replicated params (the correctness claim behind 'no broadcast callback
needed').
"""

import json
import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "pod_worker.py")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
@pytest.mark.parametrize("flavor", ["plain", "quantized"])
def test_two_process_pod(tmp_path, flavor):
    """2-host bring-up for the plain AND int8-quantized allreduce step
    flavors (VERDICT r2 missing #3: quantized had only ever run
    single-process)."""
    coordinator = f"127.0.0.1:{free_port()}"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, coordinator, "2", str(i), str(tmp_path),
             flavor],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = []
    for i in range(2):
        with open(tmp_path / f"result_{i}.json") as f:
            results.append(json.load(f))
    assert results[0]["step"] == results[1]["step"] == 3
    # Replicated state must be identical across hosts (psum'd grads, same
    # init PRNG) — the property Horovod needed broadcast callbacks for.
    # Quantized flavor included: every process dequantizes the same
    # gathered bytes, so bitwise cross-host equality must still hold.
    assert results[0]["param_sum"] == results[1]["param_sum"]


_CKPT_WORKER = os.path.join(os.path.dirname(__file__), "pod_ckpt_eval_worker.py")


def _run_world(worker, tmp_path, phase, flavor="plain"):
    coordinator = f"127.0.0.1:{free_port()}"
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker, coordinator, "2", str(i), str(tmp_path),
             phase, flavor],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker ({phase}) failed:\n{out[-3000:]}"


@pytest.mark.slow
def test_two_process_checkpoint_resume_and_sharded_eval(tmp_path):
    """VERDICT r1 weak #7: multi-host orbax save → kill → resume → sharded
    eval, with sharded == unsharded metric parity asserted in-worker."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    # Dataset created ONCE here; both worker processes only read it.
    make_synthetic_coco(
        str(tmp_path / "data"), num_images=6, num_classes=3,
        image_size=(64, 64), seed=5, split="val",
    )
    _run_world(_CKPT_WORKER, tmp_path, "train")
    assert (tmp_path / "ckpt").exists()
    _run_world(_CKPT_WORKER, tmp_path, "resume")

    results = []
    for i in range(2):
        with open(tmp_path / f"eval_{i}.json") as f:
            results.append(json.load(f))
    assert results[0]["step"] == results[1]["step"] == 5
    # Post-gather metrics identical on every process (same merged dt list).
    assert results[0]["metrics"] == results[1]["metrics"]
    # Process 0's in-worker parity assert ran (full_metrics recorded).
    assert "full_metrics" in results[0]


@pytest.mark.slow
def test_two_process_zero_checkpoint_resume_and_sharded_eval(tmp_path):
    """VERDICT r2 missing #3: the --shard-weight-update flavor in a REAL
    2-process world — train with the sharded optimizer state, checkpoint,
    kill, resume in a fresh world (the multi-host ZeRO restore branch),
    then run the sharded eval (which must drop the non-addressable
    opt_state before pulling state to host, ADVICE r2).  The worker also
    asserts bitwise parity of the resumed run against an uninterrupted one
    — a wrong momentum restore cannot hide."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    make_synthetic_coco(
        str(tmp_path / "data"), num_images=6, num_classes=3,
        image_size=(64, 64), seed=5, split="val",
    )
    _run_world(_CKPT_WORKER, tmp_path, "train", flavor="zero")
    assert (tmp_path / "ckpt").exists()
    _run_world(_CKPT_WORKER, tmp_path, "resume", flavor="zero")

    results = []
    for i in range(2):
        with open(tmp_path / f"eval_{i}.json") as f:
            results.append(json.load(f))
    assert results[0]["step"] == results[1]["step"] == 5
    assert results[0]["metrics"] == results[1]["metrics"]
    assert "full_metrics" in results[0]
