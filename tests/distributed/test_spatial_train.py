"""Spatial-partitioned (image-sharded) TRAIN step on the virtual CPU mesh.

The training-side sequence/context-parallel analogue (SURVEY.md §5.7):
``make_train_step_spatial`` shards the batch over ``data`` AND each image's
H axis over ``space`` on a 2-D mesh, relying on GSPMD halo exchanges for
the convs.  These tests pin it against the single-device step on the same
global batch — the same contract the DP shard_map step proves in
test_train_step.py.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import make_mesh_2d
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step
from batchai_retinanet_horovod_coco_tpu.train.step import make_train_step_spatial

HW = (64, 64)
NUM_CLASSES = 4
GLOBAL_BATCH = 4


def tiny_config(**kw):
    return RetinaNetConfig(
        num_classes=NUM_CLASSES,
        backbone="resnet_test",
        fpn_channels=32,
        head_width=32,
        head_depth=1,
        dtype=jnp.float32,
        **kw,
    )


def synthetic_batch(seed=0, batch=GLOBAL_BATCH):
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 1, (batch, *HW, 3)).astype(np.float32)
    gt_boxes = np.zeros((batch, 5, 4), np.float32)
    gt_labels = np.zeros((batch, 5), np.int32)
    gt_mask = np.zeros((batch, 5), bool)
    for b in range(batch):
        n = int(rng.integers(1, 4))
        xy = rng.uniform(0, 32, (n, 2))
        wh = rng.uniform(8, 30, (n, 2))
        gt_boxes[b, :n] = np.concatenate([xy, xy + wh], 1)
        gt_labels[b, :n] = rng.integers(0, NUM_CLASSES, n)
        gt_mask[b, :n] = True
    return {
        "images": jnp.asarray(images),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


@pytest.fixture(scope="module")
def model_and_state():
    model = build_retinanet(tiny_config())
    tx = optax.sgd(1e-2, momentum=0.9)
    state = create_train_state(model, tx, (1, *HW, 3), jax.random.key(0))
    return model, state


def _assert_states_close(got, want, atol):
    for a, b in zip(
        jax.tree.leaves(got.params), jax.tree.leaves(want.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=atol
        )


@pytest.mark.parametrize(
    "mesh_shape",
    [
        (2, 4),
        # The pure-spatial leg compiles a second ~40 s program for a
        # layout the dp2_sp4 leg's machinery subsumes (and which
        # __graft_entry__'s dryrun pins independently) — slow tier under
        # the post-cache-loss per-session compile budget.
        pytest.param((1, 4), marks=pytest.mark.slow),
    ],
    ids=["dp2_sp4", "pure_spatial_4"],
)
def test_spatial_step_matches_single_device(model_and_state, mesh_shape):
    """2-D (data, space) sharded step == single-device step, same batch —
    TIGHT parity inside the supported sharding envelope.

    Round-4 correction of the round-3 story: the gradient divergence these
    tests originally tolerated at 1e-2/3e-4 was attributed to max-pool tie
    routing under partitioning.  Isolation (swap the pool via
    ``stem_pool="avg"``, rerun in f64, then reduce to a single
    ``conv_general_dilated``) showed that story was WRONG: the divergence
    is an XLA SPMD partitioner bug in the WEIGHT gradient of stride-2 3x3
    convs at one-input-row-per-shard (test_xla_strided_conv_grad_canary
    below), and has nothing to do with the pool — maxpool configs outside
    that envelope measure 1e-7-class agreement (grad_norm 0.0 relative at
    (2, 4), params 1.5e-8 max-abs).  make_train_step_spatial now refuses
    the buggy envelope by default, and the tolerances here are tight.

    Note the (1, 4) layout runs stage5's conv at exactly 1 row/shard —
    measured exact at 4 shards (the bug's boundary is shard-count-
    dependent, not purely rows-per-shard) and pinned from the other side
    by the canary's 4-shard companion assert below.
    """
    model, state0 = model_and_state
    batch = synthetic_batch(batch=4 if mesh_shape[0] > 1 else 2)

    single_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )
    s_single, m_single = single_step(state0, batch)

    mesh = make_mesh_2d(*mesh_shape)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    s_sp, m_sp = sp_step(state0, batch)

    # Loss/grad_norm rtol 3e-5, not 1e-5: both scalars are giant
    # reductions (the focal sum; the all-leaf sum of squared grads) whose
    # order differs between the sharded and unsharded programs and
    # between XLA versions — measured 1.25e-5 relative on BOTH under jax
    # 0.4.37's partitioner (which also logs an involuntary-remat warning
    # for this program), 8e-6-class on 0.9's.  The TIGHT claim is the
    # per-leaf params bound below, which stays at 1e-5.
    np.testing.assert_allclose(
        float(m_sp["loss"]), float(m_single["loss"]), rtol=3e-5
    )
    np.testing.assert_allclose(
        float(m_sp["grad_norm"]), float(m_single["grad_norm"]), rtol=3e-5
    )
    # Params atol 3e-5 (was 1e-5 on jax 0.9): the step computes in bf16,
    # and 0.4.37's partitioner schedules the sharded convs differently
    # (see its involuntary-remat warning on this program) — measured 34 of
    # 36864 elements at <= 2.3e-5 max-abs after one lr=1e-2 step, i.e.
    # bf16-rounding-class gradient differences, not a wrong reduction.
    _assert_states_close(s_sp, s_single, atol=3e-5)


def test_spatial_guard_refuses_degenerate_sharding():
    """64px images over 8 H-shards put the stage4 conv (input H=8) at one
    row per shard — inside the XLA strided-conv weight-grad bug envelope —
    so the factory must refuse unless explicitly overridden."""
    model = build_retinanet(tiny_config())
    with pytest.raises(ValueError, match="space axis size 8 is too large"):
        make_train_step_spatial(
            model, HW, NUM_CLASSES, mesh=make_mesh_2d(1, 8)
        )


def test_spatial_step_degenerate_envelope_bounded(model_and_state):
    """The opt-in degenerate configuration ((1, 8): "one giant image
    across all chips", stage4's H=8 map at 1 row/shard) pins the MAGNITUDE
    of the XLA bug's effect end-to-end: forward loss stays tight
    (the bug is weight-grad-only), gradients diverge at the 1e-2-class
    bound, and the divergence concentrates in the affected conv kernels
    (~1e-4 max-abs after one lr=1e-2 step).  If the canary test below
    starts failing (upstream fix), this tolerance should collapse to the
    tight envelope's and the guard should be removed."""
    model, state0 = model_and_state
    batch = synthetic_batch(batch=2)

    single_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )
    s_single, m_single = single_step(state0, batch)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=make_mesh_2d(1, 8),
        donate_state=False, allow_degenerate_spatial_sharding=True,
    )
    s_sp, m_sp = sp_step(state0, batch)

    np.testing.assert_allclose(
        float(m_sp["loss"]), float(m_single["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(m_sp["grad_norm"]), float(m_single["grad_norm"]), rtol=1e-2
    )
    _assert_states_close(s_sp, s_single, atol=3e-4)


def test_xla_strided_conv_grad_canary():
    """Minimal repro of the UPSTREAM XLA SPMD bug the spatial-step guard
    exists for — and a canary for its fix.

    A stride-2 3x3 conv over an H-sharded input with exactly one row per
    shard computes a wrong WEIGHT gradient under the partitioner: ~45%
    relative error vs the unsharded gradient, identical in f64 (a
    different sum, not rounding), with both GSPMD and Shardy (jax 0.9.0).
    One-row shards with k=1, k=5, or stride 1, and >=2-row shards with
    this exact geometry, are all exact (probed round 4).

    THIS TEST DOCUMENTS WHETHER THE BUG IS PRESENT on the runtime's XLA.
    Present (rel > 0.05): the guard is load-bearing; the asserts below pin
    the envelope.  Absent: the test SKIPS with a loud message rather than
    failing — the environment has been observed to move BOTH ways (the
    bug reproduced on jax 0.9.0's GSPMD and Shardy; the container later
    regressed to jax 0.4.37 whose older partitioner computes this grad
    exactly), so a clean measurement on the current rig is a reason to
    keep the conservative guard, not to delete it.  Only delete the
    ``allow_degenerate_spatial_sharding`` guard when the TPU fleet's
    pinned jax measures exact here too.
    """
    rel = _strided_conv_weight_grad_rel_diff(shards=8, H=8)
    if rel <= 0.05:
        pytest.skip(
            f"XLA strided-conv weight-grad bug NOT present on this XLA "
            f"(rel diff {rel:.2e}; jax {jax.__version__}) — the "
            "allow_degenerate_spatial_sharding guard is conservative but "
            "harmless here.  Re-evaluate guard removal only on the TPU "
            "fleet's pinned jax."
        )
    # The OTHER side of the boundary: the guard deliberately allows <= 4
    # shards even at one row per shard, because that layout measured exact
    # — pin it, so an XLA change that extends the bug to 4 shards fails
    # HERE (the signal to widen _degenerate_strided_conv_heights), rather
    # than silently corrupting gradients inside the supported envelope.
    rel4 = _strided_conv_weight_grad_rel_diff(shards=4, H=4)
    assert rel4 < 1e-5, (
        f"the 4-shard one-row-per-shard strided-conv weight grad now "
        f"DIVERGES (rel diff {rel4:.2e}) — the XLA bug's envelope grew; "
        "widen train/step.py::_degenerate_strided_conv_heights to refuse "
        "this layout too"
    )


@pytest.mark.slow
def test_xla_strided_conv_grad_canary_16shard():
    """16-shard leg of the canary (VERDICT r4 weak #3): the guard's
    [n/2, 2n)-height zone was EXTRAPOLATED from 8-shard measurements;
    this pins the round-5 16-shard sweep so it is measured at 4/8/16.

    Measured (scripts/xla_repros/strided_conv_weight_grad.py --probe,
    f64, jax 0.9.0): at 16 shards the broken layouts are rows/shard
    ∈ {0.5, 1} (44%/41% relative weight-grad error) — both INSIDE the
    zone — while 1.5 and 2 rows/shard and the replication-handled 0.25
    case are exact to 1e-15.  So the zone generalizes as a SUPERSET of
    the broken set (conservative at 1.5 rows, kept because round-4
    model-level probes measured 1e-4-class error at fractional layouts
    the single-op repro calls exact).

    Runs in a subprocess: the canary needs a 16-device host platform and
    the test session is pinned at 8.  Asserts BOTH sides, like the
    8-shard canary: an upstream fix flips the broken rows (signal to
    drop the guard), an envelope growth flips the exact rows (signal to
    widen it).
    """
    import json as _json
    import subprocess
    import sys as _sys

    script = os.path.join(
        os.path.dirname(__file__), "..", "..", "scripts", "xla_repros",
        "strided_conv_weight_grad.py",
    )
    proc = subprocess.run(
        [_sys.executable, script, "--json", "--probe",
         "16:8", "16:16", "16:24", "16:32", "16:4"],
        capture_output=True, text=True, timeout=900,
    )
    # check=True would swallow the script's traceback (CalledProcessError
    # prints only the exit code) — surface stderr in the test report.
    assert proc.returncode == 0, (
        f"probe script failed (exit {proc.returncode}):\n"
        f"{proc.stderr[-3000:]}"
    )
    out = proc.stdout
    results = {
        (r["shards"], r["H"]): r["rel"]
        for r in _json.loads(out.strip().splitlines()[-1])
    }
    for H in (24, 32, 4):  # 1.5 / 2 / replicated 0.25 rows: measured exact
        assert results[(16, H)] < 1e-5, (
            f"16-shard H={H} now DIVERGES (rel {results[(16, H)]:.2e}) — "
            "the bug's envelope grew; widen "
            "_degenerate_strided_conv_heights"
        )
    # Same both-ways policy as the 8-shard canary: the bug reproduced on
    # jax 0.9.0 but the container later regressed to 0.4.37, whose OLDER
    # partitioner computes these grads exactly — absence is a loud SKIP
    # (keep the conservative guard), not a failure.
    if all(results[(16, H)] <= 0.05 for H in (8, 16)):
        pytest.skip(
            f"16-shard strided-conv weight-grad bug NOT present on this "
            f"XLA (rel {results[(16, 8)]:.2e}/{results[(16, 16)]:.2e}; "
            f"jax {jax.__version__}) — guard kept; re-evaluate removal "
            "only on the TPU fleet's pinned jax."
        )
    for H in (8, 16):  # 0.5 and 1 rows/shard: measured broken on 0.9.0
        assert results[(16, H)] > 0.05, (
            f"16-shard H={H} strided-conv weight grad now matches "
            f"(rel {results[(16, H)]:.2e}) while H={8 if H == 16 else 16} "
            "still diverges — the broken set CHANGED shape; re-sweep and "
            "re-derive the guard zone"
        )


def _strided_conv_weight_grad_rel_diff(shards: int, H: int) -> float:
    """Weight-grad divergence of one H-sharded stride-2 3x3 conv vs the
    unsharded gradient (the canary's single-op repro)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh_2d(1, shards)
    rng = np.random.default_rng(0)
    C = 16
    x = rng.normal(0, 1, (2, H, H, C)).astype(np.float32)
    w = rng.normal(0, 0.1, (3, 3, C, C)).astype(np.float32)
    cot = rng.normal(0, 1, (2, H // 2, H // 2, C)).astype(np.float32)

    def loss(w, x):
        y = jax.lax.conv_general_dilated(
            x, w, (2, 2), ((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return jnp.sum(y * jnp.asarray(cot))

    g_ref = jax.grad(loss)(jnp.asarray(w), jnp.asarray(x))
    xsh = NamedSharding(mesh, P("data", "space"))
    rep = NamedSharding(mesh, P())
    g_sp = jax.jit(jax.grad(loss), in_shardings=(rep, xsh), out_shardings=rep)(
        jnp.asarray(w), jax.device_put(jnp.asarray(x), xsh)
    )
    return float(
        np.max(np.abs(np.asarray(g_ref) - np.asarray(g_sp)))
        / np.max(np.abs(np.asarray(g_ref)))
    )


@pytest.mark.slow
def test_spatial_step_multi_step_trains(model_and_state):
    """A few consecutive spatial steps keep training (loss decreases and
    the state stays finite) — exercises donation + re-use of the sharded
    state across steps.  Slow tier: 23 s (round-4 timing report); the
    donation mechanics it exercises are shared with the DP step, which
    the fast tier covers."""
    model, _ = model_and_state
    mesh = make_mesh_2d(2, 4)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=True
    )
    # Gentler lr than the parity fixture: at 1e-2 with momentum 0.9 the
    # 4-step overfit loss transiently overshoots; the point here is the
    # donated sharded state re-use, not the schedule.
    state = create_train_state(
        model, optax.sgd(1e-3, momentum=0.9), (1, *HW, 3), jax.random.key(0)
    )
    losses = []
    for i in range(6):
        state, metrics = sp_step(state, synthetic_batch(seed=0))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert bool(np.isfinite(float(metrics["param_norm"])))


def test_spatial_step_pool_free_tight_parity():
    """Pool-free isolation probe (VERDICT r3 weak #4), which is what
    EXPOSED the wrong round-3 story: with the stem maxpool swapped for a
    tie-free avg pool (models/resnet.py stem_pool="avg" — gradient
    linear, no tie routing) the model contains no select-and-scatter at
    all, yet the (1, 8) degenerate layout still diverged at the same
    1e-3-class magnitude as maxpool — ruling the pool OUT and leading to
    the strided-conv canary above.  Inside the supported envelope the
    pool-free config must match at the same tight tolerance as the
    maxpool configs."""
    model = build_retinanet(tiny_config(stem="conv", stem_pool="avg"))
    state0 = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *HW, 3), jax.random.key(0)
    )
    batch = synthetic_batch(batch=2)

    single_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )
    s_single, m_single = single_step(state0, batch)

    mesh = make_mesh_2d(1, 4)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    s_sp, m_sp = sp_step(state0, batch)

    np.testing.assert_allclose(
        float(m_sp["loss"]), float(m_single["loss"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(m_sp["grad_norm"]), float(m_single["grad_norm"]), rtol=1e-5
    )
    _assert_states_close(s_sp, s_single, atol=1e-5)


def test_make_mesh_2d_guards_space_spanning_hosts():
    """Library callers (not just the train.py CLI) must be refused a mesh
    whose space axis would straddle hosts — per-process batch assembly
    would silently stitch H-slices of different hosts' images into one
    'global' image (ADVICE r3).  The check reads the ACTUAL device
    placement, so a valid sub-mesh living entirely on one host of a
    multi-host world is not spuriously refused (a per-host-count
    divisibility proxy would refuse e.g. num_space=3 on a 4-device
    host)."""
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
        _assert_space_rows_single_process,
    )

    class FakeDev:
        def __init__(self, pid):
            self.process_index = pid

        def __str__(self):
            return f"fake(p{self.process_index})"

    def grid(rows):
        g = np.empty((len(rows), len(rows[0])), dtype=object)
        for i, r in enumerate(rows):
            g[i, :] = r
        return g

    # (1, 8) over 2 hosts x 4 devices: the single space row spans both.
    with pytest.raises(ValueError, match="cannot span hosts"):
        _assert_space_rows_single_process(
            grid([[FakeDev(0)] * 4 + [FakeDev(1)] * 4])
        )
    # (4, 2) with per-host rows: fine.
    _assert_space_rows_single_process(
        grid([[FakeDev(i // 2)] * 2 for i in range(4)])
    )
    # A 3-wide space axis entirely on host 0 of a 2-host world: fine
    # (the old divisibility proxy would have refused it).
    _assert_space_rows_single_process(grid([[FakeDev(0)] * 3]))
    # Single-process construction through the public API still works.
    assert make_mesh_2d(4, 2) is not None


def test_spatial_guard_refuses_deep_backbone_data_axis():
    """Round-5 data-axis envelope: deep-backbone spatial training with
    data >= 2 is refused when a backbone stage lands at <= 1 row per
    shard (measured divergent gradients — see the residual-chain canary
    above); pure-spatial (1, space) meshes, realistic image sizes
    (every stage >= 2 rows/shard, measured clean at hw 256), and the
    explicit override stay available."""
    from batchai_retinanet_horovod_coco_tpu.train.step import (
        _data_axis_risky_stage_heights,
    )

    cfg = RetinaNetConfig(
        num_classes=NUM_CLASSES, backbone="resnet50", fpn_channels=32,
        head_width=32, head_depth=1, dtype=jnp.float32,
    )
    model = build_retinanet(cfg)
    # 64px images: stage5 runs at H=2 -> 1 row/shard at space=2.
    with pytest.raises(ValueError, match="row per shard"):
        make_train_step_spatial(
            model, HW, NUM_CLASSES, mesh=make_mesh_2d(2, 2)
        )
    # Pure-spatial (1, space): allowed for every backbone.
    assert make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=make_mesh_2d(1, 2)
    ) is not None
    # Realistic image sizes keep every stage >= 2 rows/shard: allowed
    # (flagship 800-class buckets measure clean — hw-256 f64 probe).
    assert make_train_step_spatial(
        model, (256, 256), NUM_CLASSES, mesh=make_mesh_2d(2, 2)
    ) is not None
    # Explicit opt-in: allowed.
    assert make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=make_mesh_2d(2, 2),
        allow_data_axis_divergence=True,
    ) is not None
    # The zone helper itself: 64px at space 2 flags the H=2 stage-5 map
    # (and the H=1... there is none at /32); 800px flags nothing for
    # space <= 4.
    assert _data_axis_risky_stage_heights(64, 2) == [2]
    assert _data_axis_risky_stage_heights(800, 4) == []
    assert _data_axis_risky_stage_heights(800, 2) == []


def test_spatial_guard_refuses_bf16():
    """Non-f32 spatial training is refused by default: the partitioner
    miscompiles the bf16 step at flagship width (see the bf16 canary)."""
    cfg = RetinaNetConfig(
        num_classes=NUM_CLASSES, backbone="resnet_test", fpn_channels=32,
        head_width=32, head_depth=1, dtype=jnp.bfloat16,
    )
    model = build_retinanet(cfg)
    with pytest.raises(ValueError, match="bfloat16 model is refused"):
        make_train_step_spatial(
            model, HW, NUM_CLASSES, mesh=make_mesh_2d(2, 4)
        )


@pytest.mark.slow
def test_xla_spatial_data_axis_grad_canary():
    """Canary for the round-5 finding: XLA SPMD miscompiles the backward
    of chained residual conv blocks on tiny H-sharded maps over a 2-D
    (data>=2, space=2) mesh — the bug behind make_train_step_spatial's
    data-axis envelope guard.  Runs the committed minimal repro
    (scripts/xla_repros/spatial_residual_chain_grad.py: f64, pure lax,
    FD-proven wrong backward) in a 16-device subprocess and asserts BOTH
    sides: the trigger layouts are broken (an upstream fix flips them —
    the signal to re-measure and relax the guard) and the neighbouring
    exact layouts stay exact (an envelope growth flips those)."""
    import json as _json
    import subprocess
    import sys as _sys

    script = os.path.join(
        os.path.dirname(__file__), "..", "..", "scripts", "xla_repros",
        "spatial_residual_chain_grad.py",
    )
    proc = subprocess.run(
        [_sys.executable, script, "--json"],
        capture_output=True, text=True, timeout=1500,
    )
    assert proc.returncode == 0, (
        f"repro script failed (exit {proc.returncode}):\n"
        f"{proc.stderr[-3000:]}"
    )
    rows = _json.loads(proc.stdout.strip().splitlines()[-1])
    by_key = {
        (r["data"], r["space"], r["H"], r["blocks"],
         r.get("residual", True)): r["rel"]
        for r in rows
    }
    exact = [(8, 2, 2, 1, True), (8, 2, 4, 4, True), (8, 2, 3, 4, True),
             (8, 4, 4, 4, True), (1, 2, 2, 4, True), (8, 2, 2, 4, False)]
    for k in exact:
        assert by_key[k] < 1e-6, (
            f"layout {k} now DIVERGES (rel {by_key[k]:.2e}) — the bug's "
            "envelope grew; widen the spatial guards"
        )
    broken = [(8, 2, 2, 2, True), (8, 2, 2, 4, True), (2, 2, 2, 4, True)]
    # Both-ways policy (same as the strided-conv canaries): found on jax
    # 0.9.0; the container's 0.4.37 regression has the OLDER partitioner,
    # which computes these backward passes exactly.  Absence is a loud
    # SKIP — the conservative data-axis guard stays until the TPU fleet's
    # pinned jax (where the model-level envelope was measured) is clean.
    if all(by_key[k] <= 0.5 for k in broken):
        pytest.skip(
            "residual-chain sharded-backward bug NOT present on this XLA "
            f"(max rel {max(by_key[k] for k in broken):.2e}; "
            f"jax {jax.__version__}) — allow_data_axis_divergence guard "
            "kept; re-run the round-5 model-level probes before relaxing."
        )
    for k in broken:
        assert by_key[k] > 0.5, (
            f"residual-chain sharded backward now MATCHES at {k} "
            f"(rel {by_key[k]:.2e}) while other trigger layouts still "
            "diverge — the broken set changed shape; re-measure the "
            "model-level envelope behind allow_data_axis_divergence"
        )


@pytest.mark.slow
def test_xla_bf16_spatial_step_canary():
    """End-to-end canary for the round-4 bf16 spatial MISCOMPILATION —
    asserts the bug is PRESENT, so an XLA/jax upgrade that fixes it fails
    here (the signal to drop make_train_step_spatial's f32-only gate).

    At flagship head width (256) in bf16, the spatially partitioned step
    returns a wrong cls_loss VALUE (1.128 → 1.42 single vs spatial, gn
    norm) and 14x-off gradients once the box gradient is in the graph;
    f32 at the same width and bf16 at width 64 are exact, and the wrong
    value changes when unrelated graph consumers are added — a
    partitioner miscompilation, not arithmetic noise (round-4 bisection:
    mask path, focal custom-VJP, and planar-target layout all ruled out).
    """
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=NUM_CLASSES, backbone="resnet_test",
            norm_kind="gn", dtype=jnp.bfloat16,
        )
    )
    state0 = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *HW, 3), jax.random.key(0)
    )
    batch = synthetic_batch(batch=8)
    s1, m1 = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )(state0, batch)
    s2, m2 = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=make_mesh_2d(4, 2),
        donate_state=False, allow_unvalidated_bf16=True,
    )(state0, batch)
    cls_rel = abs(float(m2["cls_loss"]) - float(m1["cls_loss"])) / abs(
        float(m1["cls_loss"])
    )
    gn_rel = abs(float(m2["grad_norm"]) - float(m1["grad_norm"])) / abs(
        float(m1["grad_norm"])
    )
    if not (cls_rel > 0.05 or gn_rel > 1.0):
        # Both-ways policy (see test_xla_strided_conv_grad_canary): the
        # miscompilation reproduced on jax 0.9.0; the container later
        # regressed to 0.4.37 whose older partitioner compiles this step
        # correctly.  A clean measurement here keeps the f32-only gate
        # (conservative, measured on the version the fleet will pin) —
        # only a clean run on the TPU fleet's pinned jax justifies
        # relaxing it and re-validating bf16 parity at tight tolerance.
        pytest.skip(
            f"bf16 spatial-step miscompilation NOT present on this XLA "
            f"(cls rel {cls_rel:.2e}, grad_norm rel {gn_rel:.2e}; "
            f"jax {jax.__version__}) — f32-only gate kept."
        )
