"""Spatial-partitioned (image-sharded) TRAIN step on the virtual CPU mesh.

The training-side sequence/context-parallel analogue (SURVEY.md §5.7):
``make_train_step_spatial`` shards the batch over ``data`` AND each image's
H axis over ``space`` on a 2-D mesh, relying on GSPMD halo exchanges for
the convs.  These tests pin it against the single-device step on the same
global batch — the same contract the DP shard_map step proves in
test_train_step.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import make_mesh_2d
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step
from batchai_retinanet_horovod_coco_tpu.train.step import make_train_step_spatial

HW = (64, 64)
NUM_CLASSES = 4
GLOBAL_BATCH = 4


def tiny_config(**kw):
    return RetinaNetConfig(
        num_classes=NUM_CLASSES,
        backbone="resnet_test",
        fpn_channels=32,
        head_width=32,
        head_depth=1,
        dtype=jnp.float32,
        **kw,
    )


def synthetic_batch(seed=0, batch=GLOBAL_BATCH):
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 1, (batch, *HW, 3)).astype(np.float32)
    gt_boxes = np.zeros((batch, 5, 4), np.float32)
    gt_labels = np.zeros((batch, 5), np.int32)
    gt_mask = np.zeros((batch, 5), bool)
    for b in range(batch):
        n = int(rng.integers(1, 4))
        xy = rng.uniform(0, 32, (n, 2))
        wh = rng.uniform(8, 30, (n, 2))
        gt_boxes[b, :n] = np.concatenate([xy, xy + wh], 1)
        gt_labels[b, :n] = rng.integers(0, NUM_CLASSES, n)
        gt_mask[b, :n] = True
    return {
        "images": jnp.asarray(images),
        "gt_boxes": jnp.asarray(gt_boxes),
        "gt_labels": jnp.asarray(gt_labels),
        "gt_mask": jnp.asarray(gt_mask),
    }


@pytest.fixture(scope="module")
def model_and_state():
    model = build_retinanet(tiny_config())
    tx = optax.sgd(1e-2, momentum=0.9)
    state = create_train_state(model, tx, (1, *HW, 3), jax.random.key(0))
    return model, state


def _assert_states_close(got, want, atol):
    for a, b in zip(
        jax.tree.leaves(got.params), jax.tree.leaves(want.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=atol
        )


@pytest.mark.parametrize(
    "mesh_shape", [(2, 4), (1, 8)], ids=["dp2_sp4", "pure_spatial_8"]
)
def test_spatial_step_matches_single_device(model_and_state, mesh_shape):
    """2-D (data, space) sharded step == single-device step, same batch.

    (1, 8) is the "one giant image across all chips" configuration —
    every conv's H axis splits 8 ways and GSPMD's halos carry the
    boundaries.
    """
    model, state0 = model_and_state
    batch = synthetic_batch(batch=4 if mesh_shape[0] > 1 else 2)

    single_step = make_train_step(
        model, HW, NUM_CLASSES, mesh=None, donate_state=False
    )
    s_single, m_single = single_step(state0, batch)

    mesh = make_mesh_2d(*mesh_shape)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    s_sp, m_sp = sp_step(state0, batch)

    # Forward is partition-invariant: tight.
    np.testing.assert_allclose(
        float(m_sp["loss"]), float(m_single["loss"]), rtol=1e-5
    )
    # Gradients are looser for a REAL reason, not just f32 reordering:
    # max-pool backward routes each window's cotangent to its FIRST max,
    # and ReLU inputs tie at exactly 0 densely — which element wins a tie
    # can differ when select_and_scatter is partitioned across H shards.
    # Both routings are valid subgradients (forward values identical);
    # the divergence is bounded and shrinks with fewer shard boundaries
    # ((2, 4) measured ~1e-6, (1, 8) ~4e-3 on grad_norm;
    # params land within ~1e-4 after one lr=1e-2 momentum step).
    np.testing.assert_allclose(
        float(m_sp["grad_norm"]), float(m_single["grad_norm"]), rtol=1e-2
    )
    _assert_states_close(s_sp, s_single, atol=3e-4)


def test_spatial_step_multi_step_trains(model_and_state):
    """A few consecutive spatial steps keep training (loss decreases and
    the state stays finite) — exercises donation + re-use of the sharded
    state across steps."""
    model, _ = model_and_state
    mesh = make_mesh_2d(2, 4)
    sp_step = make_train_step_spatial(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=True
    )
    # Gentler lr than the parity fixture: at 1e-2 with momentum 0.9 the
    # 4-step overfit loss transiently overshoots; the point here is the
    # donated sharded state re-use, not the schedule.
    state = create_train_state(
        model, optax.sgd(1e-3, momentum=0.9), (1, *HW, 3), jax.random.key(0)
    )
    losses = []
    for i in range(6):
        state, metrics = sp_step(state, synthetic_batch(seed=0))
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    assert bool(np.isfinite(float(metrics["param_norm"])))
