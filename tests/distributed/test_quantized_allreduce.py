"""The deprecated ``quantized_pmean`` shim (parallel/quantize.py) on the
8-dev CPU mesh.

ISSUE 13 subsumed the per-leaf quantized allreduce into the comm/
subsystem; this file pins the COMPAT surface — the shim (and the
``make_train_step(quantized_allreduce=True)`` alias the 2-process pod
worker still uses) must keep the old contract: exact-reduce-then-
quantize error bound, small leaves exact (now via the undersized-bucket
rule instead of the per-leaf ``_MIN_QUANTIZE_SIZE`` blind spot), and
non-finite gradients surfacing as NaN.  The subsystem's own claims
(bucketing, error feedback, overlap, ZeRO composition, checkpoints)
live in tests/unit/test_comm.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu.comm import CommConfig
from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.parallel.quantize import (
    quantized_pmean,
)
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step

N = 8

# The old per-leaf threshold lives on as the bucket-level exactness
# floor: CommConfig.min_bucket_bytes == 8192 elements * 4 bytes.
_MIN_QUANTIZE_ELEMS = CommConfig().min_bucket_bytes // 4


def _run_both(tree):
    """(quantized, exact) pmean of a per-device tree on the 8-dev mesh."""
    mesh = make_mesh(N)

    @jax.jit
    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(), check_vma=False
    )
    def both(x):
        per_dev = jax.tree.map(lambda a: a[0], x)  # (1, ...) shard → (...)
        return (
            quantized_pmean(per_dev, DATA_AXIS, N),
            jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), per_dev),
        )

    return both(tree)


def test_matches_pmean_within_bound():
    rng = np.random.default_rng(0)
    big = rng.normal(0, 0.1, (N, 64, 513)).astype(np.float32)  # odd size, pads
    q, exact = _run_both({"w": jnp.asarray(big)})
    exact_np = np.asarray(exact["w"])
    # Per-element bound: quantization step/2 of the reduced tensor's
    # per-block max; bound with the global max (≥ every block max).
    bound = np.abs(exact_np).max() / 254.0 + 1e-7
    np.testing.assert_allclose(np.asarray(q["w"]), exact_np, atol=float(bound))


def test_small_single_leaf_stays_exact():
    """A lone small leaf forms an undersized bucket -> exact path (the
    successor of the old per-leaf _MIN_QUANTIZE_SIZE skip)."""
    rng = np.random.default_rng(1)
    small = rng.normal(0, 1, (N, _MIN_QUANTIZE_ELEMS // 2)).astype(np.float32)
    q, exact = _run_both({"b": jnp.asarray(small)})
    np.testing.assert_array_equal(np.asarray(q["b"]), np.asarray(exact["b"]))


def test_zero_gradients_exact():
    z = jnp.zeros((N, 16, 1024), jnp.float32)
    q, exact = _run_both({"w": z})
    np.testing.assert_array_equal(np.asarray(q["w"]), np.asarray(exact["w"]))


@pytest.mark.slow
def test_train_step_learns_with_quantization():
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=32,
            head_width=32, head_depth=1, dtype=jnp.float32,
        )
    )
    hw = (64, 64)
    rng = np.random.default_rng(3)
    batch = {
        "images": jnp.asarray(rng.normal(0, 1, (8, *hw, 3)).astype(np.float32)),
        "gt_boxes": jnp.asarray(
            np.tile(np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (8, 1, 1))
        ),
        "gt_labels": jnp.ones((8, 1), jnp.int32),
        "gt_mask": jnp.ones((8, 1), bool),
    }
    mesh = make_mesh(N)

    def train_n(quantized, steps=12):
        state = create_train_state(
            model, optax.adam(1e-3), (1, *hw, 3), jax.random.key(0)
        )
        step = make_train_step(
            model, hw, 3, mesh=mesh, donate_state=False,
            quantized_allreduce=quantized,
        )
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    q_losses = train_n(True)
    e_losses = train_n(False)
    assert q_losses[-1] < q_losses[0], "quantized step failed to learn"
    # Step 1 (identical init, loss computed pre-update) must match exactly;
    # trajectories stay close — int8 on reduced grads is a tiny perturbation.
    np.testing.assert_allclose(q_losses[0], e_losses[0], rtol=1e-6)
    np.testing.assert_allclose(q_losses[-1], e_losses[-1], rtol=0.1)


def test_non_finite_gradients_surface_as_nan():
    """Inf/NaN grads must NOT be laundered into finite int8 garbage — the
    dequantized result goes NaN so the loop's non-finite-loss abort fires
    exactly as it would on the exact-pmean path."""
    rng = np.random.default_rng(2)
    big = rng.normal(0, 0.1, (N, 16, 1024)).astype(np.float32)
    big[3, 5, 100] = np.inf
    q, _ = _run_both({"w": jnp.asarray(big)})
    assert not np.isfinite(np.asarray(q["w"])).all()
