"""Quantized gradient all-reduce (parallel/quantize.py) on the 8-dev CPU mesh.

Three claims: (1) the two-phase reduce-scatter + int8-gather pmean matches
the exact pmean within the analytic error bound (per element ≤ its reduced
shard's max/254, since quantization happens AFTER the exact f32 reduction);
(2) small/odd leaves bypass quantization and stay exact; (3) the full train
step still learns with quantization on (the opt-in --quantized-allreduce
path), and its loss stays close to the exact step's.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
    shard_map,
)

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.parallel.quantize import (
    _MIN_QUANTIZE_SIZE,
    quantized_pmean,
)
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step

N = 8


def _run_both(tree):
    """(quantized, exact) pmean of a per-device tree on the 8-dev mesh."""
    mesh = make_mesh(N)

    @jax.jit
    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P(DATA_AXIS),), out_specs=P(), check_vma=False
    )
    def both(x):
        per_dev = jax.tree.map(lambda a: a[0], x)  # (1, ...) shard → (...)
        return (
            quantized_pmean(per_dev, DATA_AXIS, N),
            jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), per_dev),
        )

    return both(tree)


def test_matches_pmean_within_bound():
    rng = np.random.default_rng(0)
    big = rng.normal(0, 0.1, (N, 64, 513)).astype(np.float32)  # odd size, pads
    q, exact = _run_both({"w": jnp.asarray(big)})
    exact_np = np.asarray(exact["w"])
    # Per-element bound: quantization step/2 of the reduced tensor's
    # per-shard max; bound with the global max (≥ every shard max).
    bound = np.abs(exact_np).max() / 254.0 + 1e-7
    np.testing.assert_allclose(np.asarray(q["w"]), exact_np, atol=float(bound))


def test_small_leaves_stay_exact():
    rng = np.random.default_rng(1)
    small = rng.normal(0, 1, (N, _MIN_QUANTIZE_SIZE // 2)).astype(np.float32)
    q, exact = _run_both({"b": jnp.asarray(small)})
    np.testing.assert_array_equal(np.asarray(q["b"]), np.asarray(exact["b"]))


def test_outlier_does_not_zero_distant_blocks():
    """Per-block scales (ADVICE r2): one huge outlier must not collapse the
    rest of the shard to zero, as a single per-shard scale would (every
    element below max/254 rounds to 0 → 100% relative error)."""
    from batchai_retinanet_horovod_coco_tpu.parallel.quantize import _QUANT_BLOCK

    rng = np.random.default_rng(5)
    shard_len = 8 * _QUANT_BLOCK  # per-device reduced shard, several blocks
    big = rng.normal(0, 1e-3, (N, N * shard_len)).astype(np.float32)
    # One outlier in block 0 of EVERY device's reduced shard (psum_scatter
    # gives device s the flat slice [s*shard_len, (s+1)*shard_len)), so the
    # per-block property is exercised on all shards, not just shard 0.
    for s in range(N):
        big[:, s * shard_len] = 1e3
    q, exact = _run_both({"w": jnp.asarray(big)})
    q_np, e_np = np.asarray(q["w"]), np.asarray(exact["w"])
    # Outside the outlier's block, relative error stays small.
    mask = np.ones_like(e_np, dtype=bool)
    for s in range(N):
        mask[s * shard_len : s * shard_len + _QUANT_BLOCK] = False
    rel = np.abs(q_np[mask] - e_np[mask]) / np.maximum(np.abs(e_np[mask]), 1e-12)
    assert np.median(rel) < 0.05, "distant blocks lost to the outlier's scale"
    # (~1% of N(0,1e-3) entries sit below their block's scale/2 and round to
    # zero legitimately; a per-shard scale would zero essentially ALL of
    # them — the cutoff there is 1e3/254, three decades above the data.)
    assert np.count_nonzero(q_np[mask]) > 0.95 * mask.sum()


def test_zero_gradients_exact():
    z = jnp.zeros((N, 16, 1024), jnp.float32)
    q, exact = _run_both({"w": z})
    np.testing.assert_array_equal(np.asarray(q["w"]), np.asarray(exact["w"]))


@pytest.mark.slow
def test_train_step_learns_with_quantization():
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=32,
            head_width=32, head_depth=1, dtype=jnp.float32,
        )
    )
    hw = (64, 64)
    rng = np.random.default_rng(3)
    batch = {
        "images": jnp.asarray(rng.normal(0, 1, (8, *hw, 3)).astype(np.float32)),
        "gt_boxes": jnp.asarray(
            np.tile(np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (8, 1, 1))
        ),
        "gt_labels": jnp.ones((8, 1), jnp.int32),
        "gt_mask": jnp.ones((8, 1), bool),
    }
    mesh = make_mesh(N)

    def train_n(quantized, steps=12):
        state = create_train_state(
            model, optax.adam(1e-3), (1, *hw, 3), jax.random.key(0)
        )
        step = make_train_step(
            model, hw, 3, mesh=mesh, donate_state=False,
            quantized_allreduce=quantized,
        )
        losses = []
        for _ in range(steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        return losses

    q_losses = train_n(True)
    e_losses = train_n(False)
    assert q_losses[-1] < q_losses[0], "quantized step failed to learn"
    # Step 1 (identical init, loss computed pre-update) must match exactly;
    # trajectories stay close — int8 on reduced grads is a tiny perturbation.
    np.testing.assert_allclose(q_losses[0], e_losses[0], rtol=1e-6)
    np.testing.assert_allclose(q_losses[-1], e_losses[-1], rtol=0.1)


def test_non_finite_gradients_surface_as_nan():
    """Inf/NaN grads must NOT be laundered into finite int8 garbage — the
    dequantized result goes NaN so the loop's non-finite-loss abort fires
    exactly as it would on the exact-pmean path."""
    rng = np.random.default_rng(2)
    big = rng.normal(0, 0.1, (N, 16, 1024)).astype(np.float32)
    big[3, 5, 100] = np.inf
    q, _ = _run_both({"w": jnp.asarray(big)})
    assert not np.isfinite(np.asarray(q["w"])).all()
