"""Fault injection: SIGKILL a training process mid-run, resume, compare.

SURVEY.md §5.3: the reference stack's recovery model is fail-stop — a dead
rank kills the MPI job and Batch AI's job retry restarts from the last
epoch snapshot — and neither layer ever tested it.  This test makes that
model a verified property: a worker process is hard-killed between steps
(after a checkpoint landed), relaunched with auto-resume, and the resumed
run's per-step losses and final parameters must be BITWISE identical to an
uninterrupted golden run fed the same step-indexed batches.
"""

import json
import os
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "fault_worker.py")
TOTAL_STEPS = 6
DIE_BEFORE = 5


def _run(out_dir, die_before_step, expect_kill=False):
    env = {
        k: v
        for k, v in os.environ.items()
        # JAX_COMPILATION_CACHE_DIR must NOT leak into multi-process worlds:
        # the session cache can hold XLA:CPU AOT entries compiled with
        # different target-machine features; each mismatched entry costs a
        # failed-load + recompile (~25-35 s observed), the two processes
        # desynchronize, and the first cross-process collective dies on
        # Gloo's read timeout (reproduced deterministically in round 3 on
        # the ZeRO resume phase, which compiles the most programs).
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_COMPILATION_CACHE_DIR")
    }
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH")) if p
    )
    env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(str(out_dir), "jax_cache")
    proc = subprocess.run(
        [sys.executable, _WORKER, str(out_dir), str(TOTAL_STEPS),
         str(die_before_step)],
        env=env, capture_output=True, timeout=600,
    )
    out = proc.stdout.decode() + proc.stderr.decode()
    if expect_kill:
        assert proc.returncode == -9, f"expected SIGKILL, got {proc.returncode}:\n{out[-3000:]}"
    else:
        assert proc.returncode == 0, f"worker failed:\n{out[-3000:]}"


def _losses(out_dir):
    """step -> last-logged train/loss (replays overwrite earlier entries)."""
    losses = {}
    with open(os.path.join(out_dir, "logs", "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "train/loss" in rec:
                losses[rec["step"]] = rec["train/loss"]
    return losses


@pytest.mark.slow
def test_kill_and_resume_bitwise(tmp_path):
    golden_dir = tmp_path / "golden"
    fault_dir = tmp_path / "fault"
    golden_dir.mkdir()
    fault_dir.mkdir()

    _run(golden_dir, die_before_step=0)

    _run(fault_dir, die_before_step=DIE_BEFORE, expect_kill=True)
    # A COMMITTED checkpoint must have survived the kill (async orbax saves
    # commit atomically; tmp dirs don't count — latest_step ignores them).
    # Without this the relaunch would restart from scratch and the bitwise
    # comparison below would trivially pass without exercising restore.
    # Note: the kill fires inside the BATCH FETCH for DIE_BEFORE, and the
    # loop prefetches 2 batches ahead (train/loop.py _prefetch_to_device),
    # so death lands ~2 steps earlier than DIE_BEFORE — any committed step
    # proves a real mid-run restore (resume starts after it and must still
    # match the golden run bitwise).
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import latest_step

    resumed_from = latest_step(str(fault_dir / "ckpt"))
    assert resumed_from is not None and resumed_from >= 1, (
        f"no committed checkpoint survived the kill (latest={resumed_from})"
    )
    assert resumed_from < TOTAL_STEPS, "kill landed too late to test resume"
    # Relaunch — same command line, auto-resume (the Batch AI job-retry
    # analogue: same binary, picks up the latest snapshot).
    _run(fault_dir, die_before_step=0)

    golden = _losses(golden_dir)
    fault = _losses(fault_dir)
    assert set(golden) == set(range(1, TOTAL_STEPS + 1))
    assert set(fault) == set(golden)
    for step in sorted(golden):
        assert fault[step] == golden[step], (
            f"post-resume loss diverged at step {step}: "
            f"{fault[step]} != {golden[step]}"
        )

    with open(golden_dir / "result.json") as f:
        golden_res = json.load(f)
    with open(fault_dir / "result.json") as f:
        fault_res = json.load(f)
    assert golden_res["step"] == fault_res["step"] == TOTAL_STEPS
    assert golden_res["param_sum"] == fault_res["param_sum"]
