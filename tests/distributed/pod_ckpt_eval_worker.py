"""Worker for the multi-process checkpoint/resume/sharded-eval test.

Run by test_pod_launch.py:  python pod_ckpt_eval_worker.py <coordinator>
<num_procs> <proc_id> <work_dir> <phase>.

Phase "train": join the 2-process world, train 3 steps with orbax
checkpointing every step, exit (the "kill").  Phase "resume": a FRESH
world resumes from the latest checkpoint, trains to step 5, then runs the
SHARDED eval — each process decodes its slice of a synthetic COCO val set,
detects on its local 4-device mesh, and the detections all-gather before
scoring.  Process 0 additionally runs an UNSHARDED reference eval (full
val set, no gather) and asserts the metrics are identical — the claim that
sharding the eval changes nothing but the wall-clock.

Covers VERDICT r1 weak #7: orbax save/restore and eval were untested
beyond one host.

A sixth arg selects the step flavor: "plain" (replicated optimizer) or
"zero" (--shard-weight-update: optimizer state sharded 1/N per device,
VERDICT r2 missing #3) — the zero resume phase additionally trains an
uninterrupted twin and asserts bitwise param parity with the resumed run.
"""

import json
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

HW = (64, 64)
# One image per virtual device: each process contributes 4 (its local
# device count), so the global batch shards the (4 * nprocs)-device mesh
# exactly — 8 at the 2-process world, 16 at the 4-process world.
LOCAL_BATCH = 4


def build(num_classes: int, mesh=None, zero: bool = False):
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=num_classes, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=np.float32,
        )
    )
    tx = optax.sgd(1e-2, momentum=0.9)
    state = create_train_state(
        model, tx, (1, *HW, 3), jax.random.key(0), init_opt_state=not zero
    )
    if zero:
        # Mirror train.py's --shard-weight-update bring-up: params
        # replicated over the GLOBAL mesh, optimizer state initialized
        # directly in its 1/N-per-device layout.
        from batchai_retinanet_horovod_coco_tpu.parallel import (
            init_sharded_opt_state,
            replicated_sharding,
        )

        params = jax.device_put(state.params, replicated_sharding(mesh))
        state = state.replace(
            params=params, opt_state=init_sharded_opt_state(tx, params, mesh)
        )
    return model, state


def train_stream(process_id: int, num_processes: int):
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch

    local = LOCAL_BATCH
    global_batch = LOCAL_BATCH * num_processes
    rng = np.random.default_rng(0)
    images = rng.normal(0, 1, (global_batch, *HW, 3)).astype(np.float32)
    boxes = np.tile(
        np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (global_batch, 1, 1)
    )
    sl = slice(process_id * local, (process_id + 1) * local)
    while True:
        yield Batch(
            images=images[sl],
            gt_boxes=boxes[sl],
            gt_labels=np.ones((local, 1), np.int32),
            gt_mask=np.ones((local, 1), bool),
            image_ids=np.arange(local, dtype=np.int64),
            scales=np.ones((local,), np.float32),
            valid=np.ones((local,), bool),
        )


def main(coordinator, num_processes, process_id, work_dir, phase, flavor="plain"):
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
        run_coco_eval,
    )
    from batchai_retinanet_horovod_coco_tpu.launch import (
        DistributedConfig,
        initialize_distributed,
        shard_info,
    )
    from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import make_local_mesh
    from batchai_retinanet_horovod_coco_tpu.train.loop import (
        LoopConfig,
        run_training,
    )

    initialize_distributed(
        DistributedConfig(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    )
    shard_index, shard_count = shard_info()
    assert (shard_index, shard_count) == (process_id, num_processes)

    ckpt_dir = os.path.join(work_dir, "ckpt")
    dataset = CocoDataset(
        os.path.join(work_dir, "data", "instances_val.json"),
        os.path.join(work_dir, "data", "val"),
    )
    mesh = make_mesh()
    zero = flavor == "zero"
    model, state = build(dataset.num_classes, mesh=mesh, zero=zero)

    # Re-align ranks after the cold init (jit(model.init) serializes
    # across ranks on a single-core box, spreading them past Gloo's
    # ~30 s collective timeout before orbax's first sync_global_processes
    # at 4 ranks) — same mechanism as the loop's compile barrier.
    # Preferred: the coordination-service barrier (gRPC, 10 min budget —
    # the whole POINT is that ranks may be minutes apart, which a device
    # collective's ~30 s timeout cannot absorb).  Its client lives in the
    # private jax._src.distributed module, so a jax upgrade may move it;
    # when that happens, fall back to the public sync_global_devices with
    # a LOUD warning (it still aligns ranks, but only within the Gloo
    # timeout — a silent no-barrier would make this test flake instead).
    _barrier_name = f"worker_init_{phase}"
    try:
        from jax._src import distributed as _dist

        _client = getattr(
            getattr(_dist, "global_state", None), "client", None
        )
    except ImportError:
        _client = None
    if _client is not None:
        _client.wait_at_barrier(_barrier_name, 600_000)
    else:
        import warnings

        warnings.warn(
            "jax._src.distributed client unavailable (jax moved the "
            "private module?): falling back to sync_global_devices for "
            f"the {_barrier_name} barrier — ranks more than ~30s apart "
            "will now hit the Gloo collective timeout"
        )
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(_barrier_name)

    if phase == "train":
        state = run_training(
            model, state, train_stream(process_id, num_processes),
            dataset.num_classes,
            LoopConfig(
                total_steps=3, log_every=0, checkpoint_every=1,
                checkpoint_dir=ckpt_dir,
            ),
            mesh=mesh,
            shard_weight_update=zero,
        )
        assert int(state.step) == 3
        return  # exit = the "kill"; async saves are flushed by the loop

    assert phase in ("resume", "resume_noeval")
    # The restore MUST have something to restore: run_training silently
    # trains from scratch when no complete checkpoint exists, and a
    # from-scratch run satisfies every downstream assert (training is
    # collective-synced), so a failed multi-process save fan-in — the
    # exact risk this test probes — would otherwise pass unnoticed.
    # (Symmetric across ranks: every process checks at the same point,
    # right after the alignment barrier.)
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        latest_step,
    )

    found = latest_step(ckpt_dir)
    assert found == 3, (
        f"train phase left latest checkpoint step {found}, expected 3 — "
        "the multi-process orbax save fan-in failed"
    )
    # Fresh world: run_training restores from the step-3 checkpoint and
    # continues to 5 (same resume path train.py uses).  For the zero
    # flavor this exercises the multi-host restore of the SHARDED
    # optimizer state (VERDICT r2 missing #3: that branch had never run
    # under process_count > 1).
    state = run_training(
        model, state, train_stream(process_id, num_processes),
        dataset.num_classes,
        LoopConfig(
            total_steps=5, log_every=0, checkpoint_every=1,
            checkpoint_dir=ckpt_dir, resume=True,
        ),
        mesh=mesh,
        shard_weight_update=zero,
    )
    assert int(state.step) == 5

    if phase == "resume_noeval":
        # 4-process world (VERDICT r4 stretch #9): the per-rank eval
        # tails serialize on this box's single core, spreading process
        # exits beyond the coordination service's ~30 s shutdown-barrier
        # timeout at 4 ranks — and the sharded-eval parity claim already
        # has 2-process coverage.  This phase carries what the 4-process
        # world uniquely adds: orbax save fan-in from four processes and
        # restore into a fresh 4-process world, with cross-host param
        # equality asserted by the test.  Training is collective-synced,
        # so ranks reach exit nearly together.
        result = {
            "step": int(state.step),
            "param_sum": float(
                np.sum([
                    float(np.sum(np.asarray(x)))
                    for x in jax.tree.leaves(state.params)
                ])
            ),
        }
        with open(
            os.path.join(work_dir, f"eval_{process_id}.json"), "w"
        ) as f:
            json.dump(result, f)
        return

    if zero:
        # Resume-exactness including the sharded momentum: an UNINTERRUPTED
        # 5-step run from the same init and stream must match the resumed
        # run bitwise (momentum influences steps 4-5, so a wrong opt-state
        # restore cannot hide).
        _, fresh = build(dataset.num_classes, mesh=mesh, zero=True)
        fresh = run_training(
            model, fresh, train_stream(process_id, num_processes),
            dataset.num_classes,
            LoopConfig(total_steps=5, log_every=0),
            mesh=mesh,
            shard_weight_update=True,
        )
        for a, b in zip(
            jax.tree.leaves(state.params), jax.tree.leaves(fresh.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    detect_config = DetectConfig()

    def eval_batches(sharded: bool):
        return build_pipeline(
            dataset,
            PipelineConfig(
                batch_size=4, buckets=((64, 64),), min_side=64, max_side=64,
                max_gt=8, num_workers=2, shuffle=False, hflip_prob=0.0,
                shard_index=shard_index if sharded else 0,
                shard_count=shard_count if sharded else 1,
            ),
            train=False,
        )

    # Sharded eval: local data slice + local mesh + cross-process gather.
    # opt_state dropped BEFORE the host pull — under the zero flavor its
    # leaves are sharded across processes (non-addressable from one host),
    # exactly the crash train.py's eval_fn guards against (ADVICE r2).
    host_state = jax.device_get(state.replace(opt_state=()))
    sharded_metrics = run_coco_eval(
        host_state, model, dataset, eval_batches(sharded=True),
        detect_config, mesh=make_local_mesh(), gather=True,
    )

    result = {"step": int(state.step), "metrics": sharded_metrics}
    if process_id == 0:
        # Unsharded reference: full val set on this process, no gather.
        full_metrics = run_coco_eval(
            host_state, model, dataset, eval_batches(sharded=False),
            detect_config, mesh=make_local_mesh(), gather=False,
        )
        for k, v in full_metrics.items():
            assert abs(sharded_metrics[k] - v) < 1e-12, (
                f"sharded eval diverged on {k}: {sharded_metrics[k]} vs {v}"
            )
        result["full_metrics"] = full_metrics
    with open(os.path.join(work_dir, f"eval_{process_id}.json"), "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main(
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4],
        sys.argv[5], sys.argv[6] if len(sys.argv) > 6 else "plain",
    )
