"""Sharded eval == single-device eval (the detection analogue of the
grad-equivalence test).

The reference ran CocoEval on rank 0 only (SURVEY.md M10); here eval shards
the batch over the `data` mesh axis and gathers detections — this pins the
correctness of that path: identical Detections for the same global batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
)
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh


@pytest.fixture(scope="module")
def model_state():
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state

    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2), (1, 64, 64, 3), jax.random.key(3)
    )
    return model, state


def test_sharded_detect_equals_single_device(model_state):
    model, state = model_state
    hw = (64, 64)
    rng = np.random.default_rng(0)
    # uint8 batch: also exercises the on-device normalization under shard_map.
    images = jnp.asarray(
        rng.integers(0, 255, (8, *hw, 3), dtype=np.uint8)
    )
    cfg = DetectConfig(score_threshold=0.0, max_detections=20)

    single = make_detect_fn(model, hw, cfg)(state, images)
    sharded = make_detect_fn(model, hw, cfg, mesh=make_mesh(8))(state, images)

    np.testing.assert_array_equal(
        np.asarray(single.valid), np.asarray(sharded.valid)
    )
    np.testing.assert_array_equal(
        np.asarray(single.labels), np.asarray(sharded.labels)
    )
    np.testing.assert_allclose(
        np.asarray(single.scores), np.asarray(sharded.scores), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(single.boxes), np.asarray(sharded.boxes),
        rtol=1e-4, atol=1e-3,
    )
    assert bool(np.asarray(single.valid).any()), "degenerate test: no detections"
