"""Weight-update sharding (ZeRO-style) equivalence on the 8-device CPU mesh.

The contract: the sharded-update step (reduce-scatter grads → 1/N update with
1/N optimizer state → all_gather params, parallel/zero.py) must produce the
same training trajectory as the replicated pmean step — the only allowed
divergence is float reduction order.
"""

import jax
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel import (
    init_sharded_opt_state,
    make_mesh,
)
from batchai_retinanet_horovod_coco_tpu.train import (
    create_train_state,
    make_train_step,
)
from batchai_retinanet_horovod_coco_tpu.train.optim import (
    OptimizerConfig,
    make_optimizer,
)
from tests.distributed.test_train_step import (
    HW,
    NUM_CLASSES,
    synthetic_batch,
    tiny_config,
)


def make_states(opt_config: OptimizerConfig, mesh):
    """(replicated-mode state, sharded-mode state) with identical params."""
    from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS

    model = build_retinanet(tiny_config())
    tx_full, _ = make_optimizer(opt_config)
    tx_sharded, _ = make_optimizer(opt_config, shard_clip_axis=DATA_AXIS)
    state = create_train_state(model, tx_full, (1, *HW, 3), jax.random.key(0))
    sharded = state.replace(
        tx=tx_sharded,
        opt_state=init_sharded_opt_state(tx_sharded, state.params, mesh),
    )
    return model, state, sharded


def run_steps(step_fn, state, batches):
    for batch in batches:
        state, metrics = step_fn(state, batch)
    return state, metrics


@pytest.mark.parametrize(
    "opt_config",
    [
        # Each flavor costs a ~60 s per-session compile on the CPU mesh
        # (post-cache-loss recalibration; the machine-persistent compile
        # cache is gone — see tests/conftest.py); the fast tier keeps ONE
        # leg, the hardest composition (freeze + ACTIVE clip, which has
        # caught real masking bugs and subsumes the plain baseline's
        # sharded==replicated claim) — the rest run in slow.
        pytest.param(
            OptimizerConfig(optimizer="sgd", warmup_steps=2, total_steps=10),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            OptimizerConfig(optimizer="adam", warmup_steps=0, total_steps=10),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            OptimizerConfig(
                optimizer="sgd", warmup_steps=0, total_steps=10,
                freeze_backbone=True,
            ),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            OptimizerConfig(
                optimizer="sgd", warmup_steps=0, total_steps=10,
                schedule="plateau", plateau_window=2, plateau_patience=1,
            ),
            marks=pytest.mark.slow,
        ),
        # ACTIVE clip + freeze: the norm must cover only trained leaves
        # (multi_transform masks the sharded clip exactly like the
        # replicated one); tiny clip value guarantees the clip fires.
        OptimizerConfig(
            optimizer="sgd", warmup_steps=0, total_steps=10,
            freeze_backbone=True, clip_global_norm=1e-3,
        ),
    ],
    ids=["sgd", "adam", "freeze", "plateau", "freeze-clip-active"],
)
def test_matches_replicated_step(opt_config):
    mesh = make_mesh(8)
    model, state, sharded_state = make_states(opt_config, mesh)

    step = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    zstep = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False,
        shard_weight_update=True,
    )

    batches = [synthetic_batch(seed) for seed in range(3)]
    state, m = run_steps(step, state, batches)
    sharded_state, zm = run_steps(zstep, sharded_state, batches)

    assert int(sharded_state.step) == int(state.step) == 3
    np.testing.assert_allclose(
        float(zm["loss"]), float(m["loss"]), rtol=1e-5
    )
    # The zero path's hand-rolled norm (psum of per-shard square sums over
    # zero-padded flat shards) must equal the replicated optax.global_norm.
    np.testing.assert_allclose(
        float(zm["grad_norm"]), float(m["grad_norm"]), rtol=1e-4
    )
    ref = jax.tree.leaves(state.params)
    got = jax.tree.leaves(sharded_state.params)
    # Adam's g/(sqrt(g^2)+eps) update amplifies reduction-order noise
    # RELATIVELY on near-zero params (measured max-abs ~2e-6 vs updates of
    # ~1e-2/step, with a 1.6e-5 tail element after the torch-geometry
    # padding change), so the bound is absolute, scaled to the update size.
    atol = 3e-5 if opt_config.optimizer == "adam" else 1e-6
    for r, g in zip(ref, got):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-5, atol=atol
        )


def test_opt_state_is_sharded():
    """Sharded leaves live on the data axis; each device holds 1/8."""
    mesh = make_mesh(8)
    opt_config = OptimizerConfig(optimizer="sgd", total_steps=10)
    _, state, sharded_state = make_states(opt_config, mesh)

    replicated_bytes = sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(state.opt_state)
        if hasattr(x, "size")
    )
    leaves = [
        x for x in jax.tree.leaves(sharded_state.opt_state)
        if hasattr(x, "sharding") and x.ndim >= 1
    ]
    assert leaves, "expected sharded momentum leaves"
    for leaf in leaves:
        # Global (N*chunk,), one chunk addressable per device.
        shard = leaf.sharding.shard_shape(leaf.shape)
        assert shard[0] * 8 == leaf.shape[0]
    # Per-device state memory is ~1/8 of the replicated layout.
    per_device = sum(
        int(np.prod(leaf.sharding.shard_shape(leaf.shape)))
        * leaf.dtype.itemsize
        for leaf in leaves
    )
    assert per_device < replicated_bytes / 6


def test_clip_matches_optax_semantics():
    """The manual global-norm clip equals optax.clip_by_global_norm."""
    mesh = make_mesh(8)
    opt_config = OptimizerConfig(
        optimizer="sgd", warmup_steps=0, total_steps=10,
        # Tiny clip so the clip path is ACTIVE (gradients far exceed it).
        clip_global_norm=1e-3,
    )
    model, state, sharded_state = make_states(opt_config, mesh)
    step = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False
    )
    zstep = make_train_step(
        model, HW, NUM_CLASSES, mesh=mesh, donate_state=False,
        shard_weight_update=True,
    )
    batch = synthetic_batch(0)
    state, _ = step(state, batch)
    sharded_state, _ = zstep(sharded_state, batch)
    for r, g in zip(
        jax.tree.leaves(state.params), jax.tree.leaves(sharded_state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=2e-5, atol=1e-7
        )
