"""Overfit → detect → mAP > 0: the full-loop quality gate (SURVEY.md §4.2).

The reference's effective test was "the job runs, loss goes down, CocoEval
prints mAP"; this makes that loop a deterministic assertion: a tiny model
overfits two synthetic scenes in ~120 steps, and the trained detector must
localize each painted box (IoU > 0.5, right class) and score near-perfect
AP under the COCOeval-semantics oracle — exercising train step, detection
(decode + two-stage top-k + fixed-point NMS), and the mAP oracle end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    evaluate_detections,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
)
from batchai_retinanet_horovod_coco_tpu.models import (
    RetinaNetConfig,
    build_retinanet,
)
from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou
from batchai_retinanet_horovod_coco_tpu.train import (
    create_train_state,
    make_train_step,
)

HW = (64, 64)


@pytest.mark.slow
def test_overfit_then_detect_and_map():
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=32,
            head_width=32, head_depth=1, dtype=np.float32,
        )
    )
    state = create_train_state(
        model, optax.adam(1e-3), (1, *HW, 3), jax.random.key(0)
    )
    rng = np.random.default_rng(0)
    images = rng.normal(0, 1, (2, *HW, 3)).astype(np.float32)
    gt = np.array([[[8, 8, 28, 28]], [[30, 30, 56, 52]]], np.float32)
    labels = np.array([[1], [2]], np.int32)
    for b in range(2):  # paint a bright square where each box is
        x1, y1, x2, y2 = gt[b, 0].astype(int)
        images[b, y1:y2, x1:x2] = 3.0
    batch = {
        "images": jnp.asarray(images),
        "gt_boxes": jnp.asarray(gt),
        "gt_labels": jnp.asarray(labels),
        "gt_mask": jnp.ones((2, 1), bool),
    }

    step = make_train_step(model, HW, 3, donate_state=False)
    for _ in range(120):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < 0.1, "failed to overfit two scenes"

    det = make_detect_fn(
        model, HW, DetectConfig(score_threshold=0.05, max_detections=10)
    )(state, batch["images"])

    dt_anns, gt_anns = [], []
    for b in range(2):
        x1, y1, x2, y2 = gt[b, 0]
        gt_anns.append({
            "image_id": b, "category_id": int(labels[b, 0]),
            "bbox": [float(x1), float(y1), float(x2 - x1), float(y2 - y1)],
            "area": float((x2 - x1) * (y2 - y1)), "iscrowd": 0,
        })
        valid = np.asarray(det.valid[b])
        assert valid.any(), f"image {b}: no detections after overfit"
        boxes = np.asarray(det.boxes[b])[valid]
        scores = np.asarray(det.scores[b])[valid]
        labs = np.asarray(det.labels[b])[valid]
        # Top-scoring detection: right class, localized on the painted box.
        top = int(np.argmax(scores))
        assert int(labs[top]) == int(labels[b, 0])
        iou = float(
            np.asarray(pairwise_iou(jnp.asarray(boxes[top : top + 1]),
                                    jnp.asarray(gt[b])))[0, 0]
        )
        assert iou > 0.5, f"image {b}: top detection IoU {iou:.3f}"
        for bx, sc, lb in zip(boxes, scores, labs):
            dt_anns.append({
                "image_id": b, "category_id": int(lb),
                "bbox": [float(bx[0]), float(bx[1]),
                         float(bx[2] - bx[0]), float(bx[3] - bx[1])],
                "score": float(sc),
            })

    stats = evaluate_detections(gt_anns, dt_anns, img_ids=[0, 1])
    assert stats["AP50"] > 0.5, stats
    assert stats["AP"] > 0.25, stats
