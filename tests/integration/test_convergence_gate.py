"""Convergence gate: the REAL training recipe must reach a recorded mAP.

VERDICT r1 weak #4: every quality gate ran ≤120 steps on 2 images with a
hand-rolled optimizer; nothing validated that the actual recipe — linear-
scaled SGD + momentum, warmup, multistep decay, weight decay, gradient
clipping, driven through the train.py CLI — converges on anything bigger.

This gate trains 300 steps on 64 synthetic multi-object images (8-device
CPU mesh, global batch 8 → ~37 epochs) with the full recipe, evaluates
through the same CLI, and asserts AP@0.5 clears a calibrated threshold.

Calibration (2026-07-30, this exact config, CPU mesh):
  - recipe as below (--lr 0.32 → effective 0.01 by the linear-scaling
    rule):  AP=0.136  AP50=0.301  AR100=0.284   (loss 9.5 → 2.4)
  - 10x LR regression (--lr 3.2): grad-clip prevents the NaN abort but
    training is destroyed:  AP=0.004  AP50=0.019  AR100=0.163
  Threshold 0.15 sits 2x under the healthy run and 8x over the broken one,
  so an LR/schedule/weight-decay regression fails the gate while run-to-run
  noise does not.
"""

import pathlib
import sys

import pytest

sys.path.insert(
    0, str(pathlib.Path(__file__).resolve().parents[2])
)  # repo root (train.py)

THRESHOLD_AP50 = 0.15


@pytest.mark.slow
def test_real_recipe_converges(tmp_path):
    from train import main

    common = [
        "synthetic",
        "--synthetic-root", str(tmp_path / "data"),
        "--synthetic-images", "64",
        "--synthetic-size", "64",
        "--image-min-side", "64", "--image-max-side", "64",
        "--backbone", "resnet_test", "--f32",
        "--batch-size", "8", "--num-devices", "8",
        "--workers", "8",
        "--snapshot-path", str(tmp_path / "ckpt"),
        # The real recipe: SGD+momentum (linear-scaling rule), warmup,
        # multistep 10x decays at 2/3 and 8/9 of total, weight decay, clip.
        "--schedule", "multistep",
        "--warmup-steps", "30",
        "--lr", "0.32",
        "--weight-decay", "1e-4",
    ]
    out = main(
        common
        + ["--steps", "300", "--log-every", "50", "--checkpoint-every", "100"]
    )
    assert out["final_step"] == 300

    metrics = main(common + ["--preset", "eval"])
    assert metrics["AP50"] > THRESHOLD_AP50, (
        f"recipe regression: AP50={metrics['AP50']:.4f} (calibrated healthy "
        f"value 0.30, 10x-LR failure mode 0.02)"
    )
