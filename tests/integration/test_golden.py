"""Golden-step determinism harness (SURVEY.md §4.4).

A fixed-PRNG, fixed-data 5-step loss trajectory recorded in-repo: any
refactor that changes numerics (op reordering, dtype drift, matcher changes)
shows up as a diff here before it shows up as silent mAP loss.  Loss also
must strictly decrease — the 'loss goes down' smoke the reference relied on,
made deterministic.

Goldens recorded on the 8-device virtual CPU mesh, f32, jax 0.4.37 (the
container's pinned runtime; re-recorded from the jax 0.9.0 goldens when the
environment moved — the trajectory shifted up to 8% by step 5, well beyond
scheduling noise, as expected for a major XLA version change).
Regenerate (only for an INTENDED numerics change or a runtime move) with:
  python -m tests.integration.test_golden
"""

if __name__ == "__main__":
    # Regeneration must run on the same backend the pytest assertion uses
    # (conftest.py forces CPU only under pytest; bare python would pick the
    # container's TPU backend and record wrong goldens).
    import os

    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
import optax

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.train import create_train_state, make_train_step

HW = (64, 64)
GOLDEN_LOSSES = (
    5.7810754776,
    5.7719092369,
    5.7526111603,
    5.7122411728,
    5.6021413803,
)


def run_trajectory() -> list[float]:
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, *HW, 3), jax.random.key(42)
    )
    step = make_train_step(model, HW, 3)
    rng = np.random.default_rng(42)
    batch = {
        "images": jnp.asarray(rng.normal(0, 1, (4, *HW, 3)).astype(np.float32)),
        "gt_boxes": jnp.asarray(
            np.tile(np.array([[10.0, 10.0, 50.0, 50.0]], np.float32), (4, 1, 1))
        ),
        "gt_labels": jnp.ones((4, 1), jnp.int32),
        "gt_mask": jnp.ones((4, 1), bool),
    }
    losses = []
    for _ in range(len(GOLDEN_LOSSES)):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses


def test_golden_loss_trajectory():
    losses = run_trajectory()
    # rel 1e-5: loose enough for XLA version-to-version scheduling noise,
    # tight enough to catch any real numerics change.
    np.testing.assert_allclose(losses, GOLDEN_LOSSES, rtol=1e-5)
    assert all(b < a for a, b in zip(losses, losses[1:])), "loss must decrease"


if __name__ == "__main__":
    print("recorded:", [f"{l:.10f}" for l in run_trajectory()])
