"""The no-cycle control: a diamond acquisition order.

top -> {left, right} -> bottom plus the transitive top -> bottom edge —
five edges, zero cycles.  ``explicit_pair`` re-states top -> left through
bare ``.acquire()``/``.release()`` calls so the explicit-hold tracking is
exercised alongside ``with``.
"""

import threading


class Diamond:
    def __init__(self):
        self._top = threading.Lock()
        self._left = threading.Lock()
        self._right = threading.Lock()
        self._bottom = threading.Lock()

    def via_left(self):
        with self._top:
            with self._left:
                with self._bottom:
                    pass

    def via_right(self):
        with self._top, self._right:
            with self._bottom:
                pass

    def explicit_pair(self):
        self._top.acquire()
        try:
            with self._left:
                pass
        finally:
            self._top.release()
