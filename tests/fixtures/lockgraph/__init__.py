"""Lock-graph fixture modules (ISSUE 20).

Each module is a minimal, self-contained concurrency shape the
``lock-order`` / ``lock-held-blocking`` project rules must classify
correctly.  Tests copy a selection of these files into a throwaway tree
shaped like the real package (``<tmp>/<PACKAGE_NAME>/lockgraph/*.py``)
and run the engine over it — they are never imported by the live tree and
never scanned by the live lint run (``tests/`` is excluded).

- ``cyclic.py``    — a known 3-lock cycle (the one deadlock the rule must find)
- ``diamond.py``   — 4 locks, 5 edges, NO cycle (the false-positive guard)
- ``indirect.py``  — an edge only visible through one-level call resolution
- ``suppressed.py``— blocking-while-locked sites: one suppressed, two bites
"""
