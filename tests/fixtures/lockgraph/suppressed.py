"""Blocking-while-locked sites for the ``lock-held-blocking`` rule.

``quarantine`` is the suppressed twin (rationale on the offending line);
``bite`` is the direct finding; ``indirect_bite`` only blocks through a
one-level callee, so its finding must carry the via-path to ``_nap``.
"""

import threading
import time


class Sleeper:
    def __init__(self):
        self._lock = threading.Lock()

    def quarantine(self):
        with self._lock:
            time.sleep(0.01)  # lint: lock-held-blocking: fixture twin — sanctioned nap

    def bite(self):
        with self._lock:
            time.sleep(0.01)

    def _nap(self):
        time.sleep(0.01)

    def indirect_bite(self):
        with self._lock:
            self._nap()
