"""The known deadlock: three locks acquired in a 3-cycle.

``ab`` holds A then takes B, ``bc`` holds B then takes C, ``ca`` holds C
then takes A — the may-hold-while-acquiring graph is A->B->C->A and the
``lock-order`` rule must report exactly ONE cycle naming all three
identities and all three acquisition sites.
"""

import threading


class Trio:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                pass

    def bc(self):
        with self._b:
            with self._c:
                pass

    def ca(self):
        with self._c:
            with self._a:
                pass
