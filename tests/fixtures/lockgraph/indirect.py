"""The one-level-indirect edge: visible only through call resolution.

``Outer.nudge`` holds ``Outer._lock`` and calls ``self._inner.poke()``;
``Inner.poke`` takes ``Inner._lock``.  No single function acquires both
locks, so the edge Outer._lock -> Inner._lock exists only if the rule
resolves the attribute-typed call one level deep (``self._inner`` was
constructed as ``Inner()`` in ``__init__``).
"""

import threading


class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass


class Outer:
    def __init__(self):
        self._lock = threading.Lock()
        self._inner = Inner()

    def nudge(self):
        with self._lock:
            self._inner.poke()
