"""Observability subsystem tests (ISSUE 3): trace spans, Chrome export,
event sink run headers + NaN passthrough, stall watchdog, spawn-site audit.

Deliberately jax-light: the obs core must work in jax-free processes (shm
decode workers trace their decodes), so nothing here compiles a program.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.obs import events as events_lib
from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.obs import watchdog as watchdog_lib
from batchai_retinanet_horovod_coco_tpu.obs.events import (
    EventSink,
    scalarize,
    split_runs,
)
from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Every test starts and ends with tracing disabled (module-global)."""
    trace.reset()
    yield
    trace.reset()


def _load_trace(path):
    with open(path) as f:
        doc = json.load(f)
    assert "traceEvents" in doc
    return doc


def _validate_chrome_schema(doc):
    """The subset of the trace_event contract Perfetto relies on."""
    assert isinstance(doc["traceEvents"], list)
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i", "C", "M"), ev
        assert "pid" in ev and "name" in ev, ev
        if ev["ph"] == "X":
            assert isinstance(ev["ts"], int) and isinstance(ev["dur"], int)
            assert ev["dur"] >= 0 and "tid" in ev
        elif ev["ph"] == "C":
            assert "value" in ev["args"]
        elif ev["ph"] == "M":
            assert ev["name"] in (
                "process_name", "thread_name", "process_labels"
            )


class TestTrace:
    def test_disabled_mode_is_a_shared_noop(self, tmp_path):
        # No configure(): span() must return the one null singleton (no
        # allocation on the hot path), record nothing, export nothing.
        assert trace.span("a") is trace.span("b")
        with trace.span("ignored"):
            pass
        trace.instant("ignored")
        trace.counter("ignored", 1.0)
        trace.end(trace.begin("ignored"))  # begin() -> None, end(None) ok
        assert trace.export() is None
        assert not trace.enabled()

    def test_span_nesting_and_schema(self, tmp_path):
        trace.configure(str(tmp_path), process_label="t")
        with trace.span("outer", step=1):
            with trace.span("inner"):
                time.sleep(0.002)
        doc = _load_trace(trace.export())
        _validate_chrome_schema(doc)
        spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        outer, inner = spans["outer"], spans["inner"]
        assert outer["args"] == {"step": 1}
        # Same thread, inner contained within outer.
        assert inner["tid"] == outer["tid"]
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]

    def test_cross_thread_begin_end_parity(self, tmp_path):
        trace.configure(str(tmp_path), process_label="t")
        with trace.span("same_thread"):
            time.sleep(0.002)
        handle = trace.begin("cross_thread")
        t = threading.Thread(
            target=lambda: (time.sleep(0.002), trace.end(handle))
        )
        t.start()
        t.join()
        doc = _load_trace(trace.export())
        spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        cross, same = spans["cross_thread"], spans["same_thread"]
        # The cross-thread span lands on the BEGINNING thread's track and
        # measures begin->end like an in-thread span does.
        assert cross["tid"] == same["tid"]
        assert cross["dur"] >= int(0.002 * 1e6)

    def test_distinct_threads_distinct_tracks(self, tmp_path):
        trace.configure(str(tmp_path), process_label="t")

        def worker():
            with trace.span("worker_span"):
                pass

        t = threading.Thread(target=worker, name="obs-test-worker")
        t.start()
        t.join()
        with trace.span("main_span"):
            pass
        doc = _load_trace(trace.export())
        spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
        assert spans["worker_span"]["tid"] != spans["main_span"]["tid"]
        thread_names = {
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert "obs-test-worker" in thread_names

    def test_ring_capacity_drops_oldest(self, tmp_path):
        trace.configure(str(tmp_path), capacity=10, process_label="t")
        for i in range(25):
            trace.instant(f"ev{i}")
        doc = _load_trace(trace.export())
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "i"]
        assert len(names) == 10
        assert names == [f"ev{i}" for i in range(15, 25)]  # tail survives
        assert doc["otherData"]["events_dropped_by_ring"] == 15

    def test_merge_traces_combines_processes_same_run_only(self, tmp_path):
        # A stale partial from a PREVIOUS run sharing the obs dir: pids
        # recycle across runs, so only the run-id prefix can exclude it.
        stale = tmp_path / "trace-deadbeef-train-99999.json"
        stale.write_text(json.dumps({"traceEvents": [
            {"ph": "i", "name": "stale_span", "ts": 0, "s": "t",
             "pid": 99999, "tid": 1},
        ]}))
        # Simulate two processes OF THIS RUN via two explicit exports.
        trace.configure(str(tmp_path), process_label="a")
        with trace.span("span_a"):
            pass
        trace.export()
        trace.export(
            os.path.join(
                str(tmp_path), f"trace-{trace.run_id()}-b-99999.json"
            )
        )
        merged = trace.merge_traces(str(tmp_path))
        doc = _load_trace(merged)
        _validate_chrome_schema(doc)
        assert len(doc["otherData"]["merged_from"]) == 2
        assert [e for e in doc["traceEvents"] if e["name"] == "span_a"]
        assert not [
            e for e in doc["traceEvents"] if e["name"] == "stale_span"
        ]

    def test_reset_invalidates_other_threads_rings(self, tmp_path):
        # A long-lived thread surviving a reset()+reconfigure must have
        # its events land in the NEW registry, not an orphaned ring.
        trace.configure(str(tmp_path), process_label="t")
        go = threading.Event()
        done = threading.Event()

        def long_lived():
            with trace.span("before_reset"):
                pass
            go.wait(5)
            with trace.span("after_reset"):
                pass
            done.set()

        t = threading.Thread(target=long_lived)
        t.start()
        while not any(r.thread_name == t.name for r in trace._rings):
            time.sleep(0.005)
        trace.reset()
        trace.configure(str(tmp_path), process_label="t2")
        go.set()
        assert done.wait(5)
        t.join()
        doc = _load_trace(trace.export())
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
        assert "after_reset" in names and "before_reset" not in names

    def test_fork_inherited_state_relabels_and_drops_rings(
        self, tmp_path, monkeypatch
    ):
        # A FORK-started worker inherits _enabled plus the parent's rings;
        # re-exporting them under the child pid would duplicate every
        # pre-fork span on the merged timeline.  Simulate the child by
        # faking the recorded config pid.
        trace.configure(str(tmp_path), process_label="parent")
        with trace.span("parent_span"):
            pass
        monkeypatch.setattr(trace, "_config_pid", os.getpid() - 1)
        assert trace.maybe_configure_from_env("shm-worker-0")
        with trace.span("child_span"):
            pass
        doc = _load_trace(trace.export())
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
        assert "child_span" in names and "parent_span" not in names
        proc_names = [
            e["args"]["name"]
            for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        ]
        assert any("shm-worker-0" in n for n in proc_names)

    def test_monotonic_clock_alignment(self):
        t = trace.monotonic_s()
        wall = trace.to_wall(t)
        assert abs(wall - time.time()) < 1.0  # same wall timeline


class TestWatchdog:
    def test_detects_injected_stalled_consumer(self):
        w = watchdog_lib.Watchdog(stall_after=10.0)
        # Per-component budget: the "healthy" peer must stay inside its
        # (large) budget at every injected ``now`` below.
        healthy = w.register("healthy-producer", stall_after=1e6)
        stalled = w.register(
            "stalled-consumer", details=lambda: {"qsize": 4}
        )
        t0 = trace.monotonic_s()
        stalled.beat()
        healthy.beat()
        assert w.check_once(now=t0 + 1.0) is None  # nobody over budget
        healthy.beat()
        diag = w.check_once(now=trace.monotonic_s() + 11.0)
        assert diag is not None
        # The diagnosis names the right component and carries its gauges.
        assert diag["component"] == "stalled-consumer"
        by_name = {c["name"]: c for c in diag["components"]}
        assert by_name["stalled-consumer"]["details"] == {"qsize": 4}
        assert "healthy-producer" in by_name
        # One dump per stall: the same wedge does not re-fire...
        assert w.check_once(now=trace.monotonic_s() + 12.0) is None
        # ...until the component beats (recovers) and wedges again.
        stalled.beat()
        assert (
            w.check_once(now=trace.monotonic_s() + 11.0)["component"]
            == "stalled-consumer"
        )

    def test_idle_components_are_not_flagged(self):
        w = watchdog_lib.Watchdog(stall_after=0.01)
        hb = w.register("backpressured")
        hb.beat()
        hb.idle()
        assert w.check_once(now=trace.monotonic_s() + 100.0) is None
        hb.beat()  # beat clears idle
        assert (
            w.check_once(now=trace.monotonic_s() + 100.0)["component"]
            == "backpressured"
        )

    def test_poll_thread_dumps_structured_diagnosis(self, tmp_path):
        dump = tmp_path / "stacks.txt"
        stalls = []
        w = watchdog_lib.Watchdog(
            stall_after=0.05,
            poll_interval=0.02,
            dump_path=str(dump),
            on_stall=stalls.append,
        )
        hb = w.register("wedged-thread")
        hb.beat()
        w.start()
        try:
            deadline = time.monotonic() + 5.0
            while not stalls and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            w.stop()
        assert stalls and stalls[0]["component"] == "wedged-thread"
        text = dump.read_text()
        # Structured JSON line + faulthandler all-thread stacks.
        assert json.loads(text.splitlines()[0])["event"] == "watchdog_stall"
        assert "thread stacks" in text and "File " in text
        hb.close()

    def test_unregister_and_name_uniquing(self):
        w = watchdog_lib.Watchdog()
        a = w.register("eval-consumer")
        b = w.register("eval-consumer")  # repeated eval re-registers
        assert {a.name, b.name} == {"eval-consumer", "eval-consumer#2"}
        a.close()
        b.close()
        assert w.components() == {}

    def test_details_error_does_not_kill_diagnosis(self):
        w = watchdog_lib.Watchdog(stall_after=0.01)
        def boom():
            raise RuntimeError("gauge died")
        hb = w.register("flaky-gauges", details=boom)
        hb.beat()
        diag = w.check_once(now=trace.monotonic_s() + 1.0)
        assert diag["component"] == "flaky-gauges"
        assert "gauge died" in str(
            diag["components"][0]["details"]["details_error"]
        )


class TestEventSink:
    def test_run_header_and_split_runs(self, tmp_path):
        for run in range(2):
            logger = MetricLogger(str(tmp_path), stdout=False)
            logger.log(1 + run, {"loss": 0.5})
            logger.close()
        runs = split_runs(str(tmp_path / "metrics.jsonl"))
        assert len(runs) == 2
        for run in runs:
            assert run["header"]["event"] == "run_header"
            assert "run_id" in run["header"]
        assert runs[0]["header"]["run_id"] != runs[1]["header"]["run_id"]
        assert events_lib.metric_records(runs[1])[0]["step"] == 2

    def test_split_runs_headerless_prefix_and_corrupt_tail(self, tmp_path):
        p = tmp_path / "metrics.jsonl"
        p.write_text(
            '{"step": 1, "train/loss": 0.5}\n'      # pre-ISSUE-3 run
            '{"event": "run_header", "run_id": "x"}\n'
            '{"step": 1, "train/loss": 0.4}\n'
            '{"step": 2, "train/lo'                  # killed mid-write
        )
        runs = split_runs(str(p))
        assert len(runs) == 2
        assert runs[0]["header"] is None
        assert runs[1]["header"]["run_id"] == "x"
        assert len(runs[1]["records"]) == 1
        assert runs[1]["corrupt"]  # half-written tail kept, not fatal

    def test_nan_passes_through_loudly(self, tmp_path, capsys):
        logger = MetricLogger(str(tmp_path), stdout=True)
        logger.log(3, {"loss": float("nan"), "ok": 1.0})
        logger.close()
        out = capsys.readouterr().out
        assert "NON-FINITE" in out and "loss" in out
        runs = split_runs(str(tmp_path / "metrics.jsonl"))
        rec = events_lib.metric_records(runs[0])[0]
        assert np.isnan(rec["train/loss"])  # recorded, never dropped
        assert rec["train/ok"] == 1.0

    def test_noncastable_metrics_counted_not_silent(self, tmp_path):
        logger = MetricLogger(str(tmp_path), stdout=False)
        logger.log(1, {"loss": 1.0, "boxes": np.zeros((3, 4)), "tag": "x"})
        assert logger.dropped_metrics_total == 2
        logger.close()
        rec = events_lib.metric_records(
            split_runs(str(tmp_path / "metrics.jsonl"))[0]
        )[0]
        assert rec["dropped_metrics"] == ["boxes", "tag"]
        assert rec["train/loss"] == 1.0

    def test_scalarize_contract(self):
        scalars, dropped = scalarize(
            {"a": 1, "inf": float("inf"), "arr": np.ones(2)}
        )
        assert scalars["a"] == 1.0 and np.isinf(scalars["inf"])
        assert dropped == ["arr"]

    def test_events_and_gauges(self, tmp_path):
        sink = EventSink(str(tmp_path), stdout=False)
        sink.event("compile", target="train_step", bucket="64x64")
        sink.gauge("qsize", 3, step=7)
        sink.close()
        runs = split_runs(str(tmp_path / "metrics.jsonl"))
        events = {r["event"]: r for r in runs[0]["records"]}
        assert events["compile"]["bucket"] == "64x64"
        assert events["gauge"]["name"] == "qsize"
        assert events["gauge"]["value"] == 3.0

    def test_emit_event_concurrent_lines_never_interleave(self, tmp_path):
        """ISSUE 20 consolidation: every subsystem's structured emit goes
        through ONE serialized ``emit_event`` — 8 concurrent emitters into
        one stream must yield only whole, parseable JSONL lines (the PR 16
        interleaving class, now guarded in exactly one place)."""
        import io

        stream = io.StringIO()
        sink = EventSink(str(tmp_path), stdout=False)
        n_threads, n_each = 8, 50
        errors: list[BaseException] = []

        def emit(tid: int) -> None:
            try:
                for i in range(n_each):
                    events_lib.emit_event(
                        "serve_stats", sink=sink, file=stream,
                        tid=tid, i=i, pad="x" * 64,
                    )
            except BaseException as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=emit, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        sink.close()
        assert errors == []
        lines = stream.getvalue().splitlines()
        assert len(lines) == n_threads * n_each
        seen = set()
        for line in lines:
            rec = json.loads(line)  # raises on any torn/interleaved line
            assert rec["event"] == "serve_stats"
            seen.add((rec["tid"], rec["i"]))
        assert len(seen) == n_threads * n_each  # nothing lost or doubled
        # The guarded sink got every record too.
        runs = split_runs(str(tmp_path / "metrics.jsonl"))
        recs = [r for r in runs[0]["records"]
                if r.get("event") == "serve_stats"]
        assert len(recs) == n_threads * n_each

    def test_emit_event_survives_broken_sink(self, tmp_path):
        """The parseable line is the contract; a broken sink must not
        mask it."""
        import io

        class Broken:
            def event(self, *a, **k):
                raise RuntimeError("sink down")

        stream = io.StringIO()
        events_lib.emit_event("serve_stats", sink=Broken(), file=stream,
                              n=1)
        rec = json.loads(stream.getvalue())
        assert rec == {"event": "serve_stats", "n": 1}

    def test_emit_event_stream_is_an_event_field_not_the_output(self):
        """``stream`` is a live event field (``fleet_stream_reaped`` carries
        the stream id) — it must land IN the JSON line, never be captured
        as the output file (the tier-1 regression: ``'str' object has no
        attribute 'write'``)."""
        import io

        out = io.StringIO()
        events_lib.emit_event("fleet_stream_reaped", file=out, stream="s-1")
        rec = json.loads(out.getvalue())
        assert rec == {"event": "fleet_stream_reaped", "stream": "s-1"}


class TestIntegration:
    def test_prefetch_map_traces_and_heartbeats(self, tmp_path):
        """The shared prefetch skeleton registers/beats/unregisters and its
        spans land on the feeder thread's own track."""
        from batchai_retinanet_horovod_coco_tpu.data.prefetch import (
            prefetch_map,
        )

        trace.configure(str(tmp_path), process_label="t")
        seen_during: list[bool] = []

        def transfer(x):
            seen_during.append(
                any(
                    "obs-test-prefetch" in n
                    for n in watchdog_lib.default().components()
                )
            )
            return x * 2

        out = list(
            prefetch_map(
                range(4), transfer, depth=2,
                thread_name="obs-test-prefetch",
            )
        )
        assert out == [0, 2, 4, 6]
        assert any(seen_during)  # registered while running...
        assert not any(
            "obs-test-prefetch" in n
            for n in watchdog_lib.default().components()
        )  # ...unregistered after
        doc = _load_trace(trace.export())
        spans = [
            e for e in doc["traceEvents"]
            if e["ph"] == "X" and e["name"] == "obs-test-prefetch"
        ]
        assert len(spans) == 4
        assert all(s["tid"] != threading.get_ident() for s in spans)

    def test_audit_threads_clean(self):
        """Tier-1 wiring of scripts/audit_threads.py: every spawn site in
        the package registers with the watchdog or carries a rationale."""
        import importlib.util

        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        spec = importlib.util.spec_from_file_location(
            "audit_threads", os.path.join(root, "scripts", "audit_threads.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        violations = mod.audit_package(
            os.path.join(root, "batchai_retinanet_horovod_coco_tpu")
        )
        assert violations == [], violations

    def test_audit_flags_unwatched_spawn(self, tmp_path):
        """The audit actually bites: a bare Thread() spawn is a violation,
        and either coverage form clears it."""
        import importlib.util

        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        spec = importlib.util.spec_from_file_location(
            "audit_threads", os.path.join(root, "scripts", "audit_threads.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        bad = tmp_path / "bad.py"
        bad.write_text(
            "import threading\n\n\n"
            "def go():\n"
            "    t = threading.Thread(target=print)\n"
            "    t.start()\n"
        )
        assert len(mod.audit_file(str(bad))) == 1

        ok = tmp_path / "ok.py"
        ok.write_text(
            "import threading\n\n\n"
            "def go():\n"
            "    # watchdog: registers in run() at thread start.\n"
            "    t = threading.Thread(target=print)\n"
            "    t.start()\n"
        )
        assert mod.audit_file(str(ok)) == []

        reg = tmp_path / "reg.py"
        reg.write_text(
            "import threading\n"
            "from batchai_retinanet_horovod_coco_tpu.obs import watchdog\n\n\n"
            "def go():\n"
            "    hb = watchdog.register('x')\n"
            "    t = threading.Thread(target=hb.beat)\n"
            "    t.start()\n"
        )
        assert mod.audit_file(str(reg)) == []
