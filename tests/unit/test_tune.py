"""tune/ subsystem (ISSUE 6): registry round-trip, loud fallback, search.

Three contracts under test:

1. **Registry** (tune/schedule.py): schema validation names EVERY problem;
   save → load → lookup round-trips; partial artifacts deep-merge over the
   built-in defaults; an unknown/invalid device falls back to the defaults
   with ONE structured ``schedule_fallback`` stderr event per process —
   never a crash; lookups are cached (the zero-request-time-recompile
   guarantee) yet isolated per registry dir.
2. **Consumers**: ``resolve_detect_config`` (evaluate/detect.py) and
   ``resolve_kernel_schedule`` (train/step.py) fill exactly the None
   fields from the registry, and explicit values always win.
3. **Search** (tune/search.py + CLI): a CPU smoke run produces a
   schema-valid artifact that the consumers actually resolve from, with
   pallas candidates recorded as skipped (no Mosaic) and the winner drawn
   from exact-semantics trials only.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0,
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
)

from batchai_retinanet_horovod_coco_tpu.tune import (  # noqa: E402
    DEFAULT_SCHEDULE,
    ScheduleError,
    eval_batch_for,
    load_schedule,
    lookup,
    provenance,
    save_schedule,
    schedule_path,
    serve_batch_sizes_for,
    validate_schedule,
)
from batchai_retinanet_horovod_coco_tpu.tune import (  # noqa: E402
    schedule as schedule_lib,
)


@pytest.fixture(autouse=True)
def clean_registry_state():
    """Process-global lookup cache + once-per-reason warning dedupe must
    not leak between tests."""
    schedule_lib._cache.clear()
    schedule_lib._warned.clear()
    yield
    schedule_lib._cache.clear()
    schedule_lib._warned.clear()


def _doc(device_kind="TPU v5 lite", **entries):
    return {
        "format": schedule_lib.FORMAT,
        "device_kind": device_kind,
        "entries": entries,
    }


class TestSchema:
    def test_round_trip(self, tmp_path):
        doc = _doc(
            nms={"impl": "pallas", "block_k": 512, "pre_nms_size": 1000},
            focal={"impl": "pallas", "fwd_tile_a": 16384, "bwd_tile_a": 2048},
        )
        path = save_schedule(doc, str(tmp_path))
        assert path == schedule_path("TPU v5 lite", str(tmp_path))
        assert os.path.basename(path) == "tpu_v5_lite.json"
        assert load_schedule(path)["entries"] == doc["entries"]

    def test_every_problem_named_not_just_the_first(self):
        bad = _doc(
            nms={"impl": "cuda", "block_k": 100},
            focal={"fwd_tile_a": -8},
            bogus_op={"x": 1},
        )
        bad["format"] = "wrong.format"
        with pytest.raises(ScheduleError) as exc:
            validate_schedule(bad)
        msg = str(exc.value)
        for fragment in (
            "format:", "bogus_op", "nms.impl", "block_k", "fwd_tile_a"
        ):
            assert fragment in msg, (fragment, msg)

    def test_tiles_must_be_lane_multiples(self):
        with pytest.raises(ScheduleError, match="multiple of 128"):
            validate_schedule(_doc(matching={"tile_a": 1000}))

    def test_batch_tables_validated(self):
        with pytest.raises(ScheduleError, match="not HxW"):
            validate_schedule(_doc(eval={"batch": {"big": 8}}))
        with pytest.raises(ScheduleError, match="non-empty list"):
            validate_schedule(
                _doc(serve={"batch_sizes": {"800x1344": 8}})
            )

    def test_save_refuses_invalid(self, tmp_path):
        with pytest.raises(ScheduleError):
            save_schedule(_doc(nms={"impl": "nope"}), str(tmp_path))
        assert not os.listdir(tmp_path)


class TestLookupFallback:
    def test_unknown_device_falls_back_with_one_structured_event(
        self, tmp_path, capsys
    ):
        merged = lookup("never-tuned-chip", str(tmp_path))
        assert merged == DEFAULT_SCHEDULE
        merged2 = lookup("never-tuned-chip", str(tmp_path))
        assert merged2 == DEFAULT_SCHEDULE
        err_lines = [
            l for l in capsys.readouterr().err.splitlines() if l.strip()
        ]
        events = [json.loads(l) for l in err_lines]
        events = [e for e in events if e.get("event") == "schedule_fallback"]
        assert len(events) == 1, "exactly ONE event per (device, reason)"
        assert events[0]["device_kind"] == "never-tuned-chip"
        assert events[0]["reason"] == "no_schedule_artifact"
        assert events[0]["using"] == "built-in defaults"

    def test_invalid_artifact_falls_back_loudly_never_crashes(
        self, tmp_path, capsys
    ):
        path = schedule_path("brokenchip", str(tmp_path))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write('{"format": "wrong", "entries": 3}')
        merged = lookup("brokenchip", str(tmp_path))
        assert merged == DEFAULT_SCHEDULE
        events = [
            json.loads(l)
            for l in capsys.readouterr().err.splitlines()
            if l.strip()
        ]
        assert events[0]["reason"] == "invalid_schedule_artifact"
        # Strict readers DO crash on the same artifact (CI wants that).
        with pytest.raises(ScheduleError):
            load_schedule(path)

    def test_partial_artifact_merges_over_defaults(self, tmp_path):
        save_schedule(_doc(nms={"impl": "pallas"}), str(tmp_path))
        merged = lookup("TPU v5 lite", str(tmp_path))
        assert merged["nms"]["impl"] == "pallas"
        # Unsearched keys keep the hand-picked defaults.
        assert merged["nms"]["block_k"] == DEFAULT_SCHEDULE["nms"]["block_k"]
        assert merged["focal"] == DEFAULT_SCHEDULE["focal"]

    def test_lookup_cached_and_isolated_per_root(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        save_schedule(_doc(nms={"block_k": 512}), str(a))
        save_schedule(_doc(nms={"block_k": 128}), str(b))
        assert lookup("TPU v5 lite", str(a))["nms"]["block_k"] == 512
        assert lookup("TPU v5 lite", str(b))["nms"]["block_k"] == 128
        # Mutating a returned dict must not poison the cache.
        got = lookup("TPU v5 lite", str(a))
        got["nms"]["block_k"] = 999
        assert lookup("TPU v5 lite", str(a))["nms"]["block_k"] == 512

    def test_batch_table_helpers(self, tmp_path):
        save_schedule(
            _doc(
                eval={"batch": {"800x1344": 16}},
                serve={"batch_sizes": {"800x1344": [1, 16]}},
            ),
            str(tmp_path),
        )
        kind, root = "TPU v5 lite", str(tmp_path)
        assert eval_batch_for((800, 1344), 8, kind, root) == 16
        assert eval_batch_for((1344, 800), 8, kind, root) == 8  # untuned
        assert serve_batch_sizes_for((800, 1344), (8,), kind, root) == (1, 16)
        assert serve_batch_sizes_for((1344, 800), (8,), kind, root) == (8,)

    def test_provenance(self, tmp_path):
        p = provenance("TPU v5 lite", str(tmp_path))
        assert p == {
            "device_kind": "TPU v5 lite", "source": "defaults", "found": False
        }
        save_schedule(_doc(nms={"impl": "xla"}), str(tmp_path))
        p = provenance("TPU v5 lite", str(tmp_path))
        assert p["found"] and p["source"].endswith("tpu_v5_lite.json")


class TestConsumers:
    @pytest.fixture()
    def registry(self, tmp_path, monkeypatch):
        """A committed-winner registry for THIS process's device kind,
        installed via the env override every consumer honors."""
        import jax

        kind = jax.devices()[0].device_kind
        save_schedule(
            _doc(
                device_kind=kind,
                nms={"impl": "pallas", "block_k": 512, "pre_nms_size": 512},
                focal={"impl": "xla", "fwd_tile_a": 16384, "bwd_tile_a": 2048},
                matching={"impl": "pallas", "tile_a": 4096},
            ),
            str(tmp_path),
        )
        monkeypatch.setenv("RETINANET_SCHEDULE_DIR", str(tmp_path))
        schedule_lib._cache.clear()
        yield kind
        schedule_lib._cache.clear()

    def test_resolve_detect_config_fills_none_fields(self, registry):
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            resolve_detect_config,
        )

        resolved = resolve_detect_config(DetectConfig())
        assert resolved.nms_impl == "pallas"
        assert resolved.nms_block_k == 512
        assert resolved.pre_nms_size == 512
        # Semantics knobs not owned by the schedule are untouched.
        assert resolved.score_threshold == DetectConfig.score_threshold

    def test_explicit_fields_always_win(self, registry):
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            resolve_detect_config,
        )

        pinned = resolve_detect_config(
            DetectConfig(nms_impl="xla", pre_nms_size=1000, nms_block_k=128)
        )
        assert pinned.nms_impl == "xla"
        assert pinned.pre_nms_size == 1000
        assert pinned.nms_block_k == 128

    def test_typod_impl_raises_even_when_fully_pinned(self, registry):
        """A fully concrete config must not dodge impl validation via the
        early return — 'Pallas' silently running XLA would let an export
        manifest record a kernel that never ran."""
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            resolve_detect_config,
        )

        with pytest.raises(ValueError, match="nms_impl"):
            resolve_detect_config(
                DetectConfig(
                    nms_impl="Pallas", pre_nms_size=1000, nms_block_k=128
                )
            )

    def test_resolve_kernel_schedule_train_side(self, registry):
        from batchai_retinanet_horovod_coco_tpu import losses as losses_lib
        from batchai_retinanet_horovod_coco_tpu.ops import (
            matching as matching_lib,
        )
        from batchai_retinanet_horovod_coco_tpu.train.step import (
            resolve_kernel_schedule,
        )

        loss, match = resolve_kernel_schedule(
            losses_lib.LossConfig(), matching_lib.MatchingConfig()
        )
        assert loss.pallas_focal is False  # registry says impl: xla
        assert loss.focal_fwd_tile_a == 16384
        assert loss.focal_bwd_tile_a == 2048
        assert match.fused_pallas is True
        assert match.pallas_tile_a == 4096
        # Explicit values survive resolution untouched.
        loss2, match2 = resolve_kernel_schedule(
            losses_lib.LossConfig(pallas_focal=True, focal_fwd_tile_a=4096),
            matching_lib.MatchingConfig(fused_pallas=False),
        )
        assert loss2.pallas_focal is True
        assert loss2.focal_fwd_tile_a == 4096
        assert match2.fused_pallas is False

    def test_unknown_device_resolution_is_todays_defaults(
        self, tmp_path, monkeypatch
    ):
        """The no-artifact path every consumer ships with: resolution must
        reproduce the pre-ISSUE-6 hand-picked values exactly."""
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            resolve_detect_config,
        )

        monkeypatch.setenv("RETINANET_SCHEDULE_DIR", str(tmp_path / "none"))
        schedule_lib._cache.clear()
        resolved = resolve_detect_config(DetectConfig())
        assert resolved.nms_impl == "xla"
        assert resolved.pre_nms_size == 1000
        assert resolved.nms_block_k == 256


class TestSearch:
    def test_outage_vocabulary_matches_bench(self):
        import bench

        from batchai_retinanet_horovod_coco_tpu.tune import search

        assert tuple(search.UNAVAILABLE_MARKERS) == tuple(
            bench._UNAVAILABLE_MARKERS
        )

    def test_failed_candidate_is_recorded_not_fatal(self):
        from batchai_retinanet_horovod_coco_tpu.tune import search

        def build(params):
            if params.get("block_k") == 128:
                raise ValueError("XLA compile error: tile too fat")
            return lambda: np.zeros(())

        t_ok = search.run_trial(
            "nms", {"impl": "xla", "pre_nms_size": 1000}, build, steps=2
        )
        t_bad = search.run_trial(
            "nms", {"impl": "xla", "block_k": 128, "pre_nms_size": 1000},
            build, steps=2,
        )
        assert t_ok.status == "ok" and t_ok.ms_per_call is not None
        assert t_bad.status == "failed"
        assert "tile too fat" in t_bad.error

    def test_unavailable_mid_trial_raises_device_unavailable(self):
        from batchai_retinanet_horovod_coco_tpu.tune import search

        def build(params):
            raise RuntimeError(
                "Unable to initialize backend 'tpu': UNAVAILABLE: gone"
            )

        with pytest.raises(search.DeviceUnavailable):
            search.run_trial("nms", {"impl": "xla"}, build, steps=2)

    def test_chain_wrapped_unavailable_still_aborts_search(self):
        """bench.py's r05 lesson applies to the tuner too: jax re-wraps
        the backend-init UNAVAILABLE one link down the exception chain —
        it must classify as DeviceUnavailable, not a failed trial."""
        from batchai_retinanet_horovod_coco_tpu.tune import search

        def build(params):
            try:
                raise RuntimeError(
                    "Unable to initialize backend 'tpu': UNAVAILABLE: gone"
                )
            except RuntimeError as inner:
                raise ValueError("jax-filtered rewrap") from inner

        with pytest.raises(search.DeviceUnavailable):
            search.run_trial("nms", {"impl": "xla"}, build, steps=2)

    def test_cpu_smoke_produces_consumable_artifact(
        self, tmp_path, monkeypatch, capsys
    ):
        """The acceptance bar: a CPU tuner run emits a schema-valid
        artifact that detect-side resolution consumes, with a stable
        (cached) resolution — the zero-request-time-recompile property."""
        import jax

        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import main

        rc = main([
            "--smoke", "--ops", "nms", "--hw", "128x128", "--batch", "1",
            "--steps", "2", "--out-root", str(tmp_path),
        ])
        assert rc == 0
        kind = jax.devices()[0].device_kind
        path = schedule_path(kind, str(tmp_path))
        assert os.path.exists(path)
        doc = load_schedule(path)  # schema-valid by construction
        assert doc["entries"]["nms"]["impl"] == "xla"  # no Mosaic on CPU
        skipped = [t for t in doc["trials"] if t["status"] == "skipped"]
        assert skipped, "pallas candidates must be RECORDED as skipped"
        assert all("Mosaic" in t["error"] for t in skipped)
        ok = [t for t in doc["trials"] if t["status"] == "ok"]
        assert ok and all(t["ms_per_call"] > 0 for t in ok)

        # Consumable: detect resolution picks the winner up...
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            resolve_detect_config,
        )

        monkeypatch.setenv("RETINANET_SCHEDULE_DIR", str(tmp_path))
        schedule_lib._cache.clear()
        r1 = resolve_detect_config(DetectConfig())
        assert r1.pre_nms_size == doc["entries"]["nms"]["pre_nms_size"]
        # ...and resolution is STABLE for the process lifetime: same
        # concrete config on every call → the AOT table compiled at serve
        # startup keeps matching → no request-time recompiles.
        assert resolve_detect_config(DetectConfig()) == r1

    def test_winner_never_comes_from_approx_semantics(self, monkeypatch):
        """pre_nms_size trials are measured (opt-in) but the harness must
        not auto-promote a semantics-changing winner."""
        from batchai_retinanet_horovod_coco_tpu.tune import search

        def fake_builder(batch, hw):
            def build(params):
                # Make the semantics-approx candidate measurably "fastest".
                return lambda: np.zeros(())
            return build

        monkeypatch.setitem(search._BUILDERS, "nms", fake_builder)
        winner, trials = search.search_op(
            "nms",
            steps=2,
            candidates=[
                {"impl": "xla", "pre_nms_size": 1000},
                {"impl": "xla", "pre_nms_size": 512},
            ],
        )
        assert winner["pre_nms_size"] == 1000
        approx = [t for t in trials if t.semantics == "approx"]
        assert len(approx) == 1 and approx[0].status == "ok"


class TestTunebenchCheck:
    def _record(self, tmp_path, device_kind, value=1e9):
        rec = {
            "metric": "nms_postprocess_ms_per_batch",
            "value": value,
            "device_kind": device_kind,
            "hw": [128, 128],
            "batch": 1,
            "winner": {"impl": "xla", "pre_nms_size": 1000},
        }
        path = tmp_path / "TUNEBENCH.json"
        path.write_text(json.dumps(rec))
        return str(path)

    @pytest.fixture(autouse=True)
    def no_probe(self, monkeypatch):
        """--check keeps the subprocess probe (a dead tunnel would hang
        its in-process jax.devices() unboundedly); tests skip it via the
        same env contract bench-check uses."""
        monkeypatch.setenv("BENCH_PROBE", "0")

    def test_device_mismatch_passes_with_note(self, tmp_path, capsys):
        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import main

        path = self._record(tmp_path, "some-future-chip")
        rc = main(["--check", "--bench-out", path, "--steps", "2"])
        assert rc == 0
        assert "not comparable" in capsys.readouterr().out

    def test_matching_device_enforces_ceiling(self, tmp_path, capsys):
        import jax

        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import main

        kind = jax.devices()[0].device_kind
        # Committed value astronomically high → fresh measurement passes.
        path = self._record(tmp_path, kind, value=1e9)
        assert main(["--check", "--bench-out", path, "--steps", "2"]) == 0
        # Committed value impossibly low → fresh measurement regresses.
        path = self._record(tmp_path, kind, value=1e-9)
        assert main(["--check", "--bench-out", path, "--steps", "2"]) == 1
        out = capsys.readouterr().out
        assert "ok" in out and "REGRESSION" in out
