import jax
import jax.numpy as jnp
import numpy as np

from batchai_retinanet_horovod_coco_tpu.losses import (
    LossConfig,
    focal_loss,
    smooth_l1_loss,
    total_loss,
)


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def numpy_focal(logits, targets, state, alpha=0.25, gamma=2.0):
    p = sigmoid(logits)
    bce = -(targets * np.log(p) + (1 - targets) * np.log(1 - p))
    p_t = p * targets + (1 - p) * (1 - targets)
    a_t = alpha * targets + (1 - alpha) * (1 - targets)
    loss = a_t * (1 - p_t) ** gamma * bce
    loss = loss * (state != -1)[:, None]
    return loss.sum() / max((state == 1).sum(), 1)


def test_focal_matches_closed_form():
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(20, 5)).astype(np.float32)
    targets = np.zeros((20, 5), dtype=np.float32)
    state = rng.choice([-1, 0, 1], size=20)
    for i in np.where(state == 1)[0]:
        targets[i, rng.integers(5)] = 1.0
    got = float(focal_loss(logits, targets, state))
    want = numpy_focal(logits, targets, state)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_focal_ignore_masking():
    logits = np.full((2, 3), 5.0, dtype=np.float32)  # confident wrong
    targets = np.zeros((2, 3), dtype=np.float32)
    all_ignored = float(focal_loss(logits, targets, np.array([-1, -1])))
    assert all_ignored == 0.0
    one_active = float(focal_loss(logits, targets, np.array([-1, 0])))
    assert one_active > 0.0


def test_focal_alpha_gamma_edge_cases():
    logits = np.array([[0.0]], dtype=np.float32)
    targets = np.array([[1.0]], dtype=np.float32)
    state = np.array([1])
    # gamma=0, alpha=0.5 → plain BCE * 0.5 = 0.5 * log(2)
    got = float(
        focal_loss(logits, targets, state, LossConfig(focal_alpha=0.5, focal_gamma=0.0))
    )
    np.testing.assert_allclose(got, 0.5 * np.log(2.0), rtol=1e-6)


def test_smooth_l1_values_and_normalization():
    cfg = LossConfig(smooth_l1_beta=1.0 / 9.0)
    preds = np.array([[0.0, 0.0, 0.0, 0.0], [1.0, 0, 0, 0]], dtype=np.float32)
    targets = np.array([[0.05, 0, 0, 0], [0.0, 0, 0, 0]], dtype=np.float32)
    state = np.array([1, 1])
    beta = 1.0 / 9.0
    # |d|=0.05 < beta → quadratic; |d|=1 ≥ beta → linear.
    want = (0.5 * 0.05**2 / beta + (1.0 - 0.5 * beta)) / 2.0
    got = float(smooth_l1_loss(preds, targets, state, cfg))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_smooth_l1_only_positives():
    preds = np.ones((3, 4), dtype=np.float32)
    targets = np.zeros((3, 4), dtype=np.float32)
    state = np.array([0, -1, 1])
    got = float(smooth_l1_loss(preds, targets, state))
    beta = 1.0 / 9.0
    want = 4 * (1.0 - 0.5 * beta) / 1.0  # only the positive anchor counts
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_total_loss_keys_and_weighting():
    logits = np.zeros((4, 2), dtype=np.float32)
    box = np.zeros((4, 4), dtype=np.float32)
    cls_t = np.zeros((4, 2), dtype=np.float32)
    box_t = np.ones((4, 4), dtype=np.float32)
    state = np.array([1, 0, 0, 0])
    cls_t[0, 1] = 1.0
    out = total_loss(logits, box, cls_t, box_t, state, LossConfig(box_loss_weight=2.0))
    np.testing.assert_allclose(
        float(out["loss"]),
        float(out["cls_loss"]) + 2.0 * float(out["box_loss"]),
        rtol=1e-6,
    )


def test_losses_batched_shapes():
    """Losses accept a leading batch dim (targets computed per-image, vmapped)."""
    logits = np.zeros((2, 8, 3), dtype=np.float32)
    box = np.zeros((2, 8, 4), dtype=np.float32)
    cls_t = np.zeros((2, 8, 3), dtype=np.float32)
    box_t = np.zeros((2, 8, 4), dtype=np.float32)
    state = np.zeros((2, 8), dtype=np.int32)
    out = total_loss(logits, box, cls_t, box_t, state)
    assert np.isfinite(float(out["loss"]))


def test_per_image_normalization():
    """Crowded images must not dominate: normalize per image, then batch-mean."""
    A, K = 6, 2
    logits = np.full((2, A, K), 2.0, dtype=np.float32)
    cls_t = np.zeros((2, A, K), dtype=np.float32)
    # image 0: 4 positives; image 1: 1 positive
    state = np.array([[1, 1, 1, 1, 0, 0], [1, 0, 0, 0, 0, 0]])
    for b in range(2):
        for a in range(A):
            if state[b, a] == 1:
                cls_t[b, a, 0] = 1.0
    got = float(focal_loss(logits, cls_t, state))
    per_image = []
    for b in range(2):
        li = numpy_focal(logits[b], cls_t[b], state[b])
        per_image.append(li)
    want = np.mean(per_image)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_focal_compact_matches_dense():
    """focal_loss_compact(int labels) == focal_loss(one-hot) exactly."""
    from batchai_retinanet_horovod_coco_tpu.losses import (
        focal_loss_compact,
        total_loss,
        total_loss_compact,
    )

    rng = np.random.default_rng(7)
    B, A, K = 3, 16, 5
    logits = rng.normal(0, 2, (B, A, K)).astype(np.float32)
    box_preds = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    box_t = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    labels = rng.integers(0, K, (B, A)).astype(np.int32)
    state = rng.choice([-1, 0, 1], (B, A)).astype(np.int32)

    one_hot = np.zeros((B, A, K), dtype=np.float32)
    for b in range(B):
        for a in range(A):
            if state[b, a] == 1:
                one_hot[b, a, labels[b, a]] = 1.0

    np.testing.assert_allclose(
        float(focal_loss_compact(logits, labels, state)),
        float(focal_loss(logits, one_hot, state)),
        rtol=1e-6,
    )
    dense = total_loss(logits, box_preds, one_hot, box_t, state)
    compact = total_loss_compact(logits, box_preds, labels, box_t, state)
    for k in dense:
        np.testing.assert_allclose(float(compact[k]), float(dense[k]), rtol=1e-6)


def test_levels_matches_concat():
    """Per-level losses == concatenated losses (up to f32 sum order)."""
    from batchai_retinanet_horovod_coco_tpu.losses import (
        total_loss_compact,
        total_loss_compact_levels,
    )

    rng = np.random.default_rng(9)
    B, K = 2, 5
    level_sizes = (300, 80, 20)
    A = sum(level_sizes)
    logits = rng.normal(0, 2, (B, A, K)).astype(np.float32)
    box_preds = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    box_t = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    labels = rng.integers(0, K, (B, A)).astype(np.int32)
    state = rng.choice([-1, 0, 1], (B, A), p=[0.2, 0.7, 0.1]).astype(np.int32)

    cls_levels, box_levels, off = [], [], 0
    for n in level_sizes:
        cls_levels.append(logits[:, off : off + n])
        box_levels.append(box_preds[:, off : off + n])
        off += n

    want = total_loss_compact(logits, box_preds, labels, box_t, state)
    got = total_loss_compact_levels(
        tuple(cls_levels), tuple(box_levels), labels, box_t, state
    )
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), rtol=1e-5)


def test_levels_size_mismatch_raises():
    from batchai_retinanet_horovod_coco_tpu.losses import (
        total_loss_compact_levels,
    )

    import pytest as _pytest

    with _pytest.raises(ValueError, match="cover"):
        total_loss_compact_levels(
            (np.zeros((1, 10, 3)),),
            (np.zeros((1, 10, 4)),),
            np.zeros((1, 12), np.int32),
            np.zeros((1, 12, 4)),
            np.zeros((1, 12), np.int32),
        )


def test_nhwc_matches_concat():
    """NHWC-direct per-level losses == concatenated losses (f32 sum order).

    The step's hot path (train/step.py) consumes raw (B, h, w, A*K) head
    outputs; level anchor counts are h*w*A in (y, x, a) order, matching the
    anchor-major flatten the heads would otherwise do.
    """
    from batchai_retinanet_horovod_coco_tpu.losses import (
        total_loss_compact,
        total_loss_compact_nhwc,
    )

    rng = np.random.default_rng(11)
    B, K, A_LOC = 2, 5, 3
    level_hw = ((10, 12), (5, 6), (3, 3))
    level_sizes = [h * w * A_LOC for h, w in level_hw]
    A = sum(level_sizes)
    logits = rng.normal(0, 2, (B, A, K)).astype(np.float32)
    box_preds = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    box_t = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    labels = rng.integers(0, K, (B, A)).astype(np.int32)
    state = rng.choice([-1, 0, 1], (B, A), p=[0.2, 0.7, 0.1]).astype(np.int32)

    cls_levels, box_levels, off = [], [], 0
    for (h, w), n in zip(level_hw, level_sizes):
        cls_levels.append(
            logits[:, off : off + n].reshape(B, h, w, A_LOC * K)
        )
        box_levels.append(
            box_preds[:, off : off + n].reshape(B, h, w, A_LOC * 4)
        )
        off += n

    want = total_loss_compact(logits, box_preds, labels, box_t, state)
    got = total_loss_compact_nhwc(
        tuple(cls_levels), tuple(box_levels), labels, box_t, state, A_LOC
    )
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), rtol=1e-5)

    # Planar (B, 4, A) box targets — the step's layout — same values.
    got_planar = total_loss_compact_nhwc(
        tuple(cls_levels),
        tuple(box_levels),
        labels,
        np.moveaxis(box_t, -1, -2),
        state,
        A_LOC,
        planar_box_targets=True,
    )
    for k in want:
        np.testing.assert_allclose(
            float(got_planar[k]), float(want[k]), rtol=1e-5
        )

    # GRADIENT parity: the NHWC path's focal term uses a hand-written VJP
    # (losses._focal_nhwc_level_sums_bwd, closed-form derivative) — pin it
    # against autodiff of the reference concatenated path.  A sign flip,
    # a swapped d_pos/d_neg mask, or a dropped ignore mask in the custom
    # backward keeps every forward-value test green while training
    # silently diverges; this is the test that fails instead.
    def loss_nhwc(cls_ls, box_ls):
        return total_loss_compact_nhwc(
            cls_ls, box_ls, labels, box_t, state, A_LOC
        )["loss"]

    def loss_concat(lg, bp):
        return total_loss_compact(lg, bp, labels, box_t, state)["loss"]

    g_nhwc = jax.grad(loss_nhwc, argnums=(0, 1))(
        tuple(map(jnp.asarray, cls_levels)), tuple(map(jnp.asarray, box_levels))
    )
    g_concat = jax.grad(loss_concat, argnums=(0, 1))(
        jnp.asarray(logits), jnp.asarray(box_preds)
    )
    off = 0
    for i, ((h, w), n) in enumerate(zip(level_hw, level_sizes)):
        np.testing.assert_allclose(
            np.asarray(g_nhwc[0][i]).reshape(B, n, K),
            np.asarray(g_concat[0][:, off : off + n]),
            rtol=1e-5,
            atol=1e-8,
        )
        np.testing.assert_allclose(
            np.asarray(g_nhwc[1][i]).reshape(B, n, 4),
            np.asarray(g_concat[1][:, off : off + n]),
            rtol=1e-5,
            atol=1e-8,
        )
        off += n


def test_nhwc_size_mismatch_raises():
    from batchai_retinanet_horovod_coco_tpu.losses import (
        total_loss_compact_nhwc,
    )

    import pytest as _pytest

    with _pytest.raises(ValueError, match="cover"):
        total_loss_compact_nhwc(
            (np.zeros((1, 2, 2, 6)),),
            (np.zeros((1, 2, 2, 8)),),
            np.zeros((1, 12), np.int32),
            np.zeros((1, 12, 4)),
            np.zeros((1, 12), np.int32),
            2,
        )


def test_nhwc_wide_class_fallback_matches_concat():
    """k > 255 exercises the bf16-unsafe fallback branch: broadcast-reshape
    masks in _nhwc_masks plus the state4-carrying custom-VJP residual
    (_focal_nhwc_level_sums_fwd returns e_ck=None, so backward re-derives
    the masks from labels4/state4 instead of the saved encoding).  No other
    test reaches this branch (ADVICE r3) — forward AND gradient must match
    the concatenated reference path at small shapes with k = 260."""
    from batchai_retinanet_horovod_coco_tpu.losses import (
        total_loss_compact,
        total_loss_compact_nhwc,
    )

    rng = np.random.default_rng(17)
    B, K, A_LOC = 1, 260, 2
    level_hw = ((2, 3), (1, 2))
    level_sizes = [h * w * A_LOC for h, w in level_hw]
    A = sum(level_sizes)
    logits = rng.normal(0, 2, (B, A, K)).astype(np.float32)
    box_preds = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    box_t = rng.normal(0, 1, (B, A, 4)).astype(np.float32)
    # Labels beyond 255 must appear so an encoding regression cannot hide.
    labels = rng.integers(0, K, (B, A)).astype(np.int32)
    labels[0, :3] = [256, 258, 259]
    state = rng.choice([-1, 0, 1], (B, A), p=[0.2, 0.5, 0.3]).astype(np.int32)
    state[0, :3] = 1

    cls_levels, box_levels, off = [], [], 0
    for (h, w), n in zip(level_hw, level_sizes):
        cls_levels.append(logits[:, off : off + n].reshape(B, h, w, A_LOC * K))
        box_levels.append(box_preds[:, off : off + n].reshape(B, h, w, A_LOC * 4))
        off += n

    want = total_loss_compact(logits, box_preds, labels, box_t, state)
    got = total_loss_compact_nhwc(
        tuple(cls_levels), tuple(box_levels), labels, box_t, state, A_LOC
    )
    for k in want:
        np.testing.assert_allclose(float(got[k]), float(want[k]), rtol=1e-5)

    def loss_nhwc(cls_ls):
        return total_loss_compact_nhwc(
            cls_ls, tuple(map(jnp.asarray, box_levels)), labels, box_t,
            state, A_LOC,
        )["loss"]

    def loss_concat(lg):
        return total_loss_compact(
            lg, jnp.asarray(box_preds), labels, box_t, state
        )["loss"]

    g_nhwc = jax.grad(loss_nhwc)(tuple(map(jnp.asarray, cls_levels)))
    g_concat = jax.grad(loss_concat)(jnp.asarray(logits))
    off = 0
    for i, n in enumerate(level_sizes):
        np.testing.assert_allclose(
            np.asarray(g_nhwc[i]).reshape(B, n, K),
            np.asarray(g_concat[:, off : off + n]),
            rtol=1e-5,
            atol=1e-8,
        )
        off += n
