"""utils/backoff.py — the one retry/backoff schedule (ISSUE 12 satellite).

The schedule is pinned EXACTLY: geometric growth, ceiling clamp,
explicit-schedule override (the bench probe's env grammar), and
deterministic-seeded jitter — same (policy, attempt) always means the
same delay, different seeds decorrelate.
"""

from __future__ import annotations

import pytest

from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy


class TestSchedule:
    def test_geometric_with_ceiling_exact(self):
        p = BackoffPolicy(
            max_tries=6, base_s=0.5, multiplier=2.0, ceiling_s=3.0
        )
        assert p.delays() == [0.5, 1.0, 2.0, 3.0, 3.0]

    def test_single_try_has_no_sleeps(self):
        assert BackoffPolicy(max_tries=1).delays() == []

    def test_explicit_schedule_reuses_last_value(self):
        p = BackoffPolicy(max_tries=5, schedule=(10.0, 30.0))
        assert p.delays() == [10.0, 30.0, 30.0, 30.0]
        # The bench probe's env grammar builds the same policy.
        q = BackoffPolicy.from_env_schedule(5, "10,30")
        assert q.delays() == p.delays()

    def test_env_schedule_empty_falls_back_to_default(self):
        p = BackoffPolicy.from_env_schedule(3, "", default=(7.0,))
        assert p.delays() == [7.0, 7.0]

    def test_delay_is_pure_per_attempt(self):
        p = BackoffPolicy(max_tries=4, base_s=1.0, jitter=0.3, seed=42)
        # Same (policy, attempt) → same delay, in any call order.
        assert p.delay_s(2) == p.delay_s(2)
        assert p.delays() == [p.delay_s(0), p.delay_s(1), p.delay_s(2)]

    def test_jitter_deterministic_per_seed_and_bounded(self):
        a = BackoffPolicy(max_tries=8, base_s=1.0, multiplier=1.0,
                          jitter=0.2, seed=1)
        b = BackoffPolicy(max_tries=8, base_s=1.0, multiplier=1.0,
                          jitter=0.2, seed=1)
        c = BackoffPolicy(max_tries=8, base_s=1.0, multiplier=1.0,
                          jitter=0.2, seed=2)
        assert a.delays() == b.delays()  # reproducible
        assert a.delays() != c.delays()  # decorrelated across seeds
        for d in a.delays():  # bounded by the jitter fraction
            assert 0.8 <= d <= 1.2

    def test_huge_attempt_counts_never_overflow(self):
        """A breaker probing a permanently dead replica grows its open
        count without bound; the geometric term must saturate at the
        ceiling, not overflow a float (2.0**1024 does)."""
        p = BackoffPolicy(
            max_tries=1_000_000, base_s=0.5, multiplier=2.0, ceiling_s=10.0
        )
        assert p.delay_s(1024) == 10.0
        assert p.delay_s(10_000_000) == 10.0
        jittered = BackoffPolicy(
            max_tries=1_000_000, base_s=0.5, multiplier=2.0,
            ceiling_s=10.0, jitter=0.2, seed=5,
        )
        assert 8.0 <= jittered.delay_s(5000) <= 12.0

    def test_zero_jitter_is_exact(self):
        p = BackoffPolicy(max_tries=3, base_s=2.0, multiplier=3.0,
                          ceiling_s=100.0, jitter=0.0, seed=99)
        assert p.delays() == [2.0, 6.0]

    def test_invalid_configs_raise(self):
        with pytest.raises(ValueError):
            BackoffPolicy(max_tries=0)
        with pytest.raises(ValueError):
            BackoffPolicy(jitter=1.0)
        with pytest.raises(ValueError):
            BackoffPolicy(schedule=())


class TestRetry:
    def test_retry_sleeps_the_exact_schedule(self):
        p = BackoffPolicy(max_tries=4, base_s=0.5, multiplier=2.0,
                          ceiling_s=10.0)
        slept: list[float] = []
        results = iter(["down", "down", "down", "down"])
        attempts, last = p.retry(
            lambda: next(results), sleep=slept.append
        )
        assert attempts == 4
        assert last == "down"
        assert slept == [0.5, 1.0, 2.0]  # max_tries - 1 sleeps, exact

    def test_retry_stops_on_success(self):
        p = BackoffPolicy(max_tries=5, base_s=1.0)
        slept: list[float] = []
        results = iter(["down", None])
        attempts, last = p.retry(lambda: next(results), sleep=slept.append)
        assert attempts == 2 and last is None
        assert slept == [1.0]  # only the sleep before the success

    def test_retry_custom_ok_predicate(self):
        p = BackoffPolicy(max_tries=3, base_s=0.1)
        attempts, last = p.retry(
            lambda: 7, ok=lambda r: r == 7, sleep=lambda _s: None
        )
        assert attempts == 1 and last == 7
