"""Oracle tests for the numpy COCOeval reimplementation.

Every expected value is hand-computed from the published COCOeval bbox
semantics (101-point interpolation, greedy matching, ignore rules) — the
style SURVEY.md §4.1 prescribes: tiny fixtures, exact assertions.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    CocoEval,
    bbox_iou_xywh,
    evaluate_detections,
)


def gt(img, cat, bbox, ann_id=None, iscrowd=0):
    x, y, w, h = bbox
    return {
        "id": ann_id or 0,
        "image_id": img,
        "category_id": cat,
        "bbox": [float(v) for v in bbox],
        "area": float(w * h),
        "iscrowd": iscrowd,
    }


def dt(img, cat, bbox, score):
    return {
        "image_id": img,
        "category_id": cat,
        "bbox": [float(v) for v in bbox],
        "score": float(score),
    }


class TestBboxIou:
    def test_identical_box(self):
        a = np.array([[0.0, 0.0, 10.0, 10.0]])
        iou = bbox_iou_xywh(a, a, np.zeros(1))
        assert iou[0, 0] == pytest.approx(1.0)

    def test_half_overlap(self):
        d = np.array([[0.0, 0.0, 10.0, 10.0]])
        g = np.array([[5.0, 0.0, 10.0, 10.0]])
        # inter 50, union 150
        assert bbox_iou_xywh(d, g, np.zeros(1))[0, 0] == pytest.approx(1 / 3)

    def test_crowd_denominator_is_det_area(self):
        d = np.array([[0.0, 0.0, 10.0, 10.0]])
        g = np.array([[0.0, 0.0, 100.0, 100.0]])
        # det fully inside crowd: inter 100 / det area 100 = 1.0
        assert bbox_iou_xywh(d, g, np.ones(1))[0, 0] == pytest.approx(1.0)
        assert bbox_iou_xywh(d, g, np.zeros(1))[0, 0] == pytest.approx(0.01)


class TestPerfectDetections:
    def test_single_perfect(self):
        stats = evaluate_detections(
            [gt(1, 1, [10, 10, 50, 50])], [dt(1, 1, [10, 10, 50, 50], 0.9)]
        )
        assert stats["AP"] == pytest.approx(1.0)
        assert stats["AP50"] == pytest.approx(1.0)
        assert stats["AR100"] == pytest.approx(1.0)

    def test_many_images_perfect(self):
        gts, dts = [], []
        rng = np.random.default_rng(0)
        for img in range(1, 6):
            for k in range(rng.integers(1, 4)):
                box = [10 * k + 1.0, 5.0 * img, 40.0 + k, 30.0]
                gts.append(gt(img, 1 + k % 2, box, ann_id=len(gts) + 1))
                dts.append(dt(img, 1 + k % 2, box, rng.uniform(0.3, 0.9)))
        stats = evaluate_detections(gts, dts)
        assert stats["AP"] == pytest.approx(1.0)

    def test_complete_miss(self):
        stats = evaluate_detections(
            [gt(1, 1, [0, 0, 10, 10])], [dt(1, 1, [500, 500, 10, 10], 0.9)]
        )
        assert stats["AP"] == pytest.approx(0.0)


class TestIouThresholdSweep:
    def test_iou_in_half_open_band(self):
        # det [0,0,11,10] vs gt [0,0,10,10]: inter 100, union 110 → IoU 0.909;
        # matches at thresholds 0.50..0.90 (9 of 10) but not 0.95.
        stats = evaluate_detections(
            [gt(1, 1, [0, 0, 10, 10])], [dt(1, 1, [0, 0, 11, 10], 0.9)]
        )
        assert stats["AP"] == pytest.approx(0.9)
        assert stats["AP50"] == pytest.approx(1.0)
        assert stats["AP75"] == pytest.approx(1.0)

    def test_iou_just_over_half(self):
        # IoU = 60/140 ≈ 0.4286 < 0.5 → no match at any threshold.
        stats = evaluate_detections(
            [gt(1, 1, [0, 0, 10, 10])], [dt(1, 1, [4, 0, 10, 10], 0.9)]
        )
        assert stats["AP"] == pytest.approx(0.0)


class TestPrecisionInterpolation:
    def test_tp_fp_tp_sequence(self):
        """2 gts; dets scored [TP 0.9, FP 0.8, TP 0.7].

        rc = [.5, .5, 1.], pr = [1, .5, 2/3] → envelope [1, 2/3, 2/3];
        101-pt AP = (51·1 + 50·(2/3)) / 101.
        """
        gts = [gt(1, 1, [0, 0, 10, 10], 1), gt(1, 1, [100, 100, 10, 10], 2)]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.9),
            dt(1, 1, [300, 300, 10, 10], 0.8),
            dt(1, 1, [100, 100, 10, 10], 0.7),
        ]
        stats = evaluate_detections(gts, dts)
        expected = (51 * 1.0 + 50 * (2.0 / 3.0)) / 101
        assert stats["AP"] == pytest.approx(expected)
        assert stats["AR100"] == pytest.approx(1.0)

    def test_missed_gt_halves_recall(self):
        gts = [gt(1, 1, [0, 0, 10, 10], 1), gt(1, 1, [100, 100, 10, 10], 2)]
        dts = [dt(1, 1, [0, 0, 10, 10], 0.9)]
        stats = evaluate_detections(gts, dts)
        # Recall caps at 0.5 with precision 1: 51 recall points reachable.
        assert stats["AP"] == pytest.approx(51 / 101)
        assert stats["AR100"] == pytest.approx(0.5)


class TestGreedyMatching:
    def test_higher_score_takes_gt(self):
        # Two dets overlap one gt; high-score det matches, other is FP.
        gts = [gt(1, 1, [0, 0, 10, 10])]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.6),
            dt(1, 1, [1, 0, 10, 10], 0.9),  # IoU 9/11 ≈ 0.818 — would match
        ]
        ev = CocoEval(gts, dts)
        ev.evaluate()
        e = ev.eval_imgs[(0, 0, 1)]
        # At IoU thr 0.5 (t=0): the 0.9-score det (sorted first) matched.
        assert e["dt_matched"][0].tolist() == [True, False]

    def test_det_prefers_higher_iou_gt(self):
        gts = [gt(1, 1, [0, 0, 10, 10], 1), gt(1, 1, [2, 0, 10, 10], 2)]
        dts = [dt(1, 1, [2, 0, 10, 10], 0.9)]
        ev = CocoEval(gts, dts)
        ev.evaluate()
        # Det matches gt #2 exactly (IoU 1.0 beats 8/12).
        assert ev.eval_imgs[(0, 0, 1)]["dt_matched"][0].tolist() == [True]
        stats = evaluate_detections(gts, dts)
        assert stats["AR100"] == pytest.approx(0.5)


class TestIgnoreRules:
    def test_crowd_match_is_neither_tp_nor_fp(self):
        gts = [
            gt(1, 1, [0, 0, 10, 10], 1),
            gt(1, 1, [100, 100, 50, 50], 2, iscrowd=1),
        ]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.9),
            dt(1, 1, [110, 110, 20, 20], 0.8),  # inside the crowd region
        ]
        stats = evaluate_detections(gts, dts)
        # Crowd det ignored → precision stays 1.0 → AP 1.0.
        assert stats["AP"] == pytest.approx(1.0)

    def test_fp_on_empty_image_counts(self):
        gts = [gt(1, 1, [0, 0, 10, 10])]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.6),
            dt(2, 1, [0, 0, 10, 10], 0.9),  # image 2 has no gt → FP
        ]
        stats = evaluate_detections(gts, dts, img_ids=[1, 2])
        # Global order: FP(0.9) then TP(0.6): pr=[0, .5], and the monotone
        # envelope lifts precision at every recall point to .5.
        assert stats["AP"] == pytest.approx(0.5)


class TestAreaRanges:
    def test_small_gt_excluded_from_large(self):
        # 16x16 = 256 < 32² → small. Perfect det.
        stats = evaluate_detections(
            [gt(1, 1, [0, 0, 16, 16])], [dt(1, 1, [0, 0, 16, 16], 0.9)]
        )
        assert stats["APsmall"] == pytest.approx(1.0)
        assert stats["APmedium"] == -1.0  # no gt in range → undefined
        assert stats["APlarge"] == -1.0

    def test_medium_and_large(self):
        stats = evaluate_detections(
            [
                gt(1, 1, [0, 0, 50, 50], 1),      # 2500 → medium
                gt(1, 1, [200, 200, 100, 100], 2),  # 10000 → large
            ],
            [
                dt(1, 1, [0, 0, 50, 50], 0.9),
                dt(1, 1, [200, 200, 100, 100], 0.8),
            ],
        )
        assert stats["APmedium"] == pytest.approx(1.0)
        assert stats["APlarge"] == pytest.approx(1.0)
        assert stats["AP"] == pytest.approx(1.0)


class TestMaxDets:
    def test_ar1_uses_only_top_det(self):
        gts = [gt(1, 1, [0, 0, 10, 10], 1), gt(1, 1, [100, 100, 10, 10], 2)]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.9),
            dt(1, 1, [100, 100, 10, 10], 0.8),
        ]
        stats = evaluate_detections(gts, dts)
        assert stats["AR1"] == pytest.approx(0.5)
        assert stats["AR10"] == pytest.approx(1.0)


class TestMultiClass:
    def test_classes_evaluated_independently(self):
        gts = [gt(1, 1, [0, 0, 10, 10], 1), gt(1, 2, [100, 100, 10, 10], 2)]
        dts = [
            dt(1, 1, [0, 0, 10, 10], 0.9),       # perfect for cat 1
            dt(1, 2, [300, 300, 10, 10], 0.8),   # miss for cat 2
        ]
        stats = evaluate_detections(gts, dts)
        # cat1 AP 1.0, cat2 AP 0.0 → mean 0.5
        assert stats["AP"] == pytest.approx(0.5)

    def test_wrong_class_is_fp(self):
        gts = [gt(1, 1, [0, 0, 10, 10])]
        dts = [dt(1, 2, [0, 0, 10, 10], 0.9)]
        stats = evaluate_detections(gts, dts)
        # cat1: no det → AP 0. cat2: no gt → undefined (excluded).
        assert stats["AP"] == pytest.approx(0.0)


def test_unsorted_max_dets_rejected():
    """_prepare caches dets truncated at max_dets[-1] and accumulate slices
    [:max_det] per entry — both silently mis-score if max_dets is not
    ascending, so construction must refuse (VERDICT r2 weak #4)."""
    from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import EvalParams

    with pytest.raises(ValueError, match="ascending"):
        CocoEval(
            [gt(1, 1, (0, 0, 10, 10))],
            [dt(1, 1, (0, 0, 10, 10), 0.9)],
            params=EvalParams(max_dets=(100, 10, 1)),
        )
