"""Invariant lint engine tests (ISSUE 5): per-rule bite fixtures, the
uniform suppression grammar, the committed non-growing baseline, the
clean-run over the live tree, and the audit_collectives async dedupe.

Contract mirrored from test_obs.py::test_audit_threads_clean: each rule
must FLAG a minimal bad snippet (the "bite" test) and PASS its suppressed
twin, and the live tree must be clean against the committed baseline —
so deleting any package-side compliance (unbounding a serve queue,
removing a rationale) fails tier-1, not just ``make lint``.

jax-free by design: the analysis package is stdlib-only and these tests
never compile a program.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import textwrap
import threading

import pytest

from batchai_retinanet_horovod_coco_tpu.analysis import engine
from batchai_retinanet_horovod_coco_tpu.utils import locks

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def run_rule(source: str, rule: str, in_package: bool = True):
    """Lint one snippet with one rule; returns the FileResult."""
    return engine.lint_source(
        "snippet.py", "snippet.py", textwrap.dedent(source),
        rule_names=[rule], in_package=in_package,
    )


def findings(source: str, rule: str, in_package: bool = True):
    return run_rule(source, rule, in_package).findings


# ---- bounded-queues ------------------------------------------------------


class TestBoundedQueues:
    def test_bites_on_unbounded_queue(self):
        got = findings(
            """
            import queue
            q = queue.Queue()
            """,
            "bounded-queues",
        )
        assert len(got) == 1 and "maxsize" in got[0].message

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            import queue
            # lint: bounded-queues: drained synchronously before returning
            q = queue.Queue()
            """,
            "bounded-queues",
        )
        assert res.findings == [] and len(res.suppressed) == 1

    def test_maxsize_positional_keyword_and_mp_context(self):
        ok = """
        import queue
        a = queue.Queue(8)
        b = queue.Queue(maxsize=4)
        c = ctx.Queue(maxsize=2)
        """
        assert findings(ok, "bounded-queues") == []

    def test_maxsize_zero_is_still_unbounded(self):
        """Stdlib semantics: maxsize <= 0 means infinite — spelling the
        unboundedness explicitly must not lint clean."""
        for src in ("queue.Queue(0)", "queue.Queue(maxsize=0)",
                    "queue.Queue(maxsize=-1)"):
            got = findings(f"import queue\nq = {src}\n", "bounded-queues")
            assert len(got) == 1 and "infinite" in got[0].message, src

    def test_simple_queue_always_flagged(self):
        got = findings(
            """
            from queue import SimpleQueue
            q = SimpleQueue()
            """,
            "bounded-queues",
        )
        assert len(got) == 1 and "no capacity bound" in got[0].message


# ---- thread-error-contract -----------------------------------------------


class TestThreadErrorContract:
    def test_bites_on_target_without_forwarding(self):
        got = findings(
            """
            import threading

            def runner():
                while True:
                    work()

            t = threading.Thread(target=runner)
            """,
            "thread-error-contract",
        )
        assert len(got) == 1 and "no broad except" in got[0].message

    def test_bites_on_swallowed_crash(self):
        got = findings(
            """
            import threading

            def runner():
                try:
                    work()
                except Exception:
                    pass

            t = threading.Thread(target=runner)
            """,
            "thread-error-contract",
        )
        # Both defects: the swallow AND the absence of a forwarding handler.
        assert len(got) == 2
        assert any("swallows" in f.message for f in got)

    def test_forwarding_target_passes(self):
        ok = """
        import threading

        def runner(out):
            try:
                work()
            except BaseException as e:
                out.put(e)

        t = threading.Thread(target=runner, args=(q,))
        """
        assert findings(ok, "thread-error-contract") == []

    def test_narrow_except_pass_is_legal(self):
        ok = """
        import queue
        import threading

        def runner(q, out):
            try:
                while True:
                    try:
                        q.get(timeout=1)
                    except queue.Empty:
                        pass
            except BaseException as e:
                out.put(e)

        t = threading.Thread(target=runner)
        """
        assert findings(ok, "thread-error-contract") == []

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            import threading

            def runner():
                while True:
                    work()

            # lint: thread-error-contract: fire-and-forget beeper, crash harmless
            t = threading.Thread(target=runner)
            """,
            "thread-error-contract",
        )
        assert res.findings == [] and len(res.suppressed) == 1

    def test_swallow_finding_suppressed_at_handler_line(self):
        """The broad-except-swallows finding anchors at the handler, so
        (per the rule docstring) the suppression goes on/above the
        ``except`` line — the spawn-site comment covers the companion
        no-forwarding finding."""
        res = run_rule(
            """
            import threading

            def runner():
                try:
                    work()
                # lint: thread-error-contract: crash surfaced by probe timeout
                except Exception:
                    pass

            # lint: thread-error-contract: fire-and-forget beeper, crash harmless
            t = threading.Thread(target=runner)
            """,
            "thread-error-contract",
        )
        assert res.findings == [], res.findings
        assert len(res.suppressed) == 2

    def test_resolves_methods_and_partial(self):
        got = findings(
            """
            import functools
            import threading

            class P:
                def _producer(self):
                    while True:
                        work()

                def start(self):
                    self._t = threading.Thread(
                        target=functools.partial(self._producer)
                    )
            """,
            "thread-error-contract",
        )
        assert len(got) == 1 and "_producer" in got[0].message


# ---- jit-purity ----------------------------------------------------------


class TestJitPurity:
    def test_bites_on_time_in_jitted_def(self):
        got = findings(
            """
            import time
            import jax

            def step(x):
                t0 = time.time()
                return x + t0

            step_c = jax.jit(step)
            """,
            "jit-purity",
        )
        assert len(got) == 1 and "time.time()" in got[0].message

    def test_bites_on_print_in_decorated_fn(self):
        got = findings(
            """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("n",))
            def step(x, n):
                print(x)
                return x * n
            """,
            "jit-purity",
        )
        assert len(got) == 1 and "print()" in got[0].message

    def test_bites_on_item_and_np_random_in_shard_map(self):
        got = findings(
            """
            import numpy as np
            from parallel.shmap import shard_map

            def step(x):
                noise = np.random.rand(4)
                return x.item() + noise

            f = shard_map(step, mesh=None, in_specs=None, out_specs=None)
            """,
            "jit-purity",
        )
        assert len(got) == 2
        assert any("host RNG" in f.message for f in got)
        assert any(".item()" in f.message for f in got)

    def test_pure_fn_and_jax_debug_print_pass(self):
        ok = """
        import jax

        def step(x):
            jax.debug.print("x = {}", x)
            return x * 2

        step_c = jax.jit(step)
        lam = jax.jit(lambda images: images + 1)
        """
        assert findings(ok, "jit-purity") == []

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            import jax

            def step(x):
                # lint: jit-purity: trace-time banner, intentionally once
                print("tracing step")
                return x

            step_c = jax.jit(step)
            """,
            "jit-purity",
        )
        assert res.findings == [] and len(res.suppressed) == 1

    def test_pure_callback_subtree_is_sanctioned(self):
        """ISSUE 20: jax.pure_callback / io_callback are THE supported
        host-escape hatches — host effects inside their callback argument
        run outside the trace by contract and must not be flagged."""
        ok = """
        import jax
        from jax.experimental import io_callback

        def step(x):
            y = jax.pure_callback(lambda v: print(v), x.dtype, x)
            io_callback(lambda v: open("/tmp/l", "a").write(str(v)), None, y)
            return y

        step_c = jax.jit(step)
        """
        assert findings(ok, "jit-purity") == []

    def test_host_effect_outside_callback_still_bites(self):
        """The sanction covers ONLY the callback call's subtree."""
        got = findings(
            """
            import jax

            def step(x):
                print("tracing")
                y = jax.pure_callback(lambda v: print(v), x.dtype, x)
                return y

            step_c = jax.jit(step)
            """,
            "jit-purity",
        )
        assert len(got) == 1 and "print()" in got[0].message

    def test_lru_cache_on_jitted_fn_bites(self):
        got = findings(
            """
            import functools
            import jax

            @jax.jit
            @functools.lru_cache(maxsize=None)
            def step(x):
                return x * 2
            """,
            "jit-purity",
        )
        assert len(got) == 1
        assert "lru_cache" in got[0].message
        assert "tracer" in got[0].message

    def test_lru_cache_via_call_form_bites(self):
        got = findings(
            """
            import functools
            import jax

            @functools.cache
            def step(x):
                return x * 2

            step_c = jax.jit(step)
            """,
            "jit-purity",
        )
        assert len(got) == 1 and "functools.cache" in got[0].message

    def test_lru_cache_suppressed_twin_passes(self):
        res = run_rule(
            """
            import functools
            import jax

            @jax.jit
            # lint: jit-purity: keyed on static python ints only
            @functools.lru_cache(maxsize=8)
            def step(x):
                return x * 2
            """,
            "jit-purity",
        )
        assert res.findings == [] and len(res.suppressed) == 1


# ---- monotonic-clock -----------------------------------------------------


class TestMonotonicClock:
    def test_bites_on_time_time(self):
        got = findings("import time\nt0 = time.time()\n", "monotonic-clock")
        assert len(got) == 1 and "monotonic_s" in got[0].message

    def test_bites_on_from_import_alias(self):
        got = findings(
            "from time import time as now\nt0 = now()\n", "monotonic-clock"
        )
        assert len(got) == 1

    def test_second_clock_banned_in_package_only(self):
        src = "import time\nt0 = time.perf_counter()\n"
        assert len(findings(src, "monotonic-clock", in_package=True)) == 1
        assert findings(src, "monotonic-clock", in_package=False) == []

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            import time
            stamp = time.time()  # lint: monotonic-clock: run header wall time
            """,
            "monotonic-clock",
        )
        assert res.findings == [] and len(res.suppressed) == 1


# ---- collective-safety ---------------------------------------------------


class TestCollectiveSafety:
    def test_bites_on_rank_conditional_collective(self):
        got = findings(
            """
            import jax
            from jax import lax

            def step(x):
                if jax.process_index() == 0:
                    x = lax.psum(x, "data")
                return x
            """,
            "collective-safety",
        )
        assert len(got) == 1 and "process_index" in got[0].message

    def test_bites_in_else_branch_and_ternary(self):
        got = findings(
            """
            from jax import lax

            def step(x, rank):
                if rank == 0:
                    y = x
                else:
                    y = lax.pmean(x, "data")
                z = lax.psum(x, "data") if rank else x
                return y + z
            """,
            "collective-safety",
        )
        assert len(got) == 2

    def test_unconditional_and_host_side_rank_work_pass(self):
        ok = """
        import jax
        from jax import lax

        def step(x):
            x = lax.pmean(x, "data")
            if jax.process_index() == 0:
                log_metrics(x)
            return x
        """
        assert findings(ok, "collective-safety") == []

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            from jax import lax

            def step(x, rank):
                if rank >= 0:
                    # lint: collective-safety: condition replica-identical by construction
                    x = lax.psum(x, "data")
                return x
            """,
            "collective-safety",
        )
        assert res.findings == [] and len(res.suppressed) == 1


# ---- watchdog-coverage ---------------------------------------------------


class TestWatchdogCoverage:
    BAD = """
    import threading

    t = threading.Thread(target=print)
    t.start()
    """

    def test_bites_on_unwatched_spawn(self):
        got = findings(self.BAD, "watchdog-coverage")
        assert len(got) == 1 and "watchdog.register" in got[0].message

    def test_legacy_marker_and_register_pass(self):
        ok_marker = """
        import threading

        # watchdog: registers in run() at thread start
        t = threading.Thread(target=print)
        """
        ok_register = """
        import threading
        from batchai_retinanet_horovod_coco_tpu.obs import watchdog

        hb = watchdog.register("worker")
        t = threading.Thread(target=print)
        """
        assert findings(ok_marker, "watchdog-coverage") == []
        assert findings(ok_register, "watchdog-coverage") == []

    def test_uniform_suppression_passes(self):
        res = run_rule(
            """
            import threading

            # lint: watchdog-coverage: short-lived helper, joined two lines down
            t = threading.Thread(target=print)
            """,
            "watchdog-coverage",
        )
        assert res.findings == [] and len(res.suppressed) == 1


# ---- atomic-artifacts ----------------------------------------------------


class TestAtomicArtifacts:
    def test_bites_on_rename_free_write(self):
        got = findings(
            """
            import json

            def write_manifest(path, doc):
                with open(path, "w") as f:
                    json.dump(doc, f)
            """,
            "atomic-artifacts",
        )
        assert len(got) == 1 and "rename commit" in got[0].message

    def test_binary_and_exclusive_modes_bite_too(self):
        src = """
        def a(p, data):
            with open(p, "wb") as f:
                f.write(data)

        def b(p, data):
            with open(p, mode="x") as f:
                f.write(data)
        """
        assert len(findings(src, "atomic-artifacts")) == 2

    def test_inline_rename_commit_passes(self):
        got = findings(
            """
            import json
            import os

            def write_manifest(path, doc):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                os.replace(tmp, path)
            """,
            "atomic-artifacts",
        )
        assert got == []

    def test_atomicio_helper_passes(self):
        got = findings(
            """
            import json
            from batchai_retinanet_horovod_coco_tpu.utils.atomicio import (
                atomic_write_text,
            )

            def write_manifest(path, doc, extra):
                atomic_write_text(path, json.dumps(doc))
                with open(path + ".sidecar", "w") as f:
                    f.write(extra)
            """,
            "atomic-artifacts",
        )
        assert got == []

    def test_append_and_read_modes_exempt(self):
        src = """
        def sink(p):
            with open(p, "a") as f:
                f.write("line")
            with open(p) as f:
                return f.read()
        """
        res = run_rule(src, "atomic-artifacts")
        assert res.findings == []
        assert res.stats.get("atomic-artifacts", 0) == 0  # no write-trunc sites

    def test_nested_helper_does_not_sanction_outer_write(self):
        # The rename lives in a DIFFERENT function that shares the module;
        # the outer bare write is still a finding.
        got = findings(
            """
            import os

            def committer(tmp, path):
                os.replace(tmp, path)

            def sloppy(path, text):
                with open(path, "w") as f:
                    f.write(text)
            """,
            "atomic-artifacts",
        )
        assert len(got) == 1 and got[0].line == 8

    def test_suppressed_twin_passes(self):
        res = run_rule(
            """
            def sink(path, text):
                # lint: atomic-artifacts: write-once private temp, unlinked on error
                with open(path, "w") as f:
                    f.write(text)
            """,
            "atomic-artifacts",
        )
        assert res.findings == [] and len(res.suppressed) == 1

    def test_out_of_package_exempt(self):
        got = findings(
            """
            def driver(path):
                with open(path, "w") as f:
                    f.write("bench artifact")
            """,
            "atomic-artifacts",
            in_package=False,
        )
        assert got == []


# ---- suppression grammar -------------------------------------------------


class TestSuppressionGrammar:
    def test_missing_rationale_does_not_suppress_and_is_a_finding(self):
        res = run_rule(
            """
            import queue
            # lint: bounded-queues:
            q = queue.Queue()
            """,
            "bounded-queues",
        )
        assert len(res.findings) == 1  # original finding survives
        assert any(
            "missing rationale" in f.message for f in res.grammar_findings
        )

    def test_unknown_rule_name_is_a_finding(self):
        res = run_rule(
            """
            import queue
            # lint: bounded-quues: typo'd rule name
            q = queue.Queue()
            """,
            "bounded-queues",
        )
        assert len(res.findings) == 1
        assert any("unknown rule" in f.message for f in res.grammar_findings)

    def test_comma_list_and_trailing_comment_placement(self):
        res = run_rule(
            """
            import queue
            import time
            q = queue.Queue()  # lint: bounded-queues, monotonic-clock: both justified here
            """,
            "bounded-queues",
        )
        assert res.findings == [] and len(res.suppressed) == 1

    def test_lint_text_inside_string_is_not_a_suppression(self):
        res = run_rule(
            '''
            import queue
            DOC = """
            # lint: bounded-queues: not a real comment
            """
            q = queue.Queue()
            ''',
            "bounded-queues",
        )
        assert len(res.findings) == 1

    def test_unused_suppressions_reported(self):
        res = run_rule(
            """
            import queue
            # lint: bounded-queues: nothing to suppress here
            q = queue.Queue(maxsize=4)
            """,
            "bounded-queues",
        )
        assert len(res.unused_suppressions) == 1


# ---- baseline mechanics --------------------------------------------------


class TestBaseline:
    def _write_tree(self, tmp_path, bounded: bool):
        pkg = tmp_path / engine.PACKAGE_NAME
        pkg.mkdir()
        (pkg / "__init__.py").write_text("")
        size = "maxsize=4" if bounded else ""
        (pkg / "mod.py").write_text(
            f"import queue\nq = queue.Queue({size})\n"
        )
        return tmp_path

    def test_grandfathered_finding_passes(self, tmp_path):
        root = self._write_tree(tmp_path, bounded=False)
        bl = tmp_path / "baseline.json"
        engine.write_baseline(str(bl), [engine.Finding(
            rule="bounded-queues",
            path=os.path.join(engine.PACKAGE_NAME, "mod.py"),
            line=2, message="", snippet="q = queue.Queue()",
        )])
        report = engine.run(str(root), baseline_path=str(bl))
        assert report["ok"], report
        assert len(report["grandfathered"]) == 1 and report["new"] == []

    def test_new_finding_fails(self, tmp_path):
        root = self._write_tree(tmp_path, bounded=False)
        bl = tmp_path / "baseline.json"
        engine.write_baseline(str(bl), [])
        report = engine.run(str(root), baseline_path=str(bl))
        assert not report["ok"] and len(report["new"]) == 1

    def test_stale_baseline_entry_fails(self, tmp_path):
        """Non-growing: a FIXED finding must be removed from the baseline."""
        root = self._write_tree(tmp_path, bounded=True)
        bl = tmp_path / "baseline.json"
        engine.write_baseline(str(bl), [engine.Finding(
            rule="bounded-queues",
            path=os.path.join(engine.PACKAGE_NAME, "mod.py"),
            line=2, message="", snippet="q = queue.Queue()",
        )])
        report = engine.run(str(root), baseline_path=str(bl))
        assert not report["ok"] and len(report["stale_baseline"]) == 1

    def test_baseline_is_line_insensitive(self, tmp_path):
        root = self._write_tree(tmp_path, bounded=False)
        mod = root / engine.PACKAGE_NAME / "mod.py"
        mod.write_text("import queue\n\n\n\n" + "q = queue.Queue()\n")
        bl = tmp_path / "baseline.json"
        engine.write_baseline(str(bl), [engine.Finding(
            rule="bounded-queues",
            path=os.path.join(engine.PACKAGE_NAME, "mod.py"),
            line=2, message="", snippet="q = queue.Queue()",
        )])
        report = engine.run(str(root), baseline_path=str(bl))
        assert report["ok"], report


# ---- the live tree -------------------------------------------------------


class TestLiveTree:
    def test_tree_is_clean(self):
        """Tier-1 wiring of the whole engine: the repo lints clean against
        the committed baseline — new violations (e.g. unbounding a serve
        queue, a fresh time.time(), a rank-guarded psum) fail HERE, not
        just in ``make lint``."""
        report = engine.run(REPO_ROOT)
        assert report["new"] == [], report["new"]
        assert report["stale_baseline"] == [], report["stale_baseline"]
        assert report["ok"]

    def test_scan_is_not_vacuous(self):
        """Every rule actually inspected real constructs in this tree (a
        rule that silently stops matching would otherwise pass forever)."""
        report = engine.run(REPO_ROOT)
        stats = report["stats"]
        assert report["files_scanned"] >= 80, report["files_scanned"]
        assert stats.get("bounded-queues", 0) >= 9, stats
        assert stats.get("thread-error-contract", 0) >= 8, stats
        assert stats.get("jit-purity", 0) >= 10, stats
        assert stats.get("monotonic-clock", 0) >= 3, stats
        assert stats.get("collective-safety", 0) >= 10, stats
        assert stats.get("watchdog-coverage", 0) >= 12, stats
        # Most artifact writers now go through utils.atomicio (no raw
        # open); the floor covers the surviving inline tmp+rename sites
        # (anchor sidecar, trace export, perf report, numerics dump,
        # checkpoint writer).
        assert stats.get("atomic-artifacts", 0) >= 5, stats
        # ISSUE 20 project rules: acceptance floors — the lock graph must
        # resolve real acquisition sites and the vocabulary checker must
        # see real emit sites (live counts: ~133 / ~69 / ~205).
        assert stats.get("lock-order", 0) >= 20, stats
        assert stats.get("event-vocabulary", 0) >= 40, stats
        assert stats.get("lock-held-blocking", 0) >= 50, stats
        assert len(report["exports"]["lock_identities"]) >= 15, (
            report["exports"]["lock_identities"])

    def test_compliance_is_load_bearing(self):
        """Removing one package-side compliance makes the engine fail:
        strip the shm pipeline's bounded-queues rationales and the two
        mp.Queue constructions become NEW findings (the acceptance
        criterion's 'deleting any one rule's compliance' probe)."""
        path = os.path.join(
            REPO_ROOT, engine.PACKAGE_NAME, "data", "shm_pipeline.py"
        )
        with open(path) as f:
            src = f.read()
        stripped = "\n".join(
            line for line in src.splitlines()
            if "# lint: bounded-queues:" not in line
        )
        res = engine.lint_source(path, "data/shm_pipeline.py", stripped,
                                 rule_names=["bounded-queues"])
        assert len(res.findings) == 2, res.findings

    def test_cli_json_and_exit_code(self):
        proc = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.analysis", "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["ok"]
        assert set(report["rules"]) == set(engine.all_rule_names())
        assert set(report["rules"]) >= {"lock-order", "lock-held-blocking",
                                        "event-vocabulary"}

    def test_cli_unknown_rule_is_a_clean_error(self):
        """A typo'd --rule must exit 2 with the known-rule list, not die
        with a raw KeyError traceback deep in the walk."""
        proc = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.analysis",
             "--rule", "bounded-quues"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "unknown rule" in proc.stderr
        assert "bounded-queues" in proc.stderr  # the known list is shown
        assert "Traceback" not in proc.stderr
        try:
            engine.run(REPO_ROOT, rule_names=["bounded-quues"])
        except ValueError as e:
            assert "unknown rule" in str(e)
        else:
            raise AssertionError("engine.run accepted an unknown rule")

    def test_cli_refuses_update_baseline_with_rule_filter(self, tmp_path):
        """--update-baseline from a single-rule run would rewrite the
        baseline with only that rule's findings, silently dropping every
        other rule's grandfathered entries — refused, baseline untouched."""
        baseline = tmp_path / "baseline.json"
        baseline.write_text("[]\n")
        proc = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.analysis",
             "--rule", "bounded-queues", "--update-baseline",
             "--baseline", str(baseline)],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "full run" in proc.stderr
        assert baseline.read_text() == "[]\n"


# ---- audit_threads shim compat -------------------------------------------


class TestAuditThreadsShim:
    def _shim(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
        try:
            import audit_threads
        finally:
            sys.path.pop(0)
        return audit_threads

    def test_shim_api_surface(self, tmp_path):
        shim = self._shim()
        bad = tmp_path / "rogue.py"
        bad.write_text("import threading\nt = threading.Thread(target=f)\n")
        v = shim.audit_file(str(bad))
        assert len(v) == 1
        assert set(v[0]) == {"path", "line", "callee", "reason"}
        assert v[0]["callee"] == "Thread"
        assert shim.audit_package(str(tmp_path)) == v

    def test_shim_accepts_engine_suppression_grammar(self, tmp_path):
        shim = self._shim()
        ok = tmp_path / "covered.py"
        ok.write_text(
            "import threading\n"
            "# lint: watchdog-coverage: joined before return\n"
            "t = threading.Thread(target=f)\n"
        )
        assert shim.audit_file(str(ok)) == []

    def test_shim_cli_exit_codes(self, tmp_path):
        script = os.path.join(REPO_ROOT, "scripts", "audit_threads.py")
        clean = subprocess.run(
            [sys.executable, script], capture_output=True, text=True,
            cwd=REPO_ROOT, timeout=120,
        )
        assert clean.returncode == 0, clean.stdout + clean.stderr
        bad = tmp_path / "rogue.py"
        bad.write_text("import threading\nt = threading.Thread(target=f)\n")
        dirty = subprocess.run(
            [sys.executable, script, str(tmp_path), "--json"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        )
        assert dirty.returncode == 1
        doc = json.loads(dirty.stdout)
        assert len(doc["violations"]) == 1


# ---- audit_collectives async dedupe --------------------------------------


class TestAuditCollectivesDedupe:
    def _mod(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
        try:
            import audit_collectives
        finally:
            sys.path.pop(0)
        return audit_collectives

    def test_async_start_counts_result_half_only(self):
        """ISSUE 5 satellite: async ``-start`` results are
        (operand, result) tuples — the payload must match the sync form,
        not double it (the over-count previously documented as a caveat)."""
        ac = self._mod()
        sync = "  %ar = f32[1000]{0} all-reduce(f32[1000]{0} %p)\n"
        async_pair = (
            "  %ars = (f32[1000]{0}, f32[1000]{0}) "
            "all-reduce-start(f32[1000]{0} %p)\n"
            "  %ard = f32[1000]{0} all-reduce-done(%ars)\n"
        )
        s = ac.audit_hlo_text(sync)["all-reduce"]
        a = ac.audit_hlo_text(async_pair)["all-reduce"]
        assert s == {"count": 1, "payload_bytes": 4000}
        assert a == s, f"async form must audit identically: {a} vs {s}"

    def test_variadic_async_start_and_done_not_double_counted(self):
        ac = self._mod()
        hlo = (
            "  %vars = ((f32[10]{0}, f32[20]{0}), (f32[10]{0}, f32[20]{0}))"
            " all-reduce-start(%a, %b)\n"
            "  %vard = (f32[10]{0}, f32[20]{0}) all-reduce-done(%vars)\n"
            "  %ags = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} %x)\n"
            "  %agd = f32[64]{0} all-gather-done(%ags)\n"
        )
        r = ac.audit_hlo_text(hlo)
        assert r["all-reduce"] == {"count": 1, "payload_bytes": 120}, r
        assert r["all-gather"] == {"count": 1, "payload_bytes": 256}, r

    def test_sync_tuple_result_unchanged(self):
        """The pinned CPU modules' variadic sync all-reduce (a plain tuple
        of gradient leaves) still counts every element."""
        ac = self._mod()
        hlo = "  %ar = (f32[10]{0}, f32[20]{0}) all-reduce(%a, %b)\n"
        r = ac.audit_hlo_text(hlo)
        assert r["all-reduce"] == {"count": 1, "payload_bytes": 120}, r


# ---- lock-order / lock-held-blocking fixtures (ISSUE 20) -----------------


FIXTURE_DIR = os.path.join(REPO_ROOT, "tests", "fixtures", "lockgraph")

_CYC = "lockgraph.cyclic.Trio."
_DIA = "lockgraph.diamond.Diamond."
_OUTER = "lockgraph.indirect.Outer._lock"
_INNER = "lockgraph.indirect.Inner._lock"


def _lock_tree(tmp_path, modules):
    """A throwaway tree shaped like the real package, populated with the
    selected ``tests/fixtures/lockgraph`` modules; returns (root, empty
    baseline path)."""
    sub = tmp_path / engine.PACKAGE_NAME / "lockgraph"
    sub.mkdir(parents=True)
    (tmp_path / engine.PACKAGE_NAME / "__init__.py").write_text("")
    (sub / "__init__.py").write_text("")
    for m in modules:
        shutil.copy(os.path.join(FIXTURE_DIR, m + ".py"),
                    str(sub / (m + ".py")))
    bl = tmp_path / "baseline.json"
    engine.write_baseline(str(bl), [])
    return str(tmp_path), str(bl)


class TestLockOrder:
    def test_finds_exactly_the_cycle(self, tmp_path):
        """The whole fixture set contains exactly ONE deadlock (cyclic.py's
        A->B->C->A); the diamond and the indirect edge must not add false
        cycles, and the finding must name all three acquisition chains."""
        root, bl = _lock_tree(
            tmp_path, ["cyclic", "diamond", "indirect", "suppressed"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"])
        assert len(report["new"]) == 1, report["new"]
        f = report["new"][0]
        assert "potential deadlock" in f["message"]
        for ident in (_CYC + "_a", _CYC + "_b", _CYC + "_c"):
            assert ident in f["message"], f["message"]
        cyc_rel = os.path.join(engine.PACKAGE_NAME, "lockgraph", "cyclic.py")
        assert list(f["paths"]) == [cyc_rel]
        assert not report["ok"]

    def test_diamond_is_acyclic_and_edges_exported(self, tmp_path):
        root, bl = _lock_tree(tmp_path, ["diamond", "indirect"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"])
        assert report["new"] == [] and report["ok"], report["new"]
        edges = {(e["src"], e["dst"])
                 for e in report["exports"]["lock_order_edges"]}
        for src, dst in (("_top", "_left"), ("_top", "_right"),
                         ("_top", "_bottom"), ("_left", "_bottom"),
                         ("_right", "_bottom")):
            assert (_DIA + src, _DIA + dst) in edges, edges
        assert (_OUTER, _INNER) in edges, edges  # one-level resolution

    def test_new_edge_vs_committed_order_fails_with_via(self, tmp_path):
        """Drift discipline: an edge the committed file lacks fails the
        run, and the one-level-indirect edge's finding names the callee
        acquisition it was resolved through."""
        root, bl = _lock_tree(tmp_path, ["diamond", "indirect"])
        r0 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        committed = [e for e in r0["exports"]["lock_order_edges"]
                     if e["src"] != _OUTER]
        from batchai_retinanet_horovod_coco_tpu.analysis.rules import (
            lock_graph,
        )
        order = tmp_path / "order.json"
        lock_graph.write_lock_order(str(order), committed)
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"],
                            lock_order_path=str(order))
        assert not report["ok"] and len(report["new"]) == 1, report["new"]
        msg = report["new"][0]["message"]
        assert "not in the committed" in msg
        assert "call lockgraph.indirect.Inner.poke()" in msg, msg

    def test_stale_committed_edge_fails(self, tmp_path):
        root, bl = _lock_tree(tmp_path, ["diamond", "indirect"])
        r0 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        from batchai_retinanet_horovod_coco_tpu.analysis.rules import (
            lock_graph,
        )
        order = tmp_path / "order.json"
        lock_graph.write_lock_order(
            str(order),
            r0["exports"]["lock_order_edges"]
            + [{"src": "lockgraph.ghost.A", "dst": "lockgraph.ghost.B"}])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"],
                            lock_order_path=str(order))
        assert not report["ok"] and len(report["new"]) == 1, report["new"]
        assert "stale committed lock-order edge" in report["new"][0]["message"]

    def test_committed_order_matching_is_clean(self, tmp_path):
        root, bl = _lock_tree(tmp_path, ["diamond", "indirect"])
        r0 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        from batchai_retinanet_horovod_coco_tpu.analysis.rules import (
            lock_graph,
        )
        order = tmp_path / "order.json"
        lock_graph.write_lock_order(
            str(order), r0["exports"]["lock_order_edges"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"],
                            lock_order_path=str(order))
        assert report["ok"] and report["new"] == [], report["new"]

    def test_cycle_fingerprint_is_cross_file_and_line_insensitive(
            self, tmp_path):
        """A cycle finding baselines on (rule, sorted-path-set, snippet):
        the grandfathered entry matches regardless of its recorded line."""
        root, bl = _lock_tree(tmp_path, ["cyclic"])
        r0 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        d = r0["new"][0]
        bl2 = tmp_path / "baseline2.json"
        engine.write_baseline(str(bl2), [engine.Finding(
            rule=d["rule"], path=d["path"], line=999, message="",
            snippet=d["snippet"], paths=d["paths"],
        )])
        r1 = engine.run(root, baseline_path=str(bl2),
                        rule_names=["lock-order"])
        assert r1["ok"], r1["new"]
        assert len(r1["grandfathered"]) == 1 and r1["new"] == []


class TestLockHeldBlocking:
    def test_bites_direct_and_via_callee_and_suppressed_twin(self, tmp_path):
        root, bl = _lock_tree(tmp_path, ["suppressed"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-held-blocking"])
        assert len(report["new"]) == 2, report["new"]
        msgs = [f["message"] for f in report["new"]]
        assert all("time.sleep" in m for m in msgs)
        assert all("lockgraph.suppressed.Sleeper._lock (acquired" in m
                   for m in msgs), msgs  # full hold-site path named
        assert any("via lockgraph.suppressed.Sleeper._nap()" in m
                   for m in msgs), msgs  # one-level blocking path
        assert len(report["suppressed"]) == 1, report["suppressed"]


class TestEngineParallelAndCache:
    def test_jobs_report_identical(self, tmp_path):
        root, bl = _lock_tree(
            tmp_path, ["cyclic", "diamond", "indirect", "suppressed"])
        serial = engine.run(root, baseline_path=bl, jobs=1)
        par = engine.run(root, baseline_path=bl, jobs=4)
        assert serial == par

    def test_parse_cache_invalidated_on_edit(self, tmp_path):
        """Warm-cache runs must still see edits: rewriting the innermost
        diamond acquisition to re-take ``_top`` creates a left<->top cycle
        that the second (cache-warm) run must report."""
        root, bl = _lock_tree(tmp_path, ["diamond"])
        r0 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        assert r0["ok"]
        mod = tmp_path / engine.PACKAGE_NAME / "lockgraph" / "diamond.py"
        mod.write_text(mod.read_text().replace(
            "with self._bottom:", "with self._top:"))
        r1 = engine.run(root, baseline_path=bl, rule_names=["lock-order"])
        assert any("potential deadlock" in f["message"]
                   for f in r1["new"]), r1["new"]

    def test_cli_refuses_update_lock_order_with_rule_filter(self):
        proc = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.analysis",
             "--rule", "lock-order", "--update-lock-order"],
            capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert "full run" in proc.stderr


# ---- event-vocabulary ----------------------------------------------------


class TestEventVocabulary:
    def _tree(self, tmp_path, suppress_rogue: bool = False):
        pkg = tmp_path / engine.PACKAGE_NAME
        obs = pkg / "obs"
        obs.mkdir(parents=True)
        (pkg / "__init__.py").write_text("")
        (obs / "__init__.py").write_text("")
        reader_rel = f"{engine.PACKAGE_NAME}/reader.py"
        (obs / "vocabulary.py").write_text(textwrap.dedent(f"""
            VOCABULARY = {{
                "good_event": {{"kinds": ("event",),
                                "consumers": ("{reader_rel}",)}},
                "ghost_event": {{"kinds": ("event",),
                                 "consumers": ("{reader_rel}",)}},
                "stale_event": {{"kinds": ("series",), "consumers": ()}},
                "lost_event": {{"kinds": ("event",),
                                "consumers": ("no/such/file.py",)}},
            }}
        """))
        sup = ("  # lint: event-vocabulary: ad-hoc debug counter\n"
               if suppress_rogue else "")
        (pkg / "emitter.py").write_text(
            "def go(sink, reg):\n"
            '    sink.event("good_event", n=1)\n'
            '    sink.event("lost_event")\n'
            f"{sup}"
            '    reg.counter("rogue_series")\n'
        )
        (pkg / "reader.py").write_text(
            "def read(ev):\n"
            '    return ev["event"] in ("good_event", "ghost_event")\n'
        )
        bl = tmp_path / "baseline.json"
        engine.write_baseline(str(bl), [])
        return str(tmp_path), str(bl)

    def test_flags_unregistered_orphan_and_stale(self, tmp_path):
        root, bl = self._tree(tmp_path)
        report = engine.run(root, baseline_path=bl,
                            rule_names=["event-vocabulary"])
        msgs = sorted(f["message"] for f in report["new"])
        assert len(msgs) == 4, msgs
        assert any("emitted-but-unregistered" in m and "rogue_series" in m
                   for m in msgs), msgs
        assert any("consumed-but-never-emitted" in m and "ghost_event" in m
                   and "reader.py" in m for m in msgs), msgs
        assert any("registered-but-never-emitted" in m and "stale_event" in m
                   for m in msgs), msgs
        assert any("not a scanned file" in m and "no/such/file.py" in m
                   for m in msgs), msgs
        assert report["stats"]["event-vocabulary"] >= 3
        assert "good_event" in report["exports"]["event_names_emitted"]

    def test_suppressed_emit_site_passes(self, tmp_path):
        root, bl = self._tree(tmp_path, suppress_rogue=True)
        report = engine.run(root, baseline_path=bl,
                            rule_names=["event-vocabulary"])
        assert not any("rogue_series" in f["message"]
                       for f in report["new"]), report["new"]
        assert any("rogue_series" in f["message"]
                   for f in report["suppressed"])

    def test_fixture_trees_without_vocabulary_are_exempt(self, tmp_path):
        root, bl = _lock_tree(tmp_path, ["diamond"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["event-vocabulary"])
        assert report["new"] == [] and report["ok"]


# ---- runtime lock-order witness (utils/locks.py) -------------------------


def test_witness_armed_in_tier1():
    """tests/conftest.py arms RETINANET_LOCK_DEBUG for the whole tier, so
    every multithreaded test validates the committed order for free."""
    assert os.environ.get(locks.ENV_FLAG) == "1"
    assert locks.enabled()


class TestLockWitness:
    @pytest.fixture(autouse=True)
    def _armed(self, monkeypatch):
        monkeypatch.setenv(locks.ENV_FLAG, "1")
        locks._set_committed_for_testing(set())
        locks.reset_observed()
        yield
        locks._set_committed_for_testing(None)
        locks.reset_observed()

    def test_disabled_is_identity(self, monkeypatch):
        """PARITY: with the flag off, make_lock returns a PLAIN lock."""
        monkeypatch.setenv(locks.ENV_FLAG, "0")
        assert type(locks.make_lock("x")) is type(threading.Lock())
        assert type(locks.make_rlock("x")) is type(threading.RLock())

    def test_committed_order_passes_and_inversion_raises(self):
        locks._set_committed_for_testing({("fix.A", "fix.B")})
        a, b = locks.make_lock("fix.A"), locks.make_lock("fix.B")
        with a:
            with b:
                pass  # the committed direction: clean
        with b:
            with pytest.raises(locks.LockOrderViolation) as ei:
                with a:
                    pass
        msg = str(ei.value)
        # Both chains named: this thread's actual chain and the committed.
        assert "[fix.B -> fix.A]" in msg, msg
        assert "'fix.A' -> 'fix.B'" in msg, msg

    def test_unknown_pairs_recorded_not_raised(self):
        a, b = locks.make_lock("w.A"), locks.make_lock("w.B")
        with a:
            with b:
                pass
        assert ("w.A", "w.B") in locks.observed_edges()

    def test_reentry_never_checked(self):
        locks._set_committed_for_testing({("r.A", "r.B")})
        r = locks.make_rlock("r.B")
        with r:
            with r:  # same-name reentry: exempt by design
                pass

    def test_condition_over_debug_rlock(self):
        cv = threading.Condition(locks.make_rlock("cv.lock"))
        with cv:
            cv.notify_all()

    def test_static_edges_drive_the_witness(self, tmp_path):
        """End-to-end over the fixture package: the edges the STATIC rule
        computes become the committed order the RUNTIME witness enforces —
        replaying the diamond's sanctioned order passes, the inverted
        acquisition raises."""
        root, bl = _lock_tree(tmp_path, ["diamond"])
        report = engine.run(root, baseline_path=bl,
                            rule_names=["lock-order"])
        edges = {(e["src"], e["dst"])
                 for e in report["exports"]["lock_order_edges"]}
        assert (_DIA + "_top", _DIA + "_bottom") in edges
        locks._set_committed_for_testing(edges)
        top = locks.make_lock(_DIA + "_top")
        bottom = locks.make_lock(_DIA + "_bottom")
        with top:
            with bottom:
                pass
        with bottom:
            with pytest.raises(locks.LockOrderViolation):
                with top:
                    pass
