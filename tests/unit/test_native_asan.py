"""AddressSanitizer run of the native COCOeval kernels (SURVEY.md §5.2).

The reference stack had no sanitizer story; here the one hand-written C++
component gets an ASAN gate: build the instrumented variant, exercise both
kernels on adversarial fixtures in a subprocess with libasan preloaded, and
fail on any sanitizer report.
"""

import os
import shutil
import subprocess
import sys

import pytest

_SCRIPT = r"""
import os
import numpy as np

from batchai_retinanet_horovod_coco_tpu.evaluate import _native

kernels = _native.get_kernels()
assert kernels is not None, "ASAN native build did not load"

rng = np.random.default_rng(0)
for trial in range(20):
    n_gt = int(rng.integers(0, 7))
    n_dt = int(rng.integers(0, 9))
    gt = np.abs(rng.normal(10, 5, (n_gt, 4)))
    dt = np.abs(rng.normal(10, 5, (n_dt, 4)))
    crowd = rng.integers(0, 2, n_gt).astype(np.uint8)
    iou = kernels.iou_matrix(dt, gt, crowd)
    assert iou.shape == (n_dt, n_gt)
    ignore = rng.integers(0, 2, n_gt).astype(np.uint8)
    thrs = np.array([0.5, 0.75])
    kernels.match_detections(iou, thrs, ignore, crowd)
print("ASAN_DRIVE_OK")
"""


def _libasan() -> str | None:
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True, text=True, timeout=60,
        )
    except (OSError, subprocess.SubprocessError):
        return None
    path = out.stdout.strip()
    return path if path and os.path.sep in path and os.path.exists(path) else None


@pytest.mark.slow
def test_native_kernels_under_asan():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    libasan = _libasan()
    if libasan is None:
        pytest.skip("no libasan")
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(
        os.environ,
        LD_PRELOAD=libasan,
        # Stock CPython is not leak-clean; we gate on memory ERRORS only.
        ASAN_OPTIONS="detect_leaks=0:abort_on_error=1",
        BATCHAI_TPU_NATIVE_ASAN="1",
        PYTHONPATH=os.pathsep.join(
            p for p in (repo_root, os.environ.get("PYTHONPATH")) if p
        ),
    )
    # An outer numpy-path run must not turn this gate into a failure.
    env.pop("BATCHAI_TPU_NO_NATIVE", None)
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        env=env, capture_output=True, text=True, timeout=300,
    )
    out = proc.stdout + proc.stderr
    assert "AddressSanitizer" not in out, out[-4000:]
    assert proc.returncode == 0, out[-4000:]
    assert "ASAN_DRIVE_OK" in out
