"""Fused Pallas NMS kernel parity (ISSUE 6, ops/pallas/nms.py).

The kernel replaces ONLY the suppression stage (ops.nms.greedy_keep);
candidate selection and compaction are the literally-shared jnp stages.
These tests pin the consequence: in interpreter mode the kernel's output
is BIT-IDENTICAL to ``ops/nms.py`` — per stage, per full program, and
through the full detect path (``collect_detections``) with the production
``DetectConfig`` dispatch — including the padding/validity edges
(sub-threshold fields, all-padding images, cross-block suppression
chains, same-class masking).

Interpreter mode runs the REAL kernel body on CPU; a TPU session runs
the same assertions compiled (nms_interpret=False path) for free via the
schedule, but parity here must never depend on a chip being present.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from batchai_retinanet_horovod_coco_tpu.ops import nms as nms_lib
from batchai_retinanet_horovod_coco_tpu.ops.pallas import nms as pallas_nms


def _random_boxes_scores(
    batch: int, num: int, num_classes: int, seed: int = 0, dup_frac: float = 0.3
):
    """Box/score fields with deliberate near-duplicates so real
    suppression chains form (pure-random boxes rarely overlap)."""
    rng = np.random.default_rng(seed)
    xy = rng.uniform(0, 400, (batch, num, 2)).astype(np.float32)
    wh = rng.uniform(4, 120, (batch, num, 2)).astype(np.float32)
    boxes = np.concatenate([xy, xy + wh], axis=-1)
    ndup = int(num * dup_frac)
    if ndup:
        src = rng.integers(0, num, (batch, ndup))
        jitter = rng.normal(0, 3, (batch, ndup, 4)).astype(np.float32)
        for b in range(batch):
            boxes[b, :ndup] = boxes[b, src[b]] + jitter[b]
    scores = rng.uniform(0, 1, (batch, num, num_classes)).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(scores)


def _assert_detections_identical(a, b, context=""):
    for field in a._fields:
        fa, fb = np.asarray(getattr(a, field)), np.asarray(getattr(b, field))
        assert fa.dtype == fb.dtype, (context, field, fa.dtype, fb.dtype)
        np.testing.assert_array_equal(fa, fb, err_msg=f"{context}:{field}")


class TestKeepMaskParity:
    @pytest.mark.parametrize(
        "batch,k,block_k",
        [
            (1, 128, 128),   # single block
            (2, 384, 128),   # three blocks: cross-block suppression
            (1, 300, 128),   # K not a block multiple (pad tail)
            (2, 500, 256),   # partial second block
        ],
    )
    def test_bit_identical_to_greedy_keep(self, batch, k, block_k):
        boxes, cls_scores = _random_boxes_scores(batch, k, 5, seed=k)
        sel = jax.vmap(
            lambda b, s: nms_lib.select_candidates(b, s, 0.05, k)
        )(boxes, cls_scores)
        cand_boxes, cand_scores, class_idx = sel
        ref = pallas_nms.nms_keep_mask_reference(
            cand_boxes, cand_scores, class_idx, 0.5
        )
        got = pallas_nms.nms_keep_mask(
            cand_boxes, cand_scores, class_idx, 0.5,
            block_k=block_k, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_cross_block_suppression_chain(self):
        """A kept box in block 0 suppresses a box in block 2, while a
        SUPPRESSED box in block 0 must not suppress anything — the greedy
        fixed point's defining property, stretched across block
        boundaries (where the kernel's keep_ref scratch carries it)."""
        block = 128
        k = 3 * block
        # Descending scores; identical box triples at positions
        # (0, block+1, 2*block+2): 0 kept -> later two suppressed.
        # Position 1 overlaps 0 (suppressed), and an exact copy of 1 at
        # 2*block+5 must survive ONLY via 0's suppression, not 1's.
        rng = np.random.default_rng(7)
        xy = rng.uniform(0, 1000, (k, 2)).astype(np.float32)
        wh = rng.uniform(500, 600, (k, 2)).astype(np.float32)
        boxes = np.concatenate([xy, xy + wh], axis=-1)
        base = np.array([10.0, 10.0, 100.0, 100.0], np.float32)
        # 20px shift of a 90px box: IoU(base, shifted) = 6300/9900 ≈ 0.64
        # (a 30px shift would be exactly 0.5 — NOT > threshold).
        shifted = base + np.array([20.0, 0.0, 20.0, 0.0], np.float32)
        boxes[0] = base
        boxes[1] = shifted           # IoU with base > 0.5 -> suppressed
        boxes[block + 1] = base      # duplicate of kept 0 -> suppressed
        boxes[2 * block + 2] = base  # two blocks down -> suppressed
        boxes[2 * block + 5] = shifted  # 1 is dead; only 0 can judge it
        scores = np.linspace(1.0, 0.5, k).astype(np.float32)
        cls = np.zeros((k,), np.int32)

        ref = nms_lib.greedy_keep(
            jnp.asarray(boxes), jnp.asarray(scores), 0.5, jnp.asarray(cls)
        )
        got = pallas_nms.nms_keep_mask(
            jnp.asarray(boxes)[None], jnp.asarray(scores)[None],
            jnp.asarray(cls)[None], 0.5, block_k=block, interpret=True,
        )[0]
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        keep = np.asarray(got)
        assert keep[0] and not keep[1]
        assert not keep[block + 1] and not keep[2 * block + 2]
        # shifted overlaps base by ~0.64 IoU -> suppressed by kept 0.
        assert not keep[2 * block + 5]

    def test_same_class_masking_matches(self):
        """Identical boxes in DIFFERENT classes never suppress each other;
        in the same class they do — both backends, bitwise."""
        box = np.array([5.0, 5.0, 50.0, 50.0], np.float32)
        boxes = jnp.asarray(np.tile(box, (4, 1))[None])
        scores = jnp.asarray(
            np.array([0.9, 0.8, 0.7, 0.6], np.float32)[None]
        )
        cls = jnp.asarray(np.array([0, 1, 0, 1], np.int32)[None])
        ref = pallas_nms.nms_keep_mask_reference(boxes, scores, cls, 0.5)
        got = pallas_nms.nms_keep_mask(
            boxes, scores, cls, 0.5, block_k=128, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        assert np.asarray(got).tolist() == [[True, True, False, False]]

    def test_padding_never_kept_never_suppresses(self):
        """_NEG_INF-scored padding slots (select_candidates' sub-threshold
        fill) must neither be kept nor suppress a live box — even when a
        padding slot's zero-box overlaps another padding zero-box."""
        k = 130  # forces the kernel's own tail padding on top
        boxes = np.zeros((k, 4), np.float32)
        boxes[0] = [0.0, 0.0, 10.0, 10.0]
        scores = np.full((k,), nms_lib._NEG_INF, np.float32)
        scores[0] = 0.9
        cls = np.full((k,), -1, np.int32)
        cls[0] = 2
        ref = pallas_nms.nms_keep_mask_reference(
            jnp.asarray(boxes)[None], jnp.asarray(scores)[None],
            jnp.asarray(cls)[None], 0.5,
        )
        got = pallas_nms.nms_keep_mask(
            jnp.asarray(boxes)[None], jnp.asarray(scores)[None],
            jnp.asarray(cls)[None], 0.5, block_k=128, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
        keep = np.asarray(got)[0]
        assert keep[0] and not keep[1:].any()

    def test_block_k_must_be_lane_multiple(self):
        boxes, cls_scores = _random_boxes_scores(1, 64, 2)
        with pytest.raises(ValueError, match="multiple of 128"):
            pallas_nms.nms_keep_mask(
                boxes[:, :, :4], cls_scores[:, :, 0],
                jnp.zeros((1, 64), jnp.int32), block_k=100, interpret=True,
            )


class TestFullProgramParity:
    @pytest.mark.parametrize("pre_nms_size", [128, 500, 1000])
    def test_batched_multiclass_nms_bit_identical(self, pre_nms_size):
        boxes, cls_scores = _random_boxes_scores(2, 800, 6, seed=3)
        ref = nms_lib.batched_multiclass_nms(
            boxes, cls_scores, pre_nms_size=pre_nms_size
        )
        got = pallas_nms.batched_multiclass_nms_pallas(
            boxes, cls_scores, pre_nms_size=pre_nms_size,
            block_k=128, interpret=True,
        )
        fb = pallas_nms.batched_multiclass_nms_pallas(
            boxes, cls_scores, pre_nms_size=pre_nms_size, use_kernel=False
        )
        _assert_detections_identical(ref, got, "kernel")
        _assert_detections_identical(ref, fb, "jnp-fallback")

    def test_all_below_threshold_is_all_invalid(self):
        """Zero surviving candidates: every slot padded, no keeps, and the
        two backends agree bit-for-bit on the empty result."""
        boxes, cls_scores = _random_boxes_scores(1, 200, 4, seed=9)
        ref = nms_lib.batched_multiclass_nms(
            boxes, cls_scores * 0.0, score_threshold=0.5, pre_nms_size=200
        )
        got = pallas_nms.batched_multiclass_nms_pallas(
            boxes, cls_scores * 0.0, score_threshold=0.5, pre_nms_size=200,
            block_k=128, interpret=True,
        )
        _assert_detections_identical(ref, got, "empty")
        assert not np.asarray(got.valid).any()


class TestDetectPathParity:
    def test_collect_detections_bit_identical(
        self, tmp_path, tiny_model_and_state
    ):
        """The acceptance bar: the FULL detect path (forward → decode →
        clip → NMS → COCO conversion) with the schedule-dispatched Pallas
        backend is bit-identical to the XLA path.  score_threshold 0.001
        keeps the untrained head's sub-0.05 prior from making the check
        vacuous (the PR-2 lesson: detections must actually flow)."""
        import dataclasses

        from batchai_retinanet_horovod_coco_tpu.data.coco import CocoDataset
        from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
            PipelineConfig,
            build_pipeline,
        )
        from batchai_retinanet_horovod_coco_tpu.data.synthetic import (
            make_synthetic_coco,
        )
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
            collect_detections,
        )

        model, state = tiny_model_and_state
        make_synthetic_coco(
            str(tmp_path), num_images=4, num_classes=3, image_size=(128, 128)
        )
        ds = CocoDataset(
            str(tmp_path / "instances_train.json"), str(tmp_path / "train")
        )
        pipe = PipelineConfig(
            batch_size=2, buckets=((128, 128),), min_side=128, max_side=128,
            max_gt=8, shuffle=False,
        )
        base = DetectConfig(score_threshold=0.001)
        xla_cfg = dataclasses.replace(base, nms_impl="xla")
        pallas_cfg = dataclasses.replace(
            base, nms_impl="pallas", nms_block_k=128, nms_interpret=True
        )
        results = {}
        for name, cfg in [("xla", xla_cfg), ("pallas", pallas_cfg)]:
            batches = build_pipeline(ds, pipe, train=False)
            results[name] = collect_detections(
                state, model, ds, batches, cfg, pipelined=False
            )
        assert results["xla"], "no detections flowed (vacuous parity)"
        assert results["xla"] == results["pallas"]
