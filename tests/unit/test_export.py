"""Inference-artifact export/reload (the convert_model.py equivalent).

The contract (SURVEY.md M3): a converted artifact must reproduce the live
detection path — forward, decode, clip, on-device NMS — without the training
code, like the reference's inference ``.h5``.  Round-trip equality against
``make_detect_fn`` is the oracle.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
    export_model,
    load_model,
)

CONFIG = DetectConfig(pre_nms_size=64, max_detections=10)


def test_roundtrip_matches_live_detection(tiny_model_and_state, tmp_path):
    model, state = tiny_model_and_state
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (2, 64, 64, 3), dtype=np.uint8)

    manifest_path = export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=CONFIG, class_names=["a", "b", "c"],
        label_to_cat_id={0: 1, 1: 2, 2: 3},
    )
    assert manifest_path.endswith("manifest.json")

    loaded = load_model(str(tmp_path / "exp"))
    assert loaded.buckets() == [(2, 64, 64)]
    assert loaded.manifest["class_names"] == ["a", "b", "c"]

    got = loaded(images)
    want = make_detect_fn(model, (64, 64), CONFIG)(state, images)
    for g, w, name in zip(got, want, ("boxes", "scores", "labels", "valid")):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=name
        )


def test_unknown_shape_rejected(tiny_model_and_state, tmp_path):
    model, state = tiny_model_and_state
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=CONFIG,
    )
    loaded = load_model(str(tmp_path / "exp"))
    with pytest.raises(ValueError, match="no exported program"):
        loaded(np.zeros((1, 64, 64, 3), dtype=np.uint8))


@pytest.mark.slow
def test_convert_model_cli(tiny_model_and_state, tmp_path, monkeypatch):
    """End-to-end: train 1 step with snapshots, convert, reload, run."""
    import os
    import sys

    # repo root, derived from this file's own path
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import convert_model
    from train import main as train_main

    train_main(
        ["synthetic",
         "--synthetic-root", str(tmp_path / "data"),
         "--synthetic-images", "4", "--synthetic-size", "64",
         "--image-min-side", "64", "--image-max-side", "64",
         "--backbone", "resnet_test", "--f32",
         "--batch-size", "2", "--num-devices", "1",
         "--max-gt", "8", "--workers", "2", "--steps", "1",
         "--snapshot-path", str(tmp_path / "ckpt"),
         "--checkpoint-every", "1"]
    )
    manifest = convert_model.main(
        ["--snapshot-path", str(tmp_path / "ckpt"),
         "--output", str(tmp_path / "exp"),
         "--num-classes", "3", "--backbone", "resnet_test", "--f32",
         "--image-min-side", "64", "--image-max-side", "64",
         "--batch-size", "2"]
    )
    loaded = load_model(str(tmp_path / "exp"))
    boxes, scores, labels, valid = loaded(
        np.zeros((2, 64, 64, 3), dtype=np.uint8)
    )
    assert np.asarray(boxes).shape[0] == 2
    assert np.asarray(valid).dtype == bool
