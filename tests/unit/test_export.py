"""Inference-artifact export/reload (the convert_model.py equivalent).

The contract (SURVEY.md M3): a converted artifact must reproduce the live
detection path — forward, decode, clip, on-device NMS — without the training
code, like the reference's inference ``.h5``.  Round-trip equality against
``make_detect_fn`` is the oracle.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    DetectConfig,
    make_detect_fn,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
    export_model,
    load_model,
)

CONFIG = DetectConfig(pre_nms_size=64, max_detections=10)


def test_roundtrip_matches_live_detection(tiny_model_and_state, tmp_path):
    model, state = tiny_model_and_state
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (2, 64, 64, 3), dtype=np.uint8)

    manifest_path = export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=CONFIG, class_names=["a", "b", "c"],
        label_to_cat_id={0: 1, 1: 2, 2: 3},
    )
    assert manifest_path.endswith("manifest.json")

    loaded = load_model(str(tmp_path / "exp"))
    assert loaded.buckets() == [(2, 64, 64)]
    assert loaded.manifest["class_names"] == ["a", "b", "c"]

    got = loaded(images)
    want = make_detect_fn(model, (64, 64), CONFIG)(state, images)
    for g, w, name in zip(got, want, ("boxes", "scores", "labels", "valid")):
        np.testing.assert_array_equal(
            np.asarray(g), np.asarray(w), err_msg=name
        )


def test_export_multiple_batch_sizes(tiny_model_and_state, tmp_path):
    """One artifact per (bucket, batch size); the manifest records the
    inference resize rule for manifest-driven serve routing (ISSUE 4)."""
    model, state = tiny_model_and_state
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=(1, 2), config=CONFIG,
        image_min_side=64, image_max_side=64,
    )
    loaded = load_model(str(tmp_path / "exp"))
    assert loaded.buckets() == [(1, 64, 64), (2, 64, 64)]
    assert loaded.bucket_shapes() == [(64, 64)]
    assert loaded.batch_sizes((64, 64)) == [1, 2]
    assert loaded.manifest["image_min_side"] == 64
    assert loaded.manifest["image_max_side"] == 64
    # both programs run; warmup touches every one
    loaded.warmup()
    for b in (1, 2):
        out = loaded(np.zeros((b, 64, 64, 3), dtype=np.uint8))
        assert np.asarray(out[0]).shape[0] == b


_NO_IMPORT_LOADER = """
import json, os, sys
import numpy as np
from jax import export as jax_export

export_dir, in_npz, out_npz = sys.argv[1:4]
with open(os.path.join(export_dir, "manifest.json")) as f:
    manifest = json.load(f)
entry = manifest["artifacts"][0]
with open(os.path.join(export_dir, entry["file"]), "rb") as f:
    fn = jax_export.deserialize(f.read()).call
images = np.load(in_npz)["images"]
boxes, scores, labels, valid = fn(images)
np.savez(out_npz, boxes=np.asarray(boxes), scores=np.asarray(scores),
         labels=np.asarray(labels), valid=np.asarray(valid))
banned = sorted(m for m in sys.modules if "batchai_retinanet" in m)
assert not banned, f"model code leaked into the loader: {banned}"
print("loaded_without_model_code")
"""


def test_artifact_runs_with_no_model_code_imports(
    tiny_model_and_state, tmp_path
):
    """ISSUE 4 satellite: a ``detector_<H>x<W>_b<B>.stablehlo`` artifact
    is consumable by a process that imports ONLY jax + numpy — no model
    code, no package import — and its detections are bit-identical to the
    live ``make_detect_fn`` path."""
    import subprocess
    import sys

    model, state = tiny_model_and_state
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=CONFIG,
    )
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (2, 64, 64, 3), dtype=np.uint8)
    np.savez(tmp_path / "in.npz", images=images)
    r = subprocess.run(
        [sys.executable, "-c", _NO_IMPORT_LOADER, str(tmp_path / "exp"),
         str(tmp_path / "in.npz"), str(tmp_path / "out.npz")],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "loaded_without_model_code" in r.stdout

    got = np.load(tmp_path / "out.npz")
    want = make_detect_fn(model, (64, 64), CONFIG)(state, images)
    for name, w in zip(("boxes", "scores", "labels", "valid"), want):
        np.testing.assert_array_equal(got[name], np.asarray(w), err_msg=name)


def test_unknown_shape_rejected(tiny_model_and_state, tmp_path):
    model, state = tiny_model_and_state
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=CONFIG,
    )
    loaded = load_model(str(tmp_path / "exp"))
    with pytest.raises(ValueError, match="no exported program"):
        loaded(np.zeros((1, 64, 64, 3), dtype=np.uint8))


def test_convert_model_cli_roundtrip_to_server(tmp_path):
    """ISSUE 4 satellite: checkpoint → ``convert_model.py`` (with bucket /
    batch-size / platform flags) → export dir → serve engine answers a
    request.  Fast-tier: the checkpoint is written directly (no training
    run; the slow CLI test covers train.py in the loop)."""
    import os
    import sys

    import jax
    import jax.numpy as jnp
    import optax

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import convert_model
    from batchai_retinanet_horovod_coco_tpu.models import (
        RetinaNetConfig,
        build_retinanet,
    )
    from batchai_retinanet_horovod_coco_tpu.serve import (
        DetectEngine,
        DetectionServer,
        ServeConfig,
    )
    from batchai_retinanet_horovod_coco_tpu.train import create_train_state
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        CheckpointManager,
    )

    # Exactly the model convert_model.py rebuilds from these flags.
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone="resnet_test", norm_kind="gn",
            dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(0.01), (1, 64, 64, 3), jax.random.key(0)
    )
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(state, step=0, force=True)
    mgr.wait()
    mgr.close()

    manifest = convert_model.main(
        ["--snapshot-path", str(tmp_path / "ckpt"),
         "--output", str(tmp_path / "exp"),
         "--num-classes", "3", "--backbone", "resnet_test", "--f32",
         "--buckets", "64x64", "--batch-sizes", "1,2",
         "--image-min-side", "64", "--image-max-side", "64",
         "--score-threshold", "0.001", "--platform", "cpu"]
    )
    assert manifest.endswith("manifest.json")

    engine = DetectEngine.from_export(str(tmp_path / "exp"))
    assert engine.buckets == ((64, 64),)
    assert engine.batch_sizes((64, 64)) == [1, 2]
    rng = np.random.default_rng(0)
    with DetectionServer(
        engine, ServeConfig(max_delay_ms=5, preprocess_workers=1)
    ) as srv:
        dets = srv.submit(
            rng.integers(0, 256, (70, 60, 3), dtype=np.uint8)
        ).result(timeout=120)
    assert isinstance(dets, list)
    for d in dets:
        assert set(d) == {"category_id", "bbox", "score"}


@pytest.mark.slow
def test_convert_model_cli(tiny_model_and_state, tmp_path, monkeypatch):
    """End-to-end: train 1 step with snapshots, convert, reload, run."""
    import os
    import sys

    # repo root, derived from this file's own path
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    import convert_model
    from train import main as train_main

    train_main(
        ["synthetic",
         "--synthetic-root", str(tmp_path / "data"),
         "--synthetic-images", "4", "--synthetic-size", "64",
         "--image-min-side", "64", "--image-max-side", "64",
         "--backbone", "resnet_test", "--f32",
         "--batch-size", "2", "--num-devices", "1",
         "--max-gt", "8", "--workers", "2", "--steps", "1",
         "--snapshot-path", str(tmp_path / "ckpt"),
         "--checkpoint-every", "1"]
    )
    manifest = convert_model.main(
        ["--snapshot-path", str(tmp_path / "ckpt"),
         "--output", str(tmp_path / "exp"),
         "--num-classes", "3", "--backbone", "resnet_test", "--f32",
         "--image-min-side", "64", "--image-max-side", "64",
         "--batch-size", "2"]
    )
    loaded = load_model(str(tmp_path / "exp"))
    boxes, scores, labels, valid = loaded(
        np.zeros((2, 64, 64, 3), dtype=np.uint8)
    )
    assert np.asarray(boxes).shape[0] == 2
    assert np.asarray(valid).dtype == bool
