"""Pretrained-backbone import tests (torch resnet50 layout → flax tree)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.models.import_weights import (
    apply_backbone_weights,
    convert_torch_resnet50,
)
from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet


def fake_torch_resnet50_sd(rng) -> dict[str, np.ndarray]:
    """Random arrays in torchvision resnet50 names/shapes (incl. fc, ignored)."""
    sd = {"conv1.weight": rng.normal(0, 1, (64, 3, 7, 7)).astype(np.float32)}

    def bn(prefix, c):
        sd[f"{prefix}.weight"] = rng.normal(1, 0.1, c).astype(np.float32)
        sd[f"{prefix}.bias"] = rng.normal(0, 0.1, c).astype(np.float32)
        sd[f"{prefix}.running_mean"] = rng.normal(0, 0.1, c).astype(np.float32)
        sd[f"{prefix}.running_var"] = rng.uniform(0.5, 1.5, c).astype(np.float32)

    bn("bn1", 64)
    in_c = 64
    for i, (blocks, width) in enumerate(
        [(3, 64), (4, 128), (6, 256), (3, 512)], start=1
    ):
        for b in range(blocks):
            p = f"layer{i}.{b}"
            sd[f"{p}.conv1.weight"] = rng.normal(
                0, 0.05, (width, in_c, 1, 1)
            ).astype(np.float32)
            bn(f"{p}.bn1", width)
            sd[f"{p}.conv2.weight"] = rng.normal(
                0, 0.05, (width, width, 3, 3)
            ).astype(np.float32)
            bn(f"{p}.bn2", width)
            sd[f"{p}.conv3.weight"] = rng.normal(
                0, 0.05, (width * 4, width, 1, 1)
            ).astype(np.float32)
            bn(f"{p}.bn3", width * 4)
            if b == 0:
                sd[f"{p}.downsample.0.weight"] = rng.normal(
                    0, 0.05, (width * 4, in_c, 1, 1)
                ).astype(np.float32)
                bn(f"{p}.downsample.1", width * 4)
                in_c = width * 4
    sd["fc.weight"] = rng.normal(0, 0.05, (1000, 2048)).astype(np.float32)
    sd["fc.bias"] = np.zeros(1000, np.float32)
    return sd


class TestImport:
    def test_convert_and_apply_frozen_bn(self):
        rng = np.random.default_rng(0)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)

        model = ResNet(stage_sizes=(3, 4, 6, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        params, stats = apply_backbone_weights(
            {"backbone": variables["params"]},
            {"backbone": variables["batch_stats"]},
            imp_params,
            imp_stats,
        )
        # Spot checks: OIHW→HWIO transpose and BN stat placement.
        np.testing.assert_allclose(
            params["backbone"]["stem_conv"]["kernel"],
            np.transpose(sd["conv1.weight"], (2, 3, 1, 0)),
        )
        np.testing.assert_allclose(
            params["backbone"]["stage3_block1"]["conv2"]["kernel"],
            np.transpose(sd["layer2.1.conv2.weight"], (2, 3, 1, 0)),
        )
        np.testing.assert_allclose(
            stats["backbone"]["stage5_block0"]["proj_norm"]["var"],
            sd["layer4.0.downsample.1.running_var"],
        )
        # The merged tree still runs.
        out = model.apply(
            {"params": params["backbone"], "batch_stats": stats["backbone"]},
            jnp.ones((1, 64, 64, 3)),
            train=False,
        )
        assert set(out) == {"c3", "c4", "c5"}
        assert np.isfinite(float(jnp.sum(out["c5"].astype(jnp.float32))))

    def test_shape_mismatch_raises(self):
        rng = np.random.default_rng(1)
        sd = fake_torch_resnet50_sd(rng)
        sd["conv1.weight"] = sd["conv1.weight"][:, :1]  # corrupt
        imp_params, imp_stats = convert_torch_resnet50(sd)
        model = ResNet(stage_sizes=(3, 4, 6, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        with pytest.raises(ValueError, match="shape mismatch"):
            apply_backbone_weights(
                {"backbone": variables["params"]},
                {"backbone": variables["batch_stats"]},
                imp_params,
                imp_stats,
            )

    def test_partial_coverage_raises(self):
        """A resnet50 dict must NOT silently half-initialize a deeper model."""
        rng = np.random.default_rng(3)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)
        model = ResNet(stage_sizes=(3, 4, 23, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)  # resnet101: extra stage4 blocks
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        with pytest.raises(ValueError, match="uninitialized"):
            apply_backbone_weights(
                {"backbone": variables["params"]},
                {"backbone": variables["batch_stats"]},
                imp_params,
                imp_stats,
            )

    def test_gn_model_rejects_bn_stats(self):
        rng = np.random.default_rng(2)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)
        with pytest.raises(ValueError, match="BN stats"):
            apply_backbone_weights(
                {"backbone": {"stem_conv": {"kernel": np.zeros((7, 7, 3, 64))}}},
                {},
                {"stem_conv": {"kernel": np.zeros((7, 7, 3, 64))}},
                imp_stats,
            )
