"""Pretrained-backbone import tests (torch resnet50 layout → flax tree)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.models.import_weights import (
    apply_backbone_weights,
    convert_torch_resnet50,
)
from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet


def fake_torch_resnet50_sd(rng) -> dict[str, np.ndarray]:
    """Random arrays in torchvision resnet50 names/shapes (incl. fc, ignored)."""
    sd = {"conv1.weight": rng.normal(0, 1, (64, 3, 7, 7)).astype(np.float32)}

    def bn(prefix, c):
        sd[f"{prefix}.weight"] = rng.normal(1, 0.1, c).astype(np.float32)
        sd[f"{prefix}.bias"] = rng.normal(0, 0.1, c).astype(np.float32)
        sd[f"{prefix}.running_mean"] = rng.normal(0, 0.1, c).astype(np.float32)
        sd[f"{prefix}.running_var"] = rng.uniform(0.5, 1.5, c).astype(np.float32)

    bn("bn1", 64)
    in_c = 64
    for i, (blocks, width) in enumerate(
        [(3, 64), (4, 128), (6, 256), (3, 512)], start=1
    ):
        for b in range(blocks):
            p = f"layer{i}.{b}"
            sd[f"{p}.conv1.weight"] = rng.normal(
                0, 0.05, (width, in_c, 1, 1)
            ).astype(np.float32)
            bn(f"{p}.bn1", width)
            sd[f"{p}.conv2.weight"] = rng.normal(
                0, 0.05, (width, width, 3, 3)
            ).astype(np.float32)
            bn(f"{p}.bn2", width)
            sd[f"{p}.conv3.weight"] = rng.normal(
                0, 0.05, (width * 4, width, 1, 1)
            ).astype(np.float32)
            bn(f"{p}.bn3", width * 4)
            if b == 0:
                sd[f"{p}.downsample.0.weight"] = rng.normal(
                    0, 0.05, (width * 4, in_c, 1, 1)
                ).astype(np.float32)
                bn(f"{p}.downsample.1", width * 4)
                in_c = width * 4
    sd["fc.weight"] = rng.normal(0, 0.05, (1000, 2048)).astype(np.float32)
    sd["fc.bias"] = np.zeros(1000, np.float32)
    return sd


class TestImport:
    def test_convert_and_apply_frozen_bn(self):
        rng = np.random.default_rng(0)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)

        model = ResNet(stage_sizes=(3, 4, 6, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        params, stats = apply_backbone_weights(
            {"backbone": variables["params"]},
            {"backbone": variables["batch_stats"]},
            imp_params,
            imp_stats,
        )
        # Spot checks: OIHW→HWIO transpose and BN stat placement.
        np.testing.assert_allclose(
            params["backbone"]["stem_conv"]["kernel"],
            np.transpose(sd["conv1.weight"], (2, 3, 1, 0)),
        )
        np.testing.assert_allclose(
            params["backbone"]["stage3_block1"]["conv2"]["kernel"],
            np.transpose(sd["layer2.1.conv2.weight"], (2, 3, 1, 0)),
        )
        np.testing.assert_allclose(
            stats["backbone"]["stage5_block0"]["proj_norm"]["var"],
            sd["layer4.0.downsample.1.running_var"],
        )
        # The merged tree still runs.
        out = model.apply(
            {"params": params["backbone"], "batch_stats": stats["backbone"]},
            jnp.ones((1, 64, 64, 3)),
            train=False,
        )
        assert set(out) == {"c3", "c4", "c5"}
        assert np.isfinite(float(jnp.sum(out["c5"].astype(jnp.float32))))

    def test_shape_mismatch_raises(self):
        rng = np.random.default_rng(1)
        sd = fake_torch_resnet50_sd(rng)
        sd["conv1.weight"] = sd["conv1.weight"][:, :1]  # corrupt
        imp_params, imp_stats = convert_torch_resnet50(sd)
        model = ResNet(stage_sizes=(3, 4, 6, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        with pytest.raises(ValueError, match="shape mismatch"):
            apply_backbone_weights(
                {"backbone": variables["params"]},
                {"backbone": variables["batch_stats"]},
                imp_params,
                imp_stats,
            )

    def test_partial_coverage_raises(self):
        """A resnet50 dict must NOT silently half-initialize a deeper model."""
        rng = np.random.default_rng(3)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)
        model = ResNet(stage_sizes=(3, 4, 23, 3), norm_kind="frozen_bn",
                       dtype=jnp.float32)  # resnet101: extra stage4 blocks
        variables = jax.jit(model.init)(
            jax.random.key(0), jnp.zeros((1, 64, 64, 3), jnp.float32)
        )
        with pytest.raises(ValueError, match="uninitialized"):
            apply_backbone_weights(
                {"backbone": variables["params"]},
                {"backbone": variables["batch_stats"]},
                imp_params,
                imp_stats,
            )

    def test_gn_model_rejects_bn_stats(self):
        rng = np.random.default_rng(2)
        sd = fake_torch_resnet50_sd(rng)
        imp_params, imp_stats = convert_torch_resnet50(sd)
        with pytest.raises(ValueError, match="BN stats"):
            apply_backbone_weights(
                {"backbone": {"stem_conv": {"kernel": np.zeros((7, 7, 3, 64))}}},
                {},
                {"stem_conv": {"kernel": np.zeros((7, 7, 3, 64))}},
                imp_stats,
            )


@pytest.mark.slow
class TestTorchNumericalParity:
    """Imported weights must reproduce the TORCH forward exactly.

    The previous tests prove shapes/plumbing with synthetic state dicts;
    this one closes the numerical loop (VERDICT r1: the pretrained path is
    the #1 external dependency for mAP 36.0): an independent functional
    resnet50 forward written against torch.nn.functional from the state
    dict alone, compared feature-by-feature with our flax backbone running
    the imported weights.  Exercises the torch-geometry padding (stem (3,3),
    3x3 convs (1,1), maxpool (1,1)) — under XLA SAME padding this test
    fails with large boundary/shift errors.
    """

    def _torch_features(self, sd, x_nchw):
        import torch
        import torch.nn.functional as F

        t = lambda a: torch.from_numpy(np.asarray(a))  # noqa: E731

        def bn(x, p):
            return F.batch_norm(
                x, t(sd[f"{p}.running_mean"]), t(sd[f"{p}.running_var"]),
                t(sd[f"{p}.weight"]), t(sd[f"{p}.bias"]),
                training=False, eps=1e-5,
            )

        x = torch.from_numpy(x_nchw)
        x = F.conv2d(x, t(sd["conv1.weight"]), stride=2, padding=3)
        x = F.relu(bn(x, "bn1"))
        x = F.max_pool2d(x, 3, stride=2, padding=1)
        feats = {}
        for i, blocks in [(1, 3), (2, 4), (3, 6), (4, 3)]:
            for b in range(blocks):
                p = f"layer{i}.{b}"
                stride = 2 if (b == 0 and i > 1) else 1
                identity = x
                y = F.relu(bn(F.conv2d(x, t(sd[f"{p}.conv1.weight"])), f"{p}.bn1"))
                y = F.relu(
                    bn(
                        F.conv2d(y, t(sd[f"{p}.conv2.weight"]), stride=stride,
                                 padding=1),
                        f"{p}.bn2",
                    )
                )
                y = bn(F.conv2d(y, t(sd[f"{p}.conv3.weight"])), f"{p}.bn3")
                if f"{p}.downsample.0.weight" in sd:
                    identity = bn(
                        F.conv2d(x, t(sd[f"{p}.downsample.0.weight"]),
                                 stride=stride),
                        f"{p}.downsample.1",
                    )
                x = F.relu(y + identity)
            if i >= 2:
                feats[f"c{i + 1}"] = x.numpy().transpose(0, 2, 3, 1)  # NHWC
        return feats

    @pytest.mark.parametrize("stem", ["conv", "space_to_depth"])
    def test_c3_c4_c5_match_torch(self, stem):
        rng = np.random.default_rng(0)
        sd = fake_torch_resnet50_sd(rng)
        params, stats = convert_torch_resnet50(sd)

        model = ResNet(
            stage_sizes=(3, 4, 6, 3), norm_kind="frozen_bn",
            dtype=jnp.float32, stem=stem,
        )
        x = rng.normal(0, 1, (1, 64, 64, 3)).astype(np.float32)
        variables = model.init(jax.random.key(0), jnp.asarray(x))
        merged_p, merged_s = apply_backbone_weights(
            {"backbone": variables["params"]},
            {"backbone": variables["batch_stats"]},
            params,
            stats,
        )
        ours = model.apply(
            {"params": merged_p["backbone"], "batch_stats": merged_s["backbone"]},
            jnp.asarray(x),
            train=False,
        )
        theirs = self._torch_features(sd, x.transpose(0, 3, 1, 2))
        for level in ("c3", "c4", "c5"):
            # Tolerance: f32 accumulation over ~50 layers of unnormalized
            # random weights reaches ~1e-2 absolute on a handful of c5
            # elements; a geometry error (padding shift) produces O(1)
            # differences across the whole tensor, far beyond this.
            np.testing.assert_allclose(
                np.asarray(ours[level]), theirs[level],
                rtol=2e-3, atol=5e-2,
                err_msg=f"{level} diverges from the torch forward",
            )
