"""serve/autoscale.py — the autoscaling control plane (ISSUE 19).

Everything runs on the injectable clock: ``check_once(now=...)`` drives
the sustain/cooldown state machine (like ``poll_once`` drives the
breaker), so no test sleeps to make policy time pass.

Families:

- **Policy contract**: bounds validation, unknown-key rejection in
  policy files, file round-trip.
- **Anti-flap** (the acceptance pins): a sustained occupancy breach
  fires EXACTLY one decision per cooldown window; oscillating load
  inside the hysteresis band fires ZERO decisions; a breach shorter
  than ``for_s`` fires nothing.
- **Scale-up**: occupancy and p99 breach paths, the capped decision at
  ``max_replicas`` (the ``fleet:underprovisioned`` evidence), launch
  failures counted without crashing the loop, the weight-zero admission
  gate on joined replicas.
- **Scale-down**: lowest-weight owned victim, drain → reap → removal
  lifecycle, canary/foreign replicas never victimized, the draining
  gauge + occupancy exclusion (the /metrics truthfulness satellite).
- **Scale-to-zero**: strict idleness takes the last replica away;
  demand (a ``no_replica_available`` shed) scales from zero
  IMMEDIATELY — no sustain, no cooldown.
- **Preemption repair**: a pruned (respawn-budget-exhausted) slot plus
  ``below_min`` repairs capacity on the same tick.
- **RespawnBudget**: the bounded-respawn state machine behind the fleet
  CLI supervision bugfix.
- **Zero-drop scale-down** (real servers): in-flight requests on the
  victim all complete, the router redistributes, no errors; a pinned
  stream on the victim re-pins with exactly one ``stream_repinned`` and
  zero dropped frames.
"""

from __future__ import annotations

import json
import threading

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.serve import (
    AutoscalePolicy,
    Autoscaler,
    DetectionServer,
    FleetConfig,
    FleetRouter,
    LocalLauncher,
    LocalReplica,
    RequestRejected,
    ServeConfig,
)
from batchai_retinanet_horovod_coco_tpu.serve.replica import RespawnBudget
from batchai_retinanet_horovod_coco_tpu.serve.stub import StubDetectEngine
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy

DETS = [{"category_id": 0, "bbox": [1.0, 2.0, 9.0, 18.0], "score": 0.5}]

#: No-jitter breaker backoff — probe times are exact in these tests.
EXACT_BACKOFF = BackoffPolicy(
    max_tries=1_000_000, base_s=1.0, multiplier=2.0, ceiling_s=8.0,
    jitter=0.0,
)


class ScalableReplica:
    """A replica handle advertising the SLOT fields the occupancy
    aggregate reads (``slot_capacity``/``free_slots``), with scriptable
    occupancy — the autoscale counterpart of test_fleet's FakeReplica."""

    def __init__(
        self,
        replica_id: str,
        version: str = "v1",
        capacity: int = 8,
        p99_ms: float | None = 100.0,
    ):
        self.replica_id = replica_id
        self.version = version
        self.capacity = capacity
        self.p99_ms = p99_ms
        self.inflight = 0
        self.accepting = True
        self.healthy = True
        self.drained = False
        self.closed = False

    def set_occupancy(self, frac: float) -> None:
        """Advertise ``frac`` of slots claimed on the next health poll."""
        self.inflight = round(frac * self.capacity)

    def load(self) -> dict:
        free = self.capacity - self.inflight
        return {
            "replica_id": self.replica_id,
            "version": self.version,
            "inflight": self.inflight,
            "admission_qsize": 0,
            "admission_capacity": self.capacity,
            "slot_capacity": self.capacity,
            "free_slots": free,
            "p99_ms": self.p99_ms,
            "shed_total": 0,
            "accepting": self.accepting,
        }

    def healthz(self):
        if not self.healthy:
            return 0, {"status": "unreachable"}
        return 200, {"status": "ok", "load": self.load()}

    def detect(self, payload, timeout_s=None):
        return DETS

    def drain(self, timeout_s=5.0):
        self.drained = True
        self.accepting = False

    def close(self):
        self.closed = True
        self.accepting = False


class FakeLauncher:
    """Scriptable duck-typed launcher: launches ScalableReplicas, owns
    what it launched or adopted, reaps on demand (``reap_ready``)."""

    def __init__(self):
        self.launched: list[ScalableReplica] = []
        self.terminated: list[str] = []
        self.reap_ready: set[str] = set()
        self.abandoned: list[str] = []
        self.fail_launches = 0
        self._owned: set[str] = set()
        self._seq = 0

    def launch(self):
        if self.fail_launches:
            self.fail_launches -= 1
            raise RuntimeError("spawn refused (scripted)")
        rid = f"scale-{self._seq}"
        self._seq += 1
        replica = ScalableReplica(rid)
        self.launched.append(replica)
        self._owned.add(rid)
        return replica

    def adopt(self, replica) -> None:
        self._owned.add(replica.replica_id)

    def owns(self, rid: str) -> bool:
        return rid in self._owned

    def terminate(self, rid: str) -> None:
        self.terminated.append(rid)

    def reap(self, rid: str) -> bool:
        if rid in self.reap_ready:
            self._owned.discard(rid)
            return True
        return False

    def prune(self) -> list[str]:
        out, self.abandoned = self.abandoned, []
        return out


class _SinkSpy:
    def __init__(self):
        self.events: list[tuple[str, dict]] = []

    def event(self, kind: str, **fields) -> None:
        self.events.append((kind, fields))

    def of(self, kind: str) -> list[dict]:
        return [f for k, f in self.events if k == kind]


def make_scaler(replicas, policy, launcher=None, sink=None, adopt=True):
    launcher = launcher or FakeLauncher()
    router = FleetRouter(
        replicas,
        FleetConfig(probe_backoff=EXACT_BACKOFF, poll_interval_s=0.05),
        sink=sink,
        auto_poll=False,
    )
    if adopt:
        for r in replicas:
            launcher.adopt(r)
    scaler = Autoscaler(router, policy, launcher, sink=sink)
    return router, scaler, launcher


#: The band policy most tests drive: decisions need a 5s sustained
#: breach and respect a 10s per-direction cooldown.
BAND = dict(
    min_replicas=1, max_replicas=3, occupancy_low=0.25,
    occupancy_high=0.75, for_s=5.0, up_cooldown_s=10.0,
    down_cooldown_s=10.0,
)


def tick(router, scaler, now: float) -> list[dict]:
    router.poll_once(now=now)
    return scaler.check_once(now=now)


# ---- policy contract -----------------------------------------------------


class TestPolicy:
    def test_bounds_validation(self):
        with pytest.raises(ValueError, match="min_replicas"):
            AutoscalePolicy(min_replicas=-1)
        with pytest.raises(ValueError, match="max_replicas"):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError, match="max_replicas"):
            AutoscalePolicy(min_replicas=0, max_replicas=0)
        with pytest.raises(ValueError, match="occupancy"):
            AutoscalePolicy(occupancy_low=0.8, occupancy_high=0.5)
        with pytest.raises(ValueError, match="occupancy"):
            AutoscalePolicy(occupancy_low=0.2, occupancy_high=1.5)
        with pytest.raises(ValueError, match="steps"):
            AutoscalePolicy(scale_up_step=0)
        with pytest.raises(ValueError, match="for_s"):
            AutoscalePolicy(for_s=-1.0)
        # min_replicas=0 (scale-to-zero) is a legal contract.
        assert AutoscalePolicy(min_replicas=0).min_replicas == 0

    def test_policy_file_round_trip_and_unknown_key(self, tmp_path):
        doc = {
            "min_replicas": 0, "max_replicas": 5,
            "occupancy_low": 0.2, "occupancy_high": 0.8,
            "p99_slo_ms": 250.0, "for_s": 2.0,
        }
        path = tmp_path / "policy.json"
        path.write_text(json.dumps(doc))
        pol = AutoscalePolicy.from_file(str(path))
        assert pol.max_replicas == 5 and pol.p99_slo_ms == 250.0
        assert pol.up_cooldown_s == 10.0  # unset knobs keep defaults
        # A typo'd knob is an ERROR, not a silent default.
        with pytest.raises(ValueError, match="max_replicaz"):
            AutoscalePolicy.from_json({"max_replicaz": 5})


# ---- anti-flap (the acceptance pins) -------------------------------------


class TestAntiFlap:
    def test_sustained_breach_one_decision_per_cooldown_window(self):
        r0 = ScalableReplica("r0")
        r0.set_occupancy(1.0)  # saturated for the whole test
        router, scaler, launcher = make_scaler(
            [r0], AutoscalePolicy(**BAND)
        )
        decisions = []
        # Breach from t=0; for_s=5, up_cooldown=10.  Dense ticking must
        # fire exactly at t=5 (sustain met) and t=15 (cooldown met).
        # Demand outgrows capacity: joined replicas saturate too, so the
        # breach SUSTAINS across both windows.
        for now in np.arange(0.0, 20.0, 0.5):
            for r in launcher.launched:
                r.set_occupancy(1.0)
            decisions += tick(router, scaler, float(now))
        assert [d["decision"] for d in decisions] == ["scale_up"] * 2
        assert [d["reason"] for d in decisions] == ["occupancy_high"] * 2
        assert len(launcher.launched) == 2
        snap = router.federated_snapshot()
        assert snap["fleet_scale_up_total"] == 2.0

    def test_oscillation_inside_band_zero_decisions(self):
        r0 = ScalableReplica("r0")
        router, scaler, _ = make_scaler([r0], AutoscalePolicy(**BAND))
        decisions = []
        for i, now in enumerate(np.arange(0.0, 30.0, 0.5)):
            # 0.375 ↔ 0.625 with band [0.25, 0.75]: real load swing,
            # never a breach.
            r0.set_occupancy(0.375 if i % 2 else 0.625)
            decisions += tick(router, scaler, float(now))
        assert decisions == []
        st = scaler.status()
        assert not st["breaching_up"] and not st["breaching_down"]
        assert st["scale_ups"] == 0 and st["scale_downs"] == 0

    def test_breach_shorter_than_for_s_fires_nothing(self):
        r0 = ScalableReplica("r0")
        router, scaler, _ = make_scaler([r0], AutoscalePolicy(**BAND))
        decisions = []
        # High for 3s (< for_s=5), back in band, high again for 3s:
        # the sustain clock must RESET on re-entry, so nothing fires.
        for now, occ in [(0, 1.0), (1, 1.0), (3, 1.0), (4, 0.5),
                         (10, 1.0), (11, 1.0), (13, 1.0), (14, 0.5)]:
            r0.set_occupancy(occ)
            decisions += tick(router, scaler, float(now))
        assert decisions == []


# ---- scale-up ------------------------------------------------------------


class TestScaleUp:
    def test_p99_breach_scales_up_inside_band(self):
        r0 = ScalableReplica("r0", p99_ms=500.0)
        r0.set_occupancy(0.5)  # inside the band — latency is the signal
        router, scaler, launcher = make_scaler(
            [r0], AutoscalePolicy(p99_slo_ms=200.0, **BAND)
        )
        fired = []
        for now in (0.0, 2.0, 5.0):
            fired += tick(router, scaler, now)
        assert [d["reason"] for d in fired] == ["p99_breach"]
        assert fired[0]["p99_ms"] == 500.0
        assert len(launcher.launched) == 1

    def test_capped_decision_at_max_replicas(self):
        reps = [ScalableReplica(f"r{k}") for k in range(3)]
        for r in reps:
            r.set_occupancy(1.0)
        router, scaler, launcher = make_scaler(
            reps, AutoscalePolicy(**BAND)  # max_replicas=3, already there
        )
        decisions = []
        for now in np.arange(0.0, 20.0, 1.0):
            decisions += tick(router, scaler, float(now))
        # Still once per cooldown window — but capped, delta 0, and the
        # underprovisioned counter carries the evidence.
        assert [d["decision"] for d in decisions] == ["scale_up_capped"] * 2
        assert all(d["delta"] == 0 for d in decisions)
        assert launcher.launched == []
        snap = router.federated_snapshot()
        assert snap["fleet_scale_capped_total"] == 2.0
        assert snap["fleet_scale_up_total"] == 0.0

    def test_joined_replica_gates_at_weight_zero_until_polled(self):
        r0 = ScalableReplica("r0")
        r0.set_occupancy(1.0)
        router, scaler, launcher = make_scaler(
            [r0], AutoscalePolicy(**BAND)
        )
        for now in (0.0, 5.0):
            tick(router, scaler, now)
        assert len(launcher.launched) == 1
        joined = launcher.launched[0].replica_id
        by_id = {
            r["replica_id"]: r for r in router.status()["replicas"]
        }
        # Admission gate: joined but NEVER takes weight before its own
        # first successful health poll (the half-open probe contract).
        assert by_id[joined]["state"] == "closed"
        assert by_id[joined]["weight"] == 0.0
        router.poll_once(now=6.0)
        by_id = {
            r["replica_id"]: r for r in router.status()["replicas"]
        }
        assert by_id[joined]["weight"] > 0.0

    def test_launch_failure_is_counted_not_fatal(self):
        r0 = ScalableReplica("r0")
        r0.set_occupancy(1.0)
        sink = _SinkSpy()
        launcher = FakeLauncher()
        launcher.fail_launches = 1
        router, scaler, launcher = make_scaler(
            [r0], AutoscalePolicy(**BAND), launcher=launcher, sink=sink,
        )
        fired = []
        for now in (0.0, 5.0):
            fired += tick(router, scaler, now)
        assert [d["decision"] for d in fired] == ["scale_up"]
        assert fired[0]["delta"] == 0  # nothing actually joined
        assert fired[0]["launch_errors"] == 1
        assert len(sink.of("autoscale_launch_failed")) == 1
        # The loop survives to retry after the cooldown.
        fired += tick(router, scaler, 15.0)
        assert fired[-1]["delta"] == 1


# ---- scale-down ----------------------------------------------------------


class TestScaleDown:
    def test_lowest_weight_owned_victim_drains_then_removes(self):
        sink = _SinkSpy()
        r0, r1 = ScalableReplica("r0"), ScalableReplica("r1")
        r1.set_occupancy(0.125)  # busier ⇒ heavier r0 survives? no:
        # r0 idle (weight high), r1 slightly loaded (weight LOWER) —
        # the victim must be the lowest-weight replica, r1.
        router, scaler, launcher = make_scaler(
            [r0, r1], AutoscalePolicy(**BAND), sink=sink
        )
        fired = []
        for now in (0.0, 5.0):
            fired += tick(router, scaler, now)
        assert [d["decision"] for d in fired] == ["scale_down"]
        assert fired[0]["victims"] == ["r1"]
        assert launcher.terminated == ["r1"]
        by_id = {
            r["replica_id"]: r for r in router.status()["replicas"]
        }
        assert by_id["r1"]["state"] == "drained"
        assert by_id["r1"]["weight"] == 0.0
        # Draining is visible on /metrics and EXCLUDED from occupancy.
        snap = router.federated_snapshot()
        assert snap['fleet_replica_draining{replica="r1"}'] == 1.0
        assert snap['fleet_replica_draining{replica="r0"}'] == 0.0
        assert snap["fleet_autoscale_draining"] == 1.0
        assert snap["fleet_occupancy"] == 0.0  # r1's 0.125 is gone
        # Not reapable yet: the slot stays pending, no removal.
        tick(router, scaler, 6.0)
        assert "r1" in {
            r["replica_id"] for r in router.status()["replicas"]
        }
        # Drain finishes; the next tick reclaims the slot.
        launcher.reap_ready.add("r1")
        tick(router, scaler, 7.0)
        assert "r1" not in {
            r["replica_id"] for r in router.status()["replicas"]
        }
        assert [e["replica_id"] for e in sink.of("fleet_replica_draining")] \
            == ["r1"]
        assert [e["replica_id"] for e in sink.of("fleet_replica_removed")] \
            == ["r1"]

    def test_unowned_and_canary_replicas_are_never_victims(self):
        r0, r1 = ScalableReplica("r0"), ScalableReplica("r1")
        launcher = FakeLauncher()
        router, scaler, launcher = make_scaler(
            [r0, r1], AutoscalePolicy(**BAND), launcher=launcher,
            adopt=False,  # the launcher owns NEITHER seed replica
        )
        fired = []
        for now in np.arange(0.0, 12.0, 1.0):
            fired += tick(router, scaler, float(now))
        # Below the band the whole time, but nothing the launcher owns:
        # no decision at all (an event with no actuation would lie).
        assert fired == []
        assert launcher.terminated == []

    def test_occupancy_aggregate_excludes_draining_replica(self):
        r0, r1 = ScalableReplica("r0"), ScalableReplica("r1")
        r0.set_occupancy(1.0)
        r1.set_occupancy(0.5)
        router, scaler, _ = make_scaler([r0, r1], AutoscalePolicy(**BAND))
        router.poll_once(now=0.0)
        assert router.federated_snapshot()["fleet_occupancy"] == 0.75
        assert router.begin_drain("r0")
        snap = router.federated_snapshot()
        assert snap["fleet_occupancy"] == 0.5  # r0 no longer counted
        assert snap['fleet_replica_draining{replica="r0"}'] == 1.0


# ---- scale-to-zero + demand recovery -------------------------------------


class TestScaleToZero:
    def test_idle_fleet_reaches_zero_and_demand_recovers(self):
        sink = _SinkSpy()
        r0 = ScalableReplica("r0")
        pol = AutoscalePolicy(
            min_replicas=0, max_replicas=2, occupancy_low=0.25,
            occupancy_high=0.75, for_s=2.0, up_cooldown_s=5.0,
            down_cooldown_s=5.0,
        )
        router, scaler, launcher = make_scaler([r0], pol, sink=sink)
        fired = []
        for now in (0.0, 1.0, 2.0):
            fired += tick(router, scaler, float(now))
        assert [d["decision"] for d in fired] == ["scale_down"]
        assert fired[0]["reason"] == "idle"
        launcher.reap_ready.add("r0")
        tick(router, scaler, 3.0)
        assert router.status()["replicas"] == []
        assert router.active_replica_count() == 0
        assert router.federated_snapshot()["fleet_replicas_desired"] == 0.0
        # A request hits the empty fleet: shed at the edge ...
        with pytest.raises(RequestRejected, match="no_replica_available"):
            router.detect(b"payload")
        # ... and the VERY NEXT tick scales from zero, no sustain, no
        # cooldown (3.0 - last_down is inside down_cooldown_s).
        fired = scaler.check_once(now=4.0)
        assert [d["decision"] for d in fired] == ["scale_up"]
        assert fired[0]["reason"] == "demand_scale_from_zero"
        assert len(launcher.launched) == 1
        assert router.active_replica_count() == 1
        # The recovered replica serves after its first poll.
        router.poll_once(now=5.0)
        assert router.detect(b"payload") == DETS

    def test_trickle_traffic_keeps_last_replica_alive(self):
        r0 = ScalableReplica("r0")
        pol = AutoscalePolicy(
            min_replicas=0, max_replicas=2, for_s=1.0,
            up_cooldown_s=1.0, down_cooldown_s=1.0,
        )
        router, scaler, launcher = make_scaler([r0], pol)
        fired = []
        for now in np.arange(0.0, 8.0, 1.0):
            router.poll_once(now=float(now))
            router.detect(b"payload")  # sub-band trickle, NOT idle
            fired += scaler.check_once(now=float(now))
        # Occupancy reads 0 (below the band) but completions are
        # flowing: strict idleness gates the LAST replica.
        assert fired == []
        assert router.active_replica_count() == 1
        assert launcher.terminated == []


# ---- preemption repair ---------------------------------------------------


class TestPreemptionRepair:
    def test_pruned_slot_plus_below_min_repairs_same_tick(self):
        r0, r1 = ScalableReplica("r0"), ScalableReplica("r1")
        r0.set_occupancy(0.5)
        r1.set_occupancy(0.5)
        pol = AutoscalePolicy(min_replicas=2, max_replicas=3, **{
            k: v for k, v in BAND.items() if k.startswith(("occupancy",))
        }, for_s=5.0, up_cooldown_s=10.0, down_cooldown_s=10.0)
        router, scaler, launcher = make_scaler([r0, r1], pol)
        tick(router, scaler, 0.0)
        # The supervisor exhausted r1's respawn budget: the slot is
        # abandoned to the autoscaler ...
        launcher.abandoned.append("r1")
        launcher._owned.discard("r1")
        fired = scaler.check_once(now=1.0)
        # ... which forgets the corpse AND repairs capacity below the
        # floor on the SAME tick — no sustain, no cooldown.
        assert "r1" not in {
            r["replica_id"] for r in router.status()["replicas"]
        }
        assert [d["decision"] for d in fired] == ["scale_up"]
        assert fired[0]["reason"] == "below_min"
        assert len(launcher.launched) == 1
        assert router.active_replica_count() == 2


# ---- decision surface ----------------------------------------------------


class TestDecisionSurface:
    def test_decision_event_carries_signals_and_gauges_track(self):
        sink = _SinkSpy()
        r0 = ScalableReplica("r0")
        r0.set_occupancy(1.0)
        router, scaler, _ = make_scaler(
            [r0], AutoscalePolicy(**BAND), sink=sink
        )
        for now in (0.0, 5.0):
            tick(router, scaler, now)
        events = sink.of("autoscale_decision")
        assert len(events) == 1
        ev = events[0]
        assert ev["decision"] == "scale_up"
        assert ev["reason"] == "occupancy_high"
        assert ev["delta"] == 1
        assert ev["replicas_before"] == 1
        assert ev["occupancy"] == 1.0
        assert ev["sustained_s"] == 5.0
        snap = router.federated_snapshot()
        assert snap["fleet_replicas_desired"] == 2.0
        assert snap["fleet_replicas_active"] == 2.0
        assert snap["fleet_scale_up_total"] == 1.0
        assert snap["fleet_scale_down_total"] == 0.0
        st = scaler.status()
        assert st["decisions_tail"][-1]["decision"] == "scale_up"
        assert st["desired"] == 2
        # A stopped autoscaler detaches its collector: frozen gauges
        # must not outlive the control loop on the fleet registry.
        scaler.stop()
        assert "fleet_replicas_desired" not in router.federated_snapshot()


# ---- RespawnBudget (the supervision bugfix) ------------------------------


class TestRespawnBudget:
    def budget(self, tries=3):
        return RespawnBudget(
            BackoffPolicy(
                max_tries=tries, base_s=1.0, multiplier=2.0,
                ceiling_s=30.0, jitter=0.0,
            ),
            reset_after_s=60.0,
        )

    def test_exhausts_after_max_tries_crash_loops(self):
        b = self.budget(tries=3)
        assert b.note_death(now=0.0) and not b.exhausted
        assert b.note_death(now=1.0) and not b.exhausted
        assert b.note_death(now=2.0) and not b.exhausted
        # The fourth rapid death exceeds the budget: abandon the slot.
        assert not b.note_death(now=3.0)
        assert b.exhausted
        assert not b.ready(now=1e9)  # never respawns again

    def test_backoff_schedule_gates_ready(self):
        b = self.budget(tries=5)
        b.note_death(now=0.0)
        assert not b.ready(now=0.5)  # base_s=1.0 not yet elapsed
        assert b.ready(now=1.0)
        b.note_death(now=1.0)  # second death: 2.0s delay
        assert not b.ready(now=2.5)
        assert b.ready(now=3.0)

    def test_surviving_reset_window_restores_budget(self):
        b = self.budget(tries=2)
        b.note_death(now=0.0)
        b.note_death(now=1.0)
        assert b.deaths == 2
        b.note_alive(now=100.0)  # survived 60s past the last death
        assert b.deaths == 0
        # A fresh crash loop gets the full budget again.
        assert b.note_death(now=101.0) and b.deaths == 1


# ---- zero-drop scale-down on real servers --------------------------------


IMG = np.full((64, 64, 3), 7, np.uint8)


def _make_live_fleet(sink=None, delay_s=0.0):
    servers = [
        DetectionServer(
            StubDetectEngine(video=True, delay_s=delay_s),
            ServeConfig(max_delay_ms=5, preprocess_workers=1),
            replica_id=f"r{k}",
        )
        for k in range(2)
    ]
    replicas = [LocalReplica(s) for s in servers]
    router = FleetRouter(
        replicas,
        FleetConfig(probe_backoff=EXACT_BACKOFF, poll_interval_s=0.05),
        sink=sink,
        auto_poll=False,
    )
    return router, servers, replicas


class TestZeroDropScaleDown:
    def test_inflight_on_victim_completes_and_router_redistributes(self):
        router, servers, replicas = _make_live_fleet(delay_s=0.15)
        launcher = LocalLauncher(
            lambda rid: LocalReplica(
                DetectionServer(
                    StubDetectEngine(video=True),
                    ServeConfig(max_delay_ms=5, preprocess_workers=1),
                    replica_id=rid,
                )
            )
        )
        for r in replicas:
            launcher.adopt(r)
        pol = AutoscalePolicy(
            min_replicas=1, max_replicas=2, occupancy_low=0.6,
            occupancy_high=0.9, for_s=0.0, up_cooldown_s=0.0,
            down_cooldown_s=0.0,
        )
        scaler = Autoscaler(router, pol, launcher)
        victim = replicas[0]
        results: list = []
        errors: list = []

        def call():
            try:
                results.append(victim.detect(IMG, timeout_s=30))
            except Exception as exc:  # any drop/5xx fails the test
                errors.append(exc)

        threads = [
            # watchdog: short-lived request threads the test joins below
            threading.Thread(target=call, daemon=True) for _ in range(4)
        ]
        try:
            for t in threads:
                t.start()
            # The poll sees the victim busy (lower weight than its idle
            # peer); mean occupancy sits below the low mark, so the
            # autoscaler drains EXACTLY the in-flight replica.
            deadline = 50
            fired = []
            while not fired and deadline:
                router.poll_once(now=0.0)
                if any(
                    r["load"].get("inflight")
                    for r in router.status()["replicas"]
                ):
                    fired = scaler.check_once(now=0.0)
                    break
                deadline -= 1
            assert fired and fired[0]["decision"] == "scale_down"
            assert fired[0]["victims"] == ["r0"]
            by_id = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }
            assert by_id["r0"]["state"] == "drained"
            # New traffic redistributes to the survivor while the
            # victim drains — nothing sheds, nothing errors.
            assert router.detect(IMG, timeout_s=30) == \
                router.detect(IMG, timeout_s=30)
            for t in threads:
                t.join(timeout=30)
            assert not errors
            assert len(results) == 4  # every in-flight request completed
            # The reap is the BOUNDED drain: in-flight already zero, so
            # the slot reclaims and the replica vanishes.
            for now in (1.0, 2.0, 3.0):
                scaler.check_once(now=now)
                if "r0" not in {
                    r["replica_id"] for r in router.status()["replicas"]
                }:
                    break
            assert "r0" not in {
                r["replica_id"] for r in router.status()["replicas"]
            }
        finally:
            router.close()
            for s in servers:
                s.close()

    def test_pinned_stream_on_victim_repins_once_zero_dropped(self):
        sink = _SinkSpy()
        router, servers, replicas = _make_live_fleet(sink=sink)
        by_id = {r.replica_id: r for r in replicas}
        launcher = LocalLauncher(lambda rid: None)
        pol = AutoscalePolicy(
            min_replicas=1, max_replicas=2, occupancy_low=0.6,
            occupancy_high=0.9, for_s=0.0, up_cooldown_s=0.0,
            down_cooldown_s=0.0,
        )
        scaler = Autoscaler(router, pol, launcher, sink=sink)
        try:
            opened = router.stream_open(width=64, height=64)
            sid = opened["session"]
            results = []
            for seq in range(8):
                dets, _hit = router.stream_frame(sid, seq, IMG)
                results.append(dets)
            # Own ONLY the pinned replica: the scale-down victim is the
            # stream's home by construction.
            launcher.adopt(by_id[opened["replica_id"]])
            router.poll_once(now=0.0)
            fired = scaler.check_once(now=0.0)
            assert fired and fired[0]["victims"] == [opened["replica_id"]]
            # Every later frame serves: ONE re-pin to the survivor.
            for seq in range(8, 16):
                dets, _hit = router.stream_frame(sid, seq, IMG)
                results.append(dets)
            assert len(results) == 16 and all(results)
            repins = sink.of("stream_repinned")
            assert len(repins) == 1
            assert repins[0]["stream"] == sid
            assert repins[0]["to_replica"] != opened["replica_id"]
            assert router.status()["stream_repins"] == 1
        finally:
            router.close()
            # Close the REPLICA handles, not the bare servers: both ends
            # of the re-pin own a lazily-attached StreamManager whose
            # delivery thread only replica.close() stops.
            for r in replicas:
                r.close()
            for s in servers:
                s.close()
