"""Perf doctor tests (ISSUE 8, obs/analyze): committed-fixture golden
output (bit-for-bit, inline == offline CLI), report schema validation,
robustness on corrupt/legacy/empty artifacts, the shared percentile
helper's equivalence pin, the watchdog stall trace marker, bench's span
attribution, and the tune --from-report consumer.

The fixture (tests/fixtures/perf_doctor/) is a real CPU train+eval smoke
recording: trace.json + metrics.jsonl as `--obs-trace` left them, plus
PERF_REPORT.golden.json — the analyzer's committed output for exactly
those artifacts.  jax-free, like the analyzer itself.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.obs import trace
from batchai_retinanet_horovod_coco_tpu.obs import watchdog as watchdog_lib
from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
    AnalyzeError,
    analyze_dir,
    analyze_events,
    auto_emit,
    device_peak_tflops,
    span_attribution,
    validate_report,
    write_report,
)
from batchai_retinanet_horovod_coco_tpu.obs.analyze.__main__ import main as cli_main
from batchai_retinanet_horovod_coco_tpu.obs.events import latency_percentiles

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "fixtures",
    "perf_doctor",
)
GOLDEN = os.path.join(FIXTURE, "PERF_REPORT.golden.json")


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.reset()
    yield
    trace.reset()


def _golden_bytes() -> bytes:
    with open(GOLDEN, "rb") as f:
        return f.read()


class TestGoldenFixture:
    def test_analyze_dir_reproduces_golden_bit_for_bit(self, tmp_path):
        report = analyze_dir(FIXTURE)
        out = write_report(report, str(tmp_path / "PERF_REPORT.json"))
        with open(out, "rb") as f:
            assert f.read() == _golden_bytes()

    def test_cli_reproduces_golden_bit_for_bit(self, tmp_path, capsys):
        out = str(tmp_path / "PERF_REPORT.json")
        assert cli_main([FIXTURE, "--out", out]) == 0
        with open(out, "rb") as f:
            assert f.read() == _golden_bytes()
        # The CLI prints a one-line machine-readable summary.
        summary = json.loads(capsys.readouterr().out.splitlines()[-1])
        assert summary["perf_report"] == out
        assert summary["top_bottlenecks"]

    def test_golden_satisfies_the_acceptance_properties(self):
        """The acceptance criteria, pinned on the committed recording: a
        schema-valid report with decomposition summing to ~1, an eval
        overlap ratio, a cost-analysis-derived MFU estimate, and a
        non-empty ranked top-3 verdict."""
        report = json.loads(_golden_bytes())
        assert validate_report(report) == []
        d = report["steps"]["decomposition"]
        assert abs(sum(d.values()) - 1.0) < 0.02
        assert set(d) == {
            "data_wait", "compile", "step", "metrics_fetch", "eval", "other"
        }
        ev = report["pipeline"]["eval"]
        assert 0.0 <= ev["overlap_efficiency"] <= 1.0
        assert ev["batches"] > 0
        mfu = report["mfu"]
        assert mfu["flops_source"] == "trace_cost_analysis"
        assert mfu["flops_per_step"] > 0
        assert mfu["mfu"] is not None and mfu["mfu"] > 0
        assert 1 <= len(report["bottlenecks"]) <= 3
        assert [b["rank"] for b in report["bottlenecks"]] == list(
            range(1, len(report["bottlenecks"]) + 1)
        )
        assert all(b["spans"] for b in report["bottlenecks"])

    def test_stall_correlation_present_for_feed_queue(self):
        report = json.loads(_golden_bytes())
        q = report["queues"]["device-prefetch.qsize"]
        assert "starved_data_wait_fraction" in q
        assert 0.0 <= q["starved_data_wait_fraction"] <= 1.0


class TestValidation:
    def test_golden_valid_and_mutations_bite(self):
        report = json.loads(_golden_bytes())
        assert validate_report(report) == []

        bad = json.loads(_golden_bytes())
        bad["schema_version"] = 99
        assert any("schema_version" in p for p in validate_report(bad))

        bad = json.loads(_golden_bytes())
        bad["steps"]["decomposition"]["other"] += 0.1  # breaks the sum
        assert any("sums to" in p for p in validate_report(bad))

        bad = json.loads(_golden_bytes())
        bad["steps"]["decomposition"]["step"] = 1.5  # out of range
        assert any("out of [0,1]" in p for p in validate_report(bad))

        bad = json.loads(_golden_bytes())
        bad["bottlenecks"][0]["rank"] = 7
        assert any("rank" in p for p in validate_report(bad))

        bad = json.loads(_golden_bytes())
        del bad["mfu"]
        assert any("mfu" in p for p in validate_report(bad))

        assert validate_report("not a dict") == ["report is not an object"]


class TestRobustness:
    def test_missing_trace_raises_clean_error_and_cli_exits_2(
        self, tmp_path, capsys
    ):
        with pytest.raises(AnalyzeError, match="cannot read trace"):
            analyze_dir(str(tmp_path))
        assert cli_main([str(tmp_path)]) == 2
        assert "run a traced workload" in capsys.readouterr().err

    def test_invalid_json_trace(self, tmp_path):
        (tmp_path / "trace.json").write_text("{half a trace")
        with pytest.raises(AnalyzeError, match="not valid JSON"):
            analyze_dir(str(tmp_path))

    def test_empty_trace_degrades_without_crashing(self, tmp_path):
        (tmp_path / "trace.json").write_text(json.dumps({"traceEvents": []}))
        report = analyze_dir(str(tmp_path))
        assert report["steps"] is None
        assert report["bottlenecks"] == []
        assert report["memory"] == {"available": False}
        assert report["mfu"]["mfu"] is None

    def test_headerless_legacy_and_corrupt_tail_events(self, tmp_path):
        """The split_runs robustness cases, through the analyzer: a
        pre-ISSUE-3 headerless prefix and a half-written tail must show
        up as counts, never as a crash."""
        (tmp_path / "trace.json").write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {"ph": "X", "name": "step", "ts": 0, "dur": 100,
                         "pid": 1, "tid": 1},
                        {"ph": "X", "name": "data_wait", "ts": 100,
                         "dur": 10, "pid": 1, "tid": 1},
                    ]
                }
            )
        )
        (tmp_path / "metrics.jsonl").write_text(
            '{"step": 1, "train/loss": 0.5}\n'  # headerless legacy run
            '{"step": 2, "train/lo'  # killed mid-write
        )
        report = analyze_dir(str(tmp_path))
        ev = report["events"]
        assert ev["available"] is True
        assert ev["corrupt_lines"] == 1
        assert ev["header"]["device_kind"] is None
        assert report["steps"]["count"] == 1
        assert report["bottlenecks"]  # still ranks from what it has

    def test_events_name_none_skips_a_stale_jsonl(self, tmp_path):
        """The bench emitters' guard: a shared obs dir can hold a
        PREVIOUS train run's metrics.jsonl, and events_name=None keeps
        its header/compile records out of this trace's report."""
        (tmp_path / "trace.json").write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {"ph": "X", "name": "detect_fetch", "ts": 0,
                         "dur": 50, "pid": 1, "tid": 1}
                    ]
                }
            )
        )
        (tmp_path / "metrics.jsonl").write_text(
            '{"event": "run_header", "run_id": "stale", '
            '"device_kind": "TPU v5 lite"}\n'
            '{"event": "compile", "build_s": 99.0}\n'
        )
        with_events = analyze_dir(str(tmp_path))
        assert with_events["events"]["available"] is True
        skipped = analyze_dir(str(tmp_path), events_name=None)
        assert skipped["events"] == {"available": False}
        assert skipped["source"]["device_kind"] is None

    def test_no_events_jsonl_is_fine(self, tmp_path):
        (tmp_path / "trace.json").write_text(
            json.dumps(
                {
                    "traceEvents": [
                        {"ph": "X", "name": "step", "ts": 0, "dur": 50,
                         "pid": 1, "tid": 1}
                    ]
                }
            )
        )
        report = analyze_dir(str(tmp_path))
        assert report["events"] == {"available": False}
        assert report["source"]["events"] is False

    def test_auto_emit_never_raises(self, tmp_path, capsys):
        assert auto_emit(str(tmp_path / "nope")) is None
        err = capsys.readouterr().err
        line = json.loads(err.splitlines()[-1])
        assert line["event"] == "perf_report_error"

        class Sink:
            def __init__(self):
                self.events = []

            def event(self, kind, **fields):
                self.events.append((kind, fields))

        sink = Sink()
        assert auto_emit(str(tmp_path / "nope"), sink=sink) is None
        assert sink.events[0][0] == "perf_report_error"


class TestCheckMode:
    def test_identical_reports_pass(self, tmp_path, capsys):
        assert cli_main([FIXTURE, "--out", str(tmp_path / "r.json"),
                         "--check", GOLDEN]) == 0
        out = capsys.readouterr().out
        assert "REGRESSION" not in out

    def test_fraction_regression_fails(self, tmp_path, capsys):
        baseline = json.loads(_golden_bytes())
        d = baseline["steps"]["decomposition"]
        # Invert the attribution: the committed world spent its window in
        # data_wait — a fresh report matching the fixture is > band away.
        d["data_wait"], d["step"] = d["step"], d["data_wait"]
        bpath = tmp_path / "baseline.json"
        bpath.write_text(json.dumps(baseline))
        assert cli_main([FIXTURE, "--out", str(tmp_path / "r.json"),
                         "--check", str(bpath)]) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_device_class_mismatch_passes_loudly(self, tmp_path, capsys):
        baseline = json.loads(_golden_bytes())
        baseline["source"]["device_kind"] = "TPU v5 lite"
        bpath = tmp_path / "baseline.json"
        bpath.write_text(json.dumps(baseline))
        assert cli_main([FIXTURE, "--out", str(tmp_path / "r.json"),
                         "--check", str(bpath)]) == 0
        assert "not comparable across device classes" in (
            capsys.readouterr().out
        )

    def test_unreadable_baseline_fails(self, tmp_path, capsys):
        assert cli_main([FIXTURE, "--out", str(tmp_path / "r.json"),
                         "--check", str(tmp_path / "missing.json")]) == 1
        assert "cannot read committed baseline" in capsys.readouterr().out


class TestPercentileHelper:
    def test_matches_numpy_reference(self):
        """Satellite pin: the ONE helper computes exactly the quantiles
        the two former inline implementations computed."""
        rng = np.random.default_rng(0)
        samples = rng.exponential(20.0, size=257).tolist()
        out = latency_percentiles(samples)
        assert out["count"] == 257
        for p in (50, 90, 99):
            assert out[f"p{p}_ms"] == round(
                float(np.percentile(np.asarray(samples), p)), 3
            )
        assert out["mean_ms"] == round(float(np.mean(samples)), 3)
        assert out["max_ms"] == round(float(np.max(samples)), 3)
        assert latency_percentiles([]) == {}

    def test_serve_snapshot_equivalence(self):
        """LatencyStats.snapshot's p50/p99 are the shared helper's numbers
        (reuse, not a clone — the satellite's point)."""
        from batchai_retinanet_horovod_coco_tpu.serve.common import (
            LatencyStats,
        )

        rng = np.random.default_rng(1)
        stats = LatencyStats(window=4096)
        samples_s = rng.exponential(0.02, size=100).tolist()
        for s in samples_s:
            stats.record(s)
        snap = stats.snapshot()
        ref = latency_percentiles(
            [s * 1e3 for s in samples_s], ps=(50, 99)
        )
        assert snap["p50_ms"] == ref["p50_ms"]
        assert snap["p99_ms"] == ref["p99_ms"]
        assert snap["mean_ms"] == ref["mean_ms"]
        assert snap["max_ms"] == ref["max_ms"]
        assert snap["window"] == ref["count"]

    def test_histogram_record_uses_helper(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.obs.events import (
            EventSink,
            split_runs,
        )

        sink = EventSink(str(tmp_path), stdout=False)
        sink.histogram("lat", [1.0, 2.0, 3.0, 10.0])
        sink.close()
        rec = [
            r
            for r in split_runs(str(tmp_path / "metrics.jsonl"))[0]["records"]
            if r.get("event") == "histogram"
        ][0]
        ref = latency_percentiles([1.0, 2.0, 3.0, 10.0])
        for k, v in ref.items():
            assert rec[k] == v


class TestStallMarker:
    def test_watchdog_dump_emits_trace_instant(self, tmp_path):
        """Satellite: a stall diagnosis is visible ON the Perfetto
        timeline (trace.instant), not only in JSONL/stacks — and the
        analyzer reads it back into the stalls section."""
        trace.configure(str(tmp_path), process_label="t")
        w = watchdog_lib.Watchdog(
            stall_after=0.01, dump_path=str(tmp_path / "stacks.txt")
        )
        hb = w.register("wedged-component")
        hb.beat()
        diag = w.check_once(now=trace.monotonic_s() + 5.0)
        assert diag is not None
        w._dump(diag)
        hb.close()
        trace.export()
        merged = trace.merge_traces(str(tmp_path))
        with open(merged) as f:
            events = json.load(f)["traceEvents"]
        stalls = [
            e for e in events if e["ph"] == "i" and e["name"] == "stall"
        ]
        assert len(stalls) == 1
        assert stalls[0]["args"]["component"] == "wedged-component"
        report = analyze_dir(str(tmp_path))
        assert report["stalls"]["trace_markers"] == 1
        assert report["stalls"]["components"] == {"wedged-component": 1}

    def test_dump_without_tracing_still_works(self, tmp_path, capsys):
        w = watchdog_lib.Watchdog(
            stall_after=0.01, dump_path=str(tmp_path / "stacks.txt")
        )
        hb = w.register("wedged")
        hb.beat()
        diag = w.check_once(now=trace.monotonic_s() + 5.0)
        w._dump(diag)  # tracing disabled: instant is a no-op, no crash
        hb.close()
        assert "watchdog_stall" in capsys.readouterr().err


class TestSpanAttribution:
    def test_bench_style_spans_produce_attribution(self, tmp_path):
        """The bench.py --trace integration: live in-process rings →
        compact per-family accounting + overlap ratio."""
        trace.configure(str(tmp_path), process_label="bench-eval")
        with trace.span("aot_compile_detect", bucket="64x64"):
            pass
        for _ in range(3):
            with trace.span("detect_dispatch"):
                pass
            with trace.span("detect_fetch"):
                pass
        att = span_attribution(trace.snapshot_events())
        assert att is not None
        assert set(att["by_span_s"]) == {
            "aot_compile_detect", "detect_dispatch", "detect_fetch"
        }
        assert att["decomposition"] is None  # no train loop in a bench
        assert 0.0 <= att["overlap_efficiency"]["eval"] <= 1.0

    def test_disabled_tracing_yields_none(self):
        assert span_attribution(trace.snapshot_events()) is None

    def test_train_vocab_yields_decomposition(self, tmp_path):
        trace.configure(str(tmp_path), process_label="t")
        for _ in range(4):
            with trace.span("data_wait"):
                pass
            with trace.span("step"):
                pass
        att = span_attribution(trace.snapshot_events())
        d = att["decomposition"]
        assert d is not None and abs(sum(d.values()) - 1.0) < 0.02


class TestTuneFromReport:
    def test_golden_report_maps_to_tune_ops(self):
        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import (
            _ops_from_report,
        )

        ops, batch_axis = _ops_from_report(GOLDEN)
        # The fixture's #1 verdict is device_step → kernel families in
        # rank order; eval_pipeline contributes the batch axis.
        assert ops[0] == "focal"
        assert set(ops) <= {"focal", "matching", "nms"}
        assert batch_axis is True

    def test_empty_verdict_refuses_loudly(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import (
            _ops_from_report,
        )

        p = tmp_path / "r.json"
        p.write_text(json.dumps({"bottlenecks": [
            {"name": "compilation", "tune_ops": []}
        ]}))
        with pytest.raises(SystemExit, match="names no tunable ops"):
            _ops_from_report(str(p))
        with pytest.raises(SystemExit, match="cannot read"):
            _ops_from_report(str(tmp_path / "missing.json"))

    def test_structurally_wrong_reports_exit_cleanly(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.tune.__main__ import (
            _ops_from_report,
        )

        arr = tmp_path / "array.json"
        arr.write_text("[1, 2, 3]")  # top-level array
        with pytest.raises(SystemExit, match="cannot read"):
            _ops_from_report(str(arr))
        strings = tmp_path / "strings.json"
        strings.write_text(json.dumps({"bottlenecks": ["not-a-dict"]}))
        with pytest.raises(SystemExit, match="cannot read"):
            _ops_from_report(str(strings))


class TestPeakTable:
    def test_known_kinds_and_fallbacks(self, monkeypatch):
        assert device_peak_tflops("TPU v5 lite") == (197.0, "spec")
        assert device_peak_tflops("TPU v4") == (275.0, "spec")
        assert device_peak_tflops("cpu")[1] == "nominal-cpu"
        assert device_peak_tflops(None) == (None, None)
        monkeypatch.setenv("RETINANET_PEAK_TFLOPS", "123.5")
        assert device_peak_tflops("weird-npu") == (123.5, "env")

    def test_bench_uses_the_shared_table(self):
        """bench.py's MFU peak resolves through obs/analyze (one table)."""
        import bench

        assert not hasattr(bench, "_PEAK_TFLOPS")


class TestAnalyzeEventsUnits:
    def test_overlap_extremes(self):
        """overlap_efficiency ~1 when fetch barely blocks, ~0 when the
        host spends the whole pipeline blocked in fetch."""
        def mk(name, ts, dur):
            return {"ph": "X", "name": name, "ts": ts, "dur": dur,
                    "pid": 1, "tid": 1}

        # Perfect overlap: 10ms pipeline, 2x 10us fetches.
        good = [mk("detect_dispatch", 0, 100), mk("detect_fetch", 5000, 10),
                mk("detect_dispatch", 5100, 100),
                mk("detect_fetch", 9990, 10)]
        rep = analyze_events(good)
        assert rep["pipeline"]["eval"]["overlap_efficiency"] > 0.99
        # No overlap: fetch occupies the whole wall.
        bad = [mk("detect_dispatch", 0, 10),
               mk("detect_fetch", 10, 9990),
               mk("detect_dispatch", 10000, 10),
               mk("detect_fetch", 10010, 9990)]
        rep = analyze_events(bad)
        assert rep["pipeline"]["eval"]["overlap_efficiency"] < 0.01

    def test_fetch_blocking_verdict_without_train_loop(self):
        """A bench eval/serve trace (no `step` spans) still gets a
        fetch-blocking verdict with tune_ops — the detect-ceiling
        evidence `tune --from-report` exists to consume."""
        def mk(name, ts, dur):
            return {"ph": "X", "name": name, "ts": ts, "dur": dur,
                    "pid": 1, "tid": 1}

        rep = analyze_events(
            [mk("detect_dispatch", 0, 10), mk("detect_fetch", 10, 9990),
             mk("detect_dispatch", 10000, 10),
             mk("detect_fetch", 10010, 9990)]
        )
        top = rep["bottlenecks"][0]
        assert top["name"] == "eval_fetch_blocking"
        assert top["tune_ops"] == ["nms", "batch"]
        # The generic fallback does not duplicate the claimed spans.
        assert not any(
            b["name"] == "span:detect_fetch" for b in rep["bottlenecks"]
        )

    def test_starved_feed_queue_correlation(self):
        def span(name, ts, dur):
            return {"ph": "X", "name": name, "ts": ts, "dur": dur,
                    "pid": 1, "tid": 1}

        def counter(name, ts, v):
            return {"ph": "C", "name": name, "ts": ts, "pid": 1, "tid": 2,
                    "args": {"value": v}}

        events = [
            span("step", 0, 1000),
            counter("device-prefetch.qsize", 500, 0),   # empty before wait
            span("data_wait", 1000, 3000),              # starved: depth 0
            span("step", 4000, 1000),
            counter("device-prefetch.qsize", 5500, 2),  # refilled
            span("data_wait", 6000, 1000),              # depth 2: not starved
            span("step", 7000, 1000),
        ]
        rep = analyze_events(events)
        q = rep["queues"]["device-prefetch.qsize"]
        assert q["starved_data_wait_fraction"] == 0.75  # 3ms of 4ms waits
        assert q["zero_fraction"] == 0.5

    def test_memory_trend(self):
        def counter(name, ts, v):
            return {"ph": "C", "name": name, "ts": ts, "pid": 1, "tid": 1,
                    "args": {"value": v}}

        events = [
            counter("dev0.bytes_in_use", 0, 100.0),
            counter("dev0.bytes_in_use", 1_000_000, 300.0),  # +200B over 1s
            counter("dev0.bytes_in_use", 2_000_000, 200.0),
        ]
        rep = analyze_events(events)
        g = rep["memory"]["gauges"]["dev0.bytes_in_use"]
        assert g["peak_bytes"] == 300.0
        assert g["trend_bytes_per_s"] == 50.0  # (200-100)/2s
        assert rep["memory"]["available"] is True
        # Memory gauges stay out of the queue section.
        assert "dev0.bytes_in_use" not in rep["queues"]
