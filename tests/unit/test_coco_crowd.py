"""Crowd-annotation fidelity: dataset → gt extraction → oracle ignore rules."""

import json

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.data import CocoDataset
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import evaluate_detections
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import coco_gt_from_dataset


@pytest.fixture
def crowd_dataset(tmp_path):
    blob = {
        "images": [
            {"id": 1, "file_name": "a.jpg", "width": 400, "height": 400},
            {"id": 2, "file_name": "b.jpg", "width": 400, "height": 400},
        ],
        "annotations": [
            {
                "id": 1, "image_id": 1, "category_id": 1,
                "bbox": [10, 10, 50, 50], "area": 2500.0, "iscrowd": 0,
            },
            {
                "id": 2, "image_id": 1, "category_id": 1,
                "bbox": [200, 200, 100, 100], "area": 7000.0, "iscrowd": 1,
            },
            {
                "id": 3, "image_id": 2, "category_id": 2,
                "bbox": [0, 0, 30, 30], "area": 900.0, "iscrowd": 0,
            },
        ],
        "categories": [{"id": 1, "name": "a"}, {"id": 2, "name": "b"}],
    }
    path = tmp_path / "instances.json"
    path.write_text(json.dumps(blob))
    return CocoDataset(str(path), image_dir=str(tmp_path))


def test_crowds_kept_separate_from_training_boxes(crowd_dataset):
    rec = crowd_dataset.records[0]
    assert rec.boxes.shape == (1, 4)
    assert rec.crowd_boxes.shape == (1, 4)
    np.testing.assert_allclose(rec.crowd_boxes[0], [200, 200, 300, 300])
    # Segmentation area from the json is preserved, not recomputed from bbox.
    assert rec.crowd_areas[0] == pytest.approx(7000.0)
    assert rec.areas[0] == pytest.approx(2500.0)


def test_gt_extraction_marks_crowds_ignore(crowd_dataset):
    gts, img_ids = coco_gt_from_dataset(crowd_dataset)
    assert img_ids == [1, 2]
    crowds = [g for g in gts if g["iscrowd"]]
    assert len(crowds) == 1
    assert crowds[0]["bbox"] == pytest.approx([200, 200, 100, 100])


def test_detection_on_crowd_is_ignored_end_to_end(crowd_dataset):
    gts, img_ids = coco_gt_from_dataset(crowd_dataset)
    dts = [
        {"image_id": 1, "category_id": 1, "bbox": [10, 10, 50, 50], "score": 0.9},
        # Lands inside the crowd region → must be ignored, not an FP.
        {"image_id": 1, "category_id": 1, "bbox": [210, 210, 40, 40], "score": 0.8},
        {"image_id": 2, "category_id": 2, "bbox": [0, 0, 30, 30], "score": 0.9},
    ]
    stats = evaluate_detections(gts, dts, img_ids=img_ids)
    assert stats["AP"] == pytest.approx(1.0)
