"""Smoke tests for the auxiliary CLIs (evaluate.py / debug.py, SURVEY.md M12)."""

import os
import sys

import pytest

# repo root, derived from this file's own path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))


@pytest.mark.slow
class TestDebugCli:
    def test_synthetic_report_and_vis(self, tmp_path):
        import debug

        report = debug.main(
            [
                "synthetic",
                "--synthetic-root", str(tmp_path / "data"),
                "--synthetic-images", "3",
                "--synthetic-size", "128",
                "--limit", "3",
                "--output-dir", str(tmp_path / "vis"),
            ]
        )
        assert len(report) == 3
        # Every synthetic image has gt and the matcher must find positives
        # (force_match_for_gt semantics — a gt with no anchor is a data bug).
        assert all(r["positive"] > 0 for r in report)
        assert all(
            r["positive"] + r["negative"] + r["ignored"] == r["anchors"]
            for r in report
        )
        vis = list((tmp_path / "vis").glob("*.jpg"))
        assert len(vis) == 3


class TestEvaluateCli:
    """evaluate.main's metric formatting — fast, no model in the loop."""

    def _run(self, monkeypatch, capsys, metrics):
        import evaluate
        import train

        seen_argv = {}

        def fake_train_main(argv):
            seen_argv["argv"] = argv
            return metrics

        monkeypatch.setattr(train, "main", fake_train_main)
        out = evaluate.main(["synthetic"])
        assert out is metrics
        assert seen_argv["argv"][-1] == "--eval-only"
        return capsys.readouterr().out.strip().splitlines()

    def test_coco_metrics_print_without_voc_keys(self, monkeypatch, capsys):
        # Regression: COCO keys ('AP') used to hit the voc sort key's
        # rsplit('_')[1] and raise IndexError on every run.
        lines = self._run(
            monkeypatch, capsys, {"AP": 0.5, "AP50": 0.7, "loss": 1.0}
        )
        assert lines == ["AP: 0.5000", "AP50: 0.7000"]

    def test_voc_metrics_numeric_order(self, monkeypatch, capsys):
        lines = self._run(
            monkeypatch,
            capsys,
            {"AP": 0.5, "voc_AP_10": 0.2, "voc_AP_2": 0.1, "voc_mAP": 0.6},
        )
        assert lines == [
            "AP: 0.5000",
            "voc_mAP: 0.6000",
            "voc_AP_2: 0.1000",
            "voc_AP_10: 0.2000",
        ]


class TestBucketsCli:
    """debug.py buckets: exact bucket shares from annotation metadata only."""

    def _write_annotations(self, path, dims):
        import json

        blob = {
            "categories": [{"id": 1, "name": "thing"}],
            "images": [
                {"id": i, "file_name": f"{i}.jpg", "width": w, "height": h}
                for i, (w, h) in enumerate(dims)
            ],
            "annotations": [
                {
                    "id": i,
                    "image_id": i,
                    "category_id": 1,
                    "bbox": [1, 1, 10, 10],
                    "area": 100,
                    "iscrowd": 0,
                }
                for i in range(len(dims))
            ],
        }
        with open(path, "w") as f:
            json.dump(blob, f)

    def test_shares_and_weighted_mix(self, tmp_path, capsys):
        import json

        import debug

        # 2 landscape (640x480 -> 800x1067 -> 800x1344 bucket), 1 portrait
        # (480x640 -> 1067x800 -> 1344x800), 1 near-square landscape
        # (500x500 -> 800x800 -> fits 800x1344, the smallest-area bucket).
        ann = tmp_path / "instances.json"
        self._write_annotations(
            ann, [(640, 480), (640, 480), (480, 640), (500, 500)]
        )
        bench = tmp_path / "bucketbench.json"
        # An extra recorded bucket the current config does not emit (the
        # retired 1088x1088, as in the committed round-4 BUCKETBENCH)
        # must be tolerated and must not drag the mix.
        with open(bench, "w") as f:
            json.dump(
                {
                    "per_bucket_imgs_per_sec_per_chip": {
                        "800x1344": 60.0,
                        "1344x800": 60.0,
                        "1088x1088": 30.0,
                    }
                },
                f,
            )
        (out,) = debug.main(
            ["buckets", str(ann), "--bucketbench", str(bench)]
        )
        shares = out["shares"]
        assert shares["800x1344"]["count"] == 3
        assert shares["1344x800"]["count"] == 1
        assert "1088x1088" not in shares
        assert abs(shares["800x1344"]["share"] - 0.75) < 1e-9
        # All contributing buckets run at 60 -> harmonic mix is exactly 60.
        assert abs(out["weighted_mix_imgs_per_sec_per_chip"] - 60.0) < 1e-9


class TestBenchCheck:
    """bench.py's regression tripwire (VERDICT r4 weak #1): the committed
    BUCKETBENCH.json flagship rate minus the noise band is the floor."""

    def _committed(self):
        import json
        import os

        import bench

        # committed artifact lives next to bench.py, wherever the repo is
        with open(
            os.path.join(os.path.dirname(os.path.abspath(bench.__file__)),
                         "BUCKETBENCH.json")
        ) as f:
            return float(
                json.load(f)["per_bucket_imgs_per_sec_per_chip"][
                    f"{bench.BUCKET[0]}x{bench.BUCKET[1]}"
                ]
            )

    def test_r4_sized_drift_is_noise_and_real_regression_fails(self, capsys):
        import bench

        committed = self._committed()
        # r4's observed drift (-0.5%) must be classified noise BY THE TOOL.
        assert bench.check_against_committed(committed * 0.995) == 0
        # A real -5% must fail loudly.
        assert bench.check_against_committed(committed * 0.95) == 1
        out = capsys.readouterr().out
        assert "ok" in out and "REGRESSION" in out

    def test_exact_floor_passes(self):
        import bench

        committed = self._committed()
        floor = committed * (1 - bench.NOISE_BAND_PCT / 100)
        assert bench.check_against_committed(floor) == 0
