"""Smoke tests for the auxiliary CLIs (evaluate.py / debug.py, SURVEY.md M12)."""

import sys

import pytest

sys.path.insert(0, "/root/repo")


@pytest.mark.slow
class TestDebugCli:
    def test_synthetic_report_and_vis(self, tmp_path):
        import debug

        report = debug.main(
            [
                "synthetic",
                "--synthetic-root", str(tmp_path / "data"),
                "--synthetic-images", "3",
                "--synthetic-size", "128",
                "--limit", "3",
                "--output-dir", str(tmp_path / "vis"),
            ]
        )
        assert len(report) == 3
        # Every synthetic image has gt and the matcher must find positives
        # (force_match_for_gt semantics — a gt with no anchor is a data bug).
        assert all(r["positive"] > 0 for r in report)
        assert all(
            r["positive"] + r["negative"] + r["ignored"] == r["anchors"]
            for r in report
        )
        vis = list((tmp_path / "vis").glob("*.jpg"))
        assert len(vis) == 3
