"""Overlapped eval/detect fast-path contracts (ISSUE 2).

The four promises the tentpole makes:

1. the shared ``prefetch_map`` helper (data/prefetch.py) preserves order,
   propagates producer exceptions, and stops cleanly on ``close()`` — the
   train loop AND the eval driver both stand on it;
2. ``StreamingCocoEval`` (incremental per-image matching in the consumer
   thread) is stat-identical to the one-shot ``evaluate_detections`` on
   arbitrary batchings, including gt-only images, detection-free
   categories and a category superset;
3. the eval consumer thread mirrors the shm pipeline's error contract
   (tests/unit/test_shm_pipeline.py): a crash re-raises in the driver,
   ``close()`` never hangs and is idempotent;
4. the pipelined ``collect_detections``/``run_coco_eval`` produce
   BIT-IDENTICAL detections and metrics to the sequential path on the
   mini-COCO fixture (acceptance criterion), and the async in-training
   eval hook runs off the step path with clean error propagation.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.data import (
    CocoDataset,
    PipelineConfig,
    build_pipeline,
    make_synthetic_coco,
)
from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.data.prefetch import prefetch_map
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    StreamingCocoEval,
    evaluate_detections,
)
from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
    _EvalConsumer,
    collect_detections,
    run_coco_eval,
)
from batchai_retinanet_horovod_coco_tpu.ops.nms import Detections


class TestPrefetchMap:
    def test_order_and_values(self):
        out = list(prefetch_map(range(20), lambda x: x * x, depth=3))
        assert out == [x * x for x in range(20)]

    def test_depth_zero_is_synchronous(self):
        calls = []

        def transfer(x):
            calls.append(x)
            return x

        it = prefetch_map(range(5), transfer, depth=0)
        assert calls == []  # nothing eager: no background thread
        assert next(it) == 0
        assert list(it) == [1, 2, 3, 4]

    def test_transfer_exception_propagates(self):
        def transfer(x):
            if x == 3:
                raise ValueError("boom at 3")
            return x

        it = prefetch_map(range(10), transfer, depth=2)
        got = [next(it), next(it), next(it)]
        assert got == [0, 1, 2]
        with pytest.raises(ValueError, match="boom at 3"):
            for _ in range(7):
                next(it)

    def test_source_exception_propagates(self):
        def source():
            yield 1
            raise RuntimeError("source died")

        it = prefetch_map(source(), lambda x: x, depth=2)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="source died"):
            next(it)

    def test_close_stops_feeder_on_full_queue(self):
        started = threading.Event()

        def transfer(x):
            started.set()
            return x

        # Infinite source + tiny queue: the feeder would block forever on a
        # plain put once the consumer stops pulling.
        it = prefetch_map(iter(int, 1), transfer, depth=1)
        assert next(it) == 0
        started.wait(timeout=5)
        it.close()
        # The feeder is a daemon thread named by the helper; after close()
        # it must exit within the stop-gate poll interval.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            alive = [
                t for t in threading.enumerate()
                if t.name == "prefetch-map" and t.is_alive()
            ]
            if not alive:
                break
            time.sleep(0.05)
        assert not alive, "prefetch feeder thread leaked after close()"


def _random_eval_case(seed: int, num_images: int = 12, num_cats: int = 5):
    """Random gt + detections exercising crowd, gt-only images,
    detection-free categories, and empty images."""
    rng = np.random.default_rng(seed)
    img_ids = [int(i) for i in rng.choice(10_000, num_images, replace=False)]
    cats = list(range(1, num_cats + 1))
    gts, dts = [], []
    ann_id = 1
    for img in img_ids:
        for _ in range(int(rng.integers(0, 5))):
            x, y = rng.uniform(0, 200, 2)
            w, h = rng.uniform(4, 120, 2)
            gts.append(
                {
                    "id": ann_id,
                    "image_id": img,
                    "category_id": int(rng.choice(cats[:-1])),  # last cat gt-free
                    "bbox": [x, y, w, h],
                    "area": w * h,
                    "iscrowd": int(rng.random() < 0.15),
                }
            )
            ann_id += 1
        for _ in range(int(rng.integers(0, 8))):
            x, y = rng.uniform(0, 200, 2)
            w, h = rng.uniform(4, 120, 2)
            dts.append(
                {
                    "image_id": img,
                    "category_id": int(rng.choice(cats)),
                    "bbox": [x, y, w, h],
                    "score": float(rng.random()),
                }
            )
    return gts, dts, img_ids, cats


class TestStreamingCocoEval:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_matches_one_shot_evaluator(self, seed):
        gts, dts, img_ids, cats = _random_eval_case(seed)
        want = evaluate_detections(gts, dts, img_ids=img_ids)

        # Feed detections image-by-image in arbitrary batch groupings, with
        # a category SUPERSET (the label-map categories, as run_coco_eval
        # passes them) — stats must match bit-for-bit.
        scorer = StreamingCocoEval(gts, img_ids, cat_ids=cats + [99])
        by_img = {i: [d for d in dts if d["image_id"] == i] for i in img_ids}
        for start in range(0, len(img_ids), 3):
            group = img_ids[start : start + 3]
            scorer.add(
                [d for i in group for d in by_img[i]], group
            )
        got = scorer.finish()
        assert got == want  # exact float equality: same ops, same order

    def test_gt_only_images_scored_at_finish(self):
        gts, dts, img_ids, cats = _random_eval_case(3)
        want = evaluate_detections(gts, dts, img_ids=img_ids)
        scorer = StreamingCocoEval(gts, img_ids, cat_ids=cats)
        # Stream only half the images; finish() must pick up the rest
        # (gt-only/never-streamed images still count for recall).
        half = img_ids[: len(img_ids) // 2]
        scorer.add([d for d in dts if d["image_id"] in set(half)], half)
        remaining = set(img_ids) - set(half)
        scorer.add([d for d in dts if d["image_id"] in remaining], [])
        assert scorer.finish() == want

    def test_late_detection_rejected(self):
        gts, dts, img_ids, cats = _random_eval_case(5)
        scorer = StreamingCocoEval(gts, img_ids, cat_ids=cats)
        scorer.add([], [img_ids[0]])
        with pytest.raises(ValueError, match="marked complete"):
            scorer.add(
                [{"image_id": img_ids[0], "category_id": cats[0],
                  "bbox": [0, 0, 10, 10], "score": 0.5}],
                [],
            )


def _fake_det(batch: int, slots: int = 4) -> Detections:
    rng = np.random.default_rng(0)
    return Detections(
        boxes=jnp.asarray(rng.uniform(0, 50, (batch, slots, 4)).astype(np.float32)),
        scores=jnp.asarray(rng.random((batch, slots)).astype(np.float32)),
        labels=jnp.zeros((batch, slots), jnp.int32),
        valid=jnp.ones((batch, slots), bool),
    )


class TestEvalConsumer:
    def _put_batch(self, consumer, batch=2):
        consumer.put(
            _fake_det(batch),
            np.arange(batch, dtype=np.int64),
            np.ones(batch, np.float32),
            np.ones(batch, bool),
        )

    def test_crash_in_hook_raises_in_driver(self):
        def bad_hook(batch_results, done_ids):
            raise ValueError("scorer exploded")

        consumer = _EvalConsumer({0: 1}, None, on_batch=bad_hook, maxsize=1)
        with pytest.raises(RuntimeError, match="eval consumer thread failed"):
            # The first put may land before the consumer crashes; a bounded
            # number of further puts must surface the error (queue size 1).
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                self._put_batch(consumer)
            pytest.fail("consumer crash not surfaced within 30s")
        consumer.close()  # after a crash close() must not hang

    def test_finish_surfaces_crash(self):
        def bad_hook(batch_results, done_ids):
            raise ValueError("scorer exploded")

        consumer = _EvalConsumer({0: 1}, None, on_batch=bad_hook)
        self._put_batch(consumer)
        with pytest.raises(RuntimeError, match="eval consumer thread failed"):
            consumer.finish()

    def test_close_is_idempotent_and_prompt(self):
        consumer = _EvalConsumer({0: 1}, None)
        self._put_batch(consumer)
        t0 = time.monotonic()
        consumer.close()
        consumer.close()
        assert time.monotonic() - t0 < 5
        assert not consumer._thread.is_alive()

    def test_results_ordered_and_converted(self):
        consumer = _EvalConsumer({0: 7}, None)
        for i in range(3):
            det = Detections(
                boxes=jnp.asarray([[[0.0, 0.0, 10.0, 10.0]]]),
                scores=jnp.asarray([[0.5]]),
                labels=jnp.zeros((1, 1), jnp.int32),
                valid=jnp.ones((1, 1), bool),
            )
            consumer.put(
                det,
                np.asarray([100 + i], dtype=np.int64),
                np.ones(1, np.float32),
                np.ones(1, bool),
            )
        results = consumer.finish()
        assert [r["image_id"] for r in results] == [100, 101, 102]
        assert all(r["category_id"] == 7 for r in results)


class TestPipelinedParity:
    """Acceptance criterion: the overlapped path is bit-identical to the
    sequential one on the mini-COCO fixture, detections AND mAP."""

    @pytest.fixture(scope="class")
    def mini_coco(self, tmp_path_factory):
        root = str(tmp_path_factory.mktemp("coco_evalpipe"))
        make_synthetic_coco(
            root, num_images=6, num_classes=3, image_size=(96, 96), seed=11
        )
        return CocoDataset(f"{root}/instances_train.json", f"{root}/train")

    def _batches(self, ds):
        return build_pipeline(
            ds,
            PipelineConfig(
                batch_size=4, buckets=((96, 96),), min_side=96, max_side=96,
                max_gt=8, shuffle=False, hflip_prob=0.0, drop_remainder=False,
                num_workers=2,
            ),
            train=False,
        )

    def test_detections_bit_identical_and_map_equal(
        self, mini_coco, tiny_model_and_state
    ):
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
        )

        model, state = tiny_model_and_state
        # The untrained head's π=0.01 prior sits BELOW the production 0.05
        # score threshold — at the default config both paths would emit
        # zero detections and the bitwise comparison would be vacuous.
        # Lower the threshold so real detections flow through the
        # consumer/scorer.
        cfg = DetectConfig(score_threshold=0.001)
        detect_fns = {}  # share the compiled program across all four passes
        dt_seq = collect_detections(
            state, model, mini_coco, self._batches(mini_coco), cfg,
            pipelined=False, detect_fns=detect_fns,
        )
        dt_pipe = collect_detections(
            state, model, mini_coco, self._batches(mini_coco), cfg,
            pipelined=True, detect_fns=detect_fns,
        )
        assert dt_seq, "no detections — the parity check would be vacuous"
        assert dt_pipe == dt_seq  # bitwise: same dicts, same order

        m_seq = run_coco_eval(
            state, model, mini_coco, self._batches(mini_coco), cfg,
            pipelined=False, detect_fns=detect_fns,
        )
        m_pipe = run_coco_eval(
            state, model, mini_coco, self._batches(mini_coco), cfg,
            pipelined=True, detect_fns=detect_fns,
        )
        assert m_pipe == m_seq
        assert set(m_pipe) >= {"AP", "AP50", "AR100"}

    def test_pipeline_error_propagates_and_unwinds(
        self, mini_coco, tiny_model_and_state, tmp_path
    ):
        """A crashed eval input pipeline must raise out of the pipelined
        driver (through prefetch + consumer) without hanging."""
        model, state = tiny_model_and_state

        def stream():
            batches = self._batches(mini_coco)
            yield next(iter(batches))
            batches.close()
            raise RuntimeError("decode worker died")

        with pytest.raises(RuntimeError, match="decode worker died"):
            collect_detections(
                state, model, mini_coco, stream(), pipelined=True
            )
        # No leaked consumer/prefetch threads.
        time.sleep(0.2)
        leaked = [
            t.name for t in threading.enumerate()
            if t.name in ("eval-consumer", "eval-device-prefetch")
            and t.is_alive()
        ]
        assert not leaked


class TestAsyncEvalHook:
    """LoopConfig.async_eval: the mid-run hook runs off the step path on a
    snapshotted (opt_state-stripped) copy; failures surface in the loop."""

    HW = (64, 64)
    NUM_CLASSES = 3
    BATCH = 8

    def _model(self):
        from batchai_retinanet_horovod_coco_tpu.models import (
            RetinaNetConfig,
            build_retinanet,
        )

        # Same architecture/dtype as test_loop.py's tiny model: the step
        # program dedups against its compiles in the session cache.
        return build_retinanet(
            RetinaNetConfig(
                num_classes=self.NUM_CLASSES, backbone="resnet_test",
                fpn_channels=16, head_width=16, head_depth=1,
                dtype=jnp.float32,
            )
        )

    def _state(self, model):
        from batchai_retinanet_horovod_coco_tpu.train import create_train_state

        return create_train_state(
            model, optax.sgd(1e-3, momentum=0.9), (1, *self.HW, 3),
            jax.random.key(0),
        )

    def _stream(self):
        rng = np.random.default_rng(0)
        images = rng.normal(0, 1, (self.BATCH, *self.HW, 3)).astype(np.float32)
        gt_boxes = np.tile(
            np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (self.BATCH, 1, 1)
        )
        while True:
            yield Batch(
                images=images,
                gt_boxes=gt_boxes,
                gt_labels=np.ones((self.BATCH, 1), np.int32),
                gt_mask=np.ones((self.BATCH, 1), bool),
                image_ids=np.arange(self.BATCH, dtype=np.int64),
                scales=np.ones((self.BATCH,), np.float32),
                valid=np.ones((self.BATCH,), bool),
            )

    def test_async_eval_runs_on_snapshot_and_logs(self):
        from batchai_retinanet_horovod_coco_tpu.train.loop import (
            LoopConfig,
            run_training,
        )

        calls = []

        def eval_fn(state):
            calls.append((int(state.step), state.opt_state))
            return {"mAP": 0.5}

        logged = []

        class Logger:
            def log(self, step, metrics, prefix="train"):
                if prefix == "eval":
                    logged.append((step, dict(metrics)))

        model = self._model()
        state = run_training(
            model, self._state(model), self._stream(), self.NUM_CLASSES,
            LoopConfig(total_steps=4, log_every=10, eval_every=2,
                       async_eval=True),
            eval_fn=eval_fn, logger=Logger(),
        )
        assert int(state.step) == 4
        # Mid-run eval at 2 (async, opt_state stripped from the snapshot)
        # + final eval at 4 (synchronous, full state).
        assert [c[0] for c in calls] == [2, 4]
        assert calls[0][1] == ()  # snapshot drops optimizer state
        assert calls[1][1] != ()
        assert [step for step, _ in logged] == [2, 4]
        assert logged[0][1] == {"mAP": 0.5}

    def test_loop_error_reaps_inflight_async_eval(self):
        """A loop exception with an eval IN FLIGHT must reap the eval
        thread during unwind and surface its failure as a WARNING — never
        mask the original error (the loop's is the one that matters)."""
        from batchai_retinanet_horovod_coco_tpu.train.loop import (
            LoopConfig,
            run_training,
        )

        release = threading.Event()

        def eval_fn(state):
            release.wait(10)
            raise ValueError("eval exploded during unwind")

        def stream():
            src = self._stream()
            for _ in range(3):
                yield next(src)
            release.set()
            raise RuntimeError("stream died")

        model = self._model()
        with pytest.warns(UserWarning, match="async eval failed"):
            with pytest.raises(RuntimeError, match="stream died"):
                run_training(
                    model, self._state(model), stream(), self.NUM_CLASSES,
                    # Synchronous transfer: the stream's failure point
                    # stays pinned to step 4, after the step-2 eval launch.
                    LoopConfig(total_steps=6, log_every=10, eval_every=2,
                               async_eval=True, device_prefetch=0),
                    eval_fn=eval_fn,
                )

    def test_async_eval_failure_propagates(self):
        from batchai_retinanet_horovod_coco_tpu.train.loop import (
            LoopConfig,
            run_training,
        )

        def eval_fn(state):
            raise ValueError("eval exploded")

        model = self._model()
        with pytest.raises(RuntimeError, match="async eval hook failed"):
            run_training(
                model, self._state(model), self._stream(), self.NUM_CLASSES,
                LoopConfig(total_steps=4, log_every=10, eval_every=2,
                           async_eval=True),
                eval_fn=eval_fn,
            )
