import numpy as np

from batchai_retinanet_horovod_coco_tpu.ops.boxes import (
    BoxCodecConfig,
    clip_boxes,
    decode_boxes,
    encode_boxes,
)


def random_boxes(rng, n, lo=0, hi=200):
    xy = rng.uniform(lo, hi, size=(n, 2))
    wh = rng.uniform(2, 80, size=(n, 2))
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


def test_encode_decode_roundtrip():
    rng = np.random.default_rng(1)
    anchors = random_boxes(rng, 64)
    gt = random_boxes(rng, 64)
    deltas = encode_boxes(anchors, gt)
    recon = np.asarray(decode_boxes(anchors, deltas))
    np.testing.assert_allclose(recon, gt, atol=1e-3)


def test_encode_identity_is_mean():
    cfg = BoxCodecConfig()
    anchors = np.array([[10, 10, 50, 50]], dtype=np.float32)
    deltas = np.asarray(encode_boxes(anchors, anchors, cfg))
    np.testing.assert_allclose(deltas, 0.0, atol=1e-6)


def test_encode_known_values():
    cfg = BoxCodecConfig(stds=(1.0, 1.0, 1.0, 1.0))
    anchors = np.array([[0, 0, 10, 10]], dtype=np.float32)  # cx=cy=5, w=h=10
    gt = np.array([[5, 5, 25, 25]], dtype=np.float32)  # cx=cy=15, w=h=20
    deltas = np.asarray(encode_boxes(anchors, gt, cfg))[0]
    np.testing.assert_allclose(deltas, [1.0, 1.0, np.log(2.0), np.log(2.0)], atol=1e-5)


def test_decode_clamps_extreme_scales():
    anchors = np.array([[0, 0, 10, 10]], dtype=np.float32)
    deltas = np.array([[0, 0, 100.0, 100.0]], dtype=np.float32)
    boxes = np.asarray(decode_boxes(anchors, deltas))
    assert np.all(np.isfinite(boxes))


def test_clip_boxes():
    boxes = np.array([[-5, -5, 20, 20], [90, 90, 200, 300]], dtype=np.float32)
    clipped = np.asarray(clip_boxes(boxes, (100, 150)))
    np.testing.assert_allclose(
        clipped, [[0, 0, 20, 20], [90, 90, 150, 100]], atol=1e-6
    )
