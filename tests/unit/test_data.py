import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.data import (
    CocoDataset,
    PipelineConfig,
    build_pipeline,
    make_synthetic_coco,
)
from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
    pick_bucket,
    resize_scale,
)


@pytest.fixture(scope="module")
def synthetic_dataset(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("coco"))
    ann = make_synthetic_coco(root, num_images=10, num_classes=3, seed=1)
    return CocoDataset(ann, image_dir=f"{root}/train")


def test_dataset_parsing(synthetic_dataset):
    ds = synthetic_dataset
    assert ds.num_classes == 3
    assert len(ds) == 10
    rec = ds.records[0]
    assert rec.boxes.shape[1] == 4
    # Corner boxes inside the image.
    assert np.all(rec.boxes[:, 2] > rec.boxes[:, 0])
    assert np.all(rec.boxes[:, 2] <= rec.width)
    # Contiguous labels.
    assert rec.labels.min() >= 0 and rec.labels.max() < 3


def test_category_id_mapping(synthetic_dataset):
    ds = synthetic_dataset
    # COCO ids 1..3 → labels 0..2, sorted by id.
    assert ds.cat_id_to_label == {1: 0, 2: 1, 3: 2}
    assert ds.label_to_cat_id[0] == 1


def test_resize_scale_reference_rule():
    # min side → 800 unless max side would exceed 1333.
    assert resize_scale(600, 600, 800, 1333) == pytest.approx(800 / 600)
    # 480x640: scale by 800/480 would give max 1066 < 1333 → min-side rule.
    assert resize_scale(480, 640, 800, 1333) == pytest.approx(800 / 480)
    # 400x1200: min-side rule gives 2.0 → max 2400 > 1333 → cap at 1333/1200.
    assert resize_scale(400, 1200, 800, 1333) == pytest.approx(1333 / 1200)


def test_pick_bucket():
    buckets = ((800, 1344), (1344, 800), (1024, 1024))
    assert pick_bucket(800, 1066, buckets) == (800, 1344)
    assert pick_bucket(1066, 800, buckets) == (1344, 800)
    assert pick_bucket(1000, 1000, buckets) == (1024, 1024)
    # Nothing fits → largest bucket.
    assert pick_bucket(2000, 2000, buckets) in buckets


def test_train_pipeline_shapes(synthetic_dataset):
    cfg = PipelineConfig(
        batch_size=2,
        buckets=((320, 320),),
        min_side=300,
        max_side=320,
        max_gt=8,
        num_workers=2,
        prefetch=1,
        seed=0,
    )
    it = build_pipeline(synthetic_dataset, cfg, train=True)
    batch = next(it)
    assert batch.images.shape == (2, 320, 320, 3)
    assert batch.gt_boxes.shape == (2, 8, 4)
    assert batch.gt_mask.dtype == bool
    assert batch.gt_mask.any()
    # Boxes are in resized coords, inside the bucket.
    valid_boxes = batch.gt_boxes[batch.gt_mask]
    assert np.all(valid_boxes[:, 2] <= 320 + 1e-3)
    # Default contract: raw uint8, normalized on device.
    assert batch.images.dtype == np.uint8


def test_host_normalize_and_device_normalize_agree(synthetic_dataset):
    """uint8 + on-device normalize == host-side f32 normalize (same pixels)."""
    import dataclasses

    import jax.numpy as jnp

    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        normalize_images,
    )

    cfg = PipelineConfig(
        batch_size=2, buckets=((320, 320),), min_side=300, max_side=320,
        shuffle=False, hflip_prob=0.0, seed=0,
    )
    raw = next(build_pipeline(synthetic_dataset, cfg, train=True))
    host = next(
        build_pipeline(
            synthetic_dataset,
            dataclasses.replace(cfg, host_normalize=True),
            train=True,
        )
    )
    assert raw.images.dtype == np.uint8
    assert host.images.dtype == np.float32
    on_device = np.asarray(normalize_images(jnp.asarray(raw.images)))
    # Interior pixels identical (padding differs: mean-pixel uint8 vs 0.0).
    np.testing.assert_allclose(
        on_device[:, :300, :300], host.images[:, :300, :300],
        rtol=1e-5, atol=1e-5,
    )
    # f32 passthrough: already-normalized arrays are untouched.
    same = normalize_images(jnp.asarray(host.images))
    np.testing.assert_array_equal(np.asarray(same), host.images)
    # uint8 padding sits at ~0.0 in normalized space (reference semantics).
    assert abs(float(on_device[:, 310:, 310:].mean())) < 0.02


def test_eval_pipeline_covers_all_records_once(synthetic_dataset):
    cfg = PipelineConfig(
        batch_size=4,
        buckets=((320, 320),),
        min_side=300,
        max_side=320,
        max_gt=8,
        hflip_prob=0.0,
        num_workers=2,
        drop_remainder=False,
    )
    it = build_pipeline(synthetic_dataset, cfg, train=False)
    seen = []
    for batch in it:
        assert batch.images.shape[0] == 4  # padded to full batch
        seen.extend(batch.image_ids[batch.valid].tolist())
    assert sorted(seen) == sorted(r.image_id for r in synthetic_dataset.records)


def test_sharding_partitions_records(synthetic_dataset):
    ids = []
    for shard in range(2):
        cfg = PipelineConfig(
            batch_size=1,
            buckets=((320, 320),),
            min_side=300,
            max_side=320,
            max_gt=8,
            hflip_prob=0.0,
            shard_index=shard,
            shard_count=2,
            num_workers=1,
            drop_remainder=False,
        )
        for batch in build_pipeline(synthetic_dataset, cfg, train=False):
            ids.extend(batch.image_ids[batch.valid].tolist())
    assert sorted(ids) == sorted(r.image_id for r in synthetic_dataset.records)


def test_oversized_image_shrinks_to_fit_bucket(tmp_path):
    """An image no bucket fits is scaled down, not crashed on (bucket cap)."""
    from batchai_retinanet_horovod_coco_tpu.data import make_synthetic_coco

    ann = make_synthetic_coco(
        str(tmp_path), num_images=2, num_classes=2, image_size=(96, 400), seed=2
    )
    ds = CocoDataset(ann, image_dir=f"{tmp_path}/train")
    # min_side=96 → scale 1.0 → 96x400 exceeds the only (128, 128) bucket.
    cfg = PipelineConfig(
        batch_size=2,
        buckets=((128, 128),),
        min_side=96,
        max_side=400,
        max_gt=8,
        num_workers=1,
        hflip_prob=0.0,
    )
    batch = next(build_pipeline(ds, cfg, train=True))
    assert batch.images.shape == (2, 128, 128, 3)
    valid = batch.gt_boxes[batch.gt_mask]
    assert np.all(valid <= 128 + 1e-3)
    # scale reflects the extra shrink (128/400), so eval rescaling stays exact.
    assert batch.scales[0] == pytest.approx(128 / 400)


def test_abandoned_iterator_stops_producer(synthetic_dataset):
    """Closing the iterator must unblock and terminate the producer thread."""
    import threading
    import time

    cfg = PipelineConfig(
        batch_size=1,
        buckets=((320, 320),),
        min_side=300,
        max_side=320,
        max_gt=8,
        num_workers=2,
        prefetch=1,
    )
    before = threading.active_count()
    it = build_pipeline(synthetic_dataset, cfg, train=True)
    next(it)  # producer is now live and blocked on the full prefetch queue
    it.close()
    deadline = time.time() + 10
    while time.time() < deadline and threading.active_count() > before:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_determinism_same_seed(synthetic_dataset):
    cfg = PipelineConfig(
        batch_size=2,
        buckets=((320, 320),),
        min_side=300,
        max_side=320,
        max_gt=8,
        num_workers=2,
        seed=7,
    )
    a = next(build_pipeline(synthetic_dataset, cfg, train=True))
    b = next(build_pipeline(synthetic_dataset, cfg, train=True))
    np.testing.assert_array_equal(a.image_ids, b.image_ids)
    np.testing.assert_allclose(a.images, b.images)
    np.testing.assert_allclose(a.gt_boxes, b.gt_boxes)


class _ManyBoxDataset:
    """Duck-typed dataset: one image carrying ``n`` gt boxes."""

    def __init__(self, root, n=150, size=96):
        from PIL import Image
        from batchai_retinanet_horovod_coco_tpu.data.coco import ImageRecord

        rng = np.random.default_rng(0)
        path = f"{root}/img.jpg"
        Image.fromarray(
            rng.integers(0, 255, (size, size, 3), dtype=np.uint8)
        ).save(path)
        xy = rng.uniform(0, size - 10, (n, 2)).astype(np.float32)
        boxes = np.concatenate([xy, xy + rng.uniform(4, 10, (n, 2))], 1)
        boxes = np.clip(boxes, 0, size).astype(np.float32)
        self.records = [
            ImageRecord(
                image_id=1, file_name="img.jpg", width=size, height=size,
                boxes=boxes, labels=np.zeros(n, np.int32),
                areas=((boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])),
                crowd_boxes=np.zeros((0, 4), np.float32),
                crowd_labels=np.zeros(0, np.int32),
                crowd_areas=np.zeros(0, np.float32),
            )
        ]
        self._root = root

    def image_path(self, record):
        return f"{self._root}/{record.file_name}"


def test_resolve_max_gt_auto_covers_dataset(tmp_path):
    from batchai_retinanet_horovod_coco_tpu.data import resolve_max_gt

    ds = _ManyBoxDataset(str(tmp_path), n=150)
    max_gt = resolve_max_gt(None, ds)
    assert max_gt >= 150
    # All 150 boxes survive into the batch.
    batches = build_pipeline(
        ds,
        PipelineConfig(
            batch_size=1, buckets=((96, 96),), min_side=96, max_side=96,
            max_gt=max_gt, num_workers=1, shuffle=False,
        ),
        train=False,
    )
    batch = next(iter(batches))
    assert int(batch.gt_mask.sum()) == 150
    assert batches.stats.truncated_boxes == 0
    # Explicit values are honored unchanged.
    assert resolve_max_gt(100, ds) == 100


def test_max_gt_truncation_is_counted_and_warned(tmp_path, caplog):
    import logging

    ds = _ManyBoxDataset(str(tmp_path), n=150)
    with caplog.at_level(logging.WARNING, logger="batchai_retinanet_horovod_coco_tpu.data.pipeline"):
        batches = build_pipeline(
            ds,
            PipelineConfig(
                batch_size=1, buckets=((96, 96),), min_side=96, max_side=96,
                max_gt=100, num_workers=1, shuffle=False,
            ),
            train=False,
        )
        batch = next(iter(batches))
    assert int(batch.gt_mask.sum()) == 100
    assert batches.stats.truncated_boxes == 50
    assert batches.stats.truncated_images == 1
    assert any("truncates" in r.message for r in caplog.records)


def _drain(it, n):
    out = [next(it) for _ in range(n)]
    it.close()
    return out


def test_skip_batches_fast_forwards_exactly(synthetic_dataset):
    """ISSUE 11 elastic resume: skip_batches=k emits exactly the batches
    a fresh pipeline emits from position k on — across epoch boundaries
    (10 images / batch 2 = 5 plans per epoch; k=7 lands in epoch 2),
    with augmentation bit-identical (per-example RNG is positional)."""
    cfg = dict(
        batch_size=2, buckets=((320, 320),), min_side=300, max_side=320,
        max_gt=8, num_workers=2, seed=7,
    )
    full = _drain(
        build_pipeline(synthetic_dataset, PipelineConfig(**cfg), train=True),
        10,
    )
    skipped = _drain(
        build_pipeline(
            synthetic_dataset,
            PipelineConfig(skip_batches=7, **cfg),
            train=True,
        ),
        3,
    )
    for want, got in zip(full[7:], skipped):
        np.testing.assert_array_equal(want.image_ids, got.image_ids)
        np.testing.assert_array_equal(want.images, got.images)
        np.testing.assert_array_equal(want.gt_boxes, got.gt_boxes)


def test_exclude_ids_never_emitted_and_order_stable(synthetic_dataset):
    """ISSUE 11 auto-resume: excluded image_ids never appear again, and
    the surviving stream keeps the (seed, epoch) permutation ORDER of the
    unfiltered one (exclusion leaves holes, it does not reshuffle)."""
    cfg = dict(
        batch_size=2, buckets=((320, 320),), min_side=300, max_side=320,
        max_gt=8, num_workers=2, seed=7,
    )
    poison = tuple(
        int(r.image_id) for r in synthetic_dataset.records[:2]
    )
    full = _drain(
        build_pipeline(synthetic_dataset, PipelineConfig(**cfg), train=True),
        5,
    )
    filtered = _drain(
        build_pipeline(
            synthetic_dataset,
            PipelineConfig(exclude_ids=poison, **cfg),
            train=True,
        ),
        4,  # one epoch = 8 survivors / batch 2
    )
    seen = [int(i) for b in filtered for i in b.image_ids]
    assert not set(seen) & set(poison)
    full_order = [
        int(i) for b in full for i in b.image_ids if int(i) not in poison
    ]
    # Batch composition groups by bucket; within this single-bucket config
    # the survivor order must match the unfiltered order exactly.
    assert seen == full_order[: len(seen)]
