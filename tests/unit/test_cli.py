"""train.py CLI tests: preset resolution + end-to-end smoke on synthetic data.

The reference's only "test" was that the job ran and loss went down
(SURVEY.md §4); here that becomes an actual CI check driving the full CLI
surface — pipeline, SPMD loop, eval — on the 8-device CPU mesh.
"""

import os
import sys

import pytest

# repo root (train.py lives there), derived from this file's location
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, _REPO_ROOT)

from train import PRESETS, default_buckets, parse_args  # noqa: E402


class TestParseArgs:
    def test_presets_cover_all_baseline_configs(self):
        assert set(PRESETS) == {"cpu-inference", "coco-mini", "dp8", "pod", "eval"}

    def test_preset_applies_defaults(self):
        args = parse_args(["--preset", "dp8", "synthetic"])
        assert args.num_devices == 8
        assert args.batch_size == 16

    def test_explicit_flag_beats_preset(self):
        args = parse_args(
            ["--preset", "dp8", "synthetic", "--batch-size", "4"]
        )
        assert args.batch_size == 4
        assert args.num_devices == 8

    def test_plateau_schedule_flags(self):
        args = parse_args(
            ["synthetic", "--schedule", "plateau", "--plateau-factor", "0.5",
             "--plateau-patience", "3", "--plateau-window", "50"]
        )
        assert args.schedule == "plateau"
        assert args.plateau_factor == 0.5
        assert args.plateau_patience == 3
        assert args.plateau_window == 50

    def test_coco_paths(self):
        args = parse_args(["coco", "/data/coco"])
        assert args.coco_path == "/data/coco"
        assert args.train_annotations.endswith("instances_train2017.json")

    def test_pascal_paths(self):
        args = parse_args(
            ["pascal", "/data/VOC2007", "--train-split", "train",
             "--weighted-average"]
        )
        assert args.pascal_path == "/data/VOC2007"
        assert args.train_split == "train"
        assert args.val_split == "test"
        assert args.weighted_average is True
        assert args.skip_difficult is False

    def test_csv_paths(self):
        args = parse_args(
            ["csv", "/data/ann.csv", "/data/classes.csv",
             "--val-csv-annotations", "/data/val.csv"]
        )
        assert args.csv_annotations == "/data/ann.csv"
        assert args.csv_classes == "/data/classes.csv"
        assert args.val_csv_annotations == "/data/val.csv"
        assert args.image_dir is None

    def test_anchor_flags(self):
        args = parse_args(
            ["synthetic", "--anchor-sizes", "16,32,64,128,256",
             "--anchor-ratios", "0.5,1,2", "--anchor-scales", "1,1.5"]
        )
        from train import make_anchor_config

        cfg = make_anchor_config(args)
        assert cfg.sizes == (16, 32, 64, 128, 256)
        assert cfg.ratios == (0.5, 1.0, 2.0)
        assert cfg.scales == (1.0, 1.5)
        assert cfg.num_anchors_per_location == 6
        assert cfg.strides == (8, 16, 32, 64, 128)  # default kept

    def test_anchor_sizes_wrong_arity_rejected(self):
        from train import make_anchor_config

        args = parse_args(["synthetic", "--anchor-sizes", "32,64"])
        with pytest.raises(SystemExit):
            make_anchor_config(args)

    def test_anchor_config_persistence_and_conflict(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_anchor_config,
            resolve_anchor_config,
            save_anchor_config,
        )

        args = parse_args(["synthetic", "--anchor-scales", "1,1.5"])
        cfg = make_anchor_config(args)
        save_anchor_config(str(tmp_path), cfg)
        # No flags: the config persisted beside the checkpoint is used.
        assert resolve_anchor_config(parse_args(["synthetic"]), str(tmp_path)) == cfg
        # Matching flags: fine.
        assert resolve_anchor_config(args, str(tmp_path)) == cfg
        # Conflicting flags: abort, never silently decode with wrong anchors.
        bad = parse_args(["synthetic", "--anchor-scales", "1,2"])
        with pytest.raises(SystemExit, match="conflict"):
            resolve_anchor_config(bad, str(tmp_path))

    def test_no_resume_ignores_stale_anchor_sidecar(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.ops.anchors import AnchorConfig
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_anchor_config,
            resolve_anchor_config,
            save_anchor_config,
        )

        old = make_anchor_config(
            parse_args(["synthetic", "--anchor-scales", "1,1.5"])
        )
        save_anchor_config(str(tmp_path), old)
        # A deliberately fresh run (--no-resume) must NOT adopt the stale
        # sidecar: defaults (or new flags) win.
        fresh = resolve_anchor_config(
            parse_args(["synthetic"]), str(tmp_path), fresh=True
        )
        assert fresh == AnchorConfig()

    def test_fractional_anchor_strides_rejected(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import make_anchor_config

        args = parse_args(["synthetic", "--anchor-strides", "8.5,16,32,64,128"])
        with pytest.raises(SystemExit, match="whole"):
            make_anchor_config(args)

    def test_batch_not_divisible_rejected(self, tmp_path):
        from train import main

        with pytest.raises(SystemExit):
            main(
                ["synthetic", "--num-devices", "8", "--batch-size", "3",
                 "--synthetic-root", str(tmp_path)]
            )


class TestBuckets:
    def test_flagship_buckets(self):
        # Two buckets since round 5: the former (1088, 1088) mid bucket
        # is provably unreachable (tests/unit/test_buckets.py).
        b = default_buckets(800, 1333)
        assert b == ((800, 1344), (1344, 800))

    def test_square(self):
        assert default_buckets(64, 64) == ((64, 64),)


@pytest.mark.slow
class TestEndToEnd:
    def test_synthetic_train_and_eval(self, tmp_path):
        """Full CLI run: 8-device DP train on synthetic data, then eval."""
        from train import main

        common = [
            "synthetic",
            "--synthetic-root", str(tmp_path / "data"),
            "--synthetic-images", "8",
            "--synthetic-size", "64",
            "--image-min-side", "64", "--image-max-side", "64",
            "--backbone", "resnet_test", "--f32",
            "--batch-size", "8", "--num-devices", "8",
            "--max-gt", "8", "--workers", "2",
            "--snapshot-path", str(tmp_path / "ckpt"),
        ]
        out = main(
            common + ["--steps", "3", "--log-every", "1",
                      "--checkpoint-every", "1", "--log-dir", str(tmp_path / "logs")]
        )
        assert out["final_step"] == 3

        # Resume: total 5 steps picks up from the step-3 checkpoint.
        out = main(common + ["--steps", "5", "--log-every", "1"])
        assert out["final_step"] == 5

        # Eval-only from the snapshot (preset name = BASELINE configs[4]).
        metrics = main(common + ["--preset", "eval"])
        assert "AP" in metrics or "mAP" in metrics

    def test_spatial_shards_train(self, tmp_path):
        """--spatial-shards 2 trains through the CLI on a 4x2 data x space
        mesh (the GSPMD image-H sharding path, train/loop wiring)."""
        from train import main

        out = main([
            "synthetic",
            "--synthetic-root", str(tmp_path / "data"),
            "--synthetic-images", "8",
            "--synthetic-size", "64",
            "--image-min-side", "64", "--image-max-side", "64",
            "--backbone", "resnet_test", "--f32",
            "--batch-size", "4", "--num-devices", "8",
            "--spatial-shards", "2",
            "--max-gt", "8", "--workers", "2",
            "--steps", "2", "--log-every", "1",
        ])
        assert out["final_step"] == 2

    def test_spatial_shards_validation(self, tmp_path):
        from train import main

        with pytest.raises(SystemExit, match="divide"):
            main(["synthetic", "--num-devices", "8", "--spatial-shards", "3",
                  "--synthetic-root", str(tmp_path)])
        with pytest.raises(SystemExit, match="exclusive"):
            main(["synthetic", "--num-devices", "8", "--spatial-shards", "2",
                  "--shard-weight-update",
                  "--synthetic-root", str(tmp_path)])

    def test_custom_anchor_round_trip(self, tmp_path):
        """Non-default anchors thread train -> checkpoint -> eval/detect
        without shape errors (keras-retinanet --config parity)."""
        from train import main

        common = [
            "synthetic",
            "--synthetic-root", str(tmp_path / "data"),
            "--synthetic-images", "8",
            "--synthetic-size", "64",
            "--image-min-side", "64", "--image-max-side", "64",
            "--backbone", "resnet_test", "--f32",
            "--batch-size", "8", "--num-devices", "8",
            "--max-gt", "8", "--workers", "2",
            "--snapshot-path", str(tmp_path / "ckpt"),
            # 6 anchors/location instead of 9, non-default sizes.
            "--anchor-sizes", "16,32,64,128,256",
            "--anchor-scales", "1,1.26",
        ]
        out = main(common + ["--steps", "2", "--log-every", "1",
                             "--checkpoint-every", "1"])
        assert out["final_step"] == 2
        metrics = main(common + ["--preset", "eval"])
        assert "AP" in metrics

    def test_pretrained_backbone_flow(self, tmp_path):
        """The reference recipe end-to-end: torch-format weights ->
        --pretrained-backbone import -> frozen-BN fine-tune step -> the
        CHECKPOINTED stem kernel is the imported one (one warmup-LR step
        away), proving the import was applied, not silently dropped."""
        import numpy as np
        import torch

        from batchai_retinanet_horovod_coco_tpu.models.import_weights import (
            load_state_dict,
        )
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )
        from tests.unit.test_import_weights import fake_torch_resnet50_sd
        from train import main

        sd = fake_torch_resnet50_sd(np.random.default_rng(0))
        torch.save(
            {k: torch.from_numpy(v) for k, v in sd.items()},
            tmp_path / "r50.pth",
        )
        np.savez(tmp_path / "r50.npz", **sd)
        # Both file formats feed the same converter; assert equality once
        # instead of paying a second full-width CLI run for the npz branch.
        pth, npz = (
            load_state_dict(str(tmp_path / f"r50.{ext}"))
            for ext in ("pth", "npz")
        )
        assert set(pth) == set(npz)
        for k in pth:
            np.testing.assert_array_equal(pth[k], npz[k])

        out = main(
            ["synthetic",
             "--synthetic-root", str(tmp_path / "data"),
             "--synthetic-images", "2", "--synthetic-size", "64",
             "--image-min-side", "64", "--image-max-side", "64",
             "--backbone", "resnet50", "--norm", "frozen_bn", "--f32",
             "--batch-size", "2", "--num-devices", "1",
             "--max-gt", "8", "--workers", "2",
             "--steps", "1", "--log-every", "1",
             "--snapshot-path", str(tmp_path / "ckpt"),
             "--checkpoint-every", "1",
             "--pretrained-backbone", str(tmp_path / "r50.pth")]
        )
        assert out["final_step"] == 1
        saved = CheckpointManager(str(tmp_path / "ckpt")).restore_arrays()
        stem = np.asarray(
            saved["params"]["backbone"]["stem_conv"]["kernel"]
        )
        imported = np.transpose(sd["conv1.weight"], (2, 3, 1, 0))
        # Step-1 warmup LR is ~1e-7 of base: the update is below f32
        # resolution, so the checkpointed kernel equals the import — which
        # is exactly the claim (a dropped import would leave random init,
        # off by O(1)).
        np.testing.assert_allclose(stem, imported, atol=1e-3)

    def test_csv_train(self, tmp_path):
        """CLI run on a keras-retinanet-format CSV dataset."""
        import numpy as np
        from PIL import Image

        from train import main

        rng = np.random.default_rng(0)
        for name in ("a.jpg", "b.jpg", "c.jpg", "d.jpg"):
            Image.fromarray(
                rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
            ).save(tmp_path / name)
        (tmp_path / "classes.csv").write_text("thing,0\n")
        # d.jpg is an explicit negative (background-only) image — the
        # reference CSVGenerator trains on those, and so does this path.
        (tmp_path / "ann.csv").write_text(
            "".join(f"{n},4,4,40,40,thing\n" for n in ("a.jpg", "b.jpg",
                                                       "c.jpg"))
            + "d.jpg,,,,,\n"
        )
        out = main(
            ["csv", str(tmp_path / "ann.csv"), str(tmp_path / "classes.csv"),
             "--image-min-side", "64", "--image-max-side", "64",
             "--backbone", "resnet_test", "--f32",
             "--batch-size", "4", "--num-devices", "1",
             "--max-gt", "8", "--workers", "2", "--steps", "2",
             "--log-every", "1"]
        )
        assert out["final_step"] == 2

    def test_coco_train_eval_resume(self, tmp_path):
        """The FLAGSHIP subcommand end-to-end (VERDICT r4 missing #2):
        real on-disk mini-COCO — instances JSON + JPEG dirs in the
        production train2017/val2017 layout — through decode → bucket →
        train → final COCO eval → checkpoint → RESUME.  Exercises the
        production composition the `csv` test cannot: sparse
        non-contiguous category ids, a crowd annotation (excluded from
        training boxes, kept as eval ignore), a negative train image
        (dropped: keep_empty=False on the train split), and a negative
        val image (kept: keep_empty=True)."""
        import json

        import numpy as np
        from PIL import Image

        from train import main

        rng = np.random.default_rng(0)
        root = tmp_path / "coco"
        (root / "annotations").mkdir(parents=True)
        for split, names in (("train2017", ["t0", "t1", "t2", "t3"]),
                             ("val2017", ["v0", "v1"])):
            (root / split).mkdir()
            for n in names:
                Image.fromarray(
                    rng.integers(0, 255, (64, 64, 3), dtype=np.uint8)
                ).save(root / split / f"{n}.jpg")

        def img(i, name):
            return {"id": i, "file_name": f"{name}.jpg",
                    "width": 64, "height": 64}

        def ann(aid, iid, cat, crowd=0):
            return {"id": aid, "image_id": iid, "category_id": cat,
                    "bbox": [4.0, 4.0, 36.0, 36.0], "area": 1296.0,
                    "iscrowd": crowd}

        # Sparse, non-contiguous category ids (7 and 3): the contiguous
        # label mapping must sort by id (3 -> 0, 7 -> 1) like pycocotools.
        cats = [{"id": 7, "name": "thing"}, {"id": 3, "name": "other"}]
        train_json = {
            "images": [img(1, "t0"), img(2, "t1"), img(3, "t2"),
                       img(4, "t3")],
            # t2 carries a normal AND a crowd annotation; t3 is a
            # negative (background-only) image.
            "annotations": [ann(1, 1, 7), ann(2, 2, 3), ann(3, 3, 7),
                            ann(4, 3, 3, crowd=1)],
            "categories": cats,
        }
        val_json = {
            "images": [img(11, "v0"), img(12, "v1")],
            # v1 is a negative val image — keep_empty must retain it.
            "annotations": [ann(11, 11, 7)],
            "categories": cats,
        }
        with open(root / "annotations" / "instances_train2017.json", "w") as f:
            json.dump(train_json, f)
        with open(root / "annotations" / "instances_val2017.json", "w") as f:
            json.dump(val_json, f)

        common = [
            "coco", str(root),
            "--image-min-side", "64", "--image-max-side", "64",
            "--backbone", "resnet_test", "--f32",
            "--batch-size", "2", "--num-devices", "1",
            "--max-gt", "8", "--workers", "2", "--log-every", "1",
            "--snapshot-path", str(tmp_path / "ckpt"),
            "--checkpoint-every", "1",
            "--log-dir", str(tmp_path / "logs"),
        ]
        out = main(common + ["--steps", "2"])
        assert out["final_step"] == 2
        # dataset_type == "coco" runs the final COCO eval unconditionally;
        # its mAP record must land in the metrics JSONL.
        with open(tmp_path / "logs" / "metrics.jsonl") as f:
            records = [json.loads(line) for line in f]
        eval_recs = [r for r in records
                     if any(k.startswith("eval/") for k in r)]
        assert eval_recs, f"no eval record in {records}"
        assert any("eval/AP" in r for r in eval_recs), eval_recs

        # Resume from the step-2 checkpoint: same snapshot path, higher
        # --steps must CONTINUE (3, 4), not restart from 0.
        out = main(common + ["--steps", "4"])
        assert out["final_step"] == 4
        with open(tmp_path / "logs" / "metrics.jsonl") as f:
            records = [json.loads(line) for line in f]
        train_steps = [r["step"] for r in records
                       if any(k.startswith("train/") for k in r)]
        assert 3 in train_steps and 4 in train_steps, train_steps
        assert sorted(
            r["step"] for r in records
            if any(k.startswith("eval/") for k in r)
        ) == [2, 4]


class TestDurabilityFlags:
    """ISSUE 11: the preemption/recovery surface parses and the resume
    helpers derive the right plan from a manifest/dump."""

    def test_flags_parse_with_defaults(self):
        args = parse_args(["synthetic"])
        assert args.resume_elastic is False
        assert args.auto_resume is False
        assert args.max_auto_resumes == 3
        assert args.inject_nan_step is None
        args = parse_args(
            ["synthetic", "--resume-elastic", "--auto-resume",
             "--max-auto-resumes", "1", "--inject-nan-step", "7"]
        )
        assert args.resume_elastic and args.auto_resume
        assert (args.max_auto_resumes, args.inject_nan_step) == (1, 7)

    def test_elastic_skip_validates_manifest(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import optax
        import pytest

        from batchai_retinanet_horovod_coco_tpu.train.state import TrainState
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )
        from train import _elastic_skip_batches

        state = TrainState(
            step=jnp.asarray(40, jnp.int32),
            params={"w": jnp.ones((3,), jnp.float32)},
            batch_stats={}, opt_state=(), tx=optax.sgd(1e-2),
        )
        mgr = CheckpointManager(
            str(tmp_path), metadata={"global_batch_size": 16, "data_seed": 0}
        )
        mgr.save(state, step=40, force=True)
        mgr.close()

        args = parse_args(
            ["synthetic", "--resume-elastic", "--batch-size", "16",
             "--snapshot-path", str(tmp_path)]
        )
        plan = _elastic_skip_batches(args)
        assert plan["skip"] == 40
        assert plan["data_seed"] == 0
        assert plan["stream_base_step"] == 0
        # Changed global batch -> the position is meaningless: abort.
        args = parse_args(
            ["synthetic", "--resume-elastic", "--batch-size", "8",
             "--snapshot-path", str(tmp_path)]
        )
        with pytest.raises(SystemExit, match="global_batch_size"):
            _elastic_skip_batches(args)

    def test_auto_resume_plan_reads_poison_ids(self, tmp_path):
        import json

        from train import _auto_resume_plan

        ckpt = tmp_path / "ckpt"
        (ckpt / "ckpt-6").mkdir(parents=True)
        (ckpt / "ckpt-6" / "manifest.json").write_text(
            json.dumps({"format": "retinanet-ckpt", "version": 1,
                        "step": 6, "leaves": []})
        )
        (tmp_path / "logs").mkdir()
        (tmp_path / "logs" / "NUMERICS_DUMP.json").write_text(
            json.dumps({"batch_image_ids": [700, 701]})
        )
        args = parse_args(
            ["synthetic", "--auto-resume", "--seed", "5",
             "--snapshot-path", str(ckpt),
             "--log-dir", str(tmp_path / "logs")]
        )
        plan = _auto_resume_plan(args, 1, FloatingPointError("nan"))
        assert plan["restored_step"] == 6
        assert plan["exclude_ids"] == [700, 701]
        assert plan["data_seed"] == 5 + 7919
        # Attempt budget exhausted -> None (caller re-raises).
        assert _auto_resume_plan(args, 99, FloatingPointError("nan")) is None
        # No flag -> None.
        args = parse_args(
            ["synthetic", "--snapshot-path", str(ckpt),
             "--log-dir", str(tmp_path / "logs")]
        )
        assert _auto_resume_plan(args, 1, FloatingPointError("nan")) is None
