"""Training-loop tests: stepping, logging, checkpoint-resume mid-run.

The resume test is the §5.3 fault-recovery story: kill a run after N steps,
restart from the latest checkpoint, and the loop continues from there.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
from batchai_retinanet_horovod_coco_tpu.train import create_train_state
from batchai_retinanet_horovod_coco_tpu.train.loop import LoopConfig, run_training
from batchai_retinanet_horovod_coco_tpu.utils.metrics import MetricLogger

HW = (64, 64)
NUM_CLASSES = 3
BATCH = 8


def tiny_model():
    return build_retinanet(
        RetinaNetConfig(
            num_classes=NUM_CLASSES, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )


def fresh_state(model, seed=0):
    return create_train_state(
        model, optax.sgd(1e-3, momentum=0.9), (1, *HW, 3), jax.random.key(seed)
    )


def batch_stream(seed=0):
    # One fixed batch repeated forever: keeps the resume-parity test exact
    # (step k sees the same data in the resumed and uninterrupted runs).
    rng = np.random.default_rng(seed)
    images = rng.normal(0, 1, (BATCH, *HW, 3)).astype(np.float32)
    gt_boxes = np.tile(
        np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (BATCH, 1, 1)
    )
    while True:
        yield Batch(
            images=images,
            gt_boxes=gt_boxes,
            gt_labels=np.ones((BATCH, 1), np.int32),
            gt_mask=np.ones((BATCH, 1), bool),
            image_ids=np.arange(BATCH, dtype=np.int64),
            scales=np.ones((BATCH,), np.float32),
            valid=np.ones((BATCH,), bool),
        )


class TestRunTraining:
    def test_steps_and_jsonl_logging(self, tmp_path):
        model = tiny_model()
        logger = MetricLogger(str(tmp_path), stdout=False)
        state = run_training(
            model, fresh_state(model), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=4, log_every=2), logger=logger,
        )
        logger.close()
        assert int(state.step) == 4
        lines = [
            json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
        ]
        # The sink opens with a run_header record (ISSUE 3: run delimiter
        # for append-mode files) and may emit structured events (compile);
        # the step-metric records keep their historical shape.
        assert lines[0]["event"] == "run_header" and "run_id" in lines[0]
        metric_lines = [l for l in lines if "step" in l and "event" not in l]
        assert [l["step"] for l in metric_lines] == [2, 4]
        assert all(np.isfinite(l["train/loss"]) for l in metric_lines)
        assert all("train/images_per_sec" in l for l in metric_lines)

    def test_mesh_loop_runs(self):
        model = tiny_model()
        state = run_training(
            model, fresh_state(model), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=2, log_every=10), mesh=make_mesh(8),
        )
        assert int(state.step) == 2

    def test_eval_hook_called(self):
        calls = []

        def eval_fn(state):
            calls.append(int(state.step))
            return {"mAP": 0.0}

        model = tiny_model()
        run_training(
            model, fresh_state(model), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=4, log_every=10, eval_every=2),
            eval_fn=eval_fn,
        )
        assert calls == [2, 4]  # mid-run + final (final not duplicated)

    def test_checkpoint_resume_continues(self, tmp_path):
        model = tiny_model()
        ckpt_dir = str(tmp_path / "ckpt")
        cfg = dict(log_every=100, checkpoint_every=1, checkpoint_dir=ckpt_dir)

        # Run 1: 3 steps, then "crash".
        s1 = run_training(
            model, fresh_state(model), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=3, **cfg),
        )
        # Run 2: fresh state, resumes at 3, continues to 5.
        s2 = run_training(
            model, fresh_state(model, seed=99), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=5, **cfg),
        )
        assert int(s2.step) == 5

        # Bitwise parity: an uninterrupted 5-step run from the same init and
        # the same stream yields the resumed run's params exactly (the data
        # stream here is stateless per step, so resume sees the same batches).
        s_full = run_training(
            model, fresh_state(model), batch_stream(), NUM_CLASSES,
            LoopConfig(total_steps=5, log_every=100),
        )
        jax.tree.map(
            np.testing.assert_array_equal, s2.params, s_full.params
        )


def test_non_finite_loss_aborts_with_step_number():
    """SURVEY.md §5.2 numerical sanitizer: LR=inf poisons the params in the
    first update; the post-update param_norm sentinel catches it AT step 1
    (the step-2 loss would be the first pre-update witness) and the loop
    aborts instead of training garbage."""
    model = tiny_model()
    state = create_train_state(
        model, optax.sgd(float("inf")), (1, *HW, 3), jax.random.key(0)
    )
    with pytest.raises(FloatingPointError, match="before step 1"):
        run_training(
            model,
            state,
            batch_stream(),
            NUM_CLASSES,
            LoopConfig(total_steps=3, log_every=1),
        )


def test_non_finite_abort_fires_early_with_log_every_zero(monkeypatch):
    """log_every=0 must NOT defer the sanitizer to the final step: the loop
    checks every _FINITE_CHECK_EVERY steps regardless (shrunk here so the
    test stays cheap)."""
    from batchai_retinanet_horovod_coco_tpu.train import loop as loop_mod

    monkeypatch.setattr(loop_mod, "_FINITE_CHECK_EVERY", 2)
    model = tiny_model()
    state = create_train_state(
        model, optax.sgd(float("inf")), (1, *HW, 3), jax.random.key(0)
    )
    with pytest.raises(FloatingPointError, match="before step 2"):
        run_training(
            model,
            state,
            batch_stream(),
            NUM_CLASSES,
            LoopConfig(total_steps=50, log_every=0),
        )  # step 1 has no check (1 % 2 != 0, no save); step 2 aborts


def test_non_finite_state_never_checkpointed(tmp_path):
    """The abort runs BEFORE each checkpoint save and checks the
    POST-update param_norm, so a state poisoned by this very step's update
    never reaches disk — auto-resume can only ever see finite params
    (ADVICE r2; the pre-update loss alone would have let step 1's poisoned
    snapshot through)."""
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import latest_step

    model = tiny_model()
    state = create_train_state(
        model, optax.sgd(float("inf")), (1, *HW, 3), jax.random.key(0)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    with pytest.raises(FloatingPointError):
        run_training(
            model,
            state,
            batch_stream(),
            NUM_CLASSES,
            LoopConfig(
                total_steps=10,
                log_every=0,
                checkpoint_every=1,
                checkpoint_dir=ckpt_dir,
            ),
        )
    # Step 1's update already poisoned the params; its param_norm sentinel
    # must have aborted before ANY snapshot landed.
    assert latest_step(ckpt_dir) is None


def test_debug_nans_flag_parses():
    import os
    import sys

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    from train import parse_args

    args = parse_args(["synthetic", "--debug-nans"])
    assert args.debug_nans is True
    assert parse_args(["synthetic"]).debug_nans is False


class _RaisingLowerStep:
    """Step wrapper whose AOT ``lower`` raises — a stand-in for a genuine
    compile failure (bad sharding spec, OOM during compilation, ...)."""

    def lower(self, state, device_arrays):
        raise RuntimeError("compile exploded")

    def __call__(self, state, device_arrays):  # pragma: no cover
        raise AssertionError("step must not be dispatched")


def test_compile_barrier_propagates_compile_failure(monkeypatch):
    """A real compile error must RAISE out of _compile_barrier, not degrade
    to a warning: swallowing it defeats the barrier (healthy peers would
    time out in the step's collectives while this process dies later with
    a confusing secondary error).  Only the no-AOT-surface / no-client
    cases skip (ADVICE r3, VERDICT r3 weak #5)."""
    from batchai_retinanet_horovod_coco_tpu.train import loop as loop_mod

    monkeypatch.setattr(loop_mod.jax, "process_count", lambda: 2)
    with pytest.raises(RuntimeError, match="compile exploded"):
        loop_mod._compile_barrier(_RaisingLowerStep(), None, None, (64, 64))


def test_compile_barrier_skips_without_aot_surface(monkeypatch):
    """A plain callable without ``lower`` (no AOT surface) skips silently."""
    from batchai_retinanet_horovod_coco_tpu.train import loop as loop_mod

    monkeypatch.setattr(loop_mod.jax, "process_count", lambda: 2)
    loop_mod._compile_barrier(lambda s, d: (s, {}), None, None, (64, 64))


def test_compile_barrier_noop_single_process():
    """Single-process runs never touch the AOT surface or the client."""
    from batchai_retinanet_horovod_coco_tpu.train import loop as loop_mod

    assert jax.process_count() == 1
    loop_mod._compile_barrier(_RaisingLowerStep(), None, None, (64, 64))


def test_mixed_bucket_stream_compiles_per_shape():
    """The multiscale pipeline emits MULTIPLE (H, W) buckets in one run;
    the loop must compile one step per bucket and keep training across
    alternating shapes (SURVEY.md §7.3 hard part 1).  No prior test
    streamed more than one bucket through run_training."""
    model = tiny_model()
    state = fresh_state(model)

    shapes = [(64, 64), (64, 96)]

    def stream():
        rng = np.random.default_rng(0)
        i = 0
        while True:
            h, w = shapes[i % len(shapes)]
            i += 1
            yield Batch(
                images=rng.normal(0, 1, (2, h, w, 3)).astype(np.float32),
                gt_boxes=np.tile(
                    np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (2, 1, 1)
                ),
                gt_labels=np.ones((2, 1), np.int32),
                gt_mask=np.ones((2, 1), bool),
                image_ids=np.arange(2, dtype=np.int64),
                scales=np.ones((2,), np.float32),
                valid=np.ones((2,), bool),
            )

    class CapturingLogger:
        def __init__(self):
            self.records = []

        def log(self, step, metrics, prefix="train"):
            self.records.append((step, prefix, dict(metrics)))

    logger = CapturingLogger()
    out = run_training(
        model, state, stream(), NUM_CLASSES,
        LoopConfig(total_steps=4, log_every=1), logger=logger,
    )
    assert int(out.step) == 4
    # Both buckets trained (each shape ran twice) and stayed finite.
    train_recs = [r for r in logger.records if r[1] == "train"]
    assert len(train_recs) == 4
    assert all(np.isfinite(float(r[2]["loss"])) for r in train_recs)
