"""MobileNetV1 / VGG backbone contracts (keras-retinanet M2 siblings).

Every backbone must expose {"c3", "c4", "c5"} at strides 8/16/32 — the FPN
input contract — and assemble into a trainable RetinaNet.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.models.densenet import (
    DENSENET_STAGES,
    DenseNet,
)
from batchai_retinanet_horovod_coco_tpu.models.mobilenet import MobileNetV1
from batchai_retinanet_horovod_coco_tpu.models.vgg import vgg16, vgg19

HW = (64, 64)


@pytest.mark.parametrize(
    "factory, c_channels",
    [
        (lambda: MobileNetV1(alpha=1.0, dtype=jnp.float32), (256, 512, 1024)),
        (lambda: MobileNetV1(alpha=0.5, dtype=jnp.float32), (128, 256, 512)),
        (lambda: vgg16(dtype=jnp.float32), (256, 512, 512)),
        (lambda: vgg19(dtype=jnp.float32), (256, 512, 512)),
        # DenseNets build ~hundreds of concat/conv layers: 55 s / 28 s of
        # CPU compile each (round-4 timing report) for a shape contract
        # the other families already exercise — slow tier.
        pytest.param(
            lambda: DenseNet(
                stage_sizes=DENSENET_STAGES["densenet121"], dtype=jnp.float32
            ),
            (512, 1024, 1024),
            marks=pytest.mark.slow,
        ),
        pytest.param(
            lambda: DenseNet(
                stage_sizes=DENSENET_STAGES["densenet169"], dtype=jnp.float32
            ),
            (512, 1280, 1664),
            marks=pytest.mark.slow,
        ),
    ],
    ids=["mobilenet", "mobilenet-0.5", "vgg16", "vgg19", "densenet121",
         "densenet169"],
)
def test_feature_strides_and_channels(factory, c_channels):
    model = factory()
    x = jnp.zeros((1, *HW, 3), jnp.float32)
    variables = model.init(jax.random.key(0), x)
    feats = model.apply(variables, x)
    assert set(feats) == {"c3", "c4", "c5"}
    for level, ch in zip((3, 4, 5), c_channels):
        f = feats[f"c{level}"]
        stride = 2**level
        assert f.shape == (1, HW[0] // stride, HW[1] // stride, ch), (
            f"c{level}"
        )


@pytest.mark.parametrize(
    "backbone",
    [
        # One family proves assembly+grad in the fast tier; mobilenet's
        # ~43 s and densenet's ~40 s per-session compiles ride in slow
        # (post-cache-loss recalibration — mobilenet's shape contract
        # stays fast via test_feature_strides_and_channels[mobilenet]).
        pytest.param("mobilenet", marks=pytest.mark.slow),
        "vgg16",
        pytest.param("densenet121", marks=pytest.mark.slow),
    ],
)
def test_retinanet_assembly_and_grad(backbone):
    """Backbone plugs into the full model and gradients flow."""
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone=backbone, fpn_channels=32,
            head_width=32, head_depth=1, dtype=jnp.float32,
        )
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(0, 1, (1, *HW, 3)), jnp.float32
    )
    variables = jax.jit(model.init)(jax.random.key(0), x)
    out = jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, x)
    a_total = out["cls_logits"].shape[1]
    assert out["box_deltas"].shape == (1, a_total, 4)

    def loss(params):
        o = model.apply(dict(variables, params=params), x, train=True)
        return jnp.mean(o["cls_logits"] ** 2) + jnp.mean(o["box_deltas"] ** 2)

    g = jax.jit(jax.grad(loss))(variables["params"])
    norm = float(
        jnp.sqrt(sum(jnp.sum(t**2) for t in jax.tree.leaves(g)))
    )
    assert np.isfinite(norm) and norm > 0


from batchai_retinanet_horovod_coco_tpu.models.retinanet import BACKBONES


@pytest.mark.parametrize("backbone_name", BACKBONES)
def test_every_registered_backbone_builds(backbone_name):
    """Registry contract for ALL entries (incl. resnet101/152, densenet201,
    which no other test touches): the assembled RetinaNet must produce
    cls/box outputs over exactly the anchor count the anchor machinery
    derives for the input shape.  eval_shape only — no weights, no device
    compute — a few seconds of host tracing per deep variant."""
    from batchai_retinanet_horovod_coco_tpu.ops.anchors import AnchorConfig

    a_total = AnchorConfig().num_anchors(HW)
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=3, backbone=backbone_name, fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )
    x = jnp.zeros((1, *HW, 3), jnp.float32)
    variables = jax.eval_shape(model.init, jax.random.key(0), x)
    out = jax.eval_shape(lambda v: model.apply(v, x, train=False), variables)
    assert out["cls_logits"].shape == (1, a_total, 3)
    assert out["box_deltas"].shape == (1, a_total, 4)
