"""External validation of the COCO mAP oracle (SURVEY.md §7.3 hard part 4).

evaluate/coco_eval.py and native/cocoeval.cpp are validated against each
other elsewhere (tests/unit/test_coco_eval.py, test_native_cocoeval.py), but
both share one author and one reading of the COCOeval contract.  This module
breaks that circularity two ways:

1. **Analytic fixtures** — scenes small enough that the 101-point-interpolated
   AP is derived by hand (exact fractions in the comments), covering the
   contract's edges: score ties under stable sort, crowd rematch, gt and det
   area-range boundaries (exactly 32² and 96²), maxDets truncation, images
   with no gt (pure false positives), duplicate detections on one gt, and a
   recall landing exactly on a sampled threshold (the searchsorted
   side="left" edge — side="right" shifts AP from 51/101 to 50/101 and every
   test in TestInterpolationEdge fails).

2. **A brute-force independent implementation** — pure-Python, per-detection
   loops, no IoU caching, no vectorized envelope: precision at recall r is
   literally max(precision at any curve point with recall ≥ r).  Random
   scenes (ties, crowds, ignores, off-area boxes) must match the package
   oracle on all 12 stats exactly.

Nothing here imports oracle internals — only the public
``evaluate_detections`` / ``CocoEval`` surface under test.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    evaluate_detections,
)

# ---------------------------------------------------------------------------
# Independent brute-force COCOeval (bbox), written from the published
# contract: greedy per-image per-category matching in descending score order;
# crowd/out-of-range gts matchable but ignored; unmatched detections with
# out-of-range area ignored; 101-point interpolated AP.
# ---------------------------------------------------------------------------

IOU_THRS = [0.5 + 0.05 * i for i in range(10)]
REC_THRS = [i / 100.0 for i in range(101)]
AREA_RNG = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}
MAX_DETS = (1, 10, 100)


def _iou_xywh(d, g, crowd):
    dx, dy, dw, dh = d
    gx, gy, gw, gh = g
    iw = min(dx + dw, gx + gw) - max(dx, gx)
    ih = min(dy + dh, gy + gh) - max(dy, gy)
    if iw <= 0 or ih <= 0:
        return 0.0
    inter = iw * ih
    union = dw * dh if crowd else dw * dh + gw * gh - inter
    return inter / union if union > 0 else 0.0


def _match_image(dts, gts, thr, area_rng):
    """Greedy matching for one (image, category, IoU threshold, area range).

    dts: score-sorted list of det dicts; gts: list of gt dicts.
    Returns per-det (matched, ignored) flags and the non-ignored gt count.
    """
    lo, hi = area_rng
    ig = [
        bool(g.get("ignore", 0))
        or bool(g.get("iscrowd", 0))
        or g["area"] < lo
        or g["area"] > hi
        for g in gts
    ]
    # Non-ignored gts first, stably — the preference order of the greedy scan.
    order = sorted(range(len(gts)), key=lambda i: ig[i])
    claimed = [False] * len(gts)
    out = []
    for det in dts:
        floor = min(thr, 1.0 - 1e-10)
        # Pass 1: the best still-unclaimed NON-ignored gt with IoU ≥ thr;
        # equal IoU prefers the later gt in preference order (the reference
        # scan overwrites on ties).
        pick = -1
        best = floor
        for gi in order:
            if ig[gi] or claimed[gi]:
                continue
            iou = _iou_xywh(det["bbox"], gts[gi]["bbox"], False)
            if iou >= best:
                best = iou
                pick = gi
        if pick < 0:
            # Pass 2: ignored gts (crowds rematchable even when claimed).
            best = floor
            for gi in order:
                if not ig[gi]:
                    continue
                crowd = bool(gts[gi].get("iscrowd", 0))
                if claimed[gi] and not crowd:
                    continue
                iou = _iou_xywh(det["bbox"], gts[gi]["bbox"], crowd)
                if iou >= best:
                    best = iou
                    pick = gi
        if pick >= 0:
            claimed[pick] = True
            out.append((True, ig[pick]))
        else:
            w, h = det["bbox"][2], det["bbox"][3]
            area = w * h
            out.append((False, area < lo or area > hi))
    return out, sum(1 for f in ig if not f)


def brute_force_stats(gt_anns, dt_anns, img_ids=None):
    """The 12 COCO stats, computed the slow transparent way."""
    if img_ids is None:
        img_ids = sorted(
            {a["image_id"] for a in gt_anns} | {a["image_id"] for a in dt_anns}
        )
    cat_ids = sorted(
        {a["category_id"] for a in gt_anns} | {a["category_id"] for a in dt_anns}
    )
    gts = {
        (i, c): [a for a in gt_anns if a["image_id"] == i and a["category_id"] == c]
        for i in img_ids
        for c in cat_ids
    }
    dts = {
        (i, c): sorted(
            (a for a in dt_anns if a["image_id"] == i and a["category_id"] == c),
            key=lambda a: -a["score"],
        )[: MAX_DETS[-1]]
        for i in img_ids
        for c in cat_ids
    }

    # curves[(area, maxdet)][(thr, cat)] = (ap, final_recall) or None
    curves = {}
    for area_lbl, area_rng in AREA_RNG.items():
        for max_det in MAX_DETS:
            for cat in cat_ids:
                imgs = [
                    i for i in img_ids if gts[(i, cat)] or dts[(i, cat)]
                ]
                for thr in IOU_THRS:
                    entries = []  # (score, pos, matched, ignored)
                    npig = 0
                    for pos, img in enumerate(imgs):
                        flags, n = _match_image(
                            dts[(img, cat)][:max_det],
                            gts[(img, cat)],
                            thr,
                            area_rng,
                        )
                        npig += n
                        for j, (matched, ignored) in enumerate(flags):
                            entries.append(
                                (dts[(img, cat)][j]["score"], pos, j, matched, ignored)
                            )
                    if not imgs or npig == 0:
                        curves[(area_lbl, max_det, thr, cat)] = None
                        continue
                    # Global stable sort: descending score, image order, then
                    # per-image score order as tie-breaks.
                    entries.sort(key=lambda e: (-e[0], e[1], e[2]))
                    tp = fp = 0
                    points = []  # (recall, precision)
                    for _, _, _, matched, ignored in entries:
                        if not ignored:
                            tp += matched
                            fp += not matched
                        denom = tp + fp
                        points.append(
                            (tp / npig, tp / denom if denom else 0.0)
                        )
                    sampled = []
                    for r in REC_THRS:
                        qs = [p for rc, p in points if rc >= r]
                        sampled.append(max(qs) if qs else 0.0)
                    final_recall = points[-1][0] if points else 0.0
                    curves[(area_lbl, max_det, thr, cat)] = (
                        sum(sampled) / len(sampled),
                        final_recall,
                    )

    def mean_ap(area, max_det, thrs):
        vals = [
            curves[(area, max_det, t, c)][0]
            for t in thrs
            for c in cat_ids
            if curves[(area, max_det, t, c)] is not None
        ]
        return sum(vals) / len(vals) if vals else -1.0

    def mean_ar(area, max_det):
        vals = [
            curves[(area, max_det, t, c)][1]
            for t in IOU_THRS
            for c in cat_ids
            if curves[(area, max_det, t, c)] is not None
        ]
        return sum(vals) / len(vals) if vals else -1.0

    return {
        "AP": mean_ap("all", 100, IOU_THRS),
        "AP50": mean_ap("all", 100, [IOU_THRS[0]]),
        "AP75": mean_ap("all", 100, [IOU_THRS[5]]),
        "APsmall": mean_ap("small", 100, IOU_THRS),
        "APmedium": mean_ap("medium", 100, IOU_THRS),
        "APlarge": mean_ap("large", 100, IOU_THRS),
        "AR1": mean_ar("all", 1),
        "AR10": mean_ar("all", 10),
        "AR100": mean_ar("all", 100),
        "ARsmall": mean_ar("small", 100),
        "ARmedium": mean_ar("medium", 100),
        "ARlarge": mean_ar("large", 100),
    }


# ---------------------------------------------------------------------------
# Fixture helpers
# ---------------------------------------------------------------------------

_next_id = [1]


def g(img, bbox, cat=1, area=None, iscrowd=0, ignore=0):
    _next_id[0] += 1
    return {
        "id": _next_id[0],
        "image_id": img,
        "category_id": cat,
        "bbox": list(map(float, bbox)),
        "area": float(bbox[2] * bbox[3] if area is None else area),
        "iscrowd": iscrowd,
        "ignore": ignore,
    }


def d(img, bbox, score, cat=1):
    return {
        "image_id": img,
        "category_id": cat,
        "bbox": list(map(float, bbox)),
        "score": float(score),
    }


def both(gt, dt, **kw):
    """Run the package oracle and the brute force; they must agree exactly."""
    ours = evaluate_detections(gt, dt, **kw)
    ref = brute_force_stats(gt, dt, **kw)
    for name, val in ref.items():
        np.testing.assert_allclose(
            ours[name], val, atol=1e-12, err_msg=f"stat {name}"
        )
    return ours


# ---------------------------------------------------------------------------
# Analytic fixtures (expected values derived by hand in the comments)
# ---------------------------------------------------------------------------


class TestAnalyticFixtures:
    def test_perfect_detection(self):
        m = both([g(1, (0, 0, 10, 10))], [d(1, (0, 0, 10, 10), 0.9)])
        assert m["AP"] == 1.0 and m["AP50"] == 1.0 and m["AR100"] == 1.0

    def test_iou_exactly_at_threshold(self):
        # IoU(det, gt) = 100/200 = 0.5 exactly: matched at t=0.50 only
        # (the matcher floor is min(t, 1-1e-10), inclusive), so
        # AP = (1 + 9*0)/10 = 0.1 and AP50 = 1, AP75 = 0.
        m = both([g(1, (0, 0, 10, 10))], [d(1, (0, 0, 10, 20), 0.9)])
        np.testing.assert_allclose(m["AP"], 0.1, atol=1e-12)
        assert m["AP50"] == 1.0 and m["AP75"] == 0.0
        np.testing.assert_allclose(m["APsmall"], 0.1, atol=1e-12)
        assert m["APmedium"] == -1.0  # gt (area 100) out of range → no gt

    def test_score_tie_keeps_insertion_order(self):
        # FP then TP at the SAME score: the stable sort keeps insertion
        # order, so the curve is [p=0, r=0], [p=.5, r=1] → envelope 0.5
        # everywhere → AP = 0.5.  An unstable sort that flips the pair
        # would give AP = 1.0.
        gt = [g(1, (0, 0, 10, 10))]
        dt = [d(1, (50, 50, 10, 10), 0.5), d(1, (0, 0, 10, 10), 0.5)]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 0.5, atol=1e-12)
        assert m["AR100"] == 1.0

    def test_crowd_rematch_and_ignore(self):
        # Two dets inside one crowd region (both must match it — crowds are
        # rematchable — and be ignored), plus one real TP at a LOWER score.
        # Correct: AP = 1.  Crowd-as-FP would give 1/3; no-rematch (second
        # crowd det becomes FP) would give 0.5.
        gt = [g(1, (0, 0, 30, 30), iscrowd=1), g(1, (50, 50, 10, 10))]
        dt = [
            d(1, (0, 0, 10, 10), 0.9),
            d(1, (12, 0, 10, 10), 0.8),
            d(1, (50, 50, 10, 10), 0.7),
        ]
        m = both(gt, dt)
        assert m["AP"] == 1.0 and m["APsmall"] == 1.0

    def test_explicit_ignore_flag(self):
        # An ignore-flagged gt is matchable but contributes no npig: the det
        # on it is neither TP nor FP, and the remaining TP gives AP = 1.
        gt = [g(1, (0, 0, 10, 10), ignore=1), g(1, (30, 30, 10, 10))]
        dt = [d(1, (0, 0, 10, 10), 0.9), d(1, (30, 30, 10, 10), 0.8)]
        m = both(gt, dt)
        assert m["AP"] == 1.0

    def test_gt_area_boundary_inclusive_both_sides(self):
        # gt area exactly 32² = 1024 sits in BOTH small [0,1024] and
        # medium [1024,9216] (the range test is lo ≤ area ≤ hi).
        m = both([g(1, (0, 0, 32, 32))], [d(1, (0, 0, 32, 32), 0.9)])
        assert m["APsmall"] == 1.0
        assert m["APmedium"] == 1.0
        assert m["APlarge"] == -1.0

    def test_det_area_boundary_counts_as_fp(self):
        # Unmatched det with area exactly 96² = 9216 is INSIDE the large
        # range [9216,1e10] → a real FP ahead of the TP → APlarge = 0.5.
        # If the boundary were exclusive the det would be ignored and
        # APlarge would be 1.0.
        gt = [g(1, (0, 0, 150, 150))]
        dt = [d(1, (300, 300, 96, 96), 0.9), d(1, (0, 0, 150, 150), 0.5)]
        m = both(gt, dt)
        np.testing.assert_allclose(m["APlarge"], 0.5, atol=1e-12)
        np.testing.assert_allclose(m["AP"], 0.5, atol=1e-12)
        assert m["APmedium"] == -1.0  # gt out of medium range

    def test_max_dets_truncation(self):
        # 3 gts, 3 perfect dets: AR1 sees only the top-scored det → 1/3;
        # AR10/AR100 see all → 1.
        gt = [g(1, (x, 0, 10, 10)) for x in (0, 20, 40)]
        dt = [
            d(1, (0, 0, 10, 10), 0.9),
            d(1, (20, 0, 10, 10), 0.8),
            d(1, (40, 0, 10, 10), 0.7),
        ]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AR1"], 1 / 3, atol=1e-12)
        assert m["AR10"] == 1.0 and m["AR100"] == 1.0 and m["AP"] == 1.0

    def test_image_with_no_gt_contributes_fps(self):
        # The higher-scored det on a gt-less image is a real FP ahead of
        # the TP → AP = 0.5.  Dropping no-gt images would report 1.0.
        gt = [g(1, (0, 0, 10, 10))]
        dt = [d(2, (0, 0, 10, 10), 0.95), d(1, (0, 0, 10, 10), 0.9)]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 0.5, atol=1e-12)

    def test_duplicate_detections_one_gt(self):
        # d1 TP on A (r=.5, p=1), d2 duplicate on A → FP (r=.5, p=.5),
        # d3 TP on B (r=1, p=2/3).  Envelope [1, 2/3, 2/3]; sampling gives
        # 51 points at 1 (r ≤ .5) and 50 at 2/3 → AP = 253/303.
        gt = [g(1, (0, 0, 10, 10)), g(1, (20, 0, 10, 10))]
        dt = [
            d(1, (0, 0, 10, 10), 0.9),
            d(1, (0, 1, 10, 10), 0.8),
            d(1, (20, 0, 10, 10), 0.7),
        ]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 253 / 303, atol=1e-12)
        np.testing.assert_allclose(m["AR1"], 0.5, atol=1e-12)


class TestInterpolationEdge:
    """Recall landing EXACTLY on a sampled threshold (searchsorted side)."""

    def test_recall_exactly_half(self):
        # 2 gts, 1 TP: the curve's only point is (r=0.5, p=1).  Recall
        # threshold 0.50 must sample it (side="left" semantics): 51 of the
        # 101 points (0.00..0.50) get precision 1 → AP = 51/101.  A
        # side="right" implementation samples 50 → 50/101.
        gt = [g(1, (0, 0, 10, 10)), g(1, (30, 30, 10, 10))]
        dt = [d(1, (0, 0, 10, 10), 0.9)]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 51 / 101, atol=1e-12)
        np.testing.assert_allclose(m["AR100"], 0.5, atol=1e-12)

    def test_recall_exactly_quarter(self):
        # 4 gts, 1 TP: point (r=0.25, p=1) → 26 points at 1 → AP = 26/101.
        gt = [g(1, (x, y, 10, 10)) for x in (0, 30) for y in (0, 30)]
        dt = [d(1, (0, 0, 10, 10), 0.9)]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 26 / 101, atol=1e-12)

    def test_every_fifth_threshold(self):
        # 5 gts, 3 TPs with descending scores: points (0.2,1),(0.4,1),(0.6,1)
        # → r ≤ 0.6 samples 1 → AP = 61/101.
        gt = [g(1, (30 * i, 0, 10, 10)) for i in range(5)]
        dt = [
            d(1, (0, 0, 10, 10), 0.9),
            d(1, (30, 0, 10, 10), 0.8),
            d(1, (60, 0, 10, 10), 0.7),
        ]
        m = both(gt, dt)
        np.testing.assert_allclose(m["AP"], 61 / 101, atol=1e-12)


# ---------------------------------------------------------------------------
# Brute-force property test on random scenes
# ---------------------------------------------------------------------------


def random_scene(seed):
    rng = np.random.default_rng(seed)
    n_imgs = int(rng.integers(1, 5))
    n_cats = int(rng.integers(1, 4))
    gts, dts = [], []
    for img in range(1, n_imgs + 1):
        for cat in range(1, n_cats + 1):
            for _ in range(int(rng.integers(0, 5))):
                x, y = rng.uniform(0, 60, 2)
                w, h = rng.uniform(2, 60, 2)
                area = w * h if rng.random() < 0.7 else float(rng.uniform(1, 1e4))
                gts.append(
                    g(
                        img,
                        (x, y, w, h),
                        cat=cat,
                        area=area,
                        iscrowd=int(rng.random() < 0.2),
                        ignore=int(rng.random() < 0.1),
                    )
                )
            for _ in range(int(rng.integers(0, 7))):
                if gts and rng.random() < 0.5:
                    # Perturb a gt box: realistic near-matches at varied IoU.
                    base = gts[int(rng.integers(0, len(gts)))]["bbox"]
                    x, y, w, h = (
                        np.asarray(base) + rng.normal(0, 4, 4)
                    ).tolist()
                    w, h = max(w, 1.0), max(h, 1.0)
                else:
                    x, y = rng.uniform(0, 60, 2)
                    w, h = rng.uniform(2, 60, 2)
                # Coarse scores force plenty of exact ties.
                score = round(float(rng.uniform(0.05, 1.0)), 1)
                dts.append(d(img, (x, y, w, h), score, cat=cat))
    return gts, dts


@pytest.mark.parametrize("seed", range(25))
def test_random_scenes_match_brute_force(seed):
    gts, dts = random_scene(seed)
    if not gts and not dts:
        pytest.skip("empty scene")
    both(gts, dts)


def test_many_detections_beyond_maxdets():
    # 150 dets in one (image, category): only the top-100 by score may
    # count — truncation happens before matching, not after.
    rng = np.random.default_rng(7)
    gts = [g(1, (20 * i, 0, 15, 15)) for i in range(6)]
    dts = []
    for i in range(150):
        x = float(rng.uniform(0, 120))
        dts.append(d(1, (x, rng.uniform(0, 30), 15, 15), float(rng.uniform(0, 1))))
    both(gts, dts)
