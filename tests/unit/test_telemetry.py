"""Live telemetry plane tests (ISSUE 9, obs/telemetry.py + obs/slo.py).

The satellite checklist, pinned:

- registry concurrency (parallel inc/observe lose nothing),
- exposition-format golden (byte-for-byte Prometheus text) + the
  parse_exposition round-trip,
- /healthz flips 503 naming the component on an injected watchdog stall
  (unit probe AND through the serve HTTP frontend),
- the train status server starts, serves, and drains cleanly
  (bounded, idempotent close; socket actually released),
- an SLO rule fires EXACTLY ONCE per sustained breach (no flapping),
  re-arms only after clear_s of health, regression + delta modes,
- disabled-path overhead: record sites are one bool check — structurally
  a no-op (no state mutated) while telemetry is off,
- obs/analyze ingests slo_violation events: violations section + the
  slo:* verdict ranked above inferred bottlenecks.

Stub-engine serve tests only (no jax compile in the loop) — the real
end-to-end scrape runs in scripts/telemetry_smoke.py (make
telemetry-smoke) and bench.py --mode serve's consistency check.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry, trace, watchdog
from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
    Registry,
    StatusServer,
    healthz,
    parse_exposition,
)


@pytest.fixture(autouse=True)
def _telemetry_state():
    """Every test starts and ends with the push gate off and a fresh
    default registry (module-global state, like the trace tests)."""
    telemetry.reset()
    trace.reset()
    yield
    telemetry.reset()
    trace.reset()


def _get(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---- registry ------------------------------------------------------------


class TestRegistry:
    def test_concurrent_increments_lose_nothing(self):
        telemetry.enable()
        reg = Registry()
        c = reg.counter("requests_total")
        h = reg.histogram("latency_ms", window=100_000)
        n_threads, per_thread = 8, 2000
        errors: list[BaseException] = []

        def work():
            try:
                for _ in range(per_thread):
                    c.inc()
                    c.inc(reason="shed")
                    h.observe(1.0)
            except BaseException as e:  # surfaced after the join
                errors.append(e)

        # watchdog: short-lived test workers, joined 4 lines below.
        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        snap = reg.snapshot()
        assert snap["requests_total"] == n_threads * per_thread
        assert snap['requests_total{reason="shed"}'] == n_threads * per_thread
        assert snap["latency_ms.count"] == n_threads * per_thread

    def test_type_conflict_and_bad_names_raise(self):
        reg = Registry()
        reg.counter("a_total")
        with pytest.raises(ValueError):
            reg.gauge("a_total")
        with pytest.raises(ValueError):
            reg.counter("bad name")
        telemetry.enable()
        with pytest.raises(ValueError):
            reg.counter("ok_total").inc(**{"bad-label": "x"})

    def test_gauge_callback_pull_and_snapshot_aggregates(self):
        telemetry.enable()
        reg = Registry()
        reg.gauge("depth", fn=lambda: 7)
        g = reg.gauge("labeled")
        g.set(3, queue="a")
        g.set(5, queue="b")
        c = reg.counter("shed_total")
        c.inc(2, reason="x")
        c.inc(3, reason="y")
        snap = reg.snapshot()
        assert snap["depth"] == 7
        assert snap["labeled"] == 5  # gauges aggregate with max
        assert snap["shed_total"] == 5  # counters aggregate with sum

    def test_collector_callback_and_dead_collector_skipped(self):
        reg = Registry()
        reg.register_collector(
            lambda: [("x_total", "counter", "", None, 4.0)]
        )

        def dead():
            raise RuntimeError("boom")

        reg.register_collector(dead)
        assert reg.snapshot()["x_total"] == 4.0  # scrape survives


class TestDisabledOverhead:
    def test_record_sites_are_noops_while_disabled(self):
        """The acceptance bar: with telemetry off, a record site is one
        bool check — structurally, NO state may change (the timing twin
        of PR 3's shared-noop span test)."""
        assert not telemetry.enabled()
        reg = Registry()
        c = reg.counter("c_total")
        g = reg.gauge("g")
        h = reg.histogram("h_ms")
        c.inc()
        g.set(5)
        h.observe(1.0)
        assert c.samples() == []
        assert g.samples() == []
        assert h.window_ms() == []
        telemetry.record_train_window(
            step=1, images_per_s=1, step_time_ms=1, data_wait_ms=1
        )
        telemetry.record_compile("64x64", 1.0)
        # The disabled-path record sites must not even have built the
        # train metric handles on the default registry.
        assert telemetry._train_gauges is None

    def test_record_sites_feed_default_registry_when_enabled(self):
        telemetry.enable()
        telemetry.record_train_window(
            step=7, images_per_s=12.5, step_time_ms=80.0, data_wait_ms=20.0
        )
        telemetry.record_compile("64x64", 2.5)
        snap = telemetry.default().snapshot()
        assert snap["train_step"] == 7
        assert snap["train_images_per_sec"] == 12.5
        assert snap["train_data_wait_fraction"] == 0.25
        assert snap['train_compiles_total{bucket="64x64"}'] == 1
        assert snap["train_last_compile_s"] == 2.5
        # Built-in collectors ride along on the default registry.
        assert "process_uptime_seconds" in snap
        assert "watchdog_stalled" in snap


# ---- exposition ----------------------------------------------------------


EXPECTED_EXPOSITION = """\
# HELP q_depth live queue depths
# TYPE q_depth gauge
q_depth{queue="admission"} 3
q_depth{queue="bucket_64x64"} 0
# HELP req_latency_ms request latency
# TYPE req_latency_ms summary
req_latency_ms{quantile="0.5"} 2
req_latency_ms{quantile="0.9"} 80.4
req_latency_ms{quantile="0.99"} 98.04
req_latency_ms_count 3
req_latency_ms_sum 103
# HELP shed_total sheds by reason
# TYPE shed_total counter
shed_total{reason="admission_queue_full"} 2
shed_total{reason="with\\"quote"} 1
"""


def _golden_registry() -> Registry:
    reg = Registry()
    c = reg.counter("shed_total", "sheds by reason")
    c.inc(2, reason="admission_queue_full")
    c.inc(reason='with"quote')
    g = reg.gauge("q_depth", "live queue depths")
    g.set(3, queue="admission")
    g.set(0, queue="bucket_64x64")
    reg.histogram(
        "req_latency_ms", "request latency",
        source=lambda: [1.0, 2.0, 100.0],
    )
    return reg


class TestExposition:
    def test_prometheus_text_golden(self):
        telemetry.enable()
        assert _golden_registry().prometheus_text() == EXPECTED_EXPOSITION

    def test_parse_round_trip(self):
        telemetry.enable()
        reg = _golden_registry()
        types, samples = parse_exposition(reg.prometheus_text())
        assert types == {
            "shed_total": "counter",
            "q_depth": "gauge",
            "req_latency_ms": "summary",
        }
        assert samples['shed_total{reason="admission_queue_full"}'] == 2
        assert samples['q_depth{queue="admission"}'] == 3
        assert samples['req_latency_ms{quantile="0.99"}'] == 98.04
        assert samples["req_latency_ms_count"] == 3
        # parse agrees with snapshot through the other path
        snap = reg.snapshot()
        assert snap["req_latency_ms.p99"] == 98.04
        assert snap["shed_total"] == 3


# ---- healthz -------------------------------------------------------------


class TestHealthz:
    def test_flips_503_on_injected_stall_and_recovers(self):
        wd = watchdog.Watchdog(stall_after=100.0)
        code, payload = healthz(wd)
        assert code == 200 and payload["status"] == "ok"
        hb = wd.register("wedged-component", stall_after=0.01)
        hb2 = wd.register("healthy-component")
        time.sleep(0.05)
        hb2.beat()
        code, payload = healthz(wd)
        assert code == 503
        assert payload["component"] == "wedged-component"
        assert payload["stalled"][0]["stalled_for_s"] > 0.01
        assert "healthy-component" in payload["components"]
        hb.beat()  # recovery
        code, payload = healthz(wd)
        assert code == 200
        hb.close()
        hb2.close()

    def test_idle_components_never_flag(self):
        wd = watchdog.Watchdog()
        hb = wd.register("quiescent", stall_after=0.01)
        hb.idle()
        time.sleep(0.03)
        code, _payload = healthz(wd)
        assert code == 200
        hb.close()

    def test_probe_is_read_only(self):
        """stalled_components must not eat the poll thread's
        one-dump-per-stall latch."""
        wd = watchdog.Watchdog(stall_after=0.01)
        hb = wd.register("wedged")
        time.sleep(0.03)
        assert wd.stalled_components()  # the healthz probe...
        diag = wd.check_once()  # ...must not have consumed the dump
        assert diag is not None and diag["component"] == "wedged"
        hb.close()


# ---- status server (train.py --obs-port) ---------------------------------


class TestStatusServer:
    def test_serves_and_drains_cleanly(self):
        telemetry.enable()
        reg = Registry()
        reg.counter("x_total").inc(3)
        server = StatusServer(reg, port=0).start()
        base = f"http://{server.host}:{server.port}"
        code, body = _get(f"{base}/metrics")
        assert code == 200 and b"x_total 3" in body
        code, body = _get(f"{base}/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, body = _get(f"{base}/statusz")
        assert code == 200 and json.loads(body)["x_total"] == 3
        code, _body = _get(f"{base}/nope")
        assert code == 404
        # The listener is watchdog-registered while serving...
        assert any(
            n.startswith("obs-telemetry-http")
            for n in watchdog.default().components()
        )
        server.close()
        server.close()  # idempotent
        # ...unregistered after drain, and the socket is released.
        assert not any(
            n.startswith("obs-telemetry-http")
            for n in watchdog.default().components()
        )
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(f"{base}/healthz", timeout=2)

    def test_ephemeral_ports_do_not_collide(self):
        a = StatusServer(Registry(), port=0).start()
        b = StatusServer(Registry(), port=0).start()
        try:
            assert a.port != b.port
        finally:
            a.close()
            b.close()


# ---- SLO monitor ---------------------------------------------------------


class _SinkStub:
    def __init__(self):
        self.events: list[tuple[str, dict]] = []

    def event(self, kind, **fields):
        self.events.append((kind, fields))


class TestSlo:
    def _monitor(self, value_fn, rule, sink=None):
        reg = Registry()
        reg.gauge("m", fn=value_fn)
        return slo.SloMonitor(reg, [rule], sink=sink)

    def test_fires_exactly_once_per_sustained_breach(self):
        """The anti-flap pin: one event per sustained breach, re-armed
        only by clear_s of continuous health."""
        value = [100.0]
        sink = _SinkStub()
        mon = self._monitor(
            lambda: value[0],
            slo.SloRule(
                name="ceiling", metric="m", op=">", threshold=50,
                for_s=2.0, clear_s=3.0,
            ),
            sink=sink,
        )
        t = 1000.0
        assert mon.check_once(now=t) == []  # breached, not yet sustained
        assert mon.check_once(now=t + 1) == []
        fired = mon.check_once(now=t + 2.5)
        assert [v["rule"] for v in fired] == ["ceiling"]
        assert fired[0]["sustained_s"] == 2.5
        # Still breached for hours: the latch holds — NO flapping.
        for dt in (3, 10, 100, 1000):
            assert mon.check_once(now=t + dt) == []
        # Brief health below clear_s does not re-arm...
        value[0] = 1.0
        assert mon.check_once(now=t + 2000) == []
        value[0] = 100.0
        assert mon.check_once(now=t + 2001) == []  # breach_since resets
        assert mon.check_once(now=t + 2004) == []  # latch still held
        # ...but clear_s of continuous health does.
        value[0] = 1.0
        assert mon.check_once(now=t + 3000) == []
        assert mon.check_once(now=t + 3004) == []  # re-armed here
        value[0] = 100.0
        assert mon.check_once(now=t + 3005) == []
        fired = mon.check_once(now=t + 3007.5)
        assert len(fired) == 1
        assert len(sink.events) == 2  # exactly one event per breach
        assert all(k == "slo_violation" for k, _ in sink.events)
        assert mon.registry.snapshot()[
            'slo_violations_total{rule="ceiling"}'
        ] == 2

    def test_violation_reaches_sink_and_trace(self, tmp_path):
        trace.configure(str(tmp_path), process_label="test")
        sink = _SinkStub()
        mon = self._monitor(
            lambda: 9.0,
            slo.SloRule(name="r", metric="m", op=">", threshold=1.0),
            sink=sink,
        )
        assert len(mon.check_once(now=1.0)) == 1
        kind, fields = sink.events[0]
        assert kind == "slo_violation" and fields["rule"] == "r"
        instants = [
            e for e in trace.snapshot_events()
            if e.get("ph") == "i" and e.get("name") == "slo_violation"
        ]
        assert len(instants) == 1
        assert instants[0]["args"]["rule"] == "r"

    def test_missing_metric_is_not_a_breach(self):
        mon = slo.SloMonitor(
            Registry(),
            [slo.SloRule(name="r", metric="absent", op=">", threshold=0)],
        )
        assert mon.check_once(now=1.0) == []
        assert mon.check_once(now=100.0) == []

    def test_delta_rule_measures_per_poll_increase(self):
        value = [0.0]
        mon = self._monitor(
            lambda: value[0],
            slo.SloRule(
                name="shed-rate", metric="m", op=">", threshold=5,
                delta=True, clear_s=0.0,
            ),
        )
        assert mon.check_once(now=1.0) == []  # first sample: no delta yet
        value[0] = 3.0
        assert mon.check_once(now=2.0) == []  # +3 <= 5
        value[0] = 20.0
        assert len(mon.check_once(now=3.0)) == 1  # +17 > 5

    def test_regression_rule_vs_rolling_baseline(self):
        value = [100.0]
        mon = self._monitor(
            lambda: value[0],
            slo.SloRule(
                name="step-regress", metric="m", op=">",
                baseline_window=8, factor=1.5, min_baseline=3,
            ),
        )
        for i in range(5):  # build the healthy baseline
            assert mon.check_once(now=float(i)) == []
        value[0] = 300.0  # 3x the median → breach
        fired = mon.check_once(now=10.0)
        assert len(fired) == 1
        assert fired[0]["threshold"] == pytest.approx(150.0)
        # The breaching samples never poisoned their own baseline.
        assert mon.check_once(now=11.0) == []
        state = mon._states["step-regress"]
        assert max(state.baseline) == 100.0

    def test_stall_rule_and_watchdog_collector(self):
        wd = watchdog.Watchdog()
        reg = Registry()
        reg.register_collector(telemetry.watchdog_collector(wd))
        mon = slo.SloMonitor(reg, [slo.stall_rule()])
        hb = wd.register("wedge", stall_after=0.01)
        assert mon.check_once(now=1.0) == []  # not stalled yet
        time.sleep(0.03)
        fired = mon.check_once(now=2.0)
        assert [v["rule"] for v in fired] == ["watchdog-stall"]
        hb.close()

    def test_poll_thread_starts_and_stops(self):
        mon = self._monitor(
            lambda: 1.0,
            slo.SloRule(name="r", metric="m", op=">", threshold=100),
        )
        mon.poll_interval = 0.01
        mon.start()
        assert "slo-monitor" in watchdog.default().components()
        time.sleep(0.05)
        mon.stop()
        assert "slo-monitor" not in watchdog.default().components()

    def test_parse_rule_grammar(self):
        r = slo.parse_rule("serve_request_latency_ms.p99>250@30")
        assert (r.metric, r.op, r.threshold, r.for_s) == (
            "serve_request_latency_ms.p99", ">", 250.0, 30.0,
        )
        r = slo.parse_rule("train_step_time_ms>x1.5@60")
        assert r.baseline_window > 0 and r.factor == 1.5 and r.for_s == 60.0
        r = slo.parse_rule("train_data_wait_fraction>=0.5")
        assert r.op == ">=" and r.for_s == 0.0
        with pytest.raises(ValueError):
            slo.parse_rule("not a rule")
        with pytest.raises(ValueError):
            slo.SloMonitor(Registry(), [slo.stall_rule(), slo.stall_rule()])


# ---- serve frontend integration (stub engine; no jax compile) ------------


class _Det:
    def __init__(self, boxes, scores, labels, valid):
        self.boxes, self.scores, self.labels = boxes, scores, labels
        self.valid = valid


class StubEngine:
    from batchai_retinanet_horovod_coco_tpu.serve.engine import (
        IdentityLabelMap as _Ident,
    )

    min_side = 64
    max_side = 64
    buckets = ((64, 64),)
    label_to_cat_id = _Ident()

    def batch_sizes(self, hw):
        return [4]

    def max_batch(self, hw):
        return 4

    def batch_size_for(self, hw, n):
        return 4

    def warmup(self):
        pass

    def dispatch(self, hw, images):
        b = images.shape[0]
        boxes = np.tile(
            np.array([[[1.0, 2.0, 10.0, 20.0]]], np.float32), (b, 1, 1)
        )
        return _Det(
            boxes,
            np.full((b, 1), 0.5, np.float32),
            np.zeros((b, 1), np.int32),
            np.ones((b, 1), bool),
        )

    def fetch(self, det):
        return det


IMG = np.zeros((64, 64, 3), np.uint8)


class TestServeTelemetry:
    def _server(self):
        from batchai_retinanet_horovod_coco_tpu.serve import (
            DetectionServer,
            ServeConfig,
        )

        return DetectionServer(
            StubEngine(),
            ServeConfig(max_delay_ms=5.0, preprocess_workers=1),
        )

    def test_metrics_track_snapshot(self):
        with self._server() as srv:
            for _ in range(4):
                srv.submit(IMG).result(timeout=10)
            srv.stats.record_shed("test_injected")
            types, samples = parse_exposition(
                srv.telemetry.prometheus_text()
            )
            snap = srv.snapshot()
            assert types["serve_request_latency_ms"] == "summary"
            assert (
                samples["serve_requests_completed_total"]
                == snap["completed"] == 4
            )
            assert samples['serve_shed_total{reason="test_injected"}'] == 1
            assert samples['serve_queue_depth{queue="admission"}'] == 0
            assert (
                samples['serve_request_latency_ms{quantile="0.99"}']
                == snap["p99_ms"]
            )
            assert samples["serve_queue_capacity{queue=\"admission\"}"] == 128

    def test_http_metrics_healthz_and_stall_flip(self):
        from batchai_retinanet_horovod_coco_tpu.serve import serve_http

        with self._server() as srv:
            srv.submit(IMG).result(timeout=10)
            httpd = serve_http(srv, port=0)
            # watchdog: scrape-lifetime stdlib server, joined below.
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            try:
                host, port = httpd.server_address[:2]
                base = f"http://{host}:{port}"
                code, body = _get(f"{base}/metrics")
                assert code == 200
                assert b"serve_request_latency_ms" in body
                code, body = _get(f"{base}/healthz")
                payload = json.loads(body)
                assert code == 200 and payload["status"] == "ok"
                load = payload["load"]
                assert load["completed"] == 1 and load["accepting"]
                assert "admission_capacity" in load
                # /healthz is split from /stats: distinct payload shapes.
                code, body = _get(f"{base}/stats")
                assert code == 200 and "status" not in json.loads(body)
                hb = watchdog.register("http-wedge", stall_after=0.01)
                time.sleep(0.05)
                code, body = _get(f"{base}/healthz")
                assert code == 503
                assert json.loads(body)["component"] == "http-wedge"
                hb.close()
            finally:
                httpd.shutdown()
                httpd.server_close()
                t.join(timeout=10)


# ---- obs/analyze ingestion ----------------------------------------------


class TestAnalyzeViolations:
    def _events_file(self, tmp_path) -> str:
        path = tmp_path / "metrics.jsonl"
        records = [
            {"event": "run_header", "run_id": "abc12345", "t_wall": 0.0},
            {
                "event": "slo_violation", "wall_s": 5.0, "rule": "p99",
                "metric": "serve_request_latency_ms.p99", "op": ">",
                "value": 300.0, "threshold": 250.0, "sustained_s": 30.0,
                "description": "p99 ceiling",
            },
        ]
        path.write_text("".join(json.dumps(r) + "\n" for r in records))
        return str(path)

    def test_violations_section_and_verdict_ranking(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
            analyze_events,
            validate_report,
        )

        # A trace with one busy span family (an inferable bottleneck)
        # plus the violation's instant marker.
        events = [
            {"ph": "X", "name": "serve_fetch", "ts": 0, "dur": 900_000,
             "pid": 1, "tid": 1},
            {"ph": "i", "name": "slo_violation", "ts": 100, "pid": 1,
             "tid": 1,
             "args": {"rule": "p99",
                      "metric": "serve_request_latency_ms.p99",
                      "value": 300.0, "threshold": 250.0,
                      "sustained_s": 30.0}},
        ]
        report = analyze_events(
            events, events_path=self._events_file(tmp_path)
        )
        assert validate_report(report) == []
        v = report["violations"]
        assert v["jsonl_events"] == 1 and v["trace_markers"] == 1
        assert v["rules"]["p99"]["count"] == 1
        assert v["rules"]["p99"]["max_sustained_s"] == 30.0
        # The sustained violation outranks every inferred bottleneck —
        # and maps to tune ops so --from-report still closes the loop.
        top = report["bottlenecks"][0]
        assert top["name"] == "slo:p99" and top["rank"] == 1
        assert top["score"] == 1.0
        assert top["tune_ops"] == ["nms", "batch"]
        names = [b["name"] for b in report["bottlenecks"]]
        assert any(n.startswith("span:") for n in names)  # not starved

    def test_no_violations_is_empty_not_missing(self):
        from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
            analyze_events,
        )

        report = analyze_events([])
        assert report["violations"] == {
            "trace_markers": 0, "jsonl_events": 0, "rules": {},
        }


class TestCkptTelemetry:
    """ISSUE 11 satellite: checkpoint health on the telemetry plane —
    the record sites, the pull collector's age arithmetic, and the
    built-in staleness SLO rule that makes a silently wedged saver
    visible before the run dies."""

    def setup_method(self):
        telemetry.reset()
        telemetry.enable()

    def teardown_method(self):
        telemetry.reset()

    def test_record_sites_feed_the_collector(self):
        telemetry.record_ckpt_inflight(1)
        telemetry.record_ckpt_save(step=2, save_s=0.12, total_bytes=1000)
        telemetry.record_ckpt_save(step=4, save_s=0.34, total_bytes=1000)
        snap = telemetry.default().snapshot()
        assert snap["ckpt_saves_total"] == 2
        assert snap["ckpt_save_s"] == pytest.approx(0.34)
        assert snap["ckpt_bytes"] == 1000
        assert snap["ckpt_last_success_age_s"] >= 0
        # Two saves landed -> a measured interval -> the ratio exists.
        assert "ckpt_age_over_interval" in snap
        assert snap["ckpt_inflight"] == 1
        telemetry.record_ckpt_inflight(0)
        assert telemetry.default().snapshot()["ckpt_inflight"] == 0

    def test_no_checkpointing_no_metric_noise(self):
        snap = telemetry.default().snapshot()
        assert not any(k.startswith("ckpt_") for k in snap)

    def test_disabled_record_sites_are_noops(self):
        telemetry.disable()
        telemetry.record_ckpt_save(step=2, save_s=0.1, total_bytes=10)
        telemetry.record_ckpt_inflight(1)
        telemetry.enable()
        snap = telemetry.default().snapshot()
        assert not any(k.startswith("ckpt_") for k in snap)

    def test_staleness_rule_fires_once_when_saver_wedges(self):
        # Saves landed at steps 2 and 4 (measured cadence: 2 steps).
        telemetry.record_ckpt_save(step=2, save_s=0.1, total_bytes=10)
        telemetry.record_ckpt_save(step=4, save_s=0.1, total_bytes=10)
        mon = slo.SloMonitor(
            telemetry.default(), [slo.ckpt_staleness_rule()]
        )
        # Healthy: training at step 5, one step past the save -> 0.5.
        telemetry.record_train_window(
            step=5, images_per_s=1.0, step_time_ms=1.0, data_wait_ms=0.0
        )
        snap = telemetry.default().snapshot()
        assert snap["ckpt_staleness"] == pytest.approx(0.5)
        assert mon.check_once(now=1.0) == []
        # Wedged saver: training advanced 10 steps (5x the cadence) with
        # no save landing.  STEP-based, so a long eval (steps frozen)
        # could never have tripped this.
        telemetry.record_train_window(
            step=14, images_per_s=1.0, step_time_ms=1.0, data_wait_ms=0.0
        )
        fired = mon.check_once(now=2.0)
        assert [v["rule"] for v in fired] == ["ckpt-staleness"]
        assert mon.check_once(now=3.0) == []  # latched, no flapping

    def test_manager_save_lands_on_the_plane(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import optax

        from batchai_retinanet_horovod_coco_tpu.train.state import TrainState
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        state = TrainState(
            step=jnp.asarray(1, jnp.int32),
            params={"w": jnp.ones((4,), jnp.float32)},
            batch_stats={},
            opt_state=(),
            tx=optax.sgd(1e-2),
        )
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(state, step=1, force=True)
        mgr.wait()
        mgr.close()
        snap = telemetry.default().snapshot()
        assert snap["ckpt_saves_total"] == 1
        assert snap["ckpt_inflight"] == 0
        assert snap["ckpt_save_s"] >= 0
