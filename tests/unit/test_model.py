import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.models.resnet import resnet50
from batchai_retinanet_horovod_coco_tpu.ops.anchors import AnchorConfig, anchors_for_image_shape

# Small test image: keeps CPU compile fast while exercising every level.
HW = (64, 64)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = RetinaNetConfig(num_classes=7, dtype=jnp.float32)
    model = build_retinanet(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, *HW, 3)))
    return cfg, model, variables


def test_backbone_feature_strides():
    model = resnet50(dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, *HW, 3)))
    feats = model.apply(variables, jnp.zeros((1, *HW, 3)))
    assert feats["c3"].shape == (1, 8, 8, 512)
    assert feats["c4"].shape == (1, 4, 4, 1024)
    assert feats["c5"].shape == (1, 2, 2, 2048)


def test_output_matches_anchor_count(tiny_model):
    cfg, model, variables = tiny_model
    out = model.apply(variables, jnp.zeros((2, *HW, 3)))
    anchors = anchors_for_image_shape(HW, cfg.anchor)
    assert out["cls_logits"].shape == (2, anchors.shape[0], 7)
    assert out["box_deltas"].shape == (2, anchors.shape[0], 4)
    assert out["cls_logits"].dtype == jnp.float32


def test_prior_prob_bias_init(tiny_model):
    """At init, mean sigmoid(cls_logits) ≈ prior_prob = 0.01."""
    cfg, model, variables = tiny_model
    out = model.apply(
        variables, jax.random.normal(jax.random.key(1), (1, *HW, 3)) * 0.1
    )
    mean_p = float(jnp.mean(jax.nn.sigmoid(out["cls_logits"])))
    assert 0.003 < mean_p < 0.03


def test_heads_shared_across_levels(tiny_model):
    """One cls_head / box_head param set: sharing across pyramid levels."""
    _, _, variables = tiny_model
    params = variables["params"]
    assert "cls_head" in params and "box_head" in params
    # No per-level duplicates like cls_head_p4.
    assert sum(1 for k in params if k.startswith("cls_head")) == 1


def test_anchor_order_contract(tiny_model):
    """Per-level blocks of model output align with per-level anchor blocks.

    Zero out all params except a marker in the shared cls head bias: all
    levels then produce constant logits; the concat order must be P3..P7 with
    level block sizes equal to anchor block sizes.
    """
    cfg, _, _ = tiny_model
    acfg = cfg.anchor
    sizes = []
    for i, level in enumerate(acfg.levels):
        fh, fw = acfg.feature_shape(HW, level)
        sizes.append(fh * fw * acfg.num_anchors_per_location)
    anchors = anchors_for_image_shape(HW, acfg)
    assert sum(sizes) == anchors.shape[0]
    # Anchor areas grow with level: the smallest-area anchor in each block
    # must match that level's base size, proving level-major concat order.
    offset = 0
    for i, level in enumerate(acfg.levels):
        block = anchors[offset : offset + sizes[i]]
        areas = (block[:, 2] - block[:, 0]) * (block[:, 3] - block[:, 1])
        assert np.isclose(areas.min(), (acfg.sizes[i] * min(acfg.scales)) ** 2, rtol=1e-3)
        offset += sizes[i]


def test_batchnorm_variant_has_batch_stats():
    cfg = RetinaNetConfig(num_classes=3, norm_kind="bn", dtype=jnp.float32)
    model = build_retinanet(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, *HW, 3)))
    assert "batch_stats" in variables
    # Train-mode apply mutates batch_stats.
    _, mutated = model.apply(
        variables, jnp.ones((1, *HW, 3)), train=True, mutable=["batch_stats"]
    )
    assert "batch_stats" in mutated


def test_bf16_compute_f32_params():
    cfg = RetinaNetConfig(num_classes=3)  # default dtype bfloat16
    model = build_retinanet(cfg)
    variables = model.init(jax.random.key(0), jnp.zeros((1, *HW, 3)))
    leaves = jax.tree.leaves(variables["params"])
    assert all(leaf.dtype == jnp.float32 for leaf in leaves)
    out = model.apply(variables, jnp.zeros((1, *HW, 3)))
    assert out["cls_logits"].dtype == jnp.float32  # cast back at the boundary


def test_return_levels_concat_equals_default(tiny_model_and_state):
    """Levels mode is the same computation, pre-concatenation, P3->P7."""
    import numpy as np

    model, state = tiny_model_and_state
    from batchai_retinanet_horovod_coco_tpu.train.state import model_variables

    images = jnp.asarray(
        np.random.default_rng(0).normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    )
    variables = model_variables(state)
    flat = model.apply(variables, images, train=False)
    levels = model.apply(variables, images, train=False, return_levels=True)
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(levels["cls_levels"], axis=1)),
        np.asarray(flat["cls_logits"]), rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(jnp.concatenate(levels["box_levels"], axis=1)),
        np.asarray(flat["box_deltas"]), rtol=1e-6,
    )


class TestSpaceToDepthStem:
    """The MLPerf s2d stem must be EXACTLY the 7x7/2 conv, reformulated."""

    def test_equivalent_to_plain_stem(self):
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (2, 64, 96, 3)).astype(np.float32))
        plain = StemConv(space_to_depth=False, dtype=jnp.float32)
        s2d = StemConv(space_to_depth=True, dtype=jnp.float32)
        params = plain.init(jax.random.key(0), x)  # SAME (7,7,3,64) param
        a = jax.jit(plain.apply)(params, x)
        b = jax.jit(s2d.apply)(params, x)
        assert a.shape == b.shape == (2, 32, 48, 64)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )

    def test_block4_equivalent_to_plain_stem(self):
        """The 4x4 fold (two stride-2 outputs per block as channels +
        depth-to-space) must also be EXACTLY the 7x7/2 conv."""
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(0, 1, (2, 64, 96, 3)).astype(np.float32))
        plain = StemConv(space_to_depth=False, dtype=jnp.float32)
        s2d4 = StemConv(space_to_depth=True, block=4, dtype=jnp.float32)
        params = plain.init(jax.random.key(0), x)
        a = jax.jit(plain.apply)(params, x)
        b = jax.jit(s2d4.apply)(params, x)
        assert a.shape == b.shape == (2, 32, 48, 64)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
        )

    def test_block4_rejects_indivisible(self):
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        x = jnp.zeros((1, 66, 64, 3), jnp.float32)
        with pytest.raises(ValueError, match="divisible by 4"):
            StemConv(space_to_depth=True, block=4).init(jax.random.key(0), x)

    def test_param_layout_is_mode_independent(self):
        """Checkpoints / torch imports see (7,7,3,64) in both modes."""
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        x = jnp.zeros((1, 32, 32, 3), jnp.float32)
        for mode in (False, True):
            params = StemConv(space_to_depth=mode).init(jax.random.key(0), x)
            assert params["params"]["kernel"].shape == (7, 7, 3, 64)

    def test_odd_shape_rejected(self):
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        x = jnp.zeros((1, 33, 32, 3), jnp.float32)
        with pytest.raises(ValueError, match="divisible by 2"):
            StemConv(space_to_depth=True).init(jax.random.key(0), x)

    def test_plain_stem_same_padding_odd_dims(self):
        """conv mode keeps out = ceil(d/2) under torch (3,3) padding, odd dims too."""
        from batchai_retinanet_horovod_coco_tpu.models.resnet import StemConv

        x = jnp.zeros((1, 33, 47, 3), jnp.float32)
        m = StemConv(space_to_depth=False, dtype=jnp.float32)
        out = m.apply(m.init(jax.random.key(0), x), x)
        assert out.shape == (1, 17, 24, 64)

    def test_full_model_equivalence(self):
        """Whole-model outputs match between stem modes with shared params."""
        cfg = dict(
            num_classes=3, backbone="resnet_test", fpn_channels=32,
            head_width=32, head_depth=1, dtype=jnp.float32,
        )
        plain = build_retinanet(RetinaNetConfig(**cfg))
        s2d = build_retinanet(RetinaNetConfig(stem="space_to_depth", **cfg))
        x = jnp.asarray(
            np.random.default_rng(0).normal(0, 1, (1, 64, 64, 3)),
            jnp.float32,
        )
        params = plain.init(jax.random.key(0), x)
        a = jax.jit(lambda p, x: plain.apply(p, x, train=False))(params, x)
        b = jax.jit(lambda p, x: s2d.apply(p, x, train=False))(params, x)
        np.testing.assert_allclose(
            np.asarray(a["cls_logits"]), np.asarray(b["cls_logits"]),
            rtol=1e-4, atol=1e-4,
        )
        np.testing.assert_allclose(
            np.asarray(a["box_deltas"]), np.asarray(b["box_deltas"]),
            rtol=1e-4, atol=1e-4,
        )


class TestPackedStemPipeline:
    """The h2w4 packed stem pipeline (StemConv packed_output + slot-packed
    norm + maxpool_packed_w) must reproduce the unpacked backbone exactly."""

    def test_maxpool_packed_w_matches_unpacked(self):
        from batchai_retinanet_horovod_coco_tpu.models.resnet import (
            maxpool_packed_w,
        )
        import flax.linen as nn

        rng = np.random.default_rng(0)
        # Quantized relu-like values: dense max ties, the realistic regime.
        x = jnp.asarray(
            np.maximum(rng.integers(-2, 4, (2, 16, 24, 8)), 0).astype(
                np.float32
            )
        )
        want = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        b, h, w, f = x.shape
        xf = x.reshape(b, h, w // 2, 2, f)
        packed = jnp.concatenate([xf[:, :, :, 0], xf[:, :, :, 1]], axis=-1)
        got = maxpool_packed_w(packed)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # Gradients are finite and conserve the cotangent mass (the W tie
        # rule deliberately diverges — maxpool_packed_w docstring — but a
        # routing bug that dropped or duplicated mass would break this).
        g = jax.grad(lambda p: jnp.sum(maxpool_packed_w(p) ** 2))(packed)
        assert bool(jnp.all(jnp.isfinite(g)))
        np.testing.assert_allclose(
            float(jnp.sum(g)),
            float(jnp.sum(2.0 * maxpool_packed_w(packed))),
            rtol=1e-6,
        )

    @pytest.mark.parametrize("norm", ["frozen_bn", "gn", "bn"])
    @pytest.mark.parametrize("hw", [(64, 96), (32, 100), (32, 46)])
    def test_backbone_matches_conv_stem(self, norm, hw):
        """Full ResNet: s2d (packed h2w4 where W%4==0, h2w2 fallback
        otherwise) == conv stem with shared params."""
        from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet

        rng = np.random.default_rng(0)
        h, w = hw
        x = jnp.asarray(rng.normal(0, 1, (2, h, w, 3)).astype(np.float32))
        ref = ResNet(
            stage_sizes=(1, 1, 1, 1), norm_kind=norm, dtype=jnp.float32,
            stem="conv",
        )
        v = ref.init(jax.random.key(0), x, train=False)
        s2d = ResNet(
            stage_sizes=(1, 1, 1, 1), norm_kind=norm, dtype=jnp.float32,
            stem="space_to_depth",
        )
        y_ref = jax.jit(lambda v, x: ref.apply(v, x, train=False))(v, x)
        y_s2d = jax.jit(lambda v, x: s2d.apply(v, x, train=False))(v, x)
        for k in y_ref:
            np.testing.assert_allclose(
                np.asarray(y_ref[k]), np.asarray(y_s2d[k]),
                rtol=1e-4, atol=2e-5,
            )

    def test_train_mode_bn_stats_match(self):
        """Slot-major PackedBatchNorm running-stat updates == nn.BatchNorm."""
        from batchai_retinanet_horovod_coco_tpu.models.resnet import ResNet

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (2, 64, 96, 3)).astype(np.float32))
        ref = ResNet(
            stage_sizes=(1, 1, 1, 1), norm_kind="bn", dtype=jnp.float32,
            stem="conv",
        )
        v = ref.init(jax.random.key(0), x, train=True)
        s2d = ResNet(
            stage_sizes=(1, 1, 1, 1), norm_kind="bn", dtype=jnp.float32,
            stem="space_to_depth",
        )
        _, m_ref = ref.apply(v, x, train=True, mutable=["batch_stats"])
        _, m_s2d = s2d.apply(v, x, train=True, mutable=["batch_stats"])
        for a, b in zip(jax.tree.leaves(m_ref), jax.tree.leaves(m_s2d)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )
