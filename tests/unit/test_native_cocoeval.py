"""Native C++ COCOeval kernels vs the pure-numpy oracle: bit parity.

The native library (native/cocoeval.cpp) replaces the hot per-(image,
category) matching loop; these tests force both paths over randomized
fixtures (incl. crowds, ignores, empty sides) and require IDENTICAL output —
the numpy path stays the oracle, the C++ path is the shipped fast path.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate import _native
from batchai_retinanet_horovod_coco_tpu.evaluate.coco_eval import (
    CocoEval,
    numpy_bbox_iou_xywh,
    numpy_match_detections,
)

kernels = _native.get_kernels()
needs_native = pytest.mark.skipif(
    kernels is None, reason="native toolchain unavailable"
)

# The SHIPPED numpy fallbacks are the oracles here — no inlined copies, so
# an oracle change automatically re-tests the native kernel against it.
_numpy_iou = numpy_bbox_iou_xywh
_numpy_match = numpy_match_detections


def random_boxes(rng, n):
    xy = rng.uniform(0, 80, (n, 2))
    wh = rng.uniform(1, 40, (n, 2))
    return np.concatenate([xy, wh], axis=1)


@needs_native
class TestIouParity:
    @pytest.mark.parametrize("seed", range(5))
    def test_random(self, seed):
        rng = np.random.default_rng(seed)
        dt = random_boxes(rng, int(rng.integers(1, 30)))
        gt = random_boxes(rng, int(rng.integers(1, 20)))
        crowd = rng.random(len(gt)) < 0.3
        np.testing.assert_array_equal(
            kernels.iou_matrix(dt, gt, crowd), _numpy_iou(dt, gt, crowd)
        )

    def test_empty(self):
        z = np.zeros((0, 4))
        assert kernels.iou_matrix(z, z, np.zeros(0, bool)).shape == (0, 0)

    def test_zero_area(self):
        dt = np.array([[0.0, 0.0, 0.0, 0.0]])
        gt = np.array([[0.0, 0.0, 0.0, 0.0]])
        out = kernels.iou_matrix(dt, gt, np.zeros(1, bool))
        np.testing.assert_array_equal(out, _numpy_iou(dt, gt, np.zeros(1, bool)))


@needs_native
class TestMatchParity:
    @pytest.mark.parametrize("seed", range(8))
    def test_random(self, seed):
        rng = np.random.default_rng(100 + seed)
        D = int(rng.integers(0, 40))
        G = int(rng.integers(1, 25))
        # Quantized IoUs make exact ties common — the hard case for parity.
        ious = np.round(rng.random((D, G)), 1)
        g_ignore = rng.random(G) < 0.3
        g_crowd = g_ignore & (rng.random(G) < 0.5)
        # Oracle layout: non-ignored gts first.
        order = np.argsort(g_ignore, kind="stable")
        ious, g_ignore, g_crowd = ious[:, order], g_ignore[order], g_crowd[order]
        thrs = np.linspace(0.5, 0.95, 10)
        n_dtm, n_gtm, n_ign = _numpy_match(ious, thrs, g_ignore, g_crowd)
        c_dtm, c_gtm, c_ign = kernels.match_detections(
            ious, thrs, g_ignore, g_crowd
        )
        np.testing.assert_array_equal(c_dtm, n_dtm)
        np.testing.assert_array_equal(c_gtm, n_gtm)
        np.testing.assert_array_equal(c_ign, n_ign)


@needs_native
class TestEndToEndParity:
    def test_full_eval_native_vs_numpy(self, monkeypatch):
        """CocoEval stats identical with the native path forced off/on."""
        rng = np.random.default_rng(7)
        gts, dts = [], []
        ann_id = 1
        for img in range(1, 9):
            for _ in range(int(rng.integers(1, 6))):
                b = random_boxes(rng, 1)[0]
                gts.append(
                    {
                        "id": ann_id, "image_id": img,
                        "category_id": int(rng.integers(1, 4)),
                        "bbox": b.tolist(), "area": float(b[2] * b[3]),
                        "iscrowd": int(rng.random() < 0.15),
                    }
                )
                ann_id += 1
                # detection near the gt + one random spurious
                jitter = b + rng.normal(0, 2, 4)
                jitter[2:] = np.maximum(jitter[2:], 1)
                dts.append(
                    {
                        "image_id": img,
                        "category_id": gts[-1]["category_id"],
                        "bbox": jitter.tolist(),
                        "score": float(rng.random()),
                    }
                )
            spurious = random_boxes(rng, 1)[0]
            dts.append(
                {
                    "image_id": img, "category_id": int(rng.integers(1, 4)),
                    "bbox": spurious.tolist(), "score": float(rng.random()),
                }
            )

        def run():
            ev = CocoEval(gts, dts, img_ids=list(range(1, 9)))
            ev.evaluate()
            ev.accumulate()
            return ev.summarize()

        native_stats = run()
        monkeypatch.setattr(_native, "_CACHED", (True, None))
        numpy_stats = run()
        np.testing.assert_array_equal(native_stats, numpy_stats)
        assert native_stats[0] > 0  # sanity: jittered dets yield nonzero mAP
