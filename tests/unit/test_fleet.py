"""serve/fleet.py — fleet router state machine (ISSUE 12).

Everything here runs on the injectable clock: ``poll_once(now=...)``
drives the health/breaker transitions and ``canary_check_once(now=...)``
drives the canary gate (the SLO monitor's anti-flap machinery
underneath), so no test sleeps to make time pass.

Families:

- weight computation from advertised load fields (exact math);
- circuit open → half-open → close transitions, with the breaker's
  deterministic backoff schedule between probes;
- deadline-aware re-dispatch: at most once, never past the deadline;
- fleet admission control + drain (``shutting_down``);
- canary gate: fires exactly once per sustained breach, rolls back with
  one ``canary_rollback``, restores baseline weights.
"""

from __future__ import annotations

import threading
import time

import pytest

from batchai_retinanet_horovod_coco_tpu.serve import (
    DetectionServer,
    FleetConfig,
    FleetRouter,
    LocalReplica,
    ReplicaUnavailable,
    RequestRejected,
    RequestTimeout,
    ServeConfig,
    ServerClosed,
    ServerError,
)
from batchai_retinanet_horovod_coco_tpu.serve.fleet import (
    CLOSED,
    DRAINED,
    HALF_OPEN,
    OPEN,
    replica_weight,
)
from batchai_retinanet_horovod_coco_tpu.serve.stub import (
    EXPECTED_DETECTIONS,
    StubDetectEngine,
)
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy

DETS = [{"category_id": 0, "bbox": [1.0, 2.0, 9.0, 18.0], "score": 0.5}]


class FakeReplica:
    """A replica handle with scriptable health and detect behavior."""

    def __init__(
        self,
        replica_id: str,
        version: str = "v1",
        p99_ms: float | None = 100.0,
        capacity: int = 8,
        inflight: int = 0,
        qsize: int = 0,
        accepting: bool = True,
        shed_total: int = 0,
    ):
        self.replica_id = replica_id
        self.version = version
        self.p99_ms = p99_ms
        self.capacity = capacity
        self.inflight = inflight
        self.qsize = qsize
        self.accepting = accepting
        self.shed_total = shed_total
        self.healthy = True
        self.healthz_calls = 0
        self.detect_error: BaseException | None = None
        self.detect_delay_s = 0.0
        self.detect_calls = 0
        self.drained = False

    def load(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "version": self.version,
            "inflight": self.inflight,
            "admission_qsize": self.qsize,
            "admission_capacity": self.capacity,
            "p99_ms": self.p99_ms,
            "shed_total": self.shed_total,
            "accepting": self.accepting,
        }

    def healthz(self):
        self.healthz_calls += 1
        if not self.healthy:
            return 0, {"status": "unreachable"}
        return 200, {"status": "ok", "load": self.load()}

    def detect(self, payload, timeout_s=None):
        self.detect_calls += 1
        if self.detect_delay_s:
            time.sleep(self.detect_delay_s)
        if self.detect_error is not None:
            raise self.detect_error
        return DETS

    def drain(self, timeout_s=5.0):
        self.drained = True
        self.accepting = False

    def close(self):
        self.accepting = False


#: No-jitter breaker backoff — probe times are exact in these tests.
EXACT_BACKOFF = BackoffPolicy(
    max_tries=1_000_000, base_s=1.0, multiplier=2.0, ceiling_s=8.0,
    jitter=0.0,
)


def make_router(replicas, **cfg) -> FleetRouter:
    cfg.setdefault("probe_backoff", EXACT_BACKOFF)
    cfg.setdefault("poll_interval_s", 0.05)
    return FleetRouter(
        replicas, FleetConfig(**cfg), auto_poll=False
    )


# ---- weight computation --------------------------------------------------


class TestWeights:
    def test_replica_weight_exact_math(self):
        load = {
            "accepting": True, "admission_capacity": 8,
            "admission_qsize": 2, "inflight": 4, "p99_ms": None,
        }
        # headroom 0.75, inflight damping 1/(1 + 4/8) → 0.75 / 1.5 = 0.5
        assert replica_weight(load) == 0.5
        # p99 twice the fleet best halves the weight again.
        load["p99_ms"] = 200.0
        assert replica_weight(load, p99_ref=100.0) == 0.25
        # A p99 at (or better than) the reference never boosts above 1x.
        load["p99_ms"] = 50.0
        assert replica_weight(load, p99_ref=100.0) == 0.5

    def test_slot_occupancy_scales_the_weight(self):
        """ISSUE 14: the weight formula consumes the free-slot load
        fields — exact math.  A fully-claimed slot pool halves the
        weight vs an idle pool; replicas that don't advertise slots get
        the neutral factor 1 (deterministic tie-break: same fields,
        same weight, always)."""
        load = {
            "accepting": True, "admission_capacity": 8,
            "admission_qsize": 2, "inflight": 4, "p99_ms": None,
        }
        base = replica_weight(load)  # 0.5 (pinned above)
        idle = dict(load, free_slots=8, slot_capacity=8)
        full = dict(load, free_slots=0, slot_capacity=8)
        half = dict(load, free_slots=4, slot_capacity=8)
        assert replica_weight(idle) == base  # (1 + 8/8)/2 = 1.0
        assert replica_weight(full) == base / 2  # (1 + 0)/2 = 0.5
        assert replica_weight(half) == base * 0.75
        # Determinism: identical fields → identical weight, every time.
        assert replica_weight(dict(full)) == replica_weight(dict(full))

    def test_fully_occupied_replica_loses_traffic_to_idle_one(self):
        """The routing consequence, on the injectable clock: after one
        poll, a replica advertising zero free slots takes measurably
        less traffic than an idle twin with otherwise identical load."""
        idle = FakeReplica("idle")
        busy = FakeReplica("busy")
        idle.slots = (4, 4)   # (free, capacity)
        busy.slots = (0, 4)
        orig_load = FakeReplica.load

        def load_with_slots(self):
            out = orig_load(self)
            free, cap = getattr(self, "slots", (None, None))
            if cap:
                out["free_slots"] = free
                out["slot_capacity"] = cap
            return out

        FakeReplica.load = load_with_slots
        try:
            router = make_router([idle, busy], seed=7)
            try:
                router.poll_once(now=100.0)
                status = {
                    r["replica_id"]: r for r in router.status()["replicas"]
                }
                assert status["idle"]["weight"] == 2 * status["busy"]["weight"] > 0
                for _ in range(60):
                    assert router.detect(b"payload") == DETS
                # 2:1 weights: the idle replica must take the majority.
                assert idle.detect_calls > busy.detect_calls > 0
            finally:
                router.close()
        finally:
            FakeReplica.load = orig_load

    def test_not_accepting_or_empty_is_unroutable(self):
        assert replica_weight(None) == 0.0
        assert replica_weight({}) == 0.0
        assert replica_weight({"accepting": False}) == 0.0

    def test_full_admission_queue_is_unroutable(self):
        load = {
            "accepting": True, "admission_capacity": 4,
            "admission_qsize": 4, "inflight": 0,
        }
        assert replica_weight(load) == 0.0

    def test_router_weights_follow_load_fields(self):
        idle = FakeReplica("idle", inflight=0, qsize=0)
        busy = FakeReplica("busy", inflight=8, qsize=4)
        router = make_router([idle, busy])
        try:
            status = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }
            assert status["idle"]["weight"] == replica_weight(idle.load())
            assert status["busy"]["weight"] == replica_weight(busy.load())
            assert status["idle"]["weight"] > status["busy"]["weight"] > 0
        finally:
            router.close()


# ---- circuit breaker -----------------------------------------------------


class TestBreaker:
    def test_open_half_open_close_transitions(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        router = make_router([a, b])
        try:
            t = 100.0
            states = lambda: {  # noqa: E731 — tiny local reader
                r["replica_id"]: r["state"]
                for r in router.status()["replicas"]
            }
            assert states() == {"a": CLOSED, "b": CLOSED}

            # Health-poll failure opens the breaker on the first miss.
            a.healthy = False
            router.poll_once(now=t)
            assert states()["a"] == OPEN
            assert states()["b"] == CLOSED

            # Still backing off: polls before the probe time don't touch it.
            router.poll_once(now=t + 0.5)
            assert states()["a"] == OPEN

            # First probe at base_s=1.0: replica still down → re-opens
            # with the NEXT backoff step (2.0 s).
            router.poll_once(now=t + 1.0)
            assert states()["a"] == OPEN
            # The second probe is not due before +1.0+2.0.
            router.poll_once(now=t + 2.5)
            assert states()["a"] == OPEN

            # Replica restarts; the due probe (half-open) readmits it.
            a.healthy = True
            router.poll_once(now=t + 3.1)
            assert states()["a"] == CLOSED
            # ... with routing weight restored.
            st = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }["a"]
            assert st["weight"] > 0
        finally:
            router.close()

    def test_open_replica_takes_no_traffic(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        router = make_router([a, b], seed=3)
        try:
            a.healthy = False
            router.poll_once(now=10.0)
            for _ in range(8):
                assert router.detect(b"payload") == DETS
            assert a.detect_calls == 0
            assert b.detect_calls == 8
        finally:
            router.close()

    def test_all_breakers_open_sheds_with_reason(self):
        a = FakeReplica("a")
        router = make_router([a])
        try:
            a.healthy = False
            router.poll_once(now=10.0)
            with pytest.raises(RequestRejected) as ei:
                router.detect(b"payload")
            assert ei.value.reason == "no_replica_available"
            code, payload = router.healthz()
            assert code == 503 and payload["replicas_closed"] == 0
        finally:
            router.close()

    def test_dead_replica_on_request_opens_breaker_immediately(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        router = make_router([a, b])
        try:
            a.detect_error = ReplicaUnavailable("a died")
            b.detect_error = None
            assert router.detect(b"payload") == DETS
            # Whichever path the pick took, a dead replica must end OPEN
            # the moment a request finds it dead (not at the next poll).
            if a.detect_calls:
                states = {
                    r["replica_id"]: r["state"]
                    for r in router.status()["replicas"]
                }
                assert states["a"] == OPEN
        finally:
            router.close()

    def test_consecutive_sheds_trip_the_breaker(self):
        a = FakeReplica("a")
        router = make_router([a], shed_trip=3, redispatch_limit=0)
        try:
            a.detect_error = RequestRejected("admission_queue_full")
            for _ in range(3):
                with pytest.raises(RequestRejected):
                    router.detect(b"payload")
            states = {
                r["replica_id"]: r["state"]
                for r in router.status()["replicas"]
            }
            assert states["a"] == OPEN
        finally:
            router.close()


# ---- re-dispatch ---------------------------------------------------------


class TestRedispatch:
    def test_redispatch_lands_on_another_replica(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        a.detect_error = ReplicaUnavailable("a died mid-request")
        router = make_router([a, b])
        try:
            assert router.detect(b"payload") == DETS
            assert b.detect_calls >= 1
            assert a.detect_calls + b.detect_calls <= 2
            assert router.status()["redispatches"] <= 1
        finally:
            router.close()

    def test_redispatch_happens_at_most_once(self):
        reps = [FakeReplica(f"r{i}") for i in range(4)]
        for r in reps:
            r.detect_error = ReplicaUnavailable("down")
        router = make_router(reps, redispatch_limit=1)
        try:
            with pytest.raises(ServerError):
                router.detect(b"payload")
            # redispatch_limit=1 → at most TWO dispatch attempts total,
            # however many replicas remain untried.
            assert sum(r.detect_calls for r in reps) == 2
            assert router.stats.snapshot()["failed"] == 1
        finally:
            router.close()

    def test_redispatch_respects_the_deadline(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        for r in (a, b):
            r.detect_delay_s = 0.15
            r.detect_error = ReplicaUnavailable("slow death")
        router = make_router([a, b])
        try:
            with pytest.raises(RequestTimeout):
                router.detect(b"payload", timeout_s=0.1)
            # The first dispatch consumed the deadline: no second try.
            assert a.detect_calls + b.detect_calls == 1
        finally:
            router.close()

    def test_decode_error_is_never_redispatched_or_a_breaker_hit(self):
        """decode_error is the client's fault: no retry, no breaker hit."""
        a = FakeReplica("a")
        a.detect_error = RequestRejected("decode_error")
        router = make_router([a], redispatch_limit=3)
        try:
            with pytest.raises(RequestRejected) as ei:
                router.detect(b"payload")
            assert ei.value.reason == "decode_error"
            assert a.detect_calls == 1  # no blind retry of a bad input
            states = {
                r["replica_id"]: r["state"]
                for r in router.status()["replicas"]
            }
            assert states["a"] == CLOSED
        finally:
            router.close()


# ---- admission control + drain -------------------------------------------


class TestAdmission:
    def test_fleet_overloaded_sheds_at_the_edge(self):
        a = FakeReplica("a")
        router = make_router([a], max_inflight=1)
        try:
            release = threading.Event()
            started = threading.Event()

            real_detect = a.detect

            def blocking_detect(payload, timeout_s=None):
                started.set()
                release.wait(5)
                return real_detect(payload, timeout_s)

            a.detect = blocking_detect
            results: list = []
            t = threading.Thread(  # watchdog: test-local client thread
                target=lambda: results.append(router.detect(b"p")),
                daemon=True,
            )
            t.start()
            assert started.wait(5)
            with pytest.raises(RequestRejected) as ei:
                router.detect(b"payload")
            assert ei.value.reason == "fleet_overloaded"
            release.set()
            t.join(timeout=5)
            assert results == [DETS]
        finally:
            release.set()
            router.close()

    def test_closed_router_rejects_with_shutting_down(self):
        router = make_router([FakeReplica("a")])
        router.close()
        with pytest.raises(RequestRejected) as ei:
            router.detect(b"payload")
        assert ei.value.reason == "shutting_down"
        assert router.stats.snapshot()["shed"]["shutting_down"] == 1


# ---- canary gate ---------------------------------------------------------


def canary_fleet(**cfg):
    base = [
        FakeReplica("base-0", p99_ms=100.0),
        FakeReplica("base-1", p99_ms=100.0),
    ]
    cfg.setdefault("canary_for_s", 2.0)
    cfg.setdefault("canary_p99_factor", 1.5)
    cfg.setdefault("canary_weight", 0.25)
    router = make_router(base, **cfg)
    canary = FakeReplica("canary", version="v2", p99_ms=100.0)
    router.add_canary(canary)
    return router, base, canary


class TestCanary:
    def test_canary_takes_fractional_weight_while_green(self):
        router, base, canary = canary_fleet()
        try:
            status = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }
            full = replica_weight(canary.load(), p99_ref=100.0)
            assert status["canary"]["weight"] == pytest.approx(
                0.25 * full, abs=1e-6
            )
            assert status["canary"]["is_canary"]
            assert status["base-0"]["weight"] == pytest.approx(
                replica_weight(base[0].load(), p99_ref=100.0), abs=1e-6
            )
        finally:
            router.close()

    def test_sustained_p99_breach_fires_exactly_one_rollback(self):
        router, base, canary = canary_fleet()
        try:
            canary.p99_ms = 300.0  # 3x the fleet baseline
            router.poll_once(now=0.0)
            assert router.canary_check_once(now=0.0) == []  # not sustained
            assert router.canary_check_once(now=1.0) == []
            fired = router.canary_check_once(now=2.5)  # for_s=2.0 elapsed
            assert [v["rule"] for v in fired] == ["canary-p99-regression"]
            status = router.status()
            assert status["canary_rollbacks"] == 1
            assert status["canary_outcome"] == "rolled_back"
            by_id = {r["replica_id"]: r for r in status["replicas"]}
            # Drained: zero weight, terminal state, replica drained, and
            # the fleet back to baseline weights.
            assert by_id["canary"]["state"] == DRAINED
            assert by_id["canary"]["weight"] == 0.0
            assert canary.drained
            assert by_id["base-0"]["weight"] > 0
            assert by_id["base-1"]["weight"] > 0

            # Still breaching: the gate never fires again (anti-flap +
            # the terminal outcome latch).
            for t in (3.0, 10.0, 100.0):
                router.poll_once(now=t)
                assert router.canary_check_once(now=t) == []
            assert router.status()["canary_rollbacks"] == 1
        finally:
            router.close()

    def test_transient_blip_never_fires(self):
        router, base, canary = canary_fleet()
        try:
            canary.p99_ms = 300.0
            router.poll_once(now=0.0)
            assert router.canary_check_once(now=0.0) == []
            canary.p99_ms = 100.0  # heals before for_s elapses
            router.poll_once(now=1.0)
            assert router.canary_check_once(now=1.0) == []
            assert router.canary_check_once(now=10.0) == []
            assert router.status()["canary_rollbacks"] == 0
            assert router.status()["canary_outcome"] is None
        finally:
            router.close()

    def test_canary_shed_rate_rule_also_gates(self):
        router, base, canary = canary_fleet(canary_for_s=0.0)
        try:
            router.poll_once(now=0.0)
            assert router.canary_check_once(now=0.0) == []  # delta baseline
            canary.shed_total = 7  # canary started shedding
            router.poll_once(now=1.0)
            fired = router.canary_check_once(now=1.0)
            assert [v["rule"] for v in fired] == ["canary-shed-rate"]
            assert router.status()["canary_rollbacks"] == 1
        finally:
            router.close()

    def test_rolled_back_local_canary_rejects_shutting_down(self):
        """The drain half of rollback, on a REAL in-process server: new
        submits shed with ``shutting_down`` (never queue into a corpse)."""
        # Fleet baseline p99 far below the slow canary's real latency
        # (stub dispatch 50 ms), so the ratio rule visibly breaches.
        base = [FakeReplica("base-0", p99_ms=1.0),
                FakeReplica("base-1", p99_ms=1.0)]
        server = DetectionServer(
            StubDetectEngine(delay_s=0.05),
            ServeConfig(max_delay_ms=1, preprocess_workers=1),
            replica_id="canary-local",
        )
        router = make_router(base, canary_for_s=0.0, canary_weight=0.5)
        try:
            canary = LocalReplica(server)
            router.add_canary(canary)
            # Give the canary a visibly-regressed p99 via real traffic
            # (the stub device is slow); then let the gate see it.
            import numpy as np

            img = np.zeros((64, 64, 3), np.uint8)
            canary.detect(img, timeout_s=10)
            router.poll_once(now=0.0)
            fired = router.canary_check_once(now=0.0)
            assert [v["rule"] for v in fired] == ["canary-p99-regression"]
            with pytest.raises(ServerClosed):
                server.submit(img)
            assert server.snapshot()["shed"].get("shutting_down") == 1
            assert router.status()["canary_outcome"] == "rolled_back"
        finally:
            router.close()
            server.close(drain=False)

    def test_canary_slot_is_reusable_after_rollback(self):
        """A rolled-back canary frees the slot: a fixed next version can
        be admitted without restarting the router, and ITS sustained
        breach fires its own (single) rollback."""
        router, base, canary = canary_fleet()
        try:
            canary.p99_ms = 300.0
            router.poll_once(now=0.0)
            router.canary_check_once(now=0.0)
            assert router.canary_check_once(now=2.5)  # rollback #1
            assert router.status()["canary_rollbacks"] == 1

            v3 = FakeReplica("canary-v3", version="v3", p99_ms=100.0)
            router.add_canary(v3)  # must not raise "already under evaluation"
            assert router.status()["canary_outcome"] is None
            by_id = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }
            assert by_id["canary-v3"]["is_canary"]
            assert by_id["canary"]["state"] == DRAINED  # v2 stays visible

            v3.p99_ms = 400.0
            router.poll_once(now=10.0)
            router.canary_check_once(now=10.0)
            assert router.canary_check_once(now=12.5)  # rollback #2
            assert router.status()["canary_rollbacks"] == 2
            assert v3.drained
        finally:
            router.close()

    def test_promotion_graduates_to_full_weight(self):
        router, base, canary = canary_fleet()
        try:
            router.promote_canary()
            router.poll_once(now=5.0)
            by_id = {
                r["replica_id"]: r for r in router.status()["replicas"]
            }
            assert not by_id["canary"]["is_canary"]
            assert by_id["canary"]["weight"] == pytest.approx(
                replica_weight(canary.load(), p99_ref=100.0), abs=1e-6
            )
            assert router.status()["canary_outcome"] == "promoted"
            assert router.status()["canary_rollbacks"] == 0
        finally:
            router.close()


# ---- telemetry surface ---------------------------------------------------


class TestTelemetry:
    def test_fleet_metrics_families_present(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        router = make_router([a, b])
        try:
            router.detect(b"payload")
            a.healthy = False
            router.poll_once(now=50.0)
            snap = router.telemetry.snapshot()
            assert snap["fleet_requests_completed_total"] == 1
            assert snap['fleet_breaker_state{replica="a"}'] == 2.0  # OPEN
            assert snap['fleet_breaker_state{replica="b"}'] == 0.0
            assert snap['fleet_replica_weight{replica="b"}'] > 0
            assert snap["fleet_breaker_open_total"] == 1
            text = router.telemetry.prometheus_text()
            assert "fleet_request_latency_ms" in text
            assert "fleet_replica_weight" in text
        finally:
            router.close()

    def test_healthz_degrades_but_stays_up_with_one_replica(self):
        a = FakeReplica("a")
        b = FakeReplica("b")
        router = make_router([a, b])
        try:
            a.healthy = False
            router.poll_once(now=5.0)
            code, payload = router.healthz()
            assert code == 200
            assert payload["replicas_closed"] == 1
            assert router.detect(b"payload") == DETS  # degraded, serving
        finally:
            router.close()


# ---- HTTP replica error taxonomy -----------------------------------------


class TestHttpReplicaTaxonomy:
    def test_socket_timeout_is_request_timeout_not_replica_death(self):
        """A slow-but-alive replica (socket accepts, never answers) is a
        RequestTimeout — a request outcome, never a breaker hit or a
        re-dispatch while the original may still be executing."""
        import socket

        from batchai_retinanet_horovod_coco_tpu.serve.replica import (
            HttpReplica,
        )

        listener = socket.socket()
        listener.bind(("127.0.0.1", 0))
        listener.listen(1)
        try:
            host, port = listener.getsockname()
            rep = HttpReplica(f"http://{host}:{port}", timeout_s=0.3)
            with pytest.raises(RequestTimeout):
                rep.detect(b"payload", timeout_s=0.3)
        finally:
            listener.close()

    def test_refused_connection_is_replica_unavailable(self):
        import socket

        from batchai_retinanet_horovod_coco_tpu.serve.replica import (
            HttpReplica,
        )

        with socket.socket() as s:  # grab a port, then free it
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        rep = HttpReplica(f"http://127.0.0.1:{port}", timeout_s=0.5)
        with pytest.raises(ReplicaUnavailable):
            rep.detect(b"payload", timeout_s=0.5)
        code, payload = rep.healthz()
        assert code == 0 and payload["status"] == "unreachable"


# ---- routing is transport, not math (PARITY §5.16) -----------------------


class TestRoutingParity:
    def test_routed_detections_bit_identical_to_direct(self):
        """The router never touches detection payloads: a request through
        the fleet returns byte-for-byte what the replica's own submit()
        returns for the same image."""
        import numpy as np

        server = DetectionServer(
            StubDetectEngine(),
            ServeConfig(max_delay_ms=5, preprocess_workers=1),
            replica_id="parity-r0",
        )
        router = make_router([LocalReplica(server)])
        try:
            img = np.zeros((64, 64, 3), np.uint8)
            direct = server.submit(img).result(timeout=30)
            routed = router.detect(img)
            assert routed == direct == EXPECTED_DETECTIONS
        finally:
            router.close()
            server.close(drain=False)


# ---- half-open probe schedule is the backoff policy's, exactly -----------


class TestProbeSchedule:
    def test_probe_times_follow_policy_delays(self):
        a = FakeReplica("a")
        policy = BackoffPolicy(
            max_tries=1_000_000, base_s=1.0, multiplier=2.0,
            ceiling_s=4.0, jitter=0.0,
        )
        router = make_router([a, FakeReplica("b")], probe_backoff=policy)
        try:
            a.healthy = False
            router.poll_once(now=0.0)  # fails → OPEN, probe due at +1.0
            # Each re-open schedules the NEXT policy delay from the probe
            # time: delays 1, 2, 4, 4 (ceiling) → dues 1, 3, 7, 11.
            for due in (1.0, 3.0, 7.0, 11.0):
                before = a.healthz_calls
                router.poll_once(now=due - 0.01)  # backing off: no probe
                assert a.healthz_calls == before
                router.poll_once(now=due)  # due: exactly one probe
                assert a.healthz_calls == before + 1
            states = {
                r["replica_id"]: r["state"]
                for r in router.status()["replicas"]
            }
            assert states["a"] == OPEN  # stayed dead the whole time
        finally:
            router.close()
