"""Fused Pallas assignment vs the vmapped jnp path (interpret mode).

The kernel must reproduce ``anchor_targets_compact`` exactly: IoU values,
first-tie argmax, force-match rescue, thresholds, and encoded box targets.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.ops import anchors as A
from batchai_retinanet_horovod_coco_tpu.ops import matching as M

FUSED = M.MatchingConfig(fused_pallas=True, pallas_interpret=True)
JNP = M.MatchingConfig(fused_pallas=False)


def _rand_scene(B=2, G=7, hw=(64, 64), seed=0, empty_images=()):
    rng = np.random.default_rng(seed)
    h, w = hw
    boxes = np.zeros((B, G, 4), np.float32)
    labels = rng.integers(0, 3, (B, G)).astype(np.int32)
    mask = np.zeros((B, G), bool)
    for b in range(B):
        n = 0 if b in empty_images else int(rng.integers(1, G + 1))
        xy = rng.uniform(0, [w - 8, h - 8], (n, 2))
        wh = rng.uniform(4, 40, (n, 2))
        boxes[b, :n, 0] = xy[:, 0]
        boxes[b, :n, 1] = xy[:, 1]
        boxes[b, :n, 2] = np.minimum(xy[:, 0] + wh[:, 0], w)
        boxes[b, :n, 3] = np.minimum(xy[:, 1] + wh[:, 1], h)
        mask[b, :n] = True
    return jnp.asarray(boxes), jnp.asarray(labels), jnp.asarray(mask)


def _assert_targets_equal(got, want):
    np.testing.assert_array_equal(np.asarray(got.state), np.asarray(want.state))
    # Labels only matter where positive (elsewhere the one-hot is masked).
    pos = np.asarray(want.state) == M.POSITIVE
    np.testing.assert_array_equal(
        np.asarray(got.matched_labels)[pos], np.asarray(want.matched_labels)[pos]
    )
    np.testing.assert_allclose(
        np.asarray(got.box_targets), np.asarray(want.box_targets),
        rtol=1e-5, atol=1e-6,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_matches_jnp_path(seed):
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes, labels, mask = _rand_scene(seed=seed)
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, FUSED)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, JNP)
    _assert_targets_equal(got, want)


def test_empty_scene_all_negative():
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes, labels, mask = _rand_scene(B=2, seed=3, empty_images=(0, 1))
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, FUSED)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, JNP)
    _assert_targets_equal(got, want)
    assert not np.any(np.asarray(got.state) == M.POSITIVE)


def test_mixed_empty_and_populated():
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes, labels, mask = _rand_scene(B=3, seed=4, empty_images=(1,))
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, FUSED)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, JNP)
    _assert_targets_equal(got, want)


def test_force_match_small_boxes():
    """Tiny gts below the positive threshold still get their best anchor."""
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes = jnp.asarray(
        [[[10.0, 10.0, 13.0, 13.0], [40.0, 40.0, 44.0, 43.0]]], jnp.float32
    )
    labels = jnp.asarray([[1, 2]], jnp.int32)
    mask = jnp.ones((1, 2), bool)
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, FUSED)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, JNP)
    _assert_targets_equal(got, want)
    assert int(np.sum(np.asarray(got.state) == M.POSITIVE)) >= 2


def test_no_force_match_variant():
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes, labels, mask = _rand_scene(seed=5)
    fused = dataclasses.replace(FUSED, force_match_best=False)
    plain = dataclasses.replace(JNP, force_match_best=False)
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, fused)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, plain)
    _assert_targets_equal(got, want)


def test_anchor_tail_not_divisible_by_tile():
    """A < TILE_A and A % 8 == 0 tail: in-range masking must hold."""
    anchors = jnp.asarray(A.anchors_for_image_shape((32, 32)))
    assert anchors.shape[0] % pl_tile() != 0
    boxes, labels, mask = _rand_scene(hw=(32, 32), seed=6)
    got = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, FUSED)
    want = M.anchor_targets_compact_batched(anchors, boxes, labels, mask, JNP)
    _assert_targets_equal(got, want)


def pl_tile():
    from batchai_retinanet_horovod_coco_tpu.ops.pallas.matching import TILE_A

    return TILE_A


@pytest.mark.parametrize("config", [FUSED, JNP], ids=["fused", "jnp"])
def test_planar_box_targets_match(config):
    """planar_box_targets=True is the (B, A, 4) result, transposed, on BOTH
    backends — the train step's NHWC path consumes the planar layout
    (identical per-element arithmetic via ops.boxes.encode_boxes_planar)."""
    anchors = jnp.asarray(A.anchors_for_image_shape((64, 64)))
    boxes, labels, mask = _rand_scene(seed=3)
    planar = M.anchor_targets_compact_batched(
        anchors, boxes, labels, mask, config, planar_box_targets=True
    )
    plain = M.anchor_targets_compact_batched(
        anchors, boxes, labels, mask, config
    )
    assert planar.box_targets.shape == (
        plain.box_targets.shape[0], 4, plain.box_targets.shape[1]
    )
    np.testing.assert_array_equal(
        np.asarray(planar.state), np.asarray(plain.state)
    )
    np.testing.assert_array_equal(
        np.asarray(planar.matched_labels), np.asarray(plain.matched_labels)
    )
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(planar.box_targets), -2, -1),
        np.asarray(plain.box_targets),
        rtol=1e-6, atol=1e-7,
    )
