"""Checkpoint/resume tests (SURVEY.md §5.4 + ISSUE 11): bit-exact state
round trip, crash-safe torn-dir handling, the async writer contract, and
world-size-elastic ZeRO restore."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.parallel.zero import (
    _chunk,
    reshard_flat_leaf,
)
from batchai_retinanet_horovod_coco_tpu.train import create_train_state
from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
    CheckpointManager,
    latest_step,
    read_manifest,
    scan_checkpoints,
)


@pytest.fixture()
def small_state():
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=2, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, 64, 64, 3), jax.random.key(0)
    )
    return model, state


class TestCheckpointRoundTrip:
    def test_save_restore_bit_exact(self, tmp_path, small_state):
        model, state = small_state
        # Mutate so opt_state/step are non-trivial.
        grads = jax.tree.map(jnp.ones_like, state.params)
        state = state.apply_gradients(grads)

        mgr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
        assert mgr.save(state, step=1)
        mgr.wait()
        assert mgr.latest_step() == 1

        fresh = create_train_state(
            model, state.tx, (1, 64, 64, 3), jax.random.key(123)
        )
        restored = mgr.restore(fresh)
        mgr.close()

        assert int(restored.step) == 1
        jax.tree.map(
            np.testing.assert_array_equal, restored.params, state.params
        )
        jax.tree.map(
            np.testing.assert_array_equal, restored.opt_state, state.opt_state
        )

    def test_latest_step_empty_and_missing_restore(self, tmp_path, small_state):
        _, state = small_state
        mgr = CheckpointManager(str(tmp_path / "empty"))
        assert mgr.latest_step() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore(state)
        mgr.close()

    def test_save_interval_respected(self, tmp_path, small_state):
        _, state = small_state
        mgr = CheckpointManager(
            str(tmp_path / "ckpt"), save_interval_steps=10
        )
        assert mgr.save(state, step=10)
        assert not mgr.save(state, step=15)  # off-interval skipped
        assert mgr.save(state, step=20)
        mgr.close()
        assert latest_step(str(tmp_path / "ckpt")) == 20

    def test_max_to_keep_gcs_oldest(self, tmp_path, small_state):
        _, state = small_state
        mgr = CheckpointManager(
            str(tmp_path / "ckpt"), max_to_keep=2, save_interval_steps=1
        )
        for step in (1, 2, 3):
            assert mgr.save(state, step=step)
        mgr.close()
        assert [s for s, _ in scan_checkpoints(str(tmp_path / "ckpt"))] == [
            2, 3,
        ]


class TestCrashSafety:
    """The protocol's promise: any published dir is complete; anything
    torn is skipped to the previous complete checkpoint."""

    def _save_steps(self, tmp_path, state, steps):
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d, save_interval_steps=1, max_to_keep=10)
        for s in steps:
            assert mgr.save(state, step=s)
        mgr.close()
        return d

    def test_missing_manifest_skipped_to_previous(
        self, tmp_path, small_state, capfd
    ):
        model, state = small_state
        d = self._save_steps(tmp_path, state, [1, 2])
        os.unlink(os.path.join(d, "ckpt-2", "manifest.json"))
        assert latest_step(d) == 1
        fresh = create_train_state(
            model, state.tx, (1, 64, 64, 3), jax.random.key(7)
        )
        restored = CheckpointManager(d).restore(fresh)
        assert int(restored.step) == int(state.step)
        # The skip is silent in control flow but announced structurally.
        err = capfd.readouterr().err
        assert "ckpt_torn_skipped" in err

    def test_truncated_leaf_skipped(self, tmp_path, small_state):
        _, state = small_state
        d = self._save_steps(tmp_path, state, [1, 2])
        leaf = os.path.join(d, "ckpt-2", "leaf_00003.npy")
        with open(leaf, "r+b") as f:
            f.truncate(os.path.getsize(leaf) // 2)
        assert latest_step(d) == 1

    def test_stray_tmp_dir_invisible_and_gced(self, tmp_path, small_state):
        _, state = small_state
        d = self._save_steps(tmp_path, state, [1])
        # A kill mid-write leaves a .tmp dir: never restorable, pruned by
        # the next successful save's gc.
        os.makedirs(os.path.join(d, ".tmp-9-12345"))
        assert latest_step(d) == 1
        mgr = CheckpointManager(d, save_interval_steps=1)
        assert mgr.save(state, step=2)
        mgr.close()
        assert not os.path.exists(os.path.join(d, ".tmp-9-12345"))
        assert latest_step(d) == 2

    def test_async_writer_error_surfaces_at_wait(
        self, tmp_path, small_state, monkeypatch, capfd
    ):
        """The crash channel: a failing disk write is announced on stderr
        at failure time and re-raised in the training thread at the next
        wait()/save()/close() — never swallowed."""
        import batchai_retinanet_horovod_coco_tpu.utils.checkpoint as ckpt_mod

        _, state = small_state

        def boom(*a, **k):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_mod, "_write_step_dir", boom)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=True)
        assert mgr.save(state, step=1)
        with pytest.raises(RuntimeError, match="checkpoint write failed"):
            mgr.wait()
        assert "ckpt_write_error" in capfd.readouterr().err
        monkeypatch.undo()
        # The manager recovers once the fault clears.
        assert mgr.save(state, step=2, force=True)
        mgr.close()
        assert latest_step(str(tmp_path / "ckpt")) == 2

    def test_sync_escape_hatch(self, tmp_path, small_state, monkeypatch):
        _, state = small_state
        monkeypatch.setenv("RETINANET_ASYNC_CKPT", "0")
        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        assert mgr.save(state, step=1)
        # Synchronous: the checkpoint is durable before save() returns,
        # with no writer thread ever started.
        assert mgr._thread is None
        assert latest_step(str(tmp_path / "ckpt")) == 1
        mgr.close()

    def test_manifest_metadata_round_trip(self, tmp_path, small_state):
        _, state = small_state
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(
            d, metadata={"global_batch_size": 16, "data_seed": 3}
        )
        mgr.save(state, step=5, force=True)
        mgr.close()
        manifest = read_manifest(d)
        assert manifest["step"] == 5
        assert manifest["metadata"]["global_batch_size"] == 16
        assert manifest["metadata"]["data_seed"] == 3


def _tiny_tree():
    """A small params tree with sizes that do NOT divide evenly at any
    tested world size — the padding paths all exercise."""
    rng = np.random.default_rng(0)
    return {
        "w": rng.normal(size=(7, 3)).astype(np.float32),
        "b": rng.normal(size=(5,)).astype(np.float32),
    }


def _zero_layout(reference_opt_state, n):
    """The world-``n`` ZeRO storage of a replicated opt_state: every
    params-shaped leaf flattened + zero-padded to ``n * chunk`` (the
    parallel/zero.py storage rule), scalars untouched."""

    def lay(leaf):
        leaf = np.asarray(leaf)
        if leaf.ndim == 0:
            return leaf
        flat = leaf.reshape(-1)
        pad = n * _chunk(flat.size, n) - flat.size
        return np.pad(flat, (0, pad))

    return jax.tree.map(lay, reference_opt_state)


class TestElasticRestore:
    """ISSUE 11 acceptance: a ZeRO checkpoint saved at world size 4
    restores at world sizes 2 and 8 — and into the replicated layout
    (single-host pod recovery) — with optimizer state equal to the
    gathered (unsharded) reference."""

    def _state(self, opt_state, params=None, tx=None):
        from batchai_retinanet_horovod_coco_tpu.train.state import TrainState

        params = params if params is not None else _tiny_tree()
        return TrainState(
            step=jnp.asarray(3, jnp.int32),
            params=params,
            batch_stats={},
            opt_state=opt_state,
            tx=tx or optax.sgd(1e-2, momentum=0.9),
        )

    def _reference(self):
        tx = optax.sgd(1e-2, momentum=0.9)
        params = _tiny_tree()
        ref = tx.init(params)
        # Non-trivial momentum so equality is a real claim.
        rng = np.random.default_rng(1)
        ref = jax.tree.map(
            lambda l: rng.normal(size=np.shape(l)).astype(
                np.asarray(l).dtype
            )
            if np.ndim(l)
            else l,
            ref,
        )
        return tx, params, ref

    @pytest.mark.parametrize("target_world", [2, 8])
    def test_world4_restores_at_other_worlds(self, tmp_path, target_world):
        tx, params, ref = self._reference()
        saved_state = self._state(_zero_layout(ref, 4), params, tx)
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d)
        mgr.save(saved_state, step=3, force=True)
        mgr.wait()

        template = self._state(_zero_layout(ref, target_world), params, tx)
        restored = CheckpointManager(d).restore(template)
        mgr.close()
        assert int(restored.step) == 3
        expected = _zero_layout(ref, target_world)
        jax.tree.map(
            np.testing.assert_array_equal, restored.opt_state, expected
        )
        jax.tree.map(
            np.testing.assert_array_equal, restored.params, params
        )

    def test_world4_restores_replicated_single_host(self, tmp_path):
        tx, params, ref = self._reference()
        saved_state = self._state(_zero_layout(ref, 4), params, tx)
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d)
        mgr.save(saved_state, step=3, force=True)
        mgr.close()

        template = self._state(tx.init(params), params, tx)
        restored = CheckpointManager(d).restore(template)
        # The gathered reference, exactly — pod snapshot → one host.
        jax.tree.map(
            np.testing.assert_array_equal, restored.opt_state, ref
        )

    def test_replicated_restores_into_zero_world(self, tmp_path):
        tx, params, ref = self._reference()
        saved_state = self._state(ref, params, tx)
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d)
        mgr.save(saved_state, step=3, force=True)
        mgr.close()

        template = self._state(_zero_layout(ref, 8), params, tx)
        restored = CheckpointManager(d).restore(template)
        jax.tree.map(
            np.testing.assert_array_equal,
            restored.opt_state,
            _zero_layout(ref, 8),
        )

    def test_params_shape_mismatch_refuses(self, tmp_path):
        tx, params, ref = self._reference()
        d = str(tmp_path / "ckpt")
        mgr = CheckpointManager(d)
        mgr.save(self._state(ref, params, tx), step=1, force=True)
        mgr.close()
        other = {
            "w": np.zeros((9, 3), np.float32),
            "b": np.zeros((5,), np.float32),
        }
        template = self._state(tx.init(other), other, tx)
        # Both refusal paths are acceptable here: the params leaf's exact
        # shape check, or the opt-state leaf's nd-to-nd mismatch —
        # whichever flat-order iteration reaches first.
        with pytest.raises(ValueError, match="!= expected"):
            CheckpointManager(d).restore(template)


class TestReshardFlatLeaf:
    def test_truncation_of_real_data_refuses(self):
        with pytest.raises(ValueError, match="non-zero"):
            reshard_flat_leaf(
                np.arange(1, 13, dtype=np.float32), (10,), np.float32
            )

    def test_zero_padding_truncates_fine(self):
        src = np.pad(np.arange(1, 11, dtype=np.float32), (0, 2))
        out = reshard_flat_leaf(src, (10,), np.float32)
        np.testing.assert_array_equal(
            out, np.arange(1, 11, dtype=np.float32)
        )

    def test_dtype_mismatch_refuses(self):
        with pytest.raises(ValueError, match="dtype"):
            reshard_flat_leaf(np.zeros(4, np.float32), (4,), np.int32)

    def test_nd_to_nd_mismatch_refuses(self):
        with pytest.raises(ValueError, match="neither is a flat"):
            reshard_flat_leaf(
                np.zeros((2, 3), np.float32), (3, 2), np.float32
            )
