"""Checkpoint/resume tests (SURVEY.md §5.4): bit-exact state round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.models import RetinaNetConfig, build_retinanet
from batchai_retinanet_horovod_coco_tpu.train import create_train_state
from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
    CheckpointManager,
    latest_step,
)


@pytest.fixture()
def small_state():
    model = build_retinanet(
        RetinaNetConfig(
            num_classes=2, backbone="resnet_test", fpn_channels=16,
            head_width=16, head_depth=1, dtype=jnp.float32,
        )
    )
    state = create_train_state(
        model, optax.sgd(1e-2, momentum=0.9), (1, 64, 64, 3), jax.random.key(0)
    )
    return model, state


class TestCheckpointRoundTrip:
    def test_save_restore_bit_exact(self, tmp_path, small_state):
        model, state = small_state
        # Mutate so opt_state/step are non-trivial.
        grads = jax.tree.map(jnp.ones_like, state.params)
        state = state.apply_gradients(grads)

        mgr = CheckpointManager(str(tmp_path / "ckpt"), save_interval_steps=1)
        assert mgr.save(state, step=1)
        mgr.wait()
        assert mgr.latest_step() == 1

        fresh = create_train_state(
            model, state.tx, (1, 64, 64, 3), jax.random.key(123)
        )
        restored = mgr.restore(fresh)
        mgr.close()

        assert int(restored.step) == 1
        jax.tree.map(
            np.testing.assert_array_equal, restored.params, state.params
        )
        jax.tree.map(
            np.testing.assert_array_equal, restored.opt_state, state.opt_state
        )

    def test_latest_step_empty_and_missing_restore(self, tmp_path, small_state):
        _, state = small_state
        mgr = CheckpointManager(str(tmp_path / "empty"))
        assert mgr.latest_step() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore(state)
        mgr.close()

    def test_save_interval_respected(self, tmp_path, small_state):
        _, state = small_state
        mgr = CheckpointManager(
            str(tmp_path / "ckpt"), save_interval_steps=10
        )
        assert mgr.save(state, step=10)
        assert not mgr.save(state, step=15)  # off-interval skipped
        assert mgr.save(state, step=20)
        mgr.close()
        assert latest_step(str(tmp_path / "ckpt")) == 20
