"""Optimizer/schedule factory tests (reference LR rules, SURVEY.md M11/H1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.train.optim import (
    OptimizerConfig,
    make_optimizer,
    make_schedule,
    peak_lr,
)


class TestSchedule:
    def test_linear_scaling_rule_sgd(self):
        cfg = OptimizerConfig(base_lr=0.01, global_batch_size=256)
        assert peak_lr(cfg) == pytest.approx(0.01)
        cfg = OptimizerConfig(base_lr=0.01, global_batch_size=16)
        assert peak_lr(cfg) == pytest.approx(0.01 / 16)

    def test_adam_world_size_rule(self):
        # The reference's hvd.size() LR scaling (SURVEY.md call stack 3.2).
        cfg = OptimizerConfig(optimizer="adam", base_lr=1e-5, world_size=8)
        assert peak_lr(cfg) == pytest.approx(8e-5)

    def test_warmup_then_multistep(self):
        cfg = OptimizerConfig(
            base_lr=0.01,
            global_batch_size=256,
            warmup_steps=100,
            total_steps=1000,
            milestones=(0.5, 0.9),
        )
        s = make_schedule(cfg)
        assert float(s(0)) == pytest.approx(0.01 / 100, rel=1e-4)
        assert float(s(100)) == pytest.approx(0.01, rel=1e-4)
        assert float(s(499)) == pytest.approx(0.01, rel=1e-4)
        assert float(s(501)) == pytest.approx(0.001, rel=1e-4)
        assert float(s(901)) == pytest.approx(0.0001, rel=1e-4)

    def test_no_warmup(self):
        cfg = OptimizerConfig(
            base_lr=0.01, global_batch_size=256, warmup_steps=0,
            schedule="constant",
        )
        assert float(make_schedule(cfg)(0)) == pytest.approx(0.01)


class TestFreezeBackbone:
    def test_backbone_updates_zeroed(self):
        cfg = OptimizerConfig(
            freeze_backbone=True, warmup_steps=0, schedule="constant",
            global_batch_size=256, weight_decay=0.0,
        )
        tx, _ = make_optimizer(cfg)
        params = {
            "backbone": {"w": jnp.ones((3,))},
            "fpn": {"w": jnp.ones((3,))},
        }
        grads = jax.tree.map(jnp.ones_like, params)
        opt_state = tx.init(params)
        updates, _ = tx.update(grads, opt_state, params)
        np.testing.assert_array_equal(updates["backbone"]["w"], 0.0)
        assert float(jnp.abs(updates["fpn"]["w"]).sum()) > 0
