"""Optimizer/schedule factory tests (reference LR rules, SURVEY.md M11/H1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.train.optim import (
    OptimizerConfig,
    make_optimizer,
    make_schedule,
    peak_lr,
    plateau_scale,
)


class TestSchedule:
    def test_linear_scaling_rule_sgd(self):
        cfg = OptimizerConfig(base_lr=0.01, global_batch_size=256)
        assert peak_lr(cfg) == pytest.approx(0.01)
        cfg = OptimizerConfig(base_lr=0.01, global_batch_size=16)
        assert peak_lr(cfg) == pytest.approx(0.01 / 16)

    def test_adam_world_size_rule(self):
        # The reference's hvd.size() LR scaling (SURVEY.md call stack 3.2).
        cfg = OptimizerConfig(optimizer="adam", base_lr=1e-5, world_size=8)
        assert peak_lr(cfg) == pytest.approx(8e-5)

    def test_warmup_then_multistep(self):
        cfg = OptimizerConfig(
            base_lr=0.01,
            global_batch_size=256,
            warmup_steps=100,
            total_steps=1000,
            milestones=(0.5, 0.9),
        )
        s = make_schedule(cfg)
        assert float(s(0)) == pytest.approx(0.01 / 100, rel=1e-4)
        assert float(s(100)) == pytest.approx(0.01, rel=1e-4)
        assert float(s(499)) == pytest.approx(0.01, rel=1e-4)
        assert float(s(501)) == pytest.approx(0.001, rel=1e-4)
        assert float(s(901)) == pytest.approx(0.0001, rel=1e-4)

    def test_no_warmup(self):
        cfg = OptimizerConfig(
            base_lr=0.01, global_batch_size=256, warmup_steps=0,
            schedule="constant",
        )
        assert float(make_schedule(cfg)(0)) == pytest.approx(0.01)


class TestFreezeBackbone:
    def test_backbone_updates_zeroed(self):
        cfg = OptimizerConfig(
            freeze_backbone=True, warmup_steps=0, schedule="constant",
            global_batch_size=256, weight_decay=0.0,
        )
        tx, _ = make_optimizer(cfg)
        params = {
            "backbone": {"w": jnp.ones((3,))},
            "fpn": {"w": jnp.ones((3,))},
        }
        grads = jax.tree.map(jnp.ones_like, params)
        opt_state = tx.init(params)
        updates, _ = tx.update(grads, opt_state, params)
        np.testing.assert_array_equal(updates["backbone"]["w"], 0.0)
        assert float(jnp.abs(updates["fpn"]["w"]).sum()) > 0


class TestPlateau:
    """ReduceLROnPlateau parity (reference monitors loss, factor/patience)."""

    def _cfg(self, **kw):
        return OptimizerConfig(
            schedule="plateau", warmup_steps=0, global_batch_size=256,
            weight_decay=0.0, plateau_factor=0.1, plateau_patience=1,
            plateau_window=2, plateau_min_delta=1e-8, **kw,
        )

    def _run(self, losses):
        tx, _ = make_optimizer(self._cfg())
        params = {"w": jnp.ones((3,))}
        opt_state = tx.init(params)
        grads = {"w": jnp.ones((3,))}
        scales = []
        for v in losses:
            _, opt_state = tx.update(
                grads, opt_state, params, value=jnp.asarray(v, jnp.float32)
            )
            scales.append(plateau_scale(opt_state))
        return scales

    def test_flat_loss_reduces_scale(self):
        # window=2, patience=1: every flat window after the best is a
        # plateau, so the scale steps down by `factor` per window.
        scales = self._run([1.0] * 8)
        assert scales[0] == pytest.approx(1.0)
        reduced = [s for s in scales if s < 1.0]
        assert reduced and reduced[0] == pytest.approx(0.1)
        assert scales == sorted(scales, reverse=True)  # monotone decay

    def test_improving_loss_keeps_scale(self):
        scales = self._run([1.0, 0.9, 0.8, 0.7, 0.6, 0.5, 0.4, 0.3])
        assert scales[-1] == pytest.approx(1.0)

    def test_absolute_min_delta_semantics(self):
        # Keras parity regression: improvement is judged absolutely, not
        # relative to best_value.  At loss ~100 improving 0.005/window,
        # optax's default rtol=1e-4 (threshold 100*1e-4=0.01) would declare
        # a plateau and cut the LR; the absolute semantics must not.
        tx, _ = make_optimizer(self._cfg())
        params = {"w": jnp.ones((3,))}
        opt_state = tx.init(params)
        grads = {"w": jnp.ones((3,))}
        v = 100.0
        for _ in range(10):
            _, opt_state = tx.update(
                grads, opt_state, params, value=jnp.asarray(v, jnp.float32)
            )
            v -= 0.0025  # 0.005 improvement per window of 2
        assert plateau_scale(opt_state) == pytest.approx(1.0)

    def test_scale_none_without_plateau(self):
        tx, _ = make_optimizer(
            OptimizerConfig(schedule="constant", warmup_steps=0,
                            global_batch_size=256)
        )
        params = {"w": jnp.ones((3,))}
        assert plateau_scale(tx.init(params)) is None

    def test_apply_gradients_threads_loss_value(self):
        # The TrainState path: plateau state advances inside apply_gradients.
        from batchai_retinanet_horovod_coco_tpu.train.state import TrainState

        tx, _ = make_optimizer(self._cfg())
        params = {"w": jnp.ones((3,))}
        state = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, batch_stats={},
            opt_state=tx.init(params), tx=tx,
        )
        for _ in range(8):
            state = state.apply_gradients(
                {"w": jnp.ones((3,))}, loss_value=jnp.asarray(1.0)
            )
        assert plateau_scale(state.opt_state) < 1.0
        # Plain (non-extra-args) transforms still work without loss_value.
        import optax

        plain = TrainState(
            step=jnp.zeros((), jnp.int32), params=params, batch_stats={},
            opt_state=optax.sgd(0.1).init(params), tx=optax.sgd(0.1),
        )
        plain = plain.apply_gradients({"w": jnp.ones((3,))},
                                      loss_value=jnp.asarray(1.0))
        assert int(plain.step) == 1
