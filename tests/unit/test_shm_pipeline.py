"""Multiprocess shared-memory pipeline contracts (data/shm_pipeline.py).

The four promises the tentpole makes (ISSUE 1):
1. determinism PARITY: procs path emits bit-identical batches to the thread
   path for a fixed seed (the dispatch in ``build_pipeline`` is a pure
   performance choice, never a semantics choice);
2. a killed worker RAISES in the consumer quickly (bounded, well under the
   30 s contract) and leaves no orphan processes or /dev/shm segments;
3. a WEDGED (alive but stuck) worker trips ``worker_timeout`` rather than
   hanging forever;
4. ``close()`` reaps every child and unlinks every segment (no leaks under
   pytest), including in eval mode where the short final batch pads through
   the shm path exactly like the thread path.
"""

import dataclasses
import os
import signal
import time

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.data import (
    CocoDataset,
    PipelineConfig,
    TransformConfig,
    build_pipeline,
    make_synthetic_coco,
)

pytestmark = pytest.mark.skipif(
    not os.path.isdir("/dev/shm"), reason="needs POSIX shared memory"
)


@pytest.fixture(scope="module")
def synthetic_dataset(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("coco_shm"))
    ann = make_synthetic_coco(root, num_images=10, num_classes=3, seed=1)
    return CocoDataset(ann, image_dir=f"{root}/train")


def _config(**kw) -> PipelineConfig:
    base = dict(
        batch_size=2, buckets=((320, 320),), min_side=300, max_side=320,
        max_gt=8, num_workers=2, num_worker_procs=2, seed=7,
    )
    base.update(kw)
    return PipelineConfig(**base)


def _shm_leftovers() -> list[str]:
    return [f for f in os.listdir("/dev/shm") if f.startswith("bretshm")]


def _assert_reaped(pipe) -> None:
    assert all(p.exitcode is not None for p in pipe.processes), (
        "orphan worker processes after close()"
    )
    assert not _shm_leftovers(), "leaked /dev/shm segments after close()"


def test_procs_match_threads_bitwise(synthetic_dataset):
    """Same seed → byte-identical batches from both producers, including
    under the full random-transform augmentation path (the per-(seed,
    epoch, idx) RNG contract is what makes worker count irrelevant)."""
    cfg_threads = _config(num_worker_procs=0, transform=TransformConfig())
    cfg_procs = dataclasses.replace(cfg_threads, num_worker_procs=2)

    pipe_t = build_pipeline(synthetic_dataset, cfg_threads, train=True)
    it = iter(pipe_t)
    want = [next(it) for _ in range(4)]
    pipe_t.close()

    pipe_p = build_pipeline(synthetic_dataset, cfg_procs, train=True)
    got = [next(pipe_p) for _ in range(4)]
    pipe_p.close()

    for bt, bp in zip(want, got):
        for field in bt._fields:
            np.testing.assert_array_equal(
                getattr(bt, field), getattr(bp, field), err_msg=field
            )
    _assert_reaped(pipe_p)


def test_eval_covers_all_records_once_with_padding(synthetic_dataset):
    """Eval through the shm path: order-preserving, every record exactly
    once, final short batch padded to full size with valid=False rows."""
    cfg = _config(
        batch_size=4, hflip_prob=0.0, drop_remainder=False, shuffle=False
    )
    pipe = build_pipeline(synthetic_dataset, cfg, train=False)
    seen = []
    for batch in pipe:
        assert batch.images.shape[0] == 4  # padded to full batch
        seen.extend(batch.image_ids[batch.valid].tolist())
    assert sorted(seen) == sorted(
        r.image_id for r in synthetic_dataset.records
    )
    _assert_reaped(pipe)


def test_close_reaps_processes_and_unlinks_shm(synthetic_dataset):
    pipe = build_pipeline(synthetic_dataset, _config(), train=True)
    next(pipe)
    assert _shm_leftovers(), "expected live segments while running"
    pipe.close()
    _assert_reaped(pipe)
    pipe.close()  # idempotent


def test_killed_worker_raises_and_cleans_up(synthetic_dataset):
    """SIGKILL one worker mid-epoch: the consumer must see a raised
    exception within the 30 s contract (in practice <1 s via the liveness
    poll), with children reaped and segments unlinked by the time it
    propagates."""
    pipe = build_pipeline(synthetic_dataset, _config(), train=True)
    next(pipe)
    os.kill(pipe.processes[0].pid, signal.SIGKILL)
    deadline = time.monotonic() + 30
    with pytest.raises(RuntimeError, match="died unexpectedly"):
        while time.monotonic() < deadline:
            next(pipe)
        pytest.fail("worker death not surfaced within 30s")
    _assert_reaped(pipe)


def test_wedged_worker_trips_timeout(synthetic_dataset):
    """SIGSTOP the only worker: alive-but-stuck must trip worker_timeout
    (never a silent hang).  One worker so the stall is deterministic."""
    cfg = _config(num_worker_procs=1, worker_timeout=3.0)
    pipe = build_pipeline(synthetic_dataset, cfg, train=True)
    next(pipe)
    os.kill(pipe.processes[0].pid, signal.SIGSTOP)
    deadline = time.monotonic() + 30
    try:
        with pytest.raises(RuntimeError, match="stalled"):
            while time.monotonic() < deadline:
                next(pipe)
            pytest.fail("wedged worker not surfaced within 30s")
    finally:
        # SIGKILL works on a stopped process; cleanup must still reap it.
        _assert_reaped(pipe)


def test_worker_exception_propagates(tmp_path):
    """A decode error inside a worker re-raises in the consumer with the
    worker's traceback, instead of wedging the batch."""
    root = str(tmp_path)
    ann = make_synthetic_coco(root, num_images=4, num_classes=2, seed=3)
    ds = CocoDataset(ann, image_dir=f"{root}/train")
    os.remove(ds.image_path(ds.records[0]))  # poison one record
    cfg = _config(batch_size=2, shuffle=False)
    pipe = build_pipeline(ds, cfg, train=True)
    with pytest.raises(RuntimeError, match="worker"):
        for _ in range(4):
            next(pipe)
    _assert_reaped(pipe)
