"""Pascal VOC dataset source (keras-retinanet PascalVocGenerator parity).

Hand-built VOCdevkit tree: XML parsing (1-based coords), the canonical
20-class mapping, difficult-object routing to the ignore channel, and
pipeline plug-compatibility.
"""

import numpy as np
import pytest
from PIL import Image

from batchai_retinanet_horovod_coco_tpu.data import (
    VOC_CLASSES,
    PascalVocDataset,
    PipelineConfig,
    build_pipeline,
)


def obj_xml(name, xmin, ymin, xmax, ymax, difficult=0):
    return (
        f"<object><name>{name}</name><difficult>{difficult}</difficult>"
        f"<bndbox><xmin>{xmin}</xmin><ymin>{ymin}</ymin>"
        f"<xmax>{xmax}</xmax><ymax>{ymax}</ymax></bndbox></object>"
    )


def write_example(root, vid, size, objects):
    w, h = size
    (root / "Annotations" / f"{vid}.xml").write_text(
        f"<annotation><filename>{vid}.jpg</filename>"
        f"<size><width>{w}</width><height>{h}</height><depth>3</depth></size>"
        + "".join(objects)
        + "</annotation>"
    )
    rng = np.random.default_rng(abs(hash(vid)) % 2**32)
    Image.fromarray(
        rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
    ).save(root / "JPEGImages" / f"{vid}.jpg")


@pytest.fixture(scope="module")
def voc_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("VOC2007")
    for d in ("Annotations", "JPEGImages", "ImageSets/Main"):
        (root / d).mkdir(parents=True)
    write_example(
        root, "000001", (64, 48),
        [obj_xml("dog", 10, 11, 40, 41), obj_xml("person", 1, 1, 20, 20)],
    )
    write_example(
        root, "000002", (48, 64),
        [obj_xml("cat", 5, 5, 30, 30, difficult=1)],
    )
    write_example(root, "000003", (32, 32), [])
    (root / "ImageSets/Main/trainval.txt").write_text(
        "000001\n000002\n000003\n"
    )
    return root


def test_parse_and_class_mapping(voc_root):
    ds = PascalVocDataset(str(voc_root), split="trainval")
    assert ds.num_classes == 20
    assert ds.class_names == list(VOC_CLASSES)
    rec = ds.records[0]
    # 1-based → the reference subtracts 1 from all four coordinates.
    np.testing.assert_allclose(rec.boxes[0], [9, 10, 39, 40])
    assert rec.labels[0] == VOC_CLASSES.index("dog")
    assert rec.labels[1] == VOC_CLASSES.index("person")
    assert rec.width == 64 and rec.height == 48


def test_difficult_routed_to_ignore(voc_root):
    ds = PascalVocDataset(str(voc_root), split="trainval")
    # 000002 has ONLY a difficult object → no training boxes → dropped
    # unless keep_empty; with keep_empty it carries the ignore box.
    assert [r.file_name for r in ds.records] == ["000001.jpg"]
    ds = PascalVocDataset(str(voc_root), split="trainval", keep_empty=True)
    rec2 = next(r for r in ds.records if r.file_name == "000002.jpg")
    assert len(rec2.boxes) == 0
    assert len(rec2.crowd_boxes) == 1
    assert rec2.crowd_labels[0] == VOC_CLASSES.index("cat")


def test_skip_difficult(voc_root):
    ds = PascalVocDataset(
        str(voc_root), split="trainval", skip_difficult=True, keep_empty=True
    )
    rec2 = next(r for r in ds.records if r.file_name == "000002.jpg")
    assert len(rec2.boxes) == 0 and len(rec2.crowd_boxes) == 0


def test_unknown_class_rejected(voc_root, tmp_path):
    import shutil

    root = tmp_path / "voc"
    shutil.copytree(voc_root, root)
    write_example(root, "000009", (32, 32), [obj_xml("dragon", 1, 1, 10, 10)])
    (root / "ImageSets/Main/trainval.txt").write_text("000009\n")
    with pytest.raises(ValueError, match="unknown class"):
        PascalVocDataset(str(root), split="trainval")


def test_pipeline_compatibility(voc_root):
    ds = PascalVocDataset(str(voc_root), split="trainval", keep_empty=True)
    batches = build_pipeline(
        ds,
        PipelineConfig(
            batch_size=3, buckets=((96, 96),), min_side=64, max_side=96,
            max_gt=10, num_workers=2, shuffle=False,
        ),
        train=False,
    )
    batch = next(iter(batches))
    assert batch.images.shape == (3, 96, 96, 3)
    assert batch.gt_mask.sum() == 2  # only 000001's two real boxes
