import numpy as np

from batchai_retinanet_horovod_coco_tpu.ops.nms import (
    multiclass_nms,
    single_class_nms,
)


def numpy_greedy_nms(boxes, scores, iou_thresh):
    """Reference greedy NMS returning kept indices in score order."""
    order = np.argsort(-scores, kind="stable")
    keep = []
    suppressed = np.zeros(len(boxes), dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        for j in order:
            if suppressed[j] or j == i:
                continue
            bi, bj = boxes[i], boxes[j]
            ix1, iy1 = max(bi[0], bj[0]), max(bi[1], bj[1])
            ix2, iy2 = min(bi[2], bj[2]), min(bi[3], bj[3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            ai = (bi[2] - bi[0]) * (bi[3] - bi[1])
            aj = (bj[2] - bj[0]) * (bj[3] - bj[1])
            iou = inter / (ai + aj - inter) if ai + aj - inter > 0 else 0.0
            if iou > iou_thresh and scores[j] < scores[i]:
                suppressed[j] = True
    return keep


def test_single_class_matches_numpy_oracle():
    rng = np.random.default_rng(2)
    n = 60
    xy = rng.uniform(0, 80, size=(n, 2))
    wh = rng.uniform(5, 40, size=(n, 2))
    boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
    scores = rng.uniform(0.01, 1.0, size=n).astype(np.float32)

    sel, valid = single_class_nms(boxes, scores, iou_threshold=0.5, max_output=n)
    got = [int(i) for i, v in zip(np.asarray(sel), np.asarray(valid)) if v]
    expected = numpy_greedy_nms(boxes, scores, 0.5)
    assert got == expected


def test_single_class_simple_suppression():
    boxes = np.array(
        [[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]], dtype=np.float32
    )
    scores = np.array([0.9, 0.8, 0.7], dtype=np.float32)
    sel, valid = single_class_nms(boxes, scores, iou_threshold=0.5, max_output=3)
    got = [int(i) for i, v in zip(np.asarray(sel), np.asarray(valid)) if v]
    assert got == [0, 2]  # box 1 suppressed by box 0


def test_multiclass_keeps_classes_separate():
    # Identical boxes, different classes: both survive (class-masked NMS).
    boxes = np.array([[0, 0, 10, 10], [0, 0, 10, 10]], dtype=np.float32)
    scores = np.array([[0.9, 0.0], [0.0, 0.8]], dtype=np.float32)
    det = multiclass_nms(boxes, scores, score_threshold=0.05, max_detections=10)
    valid = np.asarray(det.valid)
    assert valid.sum() == 2
    labels = sorted(np.asarray(det.labels)[valid].tolist())
    assert labels == [0, 1]


def test_multiclass_score_threshold_and_order():
    boxes = np.array(
        [[0, 0, 10, 10], [20, 20, 30, 30], [40, 40, 50, 50]], dtype=np.float32
    )
    scores = np.array(
        [[0.9, 0.0], [0.02, 0.0], [0.0, 0.5]], dtype=np.float32
    )  # middle box below 0.05 threshold
    det = multiclass_nms(boxes, scores, score_threshold=0.05, max_detections=10)
    valid = np.asarray(det.valid)
    assert valid.sum() == 2
    s = np.asarray(det.scores)[valid]
    assert np.all(np.diff(s) <= 0)  # descending
    np.testing.assert_allclose(s, [0.9, 0.5], atol=1e-6)


def test_multiclass_fixed_output_shape():
    boxes = np.zeros((100, 4), dtype=np.float32)
    scores = np.zeros((100, 3), dtype=np.float32)
    det = multiclass_nms(boxes, scores, max_detections=25)
    assert det.boxes.shape == (25, 4)
    assert det.scores.shape == (25,)
    assert det.labels.shape == (25,)
    assert not np.any(np.asarray(det.valid))


def test_multiclass_flagship_coords_vs_per_class_oracle():
    """Exact per-class NMS at flagship-scale coordinates and high class ids.

    Guards the regime the old class-offset trick got wrong: 80 classes with
    coordinates up to 1333 px, where offsetting class-79 boxes by 79e4 put
    them at f32 ulp ~0.06 px and borderline IoU decisions could flip.  The
    oracle here runs true per-class greedy NMS on the RAW coordinates, with
    near-threshold IoU pairs crafted in, and must match exactly.
    """
    rng = np.random.default_rng(7)
    num_classes = 80
    per_class = 6
    boxes_list, scores_rows = [], []
    for c in range(num_classes):
        # Clustered boxes per class so many pairs sit near the 0.5 threshold.
        base_xy = rng.uniform(0, 1200, size=(per_class, 2))
        jitter = rng.uniform(-8, 8, size=(per_class, 2))
        xy = np.clip(base_xy[0] + jitter, 0, 1300)
        wh = rng.uniform(20, 120, size=(per_class, 2))
        b = np.concatenate([xy, xy + wh], axis=1)
        boxes_list.append(b)
        row = np.zeros((per_class, num_classes))
        row[:, c] = rng.uniform(0.1, 1.0, size=per_class)
        scores_rows.append(row)
    boxes = np.concatenate(boxes_list).astype(np.float32)
    scores = np.concatenate(scores_rows).astype(np.float32)

    det = multiclass_nms(
        boxes, scores, score_threshold=0.05, iou_threshold=0.5, max_detections=480
    )
    valid = np.asarray(det.valid)
    # Scores pass through the device path ungathered-unmodified, so the
    # survivors' (label, score) pairs must match the oracle's bit-exactly.
    got = sorted(
        zip(
            np.asarray(det.labels)[valid].tolist(),
            np.asarray(det.scores)[valid].tolist(),
        )
    )

    expected = []
    for c in range(num_classes):
        cls_mask = scores[:, c] > 0.05
        idx = np.flatnonzero(cls_mask)
        keep = numpy_greedy_nms(boxes[idx], scores[idx, c], 0.5)
        expected.extend((c, float(scores[idx[i], c])) for i in keep)
    assert got == sorted(expected)


def test_batched_nms_accepts_kwargs():
    from batchai_retinanet_horovod_coco_tpu.ops.nms import batched_multiclass_nms

    boxes = np.zeros((2, 10, 4), dtype=np.float32)
    boxes[:, :, 2:] = 10.0
    scores = np.full((2, 10, 3), 0.2, dtype=np.float32)
    det = batched_multiclass_nms(
        boxes, scores, score_threshold=0.3, max_detections=5
    )
    assert det.boxes.shape == (2, 5, 4)
    assert not np.any(np.asarray(det.valid))  # all below threshold 0.3
