"""serve/stream.py — streaming video sessions over the slot pool (ISSUE 18).

Families:

- **TrackStitcher**: stable ids across moving boxes, the category gate,
  miss-based aging, deterministic greedy matching.
- **Session contract** (stub engine): monotonic seq enforcement,
  per-stream in-flight cap (``stream_backlogged``), in-order delivery
  with a cache hit queued behind an in-flight miss, explicit close, the
  session cap, idle reaping on the injectable clock.
- **Frame-delta cache**: hit/miss counters + bytes saved, scene cuts
  forcing misses, reference-frame convergence under slow drift, and
  ``delta_threshold=0`` disabling the cache entirely.
- **Mixed clients**: long-lived streams + one-shot single-image traffic
  on the SAME server — neither class starves (the SlotPool satellite).
- **Bit-identity** (PARITY §5.19): with the cache off, the stream path
  serves byte-identical detections to sequential single-image serving —
  pinned on the stub AND on the live tiny model at score_threshold
  0.001.
- **Fleet affinity**: frames route to the pinned replica; killing it
  mid-stream re-pins with exactly one ``stream_repinned`` event and
  zero dropped in-flight frames.
- **Arrivals** (the shared bench helper): same seed ⇒ byte-identical
  schedule; per-stream frame times are sorted and non-negative.
"""

from __future__ import annotations

import io
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.serve import (
    DetectionServer,
    FleetConfig,
    FleetRouter,
    LocalReplica,
    RequestRejected,
    ServeConfig,
    StreamConfig,
    StreamManager,
    TrackStitcher,
)
from batchai_retinanet_horovod_coco_tpu.serve.stub import (
    StubDetectEngine,
    drift_frames,
)
from batchai_retinanet_horovod_coco_tpu.utils.arrivals import (
    diurnal_spike_schedule,
    mixed_arrival_schedule,
    multi_stream_schedule,
)
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy


def make_server(engine=None, **cfg) -> DetectionServer:
    cfg.setdefault("max_delay_ms", 10)
    cfg.setdefault("preprocess_workers", 1)
    return DetectionServer(
        engine or StubDetectEngine(video=True), ServeConfig(**cfg)
    )


def _frame(value: int, hw=(64, 64)) -> np.ndarray:
    return np.full((hw[0], hw[1], 3), value, np.uint8)


def _submit_all(mgr, sid, frames, timeout_s=30.0):
    """Replay ``frames`` in order, retrying ``stream_backlogged`` (the
    designed per-stream in-flight cap — a real client paces itself the
    same way).  Returns the resolved detections per frame."""
    futs = []
    for seq, fr in enumerate(frames):
        while True:
            try:
                futs.append(mgr.submit_frame(sid, seq, fr))
                break
            except RequestRejected as exc:
                if exc.reason != "stream_backlogged":
                    raise
                time.sleep(0.002)
    return [f.result(timeout=timeout_s) for f in futs]


def _strip(dets: list[dict]) -> list[dict]:
    return [{k: v for k, v in d.items() if k != "track_id"} for d in dets]


# ---- TrackStitcher (host-side, no server) --------------------------------


class TestTrackStitcher:
    def test_stable_id_across_moving_box(self):
        st = TrackStitcher(iou_threshold=0.3)
        a = [{"category_id": 0, "bbox": [10.0, 10.0, 20.0, 20.0], "score": 0.9}]
        st.update(a)
        assert a[0]["track_id"] == 0
        # Shifted but still overlapping: same track.
        b = [{"category_id": 0, "bbox": [13.0, 12.0, 20.0, 20.0], "score": 0.9}]
        st.update(b)
        assert b[0]["track_id"] == 0
        assert st.live_tracks == 1

    def test_category_gate_never_continues_other_class(self):
        st = TrackStitcher(iou_threshold=0.3)
        a = [{"category_id": 0, "bbox": [10.0, 10.0, 20.0, 20.0], "score": 0.9}]
        st.update(a)
        # Identical box, different category: a fresh track, not id 0.
        b = [{"category_id": 1, "bbox": [10.0, 10.0, 20.0, 20.0], "score": 0.9}]
        st.update(b)
        assert b[0]["track_id"] == 1

    def test_track_ages_out_and_id_never_reused(self):
        st = TrackStitcher(iou_threshold=0.3, max_misses=2)
        a = [{"category_id": 0, "bbox": [10.0, 10.0, 20.0, 20.0], "score": 0.9}]
        st.update(a)
        for _ in range(3):  # misses 1, 2, then 3 > max_misses → dropped
            st.update([])
        assert st.live_tracks == 0
        # The box returns: it gets a NEW id — ids are never recycled.
        b = [{"category_id": 0, "bbox": [10.0, 10.0, 20.0, 20.0], "score": 0.9}]
        st.update(b)
        assert b[0]["track_id"] == 1

    def test_greedy_matching_is_deterministic(self):
        def run():
            st = TrackStitcher(iou_threshold=0.1)
            f0 = [
                {"category_id": 0, "bbox": [0.0, 0.0, 10.0, 10.0], "score": 0.9},
                {"category_id": 0, "bbox": [20.0, 20.0, 10.0, 10.0], "score": 0.8},
            ]
            st.update(f0)
            f1 = [
                {"category_id": 0, "bbox": [21.0, 21.0, 10.0, 10.0], "score": 0.8},
                {"category_id": 0, "bbox": [1.0, 1.0, 10.0, 10.0], "score": 0.9},
            ]
            st.update(f1)
            return [d["track_id"] for d in f1]

        assert run() == run() == [1, 0]


# ---- session contract ----------------------------------------------------


class TestSessionContract:
    def test_out_of_order_seq_sheds_without_advancing(self):
        with make_server() as srv:
            mgr = StreamManager(srv)
            try:
                sid = mgr.open_stream()["session"]
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame(sid, 3, _frame(50))
                assert ei.value.reason == "stream_out_of_order"
                # The reject did NOT consume seq 0: in-order still works.
                dets = mgr.submit_frame(sid, 0, _frame(50)).result(timeout=30)
                assert dets and all("track_id" in d for d in dets)
            finally:
                mgr.close()

    def test_backlogged_stream_sheds_at_inflight_cap(self):
        # 200ms device time and a 1-frame cap: the second immediate
        # submit must shed rather than queue unboundedly.
        engine = StubDetectEngine(video=True, delay_s=0.2)
        with make_server(engine) as srv:
            mgr = StreamManager(srv, StreamConfig(max_inflight=1))
            try:
                sid = mgr.open_stream()["session"]
                mgr.submit_frame(sid, 0, _frame(50))
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame(sid, 1, _frame(50))
                assert ei.value.reason == "stream_backlogged"
            finally:
                mgr.close()

    def test_cache_hit_resolves_in_order_behind_inflight_miss(self):
        # Frame 1 is an immediate cache hit on admission, but frame 0's
        # miss is still on the (200ms-slow) device — the hit must wait
        # and then serve the MISS's freshly-stitched detections.
        engine = StubDetectEngine(video=True, delay_s=0.2)
        with make_server(engine) as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.0))
            try:
                sid = mgr.open_stream()["session"]
                f0 = mgr.submit_frame(sid, 0, _frame(50))
                f1 = mgr.submit_frame(sid, 1, _frame(50))
                assert not f0.cache_hit and f1.cache_hit
                d1 = f1.result(timeout=30)
                d0 = f0.result(timeout=30)
                assert d1 == d0  # the hit's payload IS the miss's result
                assert all("track_id" in d for d in d0)
            finally:
                mgr.close()

    def test_unknown_and_closed_sessions_reject(self):
        with make_server() as srv:
            mgr = StreamManager(srv)
            try:
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame("nope", 0, _frame(50))
                assert ei.value.reason == "unknown_stream"
                sid = mgr.open_stream()["session"]
                mgr.submit_frame(sid, 0, _frame(50)).result(timeout=30)
                summary = mgr.close_stream(sid)
                assert summary["frames"] == 1
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame(sid, 1, _frame(50))
                assert ei.value.reason == "unknown_stream"
            finally:
                mgr.close()

    def test_session_cap_sheds_with_stream_limit(self):
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(max_streams=1))
            try:
                mgr.open_stream()
                with pytest.raises(RequestRejected) as ei:
                    mgr.open_stream()
                assert ei.value.reason == "stream_limit"
            finally:
                mgr.close()

    def test_idle_session_reaped_on_injectable_clock(self):
        clock = [0.0]
        with make_server() as srv:
            mgr = StreamManager(
                srv, StreamConfig(idle_timeout_s=5.0), now_fn=lambda: clock[0]
            )
            try:
                sid = mgr.open_stream()["session"]
                mgr.submit_frame(sid, 0, _frame(50)).result(timeout=30)
                # Not idle long enough: survives.
                clock[0] = 4.0
                mgr.reap_idle()
                assert sid in mgr.status()["streams"]
                # Past the timeout: reaped (the delivery thread races the
                # explicit call on the same clock — either path retires).
                clock[0] = 6.0
                mgr.reap_idle()
                deadline = time.monotonic() + 5.0
                while sid in mgr.status()["streams"]:
                    assert time.monotonic() < deadline, "session never reaped"
                    time.sleep(0.01)
                assert mgr.status()["reaped"] == 1
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame(sid, 1, _frame(50))
                assert ei.value.reason == "unknown_stream"
            finally:
                mgr.close()


# ---- frame-delta cache ---------------------------------------------------


class TestDeltaCache:
    def test_hits_misses_and_bytes_counted(self):
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.0))
            try:
                sid = mgr.open_stream()["session"]
                futs = [
                    mgr.submit_frame(sid, i, _frame(50)) for i in range(4)
                ]
                results = [f.result(timeout=30) for f in futs]
                assert [f.cache_hit for f in futs] == [
                    False, True, True, True,
                ]
                assert results[1:] == [results[0]] * 3
                status = mgr.status()
                assert status["cache_hits"] == 3
                assert status["cache_misses"] == 1
                assert status["cache_bytes_saved"] == 3 * 64 * 64 * 3
            finally:
                mgr.close()

    def test_scene_cut_forces_miss_and_breaks_tracks(self):
        frames = drift_frames(seed=7, n=12, step=0.2, cut_every=6)
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.0))
            try:
                sid = mgr.open_stream()["session"]
                results = _submit_all(mgr, sid, frames)
                status = mgr.status()["streams"][sid]
                # Hits on the drift plateaus; the cut at frame 6 (mean
                # jump ≥ 30) forces a device pass.
                assert status["cache_hits"] >= 1
                assert status["cache_misses"] >= 2
                # The cut's new brightness moves the boxes: fresh tracks.
                ids_before = {d["track_id"] for d in results[0]}
                ids_after = {d["track_id"] for d in results[6]}
                assert ids_before.isdisjoint(ids_after)
            finally:
                mgr.close()

    def test_slow_drift_converges_via_reference_frame(self):
        # Per-frame delta (1.0) is under the threshold, but the diff is
        # taken against the last DISPATCHED frame, so drift accumulates
        # and must eventually force a real pass.
        frames = [_frame(50 + i) for i in range(8)]
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.5))
            try:
                sid = mgr.open_stream()["session"]
                _submit_all(mgr, sid, frames)
                status = mgr.status()
                assert status["cache_hits"] >= 2
                assert status["cache_misses"] >= 3  # drift kept re-crossing
            finally:
                mgr.close()

    def test_threshold_zero_disables_cache(self):
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=0.0))
            try:
                sid = mgr.open_stream()["session"]
                futs = [
                    mgr.submit_frame(sid, i, _frame(50)) for i in range(3)
                ]
                [f.result(timeout=30) for f in futs]
                assert not any(f.cache_hit for f in futs)
                assert mgr.status()["cache_hits"] == 0
            finally:
                mgr.close()


# ---- mixed long-lived + one-shot clients (the SlotPool satellite) --------


class TestMixedClients:
    def test_streams_and_singles_share_the_pool_without_starvation(self):
        engine = StubDetectEngine(batch_sizes=(4,), video=True, delay_s=0.01)
        with make_server(engine, max_delay_ms=5) as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.0))
            try:
                n_frames, n_singles = 24, 12
                frames = drift_frames(seed=1, n=n_frames, step=1.0,
                                      cut_every=8)
                stream_out: dict = {}
                errors: list[BaseException] = []

                # watchdog: test-local load generator, joined below.
                def stream_client():
                    try:
                        sid = mgr.open_stream()["session"]
                        stream_out["results"] = _submit_all(mgr, sid, frames)
                        stream_out["stats"] = mgr.close_stream(sid)
                    except BaseException as exc:
                        errors.append(exc)

                t = threading.Thread(target=stream_client, daemon=True)
                t.start()
                singles = [
                    srv.submit(_frame(40 + i)) for i in range(n_singles)
                ]
                single_results = [f.result(timeout=60) for f in singles]
                t.join(timeout=60)
                assert not t.is_alive() and not errors
                # Neither class starved: every frame AND every one-shot
                # resolved.
                assert len(stream_out["results"]) == n_frames
                assert stream_out["stats"]["frames"] == n_frames
                assert len(single_results) == n_singles
                assert all(single_results)
                # In-order per-stream release: frame i's tracks can only
                # use ids minted by frames ≤ i (monotonic mint order).
                max_seen = -1
                for dets in stream_out["results"]:
                    ids = [d["track_id"] for d in dets]
                    assert ids, "video stub always yields boxes"
                    max_seen = max(max_seen, max(ids))
                    assert max(ids) <= max_seen
            finally:
                mgr.close()


# ---- bit-identity with the cache off (PARITY §5.19) ----------------------


class TestBitIdentity:
    def test_stream_cache_off_matches_single_image_path_stub(self):
        frames = drift_frames(seed=11, n=8, step=3.0, cut_every=3)
        with make_server() as srv:
            single = [srv.submit(fr).result(timeout=30) for fr in frames]
            mgr = StreamManager(srv, StreamConfig(delta_threshold=0.0))
            try:
                sid = mgr.open_stream()["session"]
                streamed = _submit_all(mgr, sid, frames)
            finally:
                mgr.close()
        # track_id is the ONLY field stitching adds; stripped, the
        # payloads are byte-identical.
        assert [_strip(d) for d in streamed] == single

    def test_stream_cache_off_bit_identical_live_model(
        self, tiny_model_and_state
    ):
        """PARITY §5.19 on the real compiled path: an uncacheable stream
        (delta_threshold 0) over the live tiny model serves exactly what
        sequential single-image submission serves — same program, same
        resize, same conversion; score_threshold 0.001 keeps the oracle
        non-vacuous on the untrained head."""
        from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
            DetectConfig,
        )
        from batchai_retinanet_horovod_coco_tpu.serve import DetectEngine

        model, state = tiny_model_and_state
        cfg = DetectConfig(
            score_threshold=0.001, pre_nms_size=64, max_detections=10
        )
        engine = DetectEngine.from_state(
            model, state, buckets=((64, 64),), batch_sizes=(2,), config=cfg,
            min_side=64, max_side=64,
        )
        frames = drift_frames(seed=5, n=4, step=8.0, cut_every=2)
        with DetectionServer(
            engine, ServeConfig(max_delay_ms=50, preprocess_workers=1)
        ) as srv:
            single = [srv.submit(fr).result(timeout=120) for fr in frames]
            assert any(single), "no detections anywhere (vacuous parity)"
            mgr = StreamManager(srv, StreamConfig(delta_threshold=0.0))
            try:
                sid = mgr.open_stream(width=64, height=64)["session"]
                streamed = _submit_all(mgr, sid, frames, timeout_s=120.0)
            finally:
                mgr.close()
        assert [_strip(d) for d in streamed] == single


# ---- fleet session affinity ----------------------------------------------


EXACT_BACKOFF = BackoffPolicy(
    max_tries=1_000_000, base_s=1.0, multiplier=2.0, ceiling_s=8.0,
    jitter=0.0,
)


class _SinkSpy:
    def __init__(self):
        self.events: list[tuple[str, dict]] = []

    def event(self, kind: str, **fields) -> None:
        self.events.append((kind, fields))


def _make_fleet(n=2, sink=None):
    servers = [
        DetectionServer(
            StubDetectEngine(video=True),
            ServeConfig(max_delay_ms=5, preprocess_workers=1),
            replica_id=f"r{k}",  # in-process replicas share host-pid
        )
        for k in range(n)
    ]
    router = FleetRouter(
        [LocalReplica(s) for s in servers],
        FleetConfig(probe_backoff=EXACT_BACKOFF, poll_interval_s=0.05),
        sink=sink,
        auto_poll=False,
    )
    return router, servers


class TestFleetAffinity:
    def test_frames_route_to_pinned_replica(self):
        router, servers = _make_fleet()
        try:
            opened = router.stream_open(width=64, height=64)
            sid = opened["session"]
            for seq in range(6):
                dets, _hit = router.stream_frame(sid, seq, _frame(50))
                assert dets
            # Every frame landed on the pinned replica's stream manager;
            # the other replica never saw a session (LocalReplica exposes
            # the lazily-created manager).
            frames_by_replica = {
                st.replica.replica_id:
                    st.replica.stream_manager.status()["frames"]
                for st in router._states
            }
            assert frames_by_replica[opened["replica_id"]] == 6
            others = [
                v for k, v in frames_by_replica.items()
                if k != opened["replica_id"]
            ]
            assert all(v == 0 for v in others)
            router.stream_close(sid)
        finally:
            # close_replicas reaches the LocalReplicas' lazily-attached
            # StreamManagers — closing the bare servers does not, and the
            # delivery threads outlive the test (caught by TestDrain's
            # thread-enumeration assert when file order shuffles).
            router.close(close_replicas=True)
            for s in servers:
                s.close()

    def test_replica_death_repins_once_with_zero_dropped_frames(self):
        sink = _SinkSpy()
        router, servers = _make_fleet(sink=sink)
        try:
            opened = router.stream_open(width=64, height=64)
            sid = opened["session"]
            results = []
            for seq in range(10):
                dets, _hit = router.stream_frame(sid, seq, _frame(60))
                results.append(dets)
            # Kill the pinned replica mid-stream and let the poller open
            # its breaker.
            by_id = {s.replica_id: s for s in servers}
            by_id[opened["replica_id"]].close()
            router.poll_once(now=100.0)
            # Every subsequent frame still serves: the router re-pins to
            # the survivor and re-opens a backend session there.
            for seq in range(10, 20):
                dets, _hit = router.stream_frame(sid, seq, _frame(60))
                results.append(dets)
            assert len(results) == 20 and all(results)
            repins = [e for e in sink.events if e[0] == "stream_repinned"]
            assert len(repins) == 1
            assert repins[0][1]["stream"] == sid
            assert repins[0][1]["to_replica"] != opened["replica_id"]
            assert router.status()["stream_repins"] == 1
        finally:
            # close_replicas reaches the LocalReplicas' lazily-attached
            # StreamManagers — closing the bare servers does not, and the
            # delivery threads outlive the test (caught by TestDrain's
            # thread-enumeration assert when file order shuffles).
            router.close(close_replicas=True)
            for s in servers:
                s.close()


# ---- seeded arrival schedules (the shared bench helper) ------------------


class TestArrivals:
    def test_mixed_schedule_deterministic_per_seed(self):
        a = mixed_arrival_schedule(64, base_rate=50.0, seed=3)
        b = mixed_arrival_schedule(64, base_rate=50.0, seed=3)
        assert a == b  # byte-identical, not merely close
        assert a != mixed_arrival_schedule(64, base_rate=50.0, seed=4)
        assert all(t1 > t0 for t0, t1 in zip(a, a[1:]))

    def test_diurnal_spike_schedule_deterministic_per_seed(self):
        a = diurnal_spike_schedule(256, base_rate=20.0, seed=7)
        b = diurnal_spike_schedule(256, base_rate=20.0, seed=7)
        assert a == b  # byte-identical, not merely close
        assert a != diurnal_spike_schedule(256, base_rate=20.0, seed=8)
        assert all(t1 > t0 for t0, t1 in zip(a, a[1:]))

    def test_diurnal_spike_window_densifies_arrivals(self):
        times = diurnal_spike_schedule(
            4000, base_rate=50.0, seed=11, period_s=10.0,
            amplitude=0.0, spikes=((0.5, 0.2, 4.0),),
        )
        # With the sinusoid flattened, arrival density inside the spike
        # window (period fractions [0.4, 0.6]) must dominate an equal-
        # width off-peak window — the 4x multiplier is visible.
        frac = [(t % 10.0) / 10.0 for t in times]
        in_spike = sum(1 for f in frac if 0.4 <= f <= 0.6)
        off_peak = sum(1 for f in frac if 0.7 <= f <= 0.9)
        assert in_spike > 2 * off_peak

    def test_diurnal_amplitude_bounds_rejected(self):
        with pytest.raises(ValueError, match="amplitude"):
            diurnal_spike_schedule(8, base_rate=10.0, amplitude=1.0)

    def test_multi_stream_schedule_deterministic_and_ordered(self):
        a = multi_stream_schedule(3, 20, fps=30.0, seed=9)
        b = multi_stream_schedule(3, 20, fps=30.0, seed=9)
        assert a == b
        assert a != multi_stream_schedule(3, 20, fps=30.0, seed=10)
        for times in a:
            assert len(times) == 20
            assert times == sorted(times)
            assert all(t >= 0.0 for t in times)


# ---- pipelined-admission races (REVIEW regressions) ----------------------


def _stalling_frame(value, started, release, hw=(64, 64)):
    """A frame whose ``astype`` blocks until ``release`` — pins the
    submitting thread inside ``_admit``'s delta computation, AFTER its
    seq is consumed but BEFORE its entry reaches the delivery queue, so
    tests can interleave a later frame (or the reaper) deterministically
    in that window."""

    class _Stalling(np.ndarray):
        def astype(self, *args, **kwargs):
            started.set()
            release.wait(10.0)
            return np.asarray(self).astype(*args, **kwargs)

    return _frame(value, hw).view(_Stalling)


class TestPipelinedAdmission:
    def test_cache_hit_never_overtakes_frame_still_in_admission(self):
        """A pipelined cache hit (frame 2) finishing admission while the
        previous frame (1) is still mid-``_admit`` must NOT jump the
        delivery queue: strict per-stream order, and the hit's payload is
        the frame-1 miss's detections, not stale frame-0 ones."""
        with make_server() as srv:
            mgr = StreamManager(srv, StreamConfig(delta_threshold=2.0))
            started, release = threading.Event(), threading.Event()
            try:
                sid = mgr.open_stream()["session"]
                mgr.submit_frame(sid, 0, _frame(50)).result(timeout=30)
                holder: dict = {}

                # watchdog: test-local submitter, joined below.
                def submit_stalled():
                    try:
                        holder["fut"] = mgr.submit_frame(
                            sid, 1, _stalling_frame(80, started, release)
                        )
                    except BaseException as exc:
                        holder["err"] = exc

                t = threading.Thread(target=submit_stalled, daemon=True)
                t.start()
                assert started.wait(10.0)
                # Frame 2: pixel-identical to frame 0's reference → an
                # immediate cache hit, admitted while frame 1 stalls.
                f2 = mgr.submit_frame(sid, 2, _frame(50))
                assert f2.cache_hit
                time.sleep(0.1)
                assert not f2.done(), "hit delivered ahead of frame 1"
                release.set()
                t.join(timeout=10)
                assert "err" not in holder
                f1 = holder["fut"]
                d1 = f1.result(timeout=30)
                d2 = f2.result(timeout=30)
                assert not f1.cache_hit
                # In-order delivery means the hit serves the most recent
                # MISS's detections (frame 1's), not frame 0's.
                assert d2 == d1
            finally:
                release.set()
                mgr.close()

    def test_reaper_defers_while_admission_in_progress(self):
        """A session that LOOKS idle (empty queue, stale last_active) but
        has a frame mid-admission must not be reaped out from under the
        submit — pre-fix the slipped entry's future hung forever."""
        clock = [0.0]
        with make_server() as srv:
            mgr = StreamManager(
                srv,
                StreamConfig(delta_threshold=2.0, idle_timeout_s=5.0),
                now_fn=lambda: clock[0],
            )
            started, release = threading.Event(), threading.Event()
            try:
                sid = mgr.open_stream()["session"]
                mgr.submit_frame(sid, 0, _frame(50)).result(timeout=30)
                holder: dict = {}

                # watchdog: test-local submitter, joined below.
                def submit_stalled():
                    try:
                        holder["fut"] = mgr.submit_frame(
                            sid, 1, _stalling_frame(80, started, release)
                        )
                    except BaseException as exc:
                        holder["err"] = exc

                t = threading.Thread(target=submit_stalled, daemon=True)
                t.start()
                assert started.wait(10.0)
                clock[0] = 10.0  # idle_timeout_s exceeded mid-admission
                assert mgr.reap_idle() == []
                assert sid in mgr.status()["streams"]
                release.set()
                t.join(timeout=10)
                assert "err" not in holder
                assert holder["fut"].result(timeout=10)
                # With the admission finished the session reaps normally
                # (the delivery thread races the explicit call).
                clock[0] = 20.0
                mgr.reap_idle()
                deadline = time.monotonic() + 5.0
                while sid in mgr.status()["streams"]:
                    assert time.monotonic() < deadline, "never reaped"
                    time.sleep(0.01)
                with pytest.raises(RequestRejected) as ei:
                    mgr.submit_frame(sid, 2, _frame(50))
                assert ei.value.reason == "unknown_stream"
            finally:
                release.set()
                mgr.close()


# ---- fleet edge/backend seq lockstep (REVIEW regressions) ----------------


class TestFleetSeqLockstep:
    def test_post_admission_shed_does_not_wedge_stream(self):
        """decode_error is raised AFTER the backend consumed the frame's
        seq: the edge must advance its backend_seq in lockstep — pre-fix
        every later frame shed ``stream_out_of_order`` forever."""
        router, servers = _make_fleet()
        try:
            sid = router.stream_open(width=64, height=64)["session"]
            dets, _hit = router.stream_frame(sid, 0, _frame(50))
            assert dets
            with pytest.raises(RequestRejected) as ei:
                router.stream_frame(sid, 1, b"not an image")
            assert ei.value.reason == "decode_error"
            for seq in range(2, 6):
                dets, _hit = router.stream_frame(sid, seq, _frame(50))
                assert dets
        finally:
            # close_replicas reaches the LocalReplicas' lazily-attached
            # StreamManagers — closing the bare servers does not, and the
            # delivery threads outlive the test (caught by TestDrain's
            # thread-enumeration assert when file order shuffles).
            router.close(close_replicas=True)
            for s in servers:
                s.close()

    def test_seq_drift_resyncs_by_reopening_backend_session(self):
        """Residual edge/backend seq drift (an ambiguous transport
        timeout) surfaces as a backend ``stream_out_of_order`` — the edge
        treats it as a resync signal and re-opens the backend session on
        the same replica instead of wedging the stream."""
        router, servers = _make_fleet()
        try:
            sid = router.stream_open(width=64, height=64)["session"]
            for seq in range(3):
                dets, _hit = router.stream_frame(sid, seq, _frame(60))
                assert dets
            with router._lock:
                pin = router._streams[sid]
            pin.backend_seq -= 1  # edge now one behind the backend
            for seq in range(3, 8):
                dets, _hit = router.stream_frame(sid, seq, _frame(60))
                assert dets
        finally:
            # close_replicas reaches the LocalReplicas' lazily-attached
            # StreamManagers — closing the bare servers does not, and the
            # delivery threads outlive the test (caught by TestDrain's
            # thread-enumeration assert when file order shuffles).
            router.close(close_replicas=True)
            for s in servers:
                s.close()


# ---- HTTP stream header hardening (REVIEW regression) --------------------


class TestHttpStreamHeaders:
    def test_malformed_frame_header_is_400_not_dropped_connection(self):
        from batchai_retinanet_horovod_coco_tpu.serve import serve_http

        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(_frame(50)).save(buf, "PNG")
        png = buf.getvalue()
        pre_existing = {
            t for t in threading.enumerate()
            if t.name == "serve-stream-delivery"
        }
        with make_server() as srv:
            httpd = serve_http(srv)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            host, port = httpd.server_address
            base = f"http://{host}:{port}"
            try:
                req = urllib.request.Request(
                    f"{base}/stream/open", data=b"{}", method="POST"
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    sid = json.load(r)["session"]
                req = urllib.request.Request(
                    f"{base}/stream/frame", data=png, method="POST",
                    headers={
                        "X-Retinanet-Stream": sid,
                        "X-Retinanet-Frame": "not-a-number",
                    },
                )
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=30)
                assert ei.value.code == 400
                assert json.load(ei.value)["reason"] == "decode_error"
                # The session survived the bad request: frame 0 serves.
                req = urllib.request.Request(
                    f"{base}/stream/frame", data=png, method="POST",
                    headers={
                        "X-Retinanet-Stream": sid,
                        "X-Retinanet-Frame": "0",
                    },
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    assert r.status == 200
                    out = json.load(r)
                    assert out["frame"] == 0 and out["detections"]
            finally:
                httpd.shutdown()
                httpd.server_close()
        # server_close() owns the stream manager: no delivery thread may
        # outlive the standard shutdown()/server_close() teardown.
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            leaked = [
                t for t in threading.enumerate()
                if t.name == "serve-stream-delivery" and t.is_alive()
                and t not in pre_existing
            ]
            if not leaked:
                break
            time.sleep(0.05)
        assert not leaked
