"""Numerics flight recorder tests (ISSUE 10, obs/numerics.py).

The checklist, pinned:

- the in-step summary's metrics exist, are finite on a healthy step, and
  the update ratio matches the hand-computed ||new − old|| / ||new||;
- the disabled path is structurally free: the numerics-off step's
  metrics dict carries NO summary keys (same keys as pre-ISSUE-10);
- the pre-clip grad_norm metric equals a reference value_and_grad
  global norm, and ``clip_by_global_norm_precomputed`` is equivalent to
  ``optax.clip_by_global_norm`` with and without the precomputed norm;
- injected-NaN provenance: the abort lands ONE NUMERICS_DUMP.json
  naming the first non-finite layer + the batch source ids, without any
  rerun;
- the cadence boundary: a NaN appearing BETWEEN finite-checks is caught
  at the NEXT cadence step — never silently trained past it;
- pre-save gate and cadence check share the abort path (a poisoned
  state writes the dump AND never reaches disk);
- the cross-replica agreement probe: controlled per-device values give
  the exact min/max ratio; a mesh train step reports it;
- the built-in SLO rules: nonfinite fires EXACTLY ONCE and immediately,
  grad-norm-spike uses the regression baseline;
- ``debug.py nans`` is a thin driver over load_dump/format_dump.
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
from batchai_retinanet_horovod_coco_tpu.models import (
    RetinaNetConfig,
    build_retinanet,
)
from batchai_retinanet_horovod_coco_tpu.obs import numerics, telemetry, trace
from batchai_retinanet_horovod_coco_tpu.obs.numerics import NumericsConfig
from batchai_retinanet_horovod_coco_tpu.train import create_train_state
from batchai_retinanet_horovod_coco_tpu.train.loop import (
    LoopConfig,
    run_training,
)
from batchai_retinanet_horovod_coco_tpu.train.step import make_train_step

HW = (64, 64)
NUM_CLASSES = 3
BATCH = 4


@pytest.fixture(autouse=True)
def _clean_obs_state():
    telemetry.reset()
    trace.reset()
    yield
    telemetry.reset()
    trace.reset()


def tiny_model():
    return build_retinanet(
        RetinaNetConfig(
            num_classes=NUM_CLASSES, backbone="resnet_test",
            fpn_channels=16, head_width=16, head_depth=1,
            dtype=jnp.float32,
        )
    )


def fresh_state(model, seed=0, lr=1e-3):
    return create_train_state(
        model, optax.sgd(lr, momentum=0.9), (1, *HW, 3),
        jax.random.key(seed),
    )


def make_batch(rng_seed=0, nan=False):
    rng = np.random.default_rng(rng_seed)
    images = rng.normal(0, 1, (BATCH, *HW, 3)).astype(np.float32)
    if nan:
        images[0, 0, 0, 0] = np.nan
    return {
        "images": jnp.asarray(images),
        "gt_boxes": jnp.asarray(
            np.tile(np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                    (BATCH, 1, 1))
        ),
        "gt_labels": jnp.ones((BATCH, 1), jnp.int32),
        "gt_mask": jnp.ones((BATCH, 1), bool),
    }


def batch_stream(nan_at_step=None, seed=0):
    rng = np.random.default_rng(seed)
    i = 0
    while True:
        i += 1
        images = rng.normal(0, 1, (BATCH, *HW, 3)).astype(np.float32)
        if nan_at_step is not None and i == nan_at_step:
            images[0, 0, 0, 0] = np.nan
        yield Batch(
            images=images,
            gt_boxes=np.tile(
                np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                (BATCH, 1, 1),
            ),
            gt_labels=np.ones((BATCH, 1), np.int32),
            gt_mask=np.ones((BATCH, 1), bool),
            image_ids=np.arange(BATCH, dtype=np.int64) + i * 100,
            scales=np.ones((BATCH,), np.float32),
            valid=np.ones((BATCH,), bool),
        )


class TestInStepSummary:
    def test_summary_keys_present_and_update_ratio_exact(self):
        model = tiny_model()
        state = fresh_state(model)
        step = make_train_step(
            model, HW, NUM_CLASSES, donate_state=False,
            numerics=NumericsConfig(enabled=True),
        )
        new_state, metrics = step(state, make_batch())
        for key in ("grad_norm", "update_ratio", "nonfinite_grads"):
            assert key in metrics
        groups = {k for k in metrics if k.startswith("gnorm/")}
        assert groups == {
            "gnorm/backbone", "gnorm/fpn", "gnorm/cls_head",
            "gnorm/box_head",
        }
        assert float(metrics["nonfinite_grads"]) == 0.0
        # Hand-computed ratio from the actual param trees.
        diff_sq = sum(
            float(jnp.sum(jnp.square(n - o)))
            for n, o in zip(
                jax.tree.leaves(new_state.params),
                jax.tree.leaves(state.params),
            )
        )
        expected = np.sqrt(diff_sq) / float(metrics["param_norm"])
        assert float(metrics["update_ratio"]) == pytest.approx(
            expected, rel=1e-4
        )

    def test_disabled_path_adds_no_keys(self):
        """The pre-ISSUE-10 metric vocabulary is unchanged with numerics
        off — the gate is compile-time, not a runtime branch."""
        model = tiny_model()
        step = make_train_step(model, HW, NUM_CLASSES, donate_state=False)
        _, metrics = step(fresh_state(model), make_batch())
        assert set(metrics) == {
            "loss", "cls_loss", "box_loss", "num_pos", "grad_norm",
            "param_norm",
        }

    def test_grad_norm_matches_reference(self):
        """The recorded pre-clip norm equals an independent global_norm
        of the raw gradients (the clip shares it, never recomputes)."""
        from batchai_retinanet_horovod_coco_tpu.train.step import (
            _forward_and_loss,
        )
        from batchai_retinanet_horovod_coco_tpu import losses as losses_lib
        from batchai_retinanet_horovod_coco_tpu.ops import (
            anchors as anchors_lib,
            matching as matching_lib,
        )

        model = tiny_model()
        state = fresh_state(model)
        batch = make_batch()
        step = make_train_step(
            model, HW, NUM_CLASSES, donate_state=False,
            numerics=NumericsConfig(enabled=True),
        )
        _, metrics = step(state, batch)
        anchors = jnp.asarray(
            anchors_lib.anchors_for_image_shape(
                HW, anchors_lib.AnchorConfig()
            )
        )
        _, grads = jax.value_and_grad(
            lambda p: _forward_and_loss(
                model, state, p, batch["images"], batch["gt_boxes"],
                batch["gt_labels"], batch["gt_mask"], anchors,
                losses_lib.LossConfig(pallas_focal=False),
                matching_lib.MatchingConfig(fused_pallas=False),
                train=True,
            )[0],
            has_aux=False,
        )(state.params)
        assert float(metrics["grad_norm"]) == pytest.approx(
            float(optax.global_norm(grads)), rel=1e-5
        )

    def test_nonfinite_count_detects_poison(self):
        model = tiny_model()
        step = make_train_step(
            model, HW, NUM_CLASSES, donate_state=False,
            numerics=NumericsConfig(enabled=True),
        )
        _, metrics = step(fresh_state(model), make_batch(nan=True))
        assert float(metrics["nonfinite_grads"]) > 0
        assert not np.isfinite(float(metrics["loss"]))


class TestPrecomputedClip:
    def test_equivalent_to_optax_clip(self):
        from batchai_retinanet_horovod_coco_tpu.train.optim import (
            clip_by_global_norm_precomputed,
        )

        grads = {"w": jnp.array([3.0, 4.0]), "b": jnp.zeros(2)}  # norm 5
        for max_norm in (1.0, 10.0):  # clipping engaged / not engaged
            ref, _ = optax.clip_by_global_norm(max_norm).update(
                grads, optax.EmptyState()
            )
            mine = clip_by_global_norm_precomputed(max_norm)
            got_implicit, _ = mine.update(grads, optax.EmptyState())
            got_explicit, _ = mine.update(
                grads, optax.EmptyState(),
                grad_norm=optax.global_norm(grads),
            )
            for got in (got_implicit, got_explicit):
                jax.tree.map(
                    np.testing.assert_allclose, got, ref
                )

    def test_make_optimizer_chain_consumes_grad_norm(self):
        """The unmasked production chain (clip + sgd + plateau) forwards
        grad_norm and clips by the SUPPLIED value (the proof it consumes
        the precomputed one, not a recomputation)."""
        from batchai_retinanet_horovod_coco_tpu.train.optim import (
            OptimizerConfig,
            make_optimizer,
        )

        cfg = OptimizerConfig(
            optimizer="sgd", schedule="plateau", warmup_steps=0,
            total_steps=10, clip_global_norm=1.0,
            momentum=0.0, weight_decay=0.0,
        )
        tx, _ = make_optimizer(cfg)
        params = {"head": jnp.array([3.0, 4.0])}
        opt_state = tx.init(params)
        grads = {"head": jnp.array([3.0, 4.0])}  # true norm 5
        updates, _ = tx.update(
            grads, opt_state, params,
            value=jnp.asarray(1.0), grad_norm=jnp.asarray(10.0),  # a lie
        )
        got = np.abs(np.asarray(updates["head"]))
        lr = cfg.base_lr * cfg.global_batch_size / 256.0
        np.testing.assert_allclose(  # scaled by 1/10, not 1/5
            got, np.array([0.3, 0.4]) * lr, rtol=1e-5
        )

    def test_freeze_masked_chain_ignores_full_tree_norm(self):
        """Review-round regression pin: under --freeze-backbone the clip
        inside multi_transform sees only the trained SUBTREE, so the
        step's full-tree grad_norm must be IGNORED — forwarding it would
        clip trained params by a norm inflated with frozen-backbone
        gradients (a silent effective-LR collapse)."""
        from batchai_retinanet_horovod_coco_tpu.train.optim import (
            OptimizerConfig,
            make_optimizer,
        )

        cfg = OptimizerConfig(
            optimizer="sgd", warmup_steps=0, total_steps=10,
            freeze_backbone=True, clip_global_norm=1.0,
            momentum=0.0, weight_decay=0.0, schedule="constant",
        )
        tx, _ = make_optimizer(cfg)
        params = {
            "backbone": jnp.full((4,), 100.0), "head": jnp.array([0.1, 0.12])
        }
        opt_state = tx.init(params)
        # Huge frozen gradient, tiny trained one: the full-tree norm is
        # ~200 while the trained subtree's is ~0.16 (below the clip).
        grads = {
            "backbone": jnp.full((4,), 100.0),
            "head": jnp.array([0.1, 0.12]),
        }
        full_norm = optax.global_norm(grads)
        updates, _ = tx.update(
            grads, opt_state, params, grad_norm=full_norm
        )
        np.testing.assert_allclose(np.asarray(updates["backbone"]), 0.0)
        # Reference: the stock optax clip over the trained subtree only
        # (no clipping engages at norm 0.16 < 1.0) — the pre-ISSUE-10
        # semantics the freeze path must keep.
        lr = cfg.base_lr * cfg.global_batch_size / 256.0
        np.testing.assert_allclose(
            np.abs(np.asarray(updates["head"])),
            np.array([0.1, 0.12]) * lr,
            rtol=1e-5,
        )


class TestProvenance:
    def test_injected_nan_writes_dump_with_layer_and_ids(self, tmp_path):
        model = tiny_model()
        with pytest.raises(FloatingPointError, match="provenance dump"):
            run_training(
                model, fresh_state(model), batch_stream(nan_at_step=2),
                NUM_CLASSES,
                LoopConfig(
                    total_steps=4, log_every=1, numerics=True,
                    numerics_dump_dir=str(tmp_path), rng_seed=7,
                ),
            )
        dump = json.loads(
            (tmp_path / "NUMERICS_DUMP.json").read_text()
        )
        assert dump["step"] == 2
        assert dump["tripped"]["metric"] == "loss"
        # NaN images poison everything downstream: the first non-finite
        # layer in forward order is in the backbone (the stem).
        assert "backbone" in str(dump["first_nonfinite"])
        # Step 2's batch fed the trip (ids are 100*step + i).
        assert dump["batch_image_ids"] == [200, 201, 202, 203]
        assert dump["rng_seed"] == 7
        assert dump["forward"]["nonfinite_layers"] > 0

    def test_cadence_boundary_catches_at_next_check(self, monkeypatch):
        """A NaN appearing BETWEEN checks (step 2; cadence 4) trains
        through AT MOST until the next cadence step, where it aborts —
        never silently past it (the recorded ISSUE-10 satellite)."""
        from batchai_retinanet_horovod_coco_tpu.train import loop as loop_mod

        monkeypatch.setattr(loop_mod, "_FINITE_CHECK_EVERY", 4)
        model = tiny_model()
        with pytest.raises(
            FloatingPointError, match="at or before step 4"
        ):
            run_training(
                model, fresh_state(model), batch_stream(nan_at_step=2),
                NUM_CLASSES,
                LoopConfig(total_steps=50, log_every=0),
            )

    def test_pre_save_gate_dumps_and_never_checkpoints(self, tmp_path):
        """Both the ISSUE-10 satellite pins in one scenario: the
        pre-save check goes through the SAME abort path (dump written)
        and the poisoned state never reaches disk."""
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            latest_step,
        )

        model = tiny_model()
        state = create_train_state(
            model, optax.sgd(float("inf")), (1, *HW, 3), jax.random.key(0)
        )
        ckpt_dir = str(tmp_path / "ckpt")
        dump_dir = str(tmp_path / "obs")
        with pytest.raises(FloatingPointError):
            run_training(
                model, state, batch_stream(), NUM_CLASSES,
                LoopConfig(
                    total_steps=10, log_every=0, checkpoint_every=1,
                    checkpoint_dir=ckpt_dir, numerics_dump_dir=dump_dir,
                ),
            )
        assert latest_step(ckpt_dir) is None
        dump = json.loads(
            open(os.path.join(dump_dir, "NUMERICS_DUMP.json")).read()
        )
        # LR=inf poisons the params via the update: param_norm trips.
        assert dump["tripped"]["metric"] == "param_norm"
        assert dump["params"]["nonfinite_total"] > 0

    def test_forward_provenance_clean_and_poisoned(self):
        model = tiny_model()
        state = fresh_state(model)
        variables = {"params": state.params}
        clean = numerics.forward_provenance(
            model, variables, make_batch()["images"]
        )
        assert clean["nonfinite_layers"] == 0
        assert clean["first_nonfinite_layer"] is None
        poisoned = numerics.forward_provenance(
            model, variables, make_batch(nan=True)["images"]
        )
        assert poisoned["nonfinite_layers"] > 0
        assert "backbone" in poisoned["first_nonfinite_layer"]

    def test_first_nonfinite_scalar_root_cause_order(self):
        hit = numerics.first_nonfinite_scalar(
            {"loss": float("nan"), "cls_loss": float("nan"), "lr": 0.1}
        )
        assert hit[0] == "cls_loss"  # more specific than the total
        assert numerics.first_nonfinite_scalar({"loss": 1.0}) is None

    def test_dump_format_and_debug_cli(self, tmp_path, capsys):
        import sys

        dump = {
            "step": 7,
            "tripped": {"metric": "loss", "value": float("nan")},
            "first_nonfinite": "['backbone']['stem_conv']",
            "batch_image_ids": [1, 2],
            "rng_seed": 0,
            "metrics": {"loss": float("nan"), "num_pos": 3.0},
            "params": {
                "nonfinite_total": 5,
                "entries": {
                    "['backbone']['stem_conv']['kernel']": {
                        "size": 10, "nonfinite": 5, "nan": 5, "inf": 0,
                    }
                },
            },
        }
        text = numerics.format_dump(dump)
        assert "step 7" in text
        assert "stem_conv" in text
        assert "batch image ids: 1, 2" in text
        path = tmp_path / "NUMERICS_DUMP.json"
        numerics.write_dump(dump, str(tmp_path))
        assert path.exists()
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        if repo_root not in sys.path:
            sys.path.insert(0, repo_root)
        from debug import main as debug_main

        out = debug_main(["nans", str(path)])
        assert out[0]["step"] == 7
        assert "stem_conv" in capsys.readouterr().out


class TestReplicaAgreement:
    def test_controlled_values_exact_ratio(self):
        from functools import partial

        from jax.sharding import PartitionSpec as P

        from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
        from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
            DATA_AXIS,
        )
        from batchai_retinanet_horovod_coco_tpu.parallel.shmap import (
            shard_map,
        )

        mesh = make_mesh(8)
        norms = jnp.array([1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 4.0])

        @partial(
            shard_map, mesh=mesh, in_specs=(P(DATA_AXIS),),
            out_specs=P(DATA_AXIS), check_vma=False,
        )
        def probe(n):
            return jnp.reshape(
                numerics.replica_agreement(n[0], DATA_AXIS), (1,)
            )

        out = np.asarray(probe(norms))
        np.testing.assert_allclose(out, 0.25, rtol=1e-6)

    def test_mesh_train_step_reports_agreement(self):
        from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh

        model = tiny_model()
        state = fresh_state(model)
        rng = np.random.default_rng(0)
        b8 = {
            "images": jnp.asarray(
                rng.normal(0, 1, (8, *HW, 3)).astype(np.float32)
            ),
            "gt_boxes": jnp.asarray(
                np.tile(
                    np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                    (8, 1, 1),
                )
            ),
            "gt_labels": jnp.ones((8, 1), jnp.int32),
            "gt_mask": jnp.ones((8, 1), bool),
        }
        step = make_train_step(
            model, HW, NUM_CLASSES, mesh=make_mesh(8), donate_state=False,
            numerics=NumericsConfig(enabled=True),
        )
        _, metrics = step(state, b8)
        agreement = float(metrics["replica_agreement"])
        assert 0.0 < agreement <= 1.0


class TestSloRules:
    def test_nonfinite_fires_exactly_once_and_immediately(self):
        from batchai_retinanet_horovod_coco_tpu.obs import slo
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            Registry,
        )

        reg = Registry()
        monitor = slo.SloMonitor(reg, [slo.nonfinite_rule()])
        telemetry.enable()
        counter = reg.counter("train_nonfinite_total", "")
        assert monitor.check_once(now=0.0) == []  # healthy: no metric yet
        counter.inc(3.0)
        fired = monitor.check_once(now=1.0)
        assert [v["rule"] for v in fired] == ["train-nonfinite"]
        # Latched: the (monotonic) counter keeps the breach alive, so no
        # second fire over the rest of the run.
        assert monitor.check_once(now=2.0) == []
        assert monitor.check_once(now=100.0) == []

    def test_record_nonfinite_trip_feeds_the_rule(self):
        from batchai_retinanet_horovod_coco_tpu.obs import slo

        telemetry.enable()
        telemetry.record_nonfinite_trip("loss")
        monitor = slo.SloMonitor(telemetry.default(), [slo.nonfinite_rule()])
        fired = monitor.check_once(now=0.0)
        assert len(fired) == 1 and fired[0]["rule"] == "train-nonfinite"

    def test_grad_norm_spike_regression_mode(self):
        from batchai_retinanet_horovod_coco_tpu.obs import slo
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            Registry,
        )

        reg = Registry()
        telemetry.enable()
        gauge = reg.gauge("train_grad_norm", "")
        rule = slo.grad_norm_spike(factor=10.0, window=8)
        monitor = slo.SloMonitor(reg, [rule])
        for i in range(6):  # build the healthy baseline (median ~2)
            gauge.set(2.0 + 0.01 * i)
            assert monitor.check_once(now=float(i)) == []
        gauge.set(50.0)  # 25x the median
        fired = monitor.check_once(now=10.0)
        assert [v["rule"] for v in fired] == ["grad-norm-spike"]

    def test_record_numerics_sets_gauges_and_counts(self):
        telemetry.enable()
        telemetry.record_numerics(
            grad_norm=2.5, update_ratio=1e-3, nonfinite=0.0,
            replica_agreement=0.9,
        )
        snap = telemetry.default().snapshot()
        assert snap["train_grad_norm"] == 2.5
        assert snap["train_update_ratio"] == 1e-3
        assert snap["train_replica_agreement"] == 0.9
        assert "train_nonfinite_total" not in snap  # zero = no incident
        telemetry.record_numerics(nonfinite=4.0)
        assert (
            telemetry.default().snapshot()["train_nonfinite_total"] == 4.0
        )

    def test_record_sites_noop_while_disabled(self):
        telemetry.record_numerics(grad_norm=1.0, nonfinite=9.0)
        telemetry.record_nonfinite_trip("loss")
        assert telemetry.default().snapshot().get("train_grad_norm") is None
        assert (
            telemetry.default().snapshot().get("train_nonfinite_total")
            is None
        )


class TestAnalyzerNumerics:
    def _events_file(self, tmp_path, records):
        path = tmp_path / "metrics.jsonl"
        lines = [json.dumps({"event": "run_header", "run_id": "abc"})]
        lines += [json.dumps(r) for r in records]
        path.write_text("\n".join(lines) + "\n")
        return str(path)

    def test_numerics_section_and_divergence_rank_one(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
            analyze_events,
            validate_report,
        )

        events = [
            {"ph": "X", "name": "step", "ts": 0, "dur": 1000, "pid": 1,
             "tid": 1},
            {"ph": "i", "name": "numerics_trip", "ts": 900,
             "args": {"metric": "loss", "step": 3}},
            {"ph": "i", "name": "slo_violation", "ts": 950,
             "args": {"rule": "train-nonfinite",
                      "metric": "train_nonfinite_total", "value": 1.0,
                      "threshold": 0.0, "sustained_s": 0.0}},
        ]
        records = [
            {"event": "numerics", "step": 2, "grad_norm": 2.0,
             "update_ratio": 1e-3, "nonfinite_grads": 0.0},
            {"event": "numerics", "step": 3, "grad_norm": 7.0,
             "update_ratio": 2e-3, "nonfinite_grads": 5.0},
            {"event": "numerics_trip", "metric": "loss", "step": 3,
             "value": float("nan")},
        ]
        dump_path = tmp_path / "NUMERICS_DUMP.json"
        dump_path.write_text(json.dumps({
            "step": 3,
            "first_nonfinite": "['backbone']['stem_conv']",
            "tripped": {"metric": "loss", "value": None},
        }))
        report = analyze_events(
            events,
            events_path=self._events_file(tmp_path, records),
            dump_path=str(dump_path),
        )
        assert validate_report(report) == []
        num = report["numerics"]
        assert num["available"]
        assert num["records"] == 2
        assert num["grad_norm"]["max"] == 7.0
        assert num["nonfinite_total"] == 5.0
        assert num["trips"]["count"] == 1
        assert num["dump"]["first_nonfinite"] == (
            "['backbone']['stem_conv']"
        )
        # The divergence verdict outranks the slo:* verdict AND the
        # inferred device_step bottleneck.
        names = [b["name"] for b in report["bottlenecks"]]
        assert names[0] == "numerics:divergence"
        assert any(n.startswith("slo:") for n in names[1:])
        assert report["bottlenecks"][0]["rank"] == 1

    def test_healthy_run_has_no_divergence_verdict(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.obs.analyze import (
            analyze_events,
        )

        events = [
            {"ph": "X", "name": "step", "ts": 0, "dur": 1000, "pid": 1,
             "tid": 1},
        ]
        records = [
            {"event": "numerics", "step": 2, "grad_norm": 2.0,
             "update_ratio": 1e-3, "nonfinite_grads": 0.0},
        ]
        report = analyze_events(
            events, events_path=self._events_file(tmp_path, records)
        )
        assert report["numerics"]["available"]
        assert report["numerics"]["trips"]["count"] == 0
        assert not any(
            b["name"].startswith("numerics:")
            for b in report["bottlenecks"]
        )


class TestTreeHelpers:
    def test_tree_report_localizes_first_leaf(self):
        tree = {
            "backbone": {"w": jnp.array([1.0, float("nan")])},
            "fpn": {"w": jnp.array([float("inf"), 2.0])},
        }
        rep = numerics.tree_report(tree)
        assert rep["nonfinite_total"] == 2
        assert "backbone" in rep["first_nonfinite"]
        entry = rep["entries"][rep["first_nonfinite"]]
        assert entry["nan"] == 1 and entry["inf"] == 0

    def test_tree_all_finite(self):
        assert numerics.tree_all_finite({"a": jnp.ones(3)})
        assert not numerics.tree_all_finite(
            {"a": jnp.array([1.0, float("nan")])}
        )
