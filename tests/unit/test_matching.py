import numpy as np

from batchai_retinanet_horovod_coco_tpu.ops.matching import (
    IGNORE,
    NEGATIVE,
    POSITIVE,
    MatchingConfig,
    anchor_targets,
    assign_anchors,
)


def test_pos_neg_ignore_thresholds():
    """Crafted scene hitting all three states exactly (SURVEY.md §4.1)."""
    gt = np.array([[0, 0, 10, 10]], dtype=np.float32)
    mask = np.array([True])
    anchors = np.array(
        [
            [0, 0, 10, 10],  # IoU 1.0 → positive
            [0, 0, 10, 8],  # IoU 0.8 → positive
            [0, 0, 10, 4.5],  # IoU 0.45 → ignore
            [0, 5, 10, 16.5],  # IoU ~0.318 → negative (below 0.4)
            [50, 50, 60, 60],  # IoU 0 → negative
        ],
        dtype=np.float32,
    )
    out = assign_anchors(anchors, gt, mask, MatchingConfig(force_match_best=False))
    np.testing.assert_array_equal(
        np.asarray(out.state), [POSITIVE, POSITIVE, IGNORE, NEGATIVE, NEGATIVE]
    )
    assert np.all(np.asarray(out.matched_gt)[:2] == 0)


def test_force_match_rescues_low_iou_gt():
    # gt overlaps best anchor at IoU 0.45 (< 0.5): without force-match no
    # positives; with it, that anchor becomes positive.
    gt = np.array([[0, 0, 10, 9]], dtype=np.float32)
    mask = np.array([True])
    anchors = np.array([[0, 0, 10, 20], [30, 30, 40, 40]], dtype=np.float32)
    no_force = assign_anchors(anchors, gt, mask, MatchingConfig(force_match_best=False))
    assert not np.any(np.asarray(no_force.state) == POSITIVE)
    forced = assign_anchors(anchors, gt, mask, MatchingConfig(force_match_best=True))
    assert np.asarray(forced.state)[0] == POSITIVE
    assert np.asarray(forced.matched_gt)[0] == 0


def test_empty_gt_all_negative():
    gt = np.zeros((3, 4), dtype=np.float32)
    mask = np.zeros(3, dtype=bool)
    anchors = np.array([[0, 0, 10, 10], [5, 5, 20, 20]], dtype=np.float32)
    out = assign_anchors(anchors, gt, mask)
    np.testing.assert_array_equal(np.asarray(out.state), [NEGATIVE, NEGATIVE])


def test_padded_gt_never_matches():
    gt = np.array([[0, 0, 10, 10], [0, 0, 300, 300]], dtype=np.float32)
    mask = np.array([True, False])  # second row is padding despite huge box
    anchors = np.array([[0, 0, 300, 300]], dtype=np.float32)
    out = assign_anchors(anchors, gt, mask, MatchingConfig(force_match_best=False))
    # Anchor overlaps the padded row perfectly but must not match it.
    assert np.asarray(out.state)[0] != POSITIVE or np.asarray(out.matched_gt)[0] == 0


def test_anchor_targets_dense_outputs():
    gt = np.array([[0, 0, 10, 10], [20, 20, 40, 40]], dtype=np.float32)
    labels = np.array([3, 7], dtype=np.int32)
    mask = np.array([True, True])
    anchors = np.array(
        [[0, 0, 10, 10], [20, 20, 40, 40], [100, 100, 110, 110]], dtype=np.float32
    )
    out = anchor_targets(anchors, gt, labels, mask, num_classes=10)
    cls = np.asarray(out.cls_targets)
    assert cls.shape == (3, 10)
    assert cls[0, 3] == 1.0 and cls[0].sum() == 1.0
    assert cls[1, 7] == 1.0 and cls[1].sum() == 1.0
    assert cls[2].sum() == 0.0  # negative anchor: all-zero row
    state = np.asarray(out.state)
    np.testing.assert_array_equal(state, [POSITIVE, POSITIVE, NEGATIVE])
    # Perfect matches → zero deltas.
    np.testing.assert_allclose(np.asarray(out.box_targets)[:2], 0.0, atol=1e-5)


def test_force_match_survives_gt_padding():
    """Padded gt rows must not clobber a forced match at anchor 0.

    Regression: the scatter used to write force=False at anchor 0 for every
    padded row (argmax of an all-zero IoU column is 0), cancelling the rescue.
    """
    gt = np.zeros((3, 4), dtype=np.float32)
    gt[0] = [0, 0, 10, 9]  # best anchor is anchor 0, IoU 0.45 < 0.5
    mask = np.array([True, False, False])
    labels = np.array([2, 0, 0], dtype=np.int32)
    anchors = np.array([[0, 0, 10, 20], [30, 30, 40, 40]], dtype=np.float32)
    out = assign_anchors(anchors, gt, mask, MatchingConfig(force_match_best=True))
    assert np.asarray(out.state)[0] == POSITIVE
    assert np.asarray(out.matched_gt)[0] == 0
    tgt = anchor_targets(anchors, gt, labels, mask, num_classes=5)
    assert np.asarray(tgt.cls_targets)[0, 2] == 1.0


def test_anchor_targets_compact_matches_dense():
    """Compact targets reconstruct exactly the dense one-hot targets."""
    from batchai_retinanet_horovod_coco_tpu.ops.matching import (
        anchor_targets,
        anchor_targets_compact,
    )

    rng = np.random.default_rng(3)
    A_n, G, K = 64, 7, 4
    anchors = np.sort(rng.uniform(0, 100, (A_n, 2, 2)), axis=1).reshape(A_n, 4)[
        :, [0, 2, 1, 3]
    ].astype(np.float32)
    gt = np.sort(rng.uniform(0, 100, (G, 2, 2)), axis=1).reshape(G, 4)[
        :, [0, 2, 1, 3]
    ].astype(np.float32)
    labels = rng.integers(0, K, G).astype(np.int32)
    mask = np.array([True] * 5 + [False] * 2)

    dense = anchor_targets(anchors, gt, labels, mask, K)
    compact = anchor_targets_compact(anchors, gt, labels, mask)

    np.testing.assert_array_equal(np.asarray(dense.state), np.asarray(compact.state))
    np.testing.assert_allclose(
        np.asarray(dense.box_targets), np.asarray(compact.box_targets)
    )
    pos = np.asarray(compact.state) == 1
    rebuilt = np.zeros((A_n, K), dtype=np.float32)
    rebuilt[np.arange(A_n)[pos], np.asarray(compact.matched_labels)[pos]] = 1.0
    np.testing.assert_array_equal(np.asarray(dense.cls_targets), rebuilt)
