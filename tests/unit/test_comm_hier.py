"""Topology-aware hierarchical collectives (ISSUE 16), on the 8-dev mesh.

The claims, in dependency order:

1. topology — the interleaved grouping is exactly the documented
   convention at 2x4 AND 4x2, and ``derive_topology`` resolves the
   override chain (arg > env > device slice_index) with clear
   divisibility errors;
2. config — the per-hop fields validate (unknown stage names list the
   valid stages, errors name the ``CommConfig.`` path, ICI-compression
   mismatch is rejected), and the engage/degenerate logic
   (``hierarchical_with`` / ``flat_equivalent``) resolves every
   degenerate case to the flat tree BEFORE tracing;
3. degenerate == flat, byte-identical: equal hop modes and the
   single-slice topology lower to the SAME HLO text as the flat tree /
   the comm-free step (the pinned contract);
4. the engaged hierarchical reduce matches the exact pmean within the
   one-rounding bound (compression only on the DCN hop), and the
   per-hop EF residual telescopes bit-exactly on constant gradients;
5. per-hop EF state lives under ``"<bucket>@dcn"`` keys in GLOBAL
   bucket order (the interleaved-mesh invariant) and reshards across
   world sizes 8 -> 4 -> 16 through the PR-10 checkpoint machinery;
6. wire accounting — the DCN hop's bytes under int8 are <= 0.65x the
   all-exact hierarchical tree, the ICI hops carry ZERO quantized
   bytes, and the split reaches the step metrics / telemetry counters /
   the per-hop ``ef_residual_spike_dcn`` SLO rule;
7. the collective-safety lint rule bites on a rank-guarded
   ``reduce_bucket_hierarchical`` call;
8. the CLI maps ``--comm-ici-mode`` / ``--comm-dcn-mode`` /
   ``--comm-dcn-bucket-mb`` onto the config (and a hop-only policy
   still produces a config).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from batchai_retinanet_horovod_coco_tpu.comm import (
    CommConfig,
    init_comm_state,
    plan_buckets,
    reduce_tree,
    state_partition_specs,
)
from batchai_retinanet_horovod_coco_tpu.parallel import (
    CommTopology,
    derive_topology,
    make_mesh,
)
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import (
    COMM_SLICES_ENV,
    DATA_AXIS,
)
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import shard_map
from batchai_retinanet_horovod_coco_tpu.train import make_train_step

N = 8
HW = (64, 64)
T24 = CommTopology(num_slices=2, slice_size=4)
T42 = CommTopology(num_slices=4, slice_size=2)


def make_batch(batch=8):
    rng = np.random.default_rng(3)
    return {
        "images": jnp.asarray(
            rng.normal(0, 1, (batch, *HW, 3)).astype(np.float32)
        ),
        "gt_boxes": jnp.asarray(
            np.tile(
                np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                (batch, 1, 1),
            )
        ),
        "gt_labels": jnp.ones((batch, 1), jnp.int32),
        "gt_mask": jnp.ones((batch, 1), bool),
    }


def _hier_reduce_on_mesh(tree, config, topology, steps=1):
    """Run the HIERARCHICAL ``reduce_tree`` ``steps`` times on per-device
    data; returns (reduced, exact pmean, final comm state).  ``tree``
    leaves carry a leading (N,) device axis."""
    assert config.hierarchical_with(topology)
    mesh = make_mesh(N, topology=topology)
    per_dev_tree = jax.tree.map(lambda a: a[0], tree)
    plan = plan_buckets(per_dev_tree, config, topology)
    comm_state = {
        k: jnp.asarray(v)
        for k, v in init_comm_state(
            per_dev_tree, config, N, topology=topology
        ).items()
    }
    res_spec = state_partition_specs(comm_state)

    @jax.jit
    @lambda f: shard_map(
        f,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), res_spec),
        out_specs=(P(), P(), res_spec),
        check_vma=False,
    )
    def run(x, res):
        per_dev = jax.tree.map(lambda a: a[0], x)
        out = None
        for _ in range(steps):
            out, res, _sat = reduce_tree(
                per_dev, res, plan, config, DATA_AXIS, N, topology
            )
        exact = jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), per_dev)
        return out, exact, res

    return run(tree, comm_state)


# ---------------------------------------------------------------------------
# 1. topology: grouping convention + derivation
# ---------------------------------------------------------------------------


class TestTopology:
    def test_2x4_grouping_is_the_interleaved_convention(self):
        """Position d: slice d % S, intra-slice rank d // S."""
        assert T24.num_devices == 8
        assert T24.ici_groups() == [[0, 2, 4, 6], [1, 3, 5, 7]]
        assert T24.dcn_groups() == [[0, 1], [2, 3], [4, 5], [6, 7]]

    def test_4x2_grouping(self):
        assert T42.ici_groups() == [[0, 4], [1, 5], [2, 6], [3, 7]]
        assert T42.dcn_groups() == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_groups_partition_the_mesh(self):
        for topo in (T24, T42):
            for groups in (topo.ici_groups(), topo.dcn_groups()):
                flat = sorted(d for g in groups for d in g)
                assert flat == list(range(topo.num_devices))

    def test_derive_explicit_arg_wins(self, monkeypatch):
        monkeypatch.setenv(COMM_SLICES_ENV, "4")
        topo = derive_topology(8, 2)
        assert (topo.num_slices, topo.slice_size) == (2, 4)

    def test_derive_env_override(self, monkeypatch):
        monkeypatch.setenv(COMM_SLICES_ENV, "2")
        topo = derive_topology(8)
        assert (topo.num_slices, topo.slice_size) == (2, 4)

    def test_derive_flat_without_slice_info(self, monkeypatch):
        """Virtual CPU devices carry no slice_index: flat unless told."""
        monkeypatch.delenv(COMM_SLICES_ENV, raising=False)
        assert derive_topology(8) is None

    def test_derive_rejects_indivisible(self):
        with pytest.raises(ValueError, match="do not divide"):
            derive_topology(8, 3)
        with pytest.raises(ValueError, match=">= 1"):
            derive_topology(8, 0)

    def test_derive_rejects_garbage_env(self, monkeypatch):
        monkeypatch.setenv(COMM_SLICES_ENV, "two")
        with pytest.raises(ValueError, match=COMM_SLICES_ENV):
            derive_topology(8)

    def test_make_mesh_accepts_topology_and_checks_size(self):
        mesh = make_mesh(N, topology=T24)
        assert mesh.size == N  # CPU devices: order passes through
        with pytest.raises(ValueError, match="topology is 2x2"):
            make_mesh(N, topology=CommTopology(2, 2))


# ---------------------------------------------------------------------------
# 2. config: per-hop validation + engage/degenerate resolution
# ---------------------------------------------------------------------------


class TestConfig:
    def test_unknown_stage_name_lists_valid_stages(self):
        with pytest.raises(ValueError) as e:
            CommConfig(compress="int8", stage_modes=(("bakbone", "int8"),))
        msg = str(e.value)
        assert "bakbone" in msg
        assert "backbone" in msg and "fpn" in msg and "heads" in msg

    def test_bucket_mb_error_names_the_config_path(self):
        with pytest.raises(ValueError, match=r"CommConfig\.bucket_mb"):
            CommConfig(compress="int8", bucket_mb=0)
        with pytest.raises(ValueError, match=r"CommConfig\.dcn_bucket_mb"):
            CommConfig(compress="int8", dcn_bucket_mb=-1.0)

    def test_hop_mode_vocabulary(self):
        with pytest.raises(ValueError, match=r"CommConfig\.dcn_mode"):
            CommConfig(dcn_mode="int4")
        with pytest.raises(ValueError, match=r"CommConfig\.ici_mode"):
            CommConfig(ici_mode="fp8")

    def test_compressed_ici_with_different_dcn_is_rejected(self):
        with pytest.raises(ValueError, match="fast \\(ICI\\) hop"):
            CommConfig(compress="none", ici_mode="int8", dcn_mode="bf16")
        # Equal modes are legal — that's just the flat tree.
        cfg = CommConfig(compress="none", ici_mode="int8", dcn_mode="int8")
        assert not cfg.hierarchical_with(T24)

    def test_defaults_engage_only_on_multi_slice(self):
        cfg = CommConfig(compress="int8")  # ici none, dcn inherits int8
        assert cfg.effective_ici_mode == "none"
        assert cfg.effective_dcn_mode == "int8"
        assert cfg.hierarchical_with(T24)
        assert not cfg.hierarchical_with(None)
        assert not cfg.hierarchical_with(CommTopology(1, 8))

    def test_flat_equivalent_resolution(self):
        cfg = CommConfig(
            compress="int8", stage_modes=(("heads", "bf16"),)
        )
        # No topology: unchanged (legacy path).
        assert cfg.flat_equivalent(None) is cfg
        # Single slice: the whole world is the fast wire — exact.
        single = cfg.flat_equivalent(CommTopology(1, 8))
        assert single.compress == "none"
        assert single.stage_modes == ()
        assert not single.enabled
        # Equal modes at multi-slice: flat at the shared mode, pinned
        # on BOTH hops so the result is a fixed point — re-resolving it
        # against any topology never re-engages the hierarchy.
        eq = CommConfig(compress="none", ici_mode="bf16", dcn_mode="bf16")
        flat = eq.flat_equivalent(T24)
        assert flat.compress == "bf16"
        assert (flat.ici_mode, flat.dcn_mode) == ("bf16", "bf16")
        assert not flat.hierarchical_with(T24)
        assert flat.flat_equivalent(T24) == flat

    def test_hop_only_policy_counts_as_enabled_and_stateful(self):
        cfg = CommConfig(compress="none", dcn_mode="int8")
        assert cfg.enabled and cfg.needs_state
        assert cfg.hierarchical_with(T24)

    def test_hier_state_keys_and_shapes(self):
        tree = {"backbone": {"w": np.zeros((35000,), np.float32)}}
        cfg = CommConfig(compress="int8")
        state = init_comm_state(tree, cfg, N, topology=T24)
        # hier_chunk = ceil(ceil(35000/4)/2) = 4375, keyed per hop.
        assert set(state) == {"backbone.0@dcn"}
        assert state["backbone.0@dcn"].shape == (8 * 4375,)
        # ZeRO ignores the topology: per-leaf flat keys, no @dcn.
        zstate = init_comm_state(tree, cfg, N, zero=True, topology=T24)
        assert set(zstate) == {"['backbone']['w']"}
        # Degenerate topologies fall back to the flat bucket keys.
        flat = init_comm_state(tree, cfg, N)
        single = init_comm_state(
            tree, cfg, N, topology=CommTopology(1, 8)
        )
        assert set(flat) == {"backbone.0"}
        assert single == {}  # single slice + default ici "none": exact

    def test_plan_composition_is_slice_count_independent(self):
        """Same policy at 2x4 and 4x2: identical bucket composition
        (only chunk shapes differ) — the reshard prerequisite."""
        tree = {
            "backbone": {"w": np.zeros((40000,), np.float32)},
            "fpn": {"w": np.zeros((20000,), np.float32)},
        }
        cfg = CommConfig(compress="int8")
        key = lambda plan: [
            (b.key, b.mode, tuple(l.path for l in b.leaves))
            for b in plan.buckets
        ]
        assert key(plan_buckets(tree, cfg, T24)) == key(
            plan_buckets(tree, cfg, T42)
        )


# ---------------------------------------------------------------------------
# 3. degenerate == flat, byte-identical HLO
# ---------------------------------------------------------------------------


class TestDegenerateHlo:
    def test_equal_hop_modes_lower_to_the_flat_tree(
        self, tiny_model_and_state
    ):
        """ici == dcn == int8 at a 2-slice topology IS the flat int8
        tree: same HLO text, no grouped collectives."""
        model, state = tiny_model_and_state
        batch = make_batch()
        mesh = make_mesh(N)
        cfg_flat = CommConfig(compress="int8")
        cfg_eq = CommConfig(
            compress="int8", ici_mode="int8", dcn_mode="int8"
        )
        cs = {
            k: jnp.asarray(v)
            for k, v in init_comm_state(state.params, cfg_flat, N).items()
        }
        state = state.replace(comm_state=cs)
        flat = make_train_step(
            model, HW, 3, mesh=mesh, comm=cfg_flat, donate_state=False
        )
        eq = make_train_step(
            model, HW, 3, mesh=mesh, comm=cfg_eq, topology=T24,
            donate_state=False,
        )
        assert (
            flat.lower(state, batch).as_text()
            == eq.lower(state, batch).as_text()
        )

    def test_single_slice_topology_is_byte_identical_to_comm_off(
        self, tiny_model_and_state
    ):
        """A single-slice topology has no DCN hop; with the default
        ici_mode="none" the whole policy degenerates to the comm-free
        step — pinned at the HLO text."""
        model, state = tiny_model_and_state
        batch = make_batch()
        mesh = make_mesh(N)
        base = make_train_step(model, HW, 3, mesh=mesh, donate_state=False)
        degen = make_train_step(
            model, HW, 3, mesh=mesh, comm=CommConfig(compress="int8"),
            topology=CommTopology(1, 8), donate_state=False,
        )
        assert (
            base.lower(state, batch).as_text()
            == degen.lower(state, batch).as_text()
        )

    def test_topology_mesh_size_mismatch_is_rejected(
        self, tiny_model_and_state
    ):
        model, _ = tiny_model_and_state
        with pytest.raises(ValueError, match="mesh"):
            make_train_step(
                model, HW, 3, mesh=make_mesh(N),
                comm=CommConfig(compress="int8"),
                topology=CommTopology(2, 2), donate_state=False,
            )


# ---------------------------------------------------------------------------
# 4. engaged hierarchy: parity + per-hop EF telescoping
# ---------------------------------------------------------------------------


class TestHierarchicalReduce:
    @pytest.mark.parametrize("topo", [T24, T42], ids=["2x4", "4x2"])
    def test_matches_exact_within_bound(self, topo):
        rng = np.random.default_rng(0)
        tree = {
            "backbone": {
                "w": jnp.asarray(
                    rng.normal(0, 0.1, (N, 64, 513)).astype(np.float32)
                ),
                "bias": jnp.asarray(
                    rng.normal(0, 0.1, (N, 33)).astype(np.float32)
                ),
            }
        }
        cfg = CommConfig(compress="int8")
        q, exact, res = _hier_reduce_on_mesh(tree, cfg, topo)
        bound = np.abs(np.asarray(exact["backbone"]["w"])).max() / 254.0
        for key in ("w", "bias"):
            np.testing.assert_allclose(
                np.asarray(q["backbone"][key]),
                np.asarray(exact["backbone"][key]),
                atol=float(bound) + 1e-7,
            )
        assert set(res) == {"backbone.0@dcn"}

    def test_non_finite_gradients_surface_as_nan(self):
        rng = np.random.default_rng(2)
        big = rng.normal(0, 0.1, (N, 16, 1024)).astype(np.float32)
        big[3, 5, 100] = np.inf
        q, _, _ = _hier_reduce_on_mesh(
            {"w": jnp.asarray(big)}, CommConfig(compress="int8"), T24
        )
        assert not np.isfinite(np.asarray(q["w"])).all()

    def test_per_hop_ef_telescopes_bit_exact_on_step_2(self):
        """The flat EF telescoping claim, through the 5-phase tree: a
        constant gradient on the exact float grid is BIT-exact after the
        DCN-hop residual is applied on step 2, and the residual returns
        to zero."""
        cfg = CommConfig(compress="int8")
        size = 8192  # hier_chunk at 2x4 = 1024 = 2 blocks, pin-aligned
        v = np.full((size,), 0.5, np.float32)
        v[:: cfg.block] = 127.0
        tree = {"w": jnp.asarray(np.tile(v, (N, 1)))}

        mesh = make_mesh(N, topology=T24)
        plan = plan_buckets({"w": v}, cfg, T24)
        cs = {
            k: jnp.asarray(val)
            for k, val in init_comm_state(
                {"w": v}, cfg, N, topology=T24
            ).items()
        }
        res_spec = state_partition_specs(cs)

        @jax.jit
        @lambda f: shard_map(
            f,
            mesh=mesh,
            in_specs=(P(DATA_AXIS), res_spec),
            out_specs=(P(), P(), res_spec),
            check_vma=False,
        )
        def two_steps(x, res):
            per_dev = jax.tree.map(lambda a: a[0], x)
            out1, res, _ = reduce_tree(
                per_dev, res, plan, cfg, DATA_AXIS, N, T24
            )
            out2, res, _ = reduce_tree(
                per_dev, res, plan, cfg, DATA_AXIS, N, T24
            )
            return out1, out2, res

        out1, out2, res = two_steps(tree, cs)
        applied = np.asarray(out1["w"]) + np.asarray(out2["w"])
        np.testing.assert_array_equal(applied, 2.0 * v)  # BIT-exact
        np.testing.assert_array_equal(
            np.asarray(res["heads.0@dcn"]),
            np.zeros((res["heads.0@dcn"].size,), np.float32),
        )
        assert not np.array_equal(np.asarray(out1["w"]), v)

    def test_hier_train_step_tracks_single_device(
        self, tiny_model_and_state
    ):
        """Full integration: the hierarchical step at 2x4 stays within
        the one-rounding bound of the exact single-device update and
        emits the per-hop metric vocabulary."""
        model, state = tiny_model_and_state
        batch = make_batch()
        cfg = CommConfig(compress="int8")
        mesh = make_mesh(N, topology=T24)

        single = make_train_step(model, HW, 3, mesh=None, donate_state=False)
        s_new, s_metrics = single(state, batch)

        hstate = state.replace(
            comm_state={
                k: jnp.asarray(v)
                for k, v in init_comm_state(
                    state.params, cfg, N, topology=T24
                ).items()
            }
        )
        assert all(k.endswith("@dcn") for k in hstate.comm_state)
        hier = make_train_step(
            model, HW, 3, mesh=mesh, comm=cfg, topology=T24,
            donate_state=False,
        )
        h_new, h_metrics = hier(hstate, batch)

        np.testing.assert_allclose(
            float(h_metrics["loss"]), float(s_metrics["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(h_new.params), jax.tree.leaves(s_new.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-3
            )
        # Per-hop metric vocabulary: the step emits the plan's static
        # split (each leg f32-rounded independently, so compare against
        # the plan, not ici + dcn re-summed in f64).
        plan = plan_buckets(state.params, cfg, T24)
        hop = plan.hop_bytes(T24)
        assert hop["ici"] > 0 and hop["dcn"] > 0
        assert float(h_metrics["comm_ici_bytes"]) == np.float32(hop["ici"])
        assert float(h_metrics["comm_dcn_bytes"]) == np.float32(hop["dcn"])
        assert float(h_metrics["comm_compressed_bytes"]) == np.float32(
            hop["ici"] + hop["dcn"]
        )
        assert float(h_metrics["ef_residual_norm_dcn"]) == float(
            h_metrics["ef_residual_norm"]
        )
        assert 0.0 <= float(h_metrics["ef_saturation"]) <= 1.0


# ---------------------------------------------------------------------------
# 5. checkpoint elasticity of the per-hop EF state
# ---------------------------------------------------------------------------


def test_dcn_residuals_reshard_8_to_4_to_16(tmp_path):
    """The ``@dcn`` keys ride the same reshard_flat_leaf machinery as
    flat EF / ZeRO state: logical prefix + zero padding, truncate down,
    zero-pad up — the interleaved-mesh invariant made checkpointable."""
    import optax

    from batchai_retinanet_horovod_coco_tpu.train.state import TrainState
    from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
        CheckpointManager,
    )

    def tiny_state(comm_state):
        params = {"w": np.arange(6, dtype=np.float32)}
        tx = optax.sgd(1e-2)
        return TrainState(
            step=np.zeros((), np.int32),
            params=params,
            batch_stats={},
            opt_state=tx.init(params),
            tx=tx,
            comm_state=comm_state,
        )

    logical = np.arange(1, 101, dtype=np.float32) / 7.0
    world8 = np.zeros((8 * 13,), np.float32)  # 8 * ceil(100/8)
    world8[:100] = logical
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    assert mgr.save(
        tiny_state({"backbone.0@dcn": world8}), step=5, force=True
    )

    t4 = tiny_state({"backbone.0@dcn": np.zeros((100,), np.float32)})
    r4 = CheckpointManager(str(tmp_path)).restore(t4)
    np.testing.assert_array_equal(r4.comm_state["backbone.0@dcn"], logical)

    t16 = tiny_state({"backbone.0@dcn": np.zeros((16 * 7,), np.float32)})
    r16 = CheckpointManager(str(tmp_path)).restore(t16)
    np.testing.assert_array_equal(
        r16.comm_state["backbone.0@dcn"][:100], logical
    )
    np.testing.assert_array_equal(r16.comm_state["backbone.0@dcn"][100:], 0.0)


# ---------------------------------------------------------------------------
# 6. per-hop wire accounting + telemetry + SLO
# ---------------------------------------------------------------------------


class TestPerHopAccounting:
    def test_dcn_ratio_clears_the_claim_and_ici_stays_exact(
        self, tiny_model_and_state
    ):
        _, state = tiny_model_and_state
        cfg = CommConfig(compress="int8")
        plan = plan_buckets(state.params, cfg, T24)
        hop = plan.hop_bytes(T24)
        exact = plan.hop_bytes_exact(T24)
        ratio = hop["dcn"] / exact["dcn"]
        assert ratio <= 0.65, f"DCN bytes ratio {ratio:.3f} > 0.65"
        # The ICI hops are untouched by the policy ...
        assert hop["ici"] == exact["ici"]
        # ... and carry ZERO quantized bytes, by construction.
        quant = plan.hop_quant_bytes(T24)
        assert quant["ici"] == 0
        assert quant["dcn"] > 0

    def test_record_comm_feeds_the_per_hop_counters(self):
        from batchai_retinanet_horovod_coco_tpu.obs import telemetry

        telemetry.reset()
        telemetry.enable()
        try:
            telemetry.record_comm(
                ef_residual=0.5, compressed_bytes=300.0,
                ici_bytes=200.0, dcn_bytes=100.0, ef_residual_dcn=0.5,
                steps=10,
            )
            snap = telemetry.default().snapshot()
            assert snap["train_comm_ici_bytes_total"] == 2000.0
            assert snap["train_comm_dcn_bytes_total"] == 1000.0
            assert snap["train_ef_residual_dcn"] == 0.5
            # Disabled: one bool check, no mutation.
            telemetry.reset()
            telemetry.record_comm(ici_bytes=1.0, dcn_bytes=1.0)
            assert (
                "train_comm_dcn_bytes_total"
                not in telemetry.default().snapshot()
            )
        finally:
            telemetry.reset()

    def test_per_hop_slo_rule_watches_the_dcn_gauge(self):
        from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry

        rule = slo.ef_residual_spike(hop="dcn")
        assert rule.name == "ef_residual_spike_dcn"
        assert rule.metric == "train_ef_residual_dcn"
        telemetry.enable()
        try:
            registry = telemetry.Registry()
            gauge = registry.gauge("train_ef_residual_dcn", "test")
            monitor = slo.SloMonitor(
                registry, [slo.ef_residual_spike(factor=10.0, hop="dcn")],
                poll_interval=999,
            )
            for i in range(6):
                gauge.set(1.0 + 0.01 * i)
                assert monitor.check_once(now=float(i)) == []
            gauge.set(100.0)
            fired = monitor.check_once(now=10.0)
            assert [v["rule"] for v in fired] == ["ef_residual_spike_dcn"]
            assert monitor.check_once(now=11.0) == []
        finally:
            telemetry.disable()

    def test_per_hop_rule_silent_on_flat_runs(self):
        from batchai_retinanet_horovod_coco_tpu.obs import slo
        from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
            Registry,
        )

        registry = Registry()
        registry.gauge("train_ef_residual", "flat gauge").set
        monitor = slo.SloMonitor(
            registry, [slo.ef_residual_spike(hop="dcn")], poll_interval=999
        )
        for i in range(10):
            assert monitor.check_once(now=float(i)) == []


# ---------------------------------------------------------------------------
# 7. lint: rank-guarded hierarchical wrapper
# ---------------------------------------------------------------------------


def test_lint_bites_on_rank_guarded_hierarchical_reduce():
    from tests.unit.test_lint import run_rule

    result = run_rule(
        """
        import jax

        from batchai_retinanet_horovod_coco_tpu.comm import compress

        def step(flat, res, bucket, cfg, topo):
            if jax.process_index() == 0:
                flat, res, _ = compress.reduce_bucket_hierarchical(
                    flat, res, bucket, cfg, "data", topo
                )
            return flat
        """,
        "collective-safety",
    )
    assert len(result.findings) == 1
    assert "reduce_bucket_hierarchical" in result.findings[0].message


# ---------------------------------------------------------------------------
# 8. CLI mapping
# ---------------------------------------------------------------------------


class TestCliMapping:
    def _args(self, **kw):
        import argparse

        defaults = dict(
            comm_compress="none", comm_overlap=False, comm_bucket_mb=4.0,
            comm_no_error_feedback=False, quantized_allreduce=False,
            comm_ici_mode=None, comm_dcn_mode=None, comm_dcn_bucket_mb=None,
            comm_slices=None,
        )
        defaults.update(kw)
        return argparse.Namespace(**defaults)

    def test_all_off_maps_to_no_config(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        assert make_comm_config(self._args()) is None

    def test_hop_flags_map_to_config(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        cfg = make_comm_config(
            self._args(
                comm_compress="int8", comm_dcn_mode="bf16",
                comm_dcn_bucket_mb=8.0,
            )
        )
        assert cfg.dcn_mode == "bf16"
        assert cfg.dcn_bucket_mb == 8.0
        assert cfg.effective_ici_mode == "none"

    def test_hop_only_policy_still_produces_a_config(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        cfg = make_comm_config(self._args(comm_dcn_mode="int8"))
        assert cfg is not None
        assert cfg.compress == "none" and cfg.dcn_mode == "int8"
        assert cfg.hierarchical_with(T24)

    def test_comm_flags_parse(self):
        import argparse

        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            add_comm_flags,
        )

        parser = argparse.ArgumentParser()
        add_comm_flags(parser)
        args = parser.parse_args(
            ["--comm-slices", "2", "--comm-dcn-mode", "int8",
             "--comm-dcn-bucket-mb", "8"]
        )
        assert args.comm_slices == 2
        assert args.comm_dcn_mode == "int8"
        assert args.comm_dcn_bucket_mb == 8.0
