"""serve/ — dynamic-batching inference server (ISSUE 4).

Two test families:

- **Stub-engine tests** (no jax in the loop): the batcher/router/frontend
  machinery — deadline-fires-with-partial-batch, overload shedding,
  graceful drain, crash propagation (shm error contract), per-request
  deadlines, HTTP frontend, batch-size selection.
- **Real-model tests** (tiny resnet_test): THE acceptance pin — served
  detections are bit-identical to the sequential ``collect_detections``
  path for the same images — plus the export-directory engine path.

Plus the watchdog-coverage satellite: ``scripts/audit_threads.py`` must
see (and pass) every serve spawn site.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.serve import (
    DetectEngine,
    DetectionServer,
    RequestRejected,
    RequestTimeout,
    ServeConfig,
    ServerClosed,
    ServerError,
    serve_http,
)
# The canonical stub engine (serve/stub.py — ISSUE 12 unified the
# private copies this file and telemetry_smoke.py used to carry).
from batchai_retinanet_horovod_coco_tpu.serve.stub import (
    EXPECTED_DETECTIONS,
    StubDetectEngine as StubEngine,
)

# repo root (for scripts/), derived from this file's own path
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


IMG = np.zeros((64, 64, 3), np.uint8)
EXPECTED = EXPECTED_DETECTIONS


def make_server(engine=None, **cfg) -> DetectionServer:
    cfg.setdefault("max_delay_ms", 10)
    cfg.setdefault("preprocess_workers", 1)
    return DetectionServer(engine or StubEngine(), ServeConfig(**cfg))


# ---- batcher edge cases (ISSUE 4 satellite) ------------------------------


class TestBatcher:
    def test_deadline_fires_with_partial_batch(self):
        """Deadline-only mode (``continuous=False``, the pre-ISSUE-14
        path, kept alive): a lone request must not wait for a full batch
        — the max-latency deadline fires and it runs PADDED."""
        engine = StubEngine(batch_sizes=(4,))
        with make_server(engine, continuous=False) as srv:
            t0 = time.perf_counter()
            assert srv.submit(IMG).result(timeout=10) == EXPECTED
            dt = time.perf_counter() - t0
            snap = srv.snapshot()
        assert engine.dispatched == [4]  # padded to the compiled size
        assert snap["deadline_fires"] >= 1
        assert snap["ready_fires"] == 0  # no dispatch gate in this mode
        assert dt < 5.0  # deadline-bounded, not full-batch-bounded

    def test_full_batch_coalesces(self):
        engine = StubEngine(batch_sizes=(4,))
        with make_server(engine, max_delay_ms=200, continuous=False) as srv:
            futs = [srv.submit(IMG) for _ in range(8)]
            assert all(f.result(timeout=10) == EXPECTED for f in futs)
        assert sum(engine.dispatched) >= 8
        assert max(engine.dispatched) == 4  # actually coalesced

    def test_partial_batch_uses_smaller_compiled_size(self):
        """With batch sizes (1, 4) compiled, a lone request runs at batch
        1 instead of paying a 4-wide pad."""
        engine = StubEngine(batch_sizes=(1, 4))
        with make_server(engine) as srv:
            assert srv.submit(IMG).result(timeout=10) == EXPECTED
        assert engine.dispatched == [1]

    def test_expired_request_never_occupies_a_row(self):
        """A request whose deadline passed in the queue is rejected by the
        batcher, not dispatched."""
        engine = StubEngine(batch_sizes=(2,), delay_s=0.2)
        with make_server(engine, default_timeout_s=0.05) as srv:
            first = srv.submit(IMG)  # occupies the device for 200ms
            time.sleep(0.1)
            late = srv.submit(IMG)  # already expired when batcher sees it
            with pytest.raises((RequestTimeout, ServerClosed)):
                late.result(timeout=10)
            # the first may or may not beat its own deadline; just drain
            first._event.wait(10)
            snap = srv.snapshot()
        assert snap["timeouts"] >= 1


# ---- continuous in-flight batching (ISSUE 14) ----------------------------


class FetchBlockEngine(StubEngine):
    """Async-device model for continuous-mode tests: ``dispatch`` returns
    immediately (the enqueue), ``fetch`` blocks until released per batch
    — exactly how a real device round behaves to the dispatcher."""

    def __init__(self, batch_sizes=(1, 2, 4)):
        super().__init__(batch_sizes=batch_sizes)
        self.gates: list[threading.Event] = []
        self._lock = threading.Lock()

    def release(self, i: int) -> None:
        while True:
            with self._lock:
                if i < len(self.gates):
                    self.gates[i].set()
                    return
            time.sleep(0.005)

    def release_all(self) -> None:
        with self._lock:
            for g in self.gates:
                g.set()
            self._released_all = True

    def dispatch(self, hw, images):
        det = super().dispatch(hw, images)
        with self._lock:
            gate = threading.Event()
            if getattr(self, "_released_all", False):
                gate.set()
            self.gates.append(gate)
        return (gate, det)

    def fetch(self, det):
        gate, inner = det
        assert gate.wait(30), "test forgot to release a batch"
        return inner


class TestContinuous:
    def test_lone_request_skips_the_deadline(self):
        """The dispatch gate seals a lone request the moment the device
        is idle — light-load latency is one round, not deadline+round."""
        engine = StubEngine(batch_sizes=(4,))
        with make_server(engine, max_delay_ms=2000) as srv:
            srv.submit(IMG).result(timeout=10)  # warm the thread path
            t0 = time.perf_counter()
            assert srv.submit(IMG).result(timeout=10) == EXPECTED
            dt = time.perf_counter() - t0
            snap = srv.snapshot()
        assert dt < 1.0  # nowhere near the 2s deadline
        assert snap["ready_fires"] >= 2
        assert snap["deadline_fires"] == 0

    def test_admission_into_assembling_batch_after_dispatch(self):
        """Requests arriving AFTER batch N dispatched claim slots in the
        assembling batch N+1 and ride together the instant N returns."""
        engine = FetchBlockEngine()
        srv = make_server(engine, max_delay_ms=10_000)
        try:
            a = srv.submit(IMG)  # seals alone (device idle), in flight
            deadline = time.monotonic() + 10
            while not engine.dispatched and time.monotonic() < deadline:
                time.sleep(0.005)
            assert engine.dispatched == [1]
            b = srv.submit(IMG)  # claims the assembling batch...
            c = srv.submit(IMG)  # ...and so does its friend
            time.sleep(0.1)
            # Nothing sealed yet (device busy, deadline far away): both
            # rows sit CLAIMED in the pool.
            assert engine.dispatched == [1]
            assert srv.snapshot()["free_slots"] == 4 - 2
            engine.release(0)  # batch N returns...
            assert a.result(timeout=10) == EXPECTED
            engine.release(1)
            # ...and N+1 rides immediately with BOTH rows in one batch
            # (batch size 2 — the smallest compiled fit).
            assert b.result(timeout=10) == EXPECTED
            assert c.result(timeout=10) == EXPECTED
            assert engine.dispatched == [1, 2]
            snap = srv.snapshot()
            assert snap["ready_fires"] == 2
            assert snap["deadline_fires"] == 0
        finally:
            engine.release_all()
            srv.close(drain=False)

    def test_early_row_completes_while_sibling_in_flight(self):
        """Per-row completion release: batch N's futures resolve while
        batch N+1 is still executing on device."""
        engine = FetchBlockEngine()
        srv = make_server(engine, max_delay_ms=10_000)
        try:
            a = srv.submit(IMG)
            deadline = time.monotonic() + 10
            while not engine.dispatched and time.monotonic() < deadline:
                time.sleep(0.005)
            b = srv.submit(IMG)
            engine.release(0)  # N returns; N+1 (b) dispatches at once
            assert a.result(timeout=10) == EXPECTED  # resolved...
            assert not b.done()  # ...while its sibling is IN FLIGHT
            engine.release(1)
            assert b.result(timeout=10) == EXPECTED
        finally:
            engine.release_all()
            srv.close(drain=False)

    def test_drain_on_close_under_continuous(self):
        """close(drain=True) completes claimed-but-unsealed slots too."""
        engine = StubEngine(batch_sizes=(2,), delay_s=0.05)
        srv = make_server(engine, max_delay_ms=50)
        futs = [srv.submit(IMG) for _ in range(10)]
        srv.close(drain=True)
        assert all(f.result(timeout=1) == EXPECTED for f in futs)
        assert srv.snapshot()["completed"] == 10

    def test_rescue_seal_fires_despite_a_backlogged_dispatch_queue(self):
        """Cross-bucket starvation guard: with the SHARED dispatch queue
        held non-empty (a saturated sibling bucket) and the gate never
        ready, a claimed row must still seal via the unconditional
        deadline rescue — never held hostage to another bucket."""
        import queue as queue_mod

        from batchai_retinanet_horovod_coco_tpu.serve.batcher import (
            BucketBatcher,
        )
        from batchai_retinanet_horovod_coco_tpu.serve.engine import (
            DispatchGate,
        )

        engine = StubEngine(batch_sizes=(4,))
        in_q: queue_mod.Queue = queue_mod.Queue()
        out_q: queue_mod.Queue = queue_mod.Queue(maxsize=1)
        out_q.put_nowait("sibling-batch")  # the queue never empties
        stop = threading.Event()
        rejected = []
        fatal = []
        batcher = BucketBatcher(
            (64, 64), engine, in_q, out_q, max_delay_ms=50,
            on_reject=lambda r, e: rejected.append(e),
            on_fatal=fatal.append, stop=stop,
            gate=DispatchGate(),  # never set ready
        )
        try:
            from batchai_retinanet_horovod_coco_tpu.serve.common import (
                ServeRequest,
            )

            req = ServeRequest(0, None, None)
            req.image = IMG
            req.scale = np.float32(1.0)
            req.orig_wh = (64, 64)
            in_q.put(req)
            # rescue_at = deadline + max(0.1, max_delay) ≈ 150 ms; the
            # batcher must seal (deadline_fires) and block on the put.
            deadline = time.monotonic() + 5
            while batcher.deadline_fires == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert batcher.deadline_fires == 1
            assert batcher.pool.free_slots() == 4  # nothing orphaned
            assert not fatal and not rejected
        finally:
            stop.set()
            batcher.thread.join(timeout=10)

    def test_occupancy_and_free_slots_surface(self):
        """snapshot()/load_fields() carry the occupancy signals the
        fleet router weighs on, and /metrics exposes the families."""
        engine = StubEngine(batch_sizes=(4,))
        with make_server(engine) as srv:
            assert srv.submit(IMG).result(timeout=10) == EXPECTED
            snap = srv.snapshot()
            load = srv.load_fields()
            text = srv.telemetry.prometheus_text()
        assert snap["slot_capacity"] == 4
        assert snap["free_slots"] == 4  # nothing assembling now
        assert snap["occupancy_mean"] == 0.25  # 1 live row / 4-wide batch
        assert load["free_slots"] == 4
        assert load["slot_capacity"] == 4
        assert load["occupancy"] == 0.25
        assert "serve_free_slots 4" in text
        assert "serve_batch_occupancy_mean 0.25" in text
        assert "serve_ready_fires_total" in text
        # Pull-only on the server's OWN registry — observable on every
        # /metrics surface with no telemetry.enable() required.
        assert "serve_slot_wait_ms_count 1" in text


# ---- slot pool: eviction vs the dispatch window --------------------------


class TestSlotPool:
    def test_expired_claim_evicted_at_seal_frees_the_slot(self):
        """The race the ISSUE 14 bugfix pins, on an injectable clock: a
        claimed request whose deadline expires before the seal is
        evicted AT the dispatch window — rejected with RequestTimeout,
        slot freed, never a row in the sealed batch, no orphan."""
        from batchai_retinanet_horovod_coco_tpu.serve.batcher import (
            SlotPool,
        )
        from batchai_retinanet_horovod_coco_tpu.serve.common import (
            ServeRequest,
        )

        clock = [100.0]
        pool = SlotPool(4, now_fn=lambda: clock[0])
        live = ServeRequest(0, None, deadline_t=200.0)
        doomed = ServeRequest(1, None, deadline_t=100.5)
        assert pool.claim(live) and pool.claim(doomed)
        assert pool.free_slots() == 2
        clock[0] = 101.0  # doomed's deadline passes INSIDE its slot
        evicted = []
        rows, waits = pool.seal(lambda req, exc: evicted.append((req, exc)))
        assert rows == [live]
        assert len(waits) == 1 and waits[0] == pytest.approx(1000.0)
        assert [r.id for r, _ in evicted] == [1]
        assert isinstance(evicted[0][1], RequestTimeout)
        # No orphaned claimed slot: the pool is empty and re-armable.
        assert pool.free_slots() == 4
        assert pool.first_claim_t is None
        assert pool.evictions == 1
        assert pool.claim(ServeRequest(2, None, None))

    def test_all_claims_expired_seals_to_nothing(self):
        from batchai_retinanet_horovod_coco_tpu.serve.batcher import (
            SlotPool,
        )
        from batchai_retinanet_horovod_coco_tpu.serve.common import (
            ServeRequest,
        )

        clock = [10.0]
        pool = SlotPool(2, now_fn=lambda: clock[0])
        pool.claim(ServeRequest(0, None, deadline_t=10.1))
        clock[0] = 11.0
        evicted = []
        rows, waits = pool.seal(lambda req, exc: evicted.append(req))
        assert rows == [] and waits == []
        assert len(evicted) == 1
        assert pool.free_slots() == 2  # nothing orphaned, nothing rides


# ---- telemetry record site (ISSUE 14 satellite) --------------------------


class TestServeTelemetryRecordSite:
    def test_disabled_path_records_nothing(self):
        from batchai_retinanet_horovod_coco_tpu.obs import telemetry

        telemetry.reset()
        try:
            telemetry.record_serve_batch(0.5, 3, (1.0, 2.0))
            snap = telemetry.default().snapshot()
            assert "serve_batch_occupancy.count" not in snap
            assert "serve_free_slots" not in snap
        finally:
            telemetry.reset()

    def test_enabled_families_land_on_the_process_registry(self):
        from batchai_retinanet_horovod_coco_tpu.obs import telemetry

        telemetry.reset()
        try:
            telemetry.enable()
            telemetry.record_serve_batch(0.5, 3, (1.0, 2.0))
            telemetry.record_serve_batch(1.0, 0, (4.0,))
            snap = telemetry.default().snapshot()
            assert snap["serve_batch_occupancy.count"] == 2
            assert snap["serve_free_slots"] == 0
            assert snap["serve_slot_wait_ms.count"] == 3
            text = telemetry.default().prometheus_text()
            assert "serve_batch_occupancy" in text
            assert "serve_slot_wait_ms" in text
        finally:
            telemetry.reset()


# ---- overload / shedding -------------------------------------------------


class TestShedding:
    def test_overload_sheds_instead_of_queueing(self):
        """With a slow device and bounded queues, a flood of submits is
        REJECTED with an explicit reason — the queue never grows without
        limit and accepted requests complete."""
        engine = StubEngine(batch_sizes=(2,), delay_s=0.05)
        srv = make_server(
            engine, admission_queue=4, bucket_queue=2, max_delay_ms=1
        )
        accepted, shed = [], 0
        try:
            for _ in range(200):
                try:
                    accepted.append(srv.submit(IMG))
                except RequestRejected as exc:
                    assert exc.reason in (
                        "admission_queue_full", "bucket_queue_full"
                    )
                    shed += 1
            assert shed > 0, "flood never shed"
            done = sum(
                1 for f in accepted
                if f._event.wait(30) and f._error is None
            )
            snap = srv.snapshot()
            # every ACCEPTED request resolves (some may shed later at the
            # bucket queue); nothing is silently dropped
            assert all(f.done() or f._event.wait(30) for f in accepted)
            assert done > 0
            assert snap["shed_total"] >= shed
            # bounded in-flight: outstanding can never exceed the queue
            # bounds + what fits in the slot pool / dispatcher stages
            # (admission 4 + bucket 2 + pool 2 + dispatch queue 2x2 +
            # in-flight batch 2 + converting batch 2)
            assert snap["outstanding"] <= 4 + 2 + 2 + 2 * 2 + 2 + 2
        finally:
            srv.close(drain=False)

    def test_submit_after_close_is_shed(self):
        srv = make_server()
        srv.close()
        with pytest.raises(ServerClosed):
            srv.submit(IMG)
        assert srv.snapshot()["shed"].get("shutting_down") == 1

    def test_decode_error_rejects_request_not_server(self):
        """A bad payload fails THAT request with decode_error; the server
        keeps serving."""
        with make_server() as srv:
            bad = srv.submit(b"definitely not an image")
            with pytest.raises(RequestRejected) as ei:
                bad.result(timeout=10)
            assert ei.value.reason == "decode_error"
            assert srv.submit(IMG).result(timeout=10) == EXPECTED


# ---- drain / close -------------------------------------------------------


class TestDrain:
    def test_close_drains_inflight(self):
        """close(drain=True) completes everything already admitted."""
        engine = StubEngine(batch_sizes=(2,), delay_s=0.05)
        srv = make_server(engine, max_delay_ms=1)
        futs = [srv.submit(IMG) for _ in range(10)]
        srv.close(drain=True)
        assert all(f.result(timeout=1) == EXPECTED for f in futs)
        assert srv.snapshot()["completed"] == 10

    def test_abort_close_rejects_inflight(self):
        engine = StubEngine(batch_sizes=(2,), delay_s=0.2)
        srv = make_server(engine, max_delay_ms=1)
        futs = [srv.submit(IMG) for _ in range(6)]
        srv.close(drain=False)
        resolved = 0
        for f in futs:
            assert f._event.wait(10)
            try:
                f.result(timeout=1)
                resolved += 1
            except (ServerClosed, ServerError):
                pass
        assert resolved < 6  # at least the tail was rejected, none hang

    def test_close_is_idempotent_and_never_hangs(self):
        srv = make_server()
        srv.close()
        srv.close()
        assert not [
            t for t in threading.enumerate()
            if t.name.startswith("serve") and t.is_alive()
        ]


# ---- crash propagation (shm error contract) ------------------------------


class CrashEngine(StubEngine):
    def dispatch(self, hw, images):
        raise RuntimeError("device exploded")


class TestCrash:
    def test_dispatch_crash_reraises_at_frontend(self):
        srv = make_server(CrashEngine())
        fut = srv.submit(IMG)
        with pytest.raises(ServerError) as ei:
            fut.result(timeout=10)
        assert "device exploded" in repr(ei.value.__cause__)
        # the NEXT interaction with the frontend re-raises too
        with pytest.raises(ServerError):
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                srv.submit(IMG)
                time.sleep(0.01)
        srv.close()

    def test_batcher_crash_reraises_at_frontend(self):
        class BadSizes(StubEngine):
            def batch_size_for(self, hw, n):
                raise RuntimeError("batcher bug")

        srv = make_server(BadSizes())
        fut = srv.submit(IMG)
        with pytest.raises(ServerError):
            fut.result(timeout=10)
        srv.close()

    def test_request_timeout_surfaces(self):
        engine = StubEngine(batch_sizes=(1,), delay_s=0.3)
        with make_server(engine, default_timeout_s=0.05) as srv:
            srv.submit(IMG)  # occupy the device
            fut = srv.submit(IMG)
            with pytest.raises((RequestTimeout, ServerClosed)):
                fut.result(timeout=10)


# ---- HTTP frontend -------------------------------------------------------


def _png_bytes(shape=(64, 64, 3)) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.zeros(shape, np.uint8)).save(buf, "PNG")
    return buf.getvalue()


class TestHttp:
    def test_detect_stats_and_shed_codes(self):
        with make_server() as srv:
            httpd = serve_http(srv)
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            host, port = httpd.server_address
            base = f"http://{host}:{port}"
            try:
                req = urllib.request.Request(
                    f"{base}/detect", data=_png_bytes(), method="POST"
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    assert r.status == 200
                    assert json.load(r)["detections"] == EXPECTED
                with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
                    assert json.load(r)["completed"] == 1
                # A bad INPUT is 400 (not retryable); only load sheds are
                # 503 (retryable) — the taxonomy distinction in codes.
                req = urllib.request.Request(
                    f"{base}/detect", data=b"garbage", method="POST"
                )
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=30)
                assert ei.value.code == 400
                assert json.load(ei.value)["reason"] == "decode_error"
            finally:
                httpd.shutdown()
                httpd.server_close()


# ---- replica identity (ISSUE 12 satellite) -------------------------------


class TestIdentity:
    def test_load_fields_carry_replica_id_and_version(self):
        """The fleet router cannot attribute health/weight without
        identity: every load_fields() payload names its replica and its
        engine's export version (stub engines say 'stub')."""
        with make_server() as srv:
            load = srv.load_fields()
        assert load["replica_id"]  # host-pid default, non-empty
        assert load["version"] == "stub"

    def test_explicit_replica_id_is_stable(self):
        srv = make_server()
        try:
            default_id = srv.replica_id
        finally:
            srv.close()
        srv = DetectionServer(
            StubEngine(), ServeConfig(preprocess_workers=1),
            replica_id="replica-7",
        )
        try:
            assert srv.load_fields()["replica_id"] == "replica-7"
            assert srv.load_fields()["replica_id"] != default_id
        finally:
            srv.close()


# ---- real model: THE parity pin + export engine --------------------------


@pytest.fixture(scope="module")
def tiny_coco(tmp_path_factory):
    """A 6-image synthetic COCO split with non-bucket source sizes (80x64)
    so the serve router's resize path is exercised for real."""
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        make_synthetic_coco,
    )

    root = str(tmp_path_factory.mktemp("serve_coco"))
    make_synthetic_coco(
        root, num_images=6, num_classes=3, image_size=(80, 64), seed=0
    )
    return CocoDataset(
        os.path.join(root, "instances_train.json"),
        os.path.join(root, "train"),
    )


def _detect_config():
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        DetectConfig,
    )

    # Sub-prior threshold: the untrained head's π=0.01 score prior sits
    # below the production 0.05 cut, which would make the parity check
    # vacuous (zero detections) — same policy as the eval bench.
    return DetectConfig(
        score_threshold=0.001, pre_nms_size=64, max_detections=10
    )


def _decode(ds, rec) -> np.ndarray:
    from PIL import Image

    with Image.open(ds.image_path(rec)) as im:
        return np.asarray(im.convert("RGB"), dtype=np.uint8)


@pytest.mark.parametrize("continuous", [True, False], ids=["continuous", "deadline"])
def test_served_detections_bit_identical_to_sequential_eval(
    tiny_model_and_state, tiny_coco, continuous
):
    """ACCEPTANCE: for the same images, the dynamic-batching server emits
    byte-for-byte the detections the sequential ``collect_detections``
    path does — same resize, same batch rows, same program, same
    conversion.  Pinned in BOTH batching modes (ISSUE 14): continuous
    slot-pool admission changes WHEN rows ride, never what they
    compute; score_threshold 0.001 keeps the oracle non-vacuous."""
    from batchai_retinanet_horovod_coco_tpu.data import (
        PipelineConfig,
        build_pipeline,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        collect_detections,
    )

    model, state = tiny_model_and_state
    ds = tiny_coco
    cfg = _detect_config()
    pipe = PipelineConfig(
        batch_size=2, buckets=((64, 64),), min_side=64, max_side=64,
        shuffle=False, hflip_prob=0.0, drop_remainder=False, num_workers=2,
    )
    batches = build_pipeline(ds, pipe, train=False)
    try:
        seq = collect_detections(
            state, model, ds, batches, cfg, pipelined=False
        )
    finally:
        batches.close()
    assert seq, "sequential path produced no detections (vacuous parity)"
    by_img: dict[int, list[dict]] = {}
    for d in seq:
        by_img.setdefault(d["image_id"], []).append(
            {k: v for k, v in d.items() if k != "image_id"}
        )

    engine = DetectEngine.from_state(
        model, state, buckets=((64, 64),), batch_sizes=(2,), config=cfg,
        min_side=64, max_side=64, label_to_cat_id=ds.label_to_cat_id,
    )
    with DetectionServer(
        engine,
        ServeConfig(
            max_delay_ms=50, preprocess_workers=1, continuous=continuous
        ),
    ) as srv:
        futs = [
            (rec.image_id, srv.submit(_decode(ds, rec)))
            for rec in ds.records
        ]
        served = {iid: f.result(timeout=120) for iid, f in futs}

    for rec in ds.records:
        assert served[rec.image_id] == by_img.get(rec.image_id, []), (
            f"served detections for image {rec.image_id} diverge from the "
            "sequential eval path"
        )


def test_engine_from_export_bit_identical_to_eval_on_same_artifacts(
    tiny_model_and_state, tiny_coco, tmp_path
):
    """The export-directory engine path: convert → load (no model code) →
    serve, and the served detections are bit-identical to the sequential
    ``collect_detections`` driver running THE SAME exported artifacts.

    (Exported programs bake params in as constants, which lets XLA fold
    them differently from the live path — observed ~1e-6 box deltas on
    some inputs — so the bit-identity oracle must hold the PROGRAM fixed
    and vary only the driver: batch server vs sequential eval loop.)"""
    from batchai_retinanet_horovod_coco_tpu.data import (
        PipelineConfig,
        build_pipeline,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        collect_detections,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
        export_model,
        load_model,
    )
    from batchai_retinanet_horovod_coco_tpu.ops.nms import Detections

    model, state = tiny_model_and_state
    ds = tiny_coco
    cfg = _detect_config()
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=(2,), config=cfg,
        label_to_cat_id=ds.label_to_cat_id,
        image_min_side=64, image_max_side=64,
    )

    # Sequential reference pass, detect_fns = the exported b2 artifact.
    loaded = load_model(str(tmp_path / "exp"))
    artifact = loaded.fn(2, (64, 64))
    pipe = PipelineConfig(
        batch_size=2, buckets=((64, 64),), min_side=64, max_side=64,
        shuffle=False, hflip_prob=0.0, drop_remainder=False, num_workers=2,
    )
    batches = build_pipeline(ds, pipe, train=False)
    try:
        seq = collect_detections(
            state, model, ds, batches, cfg, pipelined=False,
            detect_fns={(64, 64): lambda _s, imgs: Detections(*artifact(imgs))},
        )
    finally:
        batches.close()
    assert seq, "no detections through the exported artifact (vacuous)"
    by_img: dict[int, list[dict]] = {}
    for d in seq:
        by_img.setdefault(d["image_id"], []).append(
            {k: v for k, v in d.items() if k != "image_id"}
        )

    engine = DetectEngine.from_export(str(tmp_path / "exp"))
    assert engine.buckets == ((64, 64),)
    assert engine.batch_sizes((64, 64)) == [2]
    assert engine.min_side == 64 and engine.max_side == 64
    # Rollout identity: no manifest version → the export dir's basename.
    assert engine.version == "exp"
    with DetectionServer(
        engine, ServeConfig(max_delay_ms=100, preprocess_workers=1)
    ) as srv:
        futs = [
            (rec.image_id, srv.submit(_decode(ds, rec)))
            for rec in ds.records
        ]
        served = {iid: f.result(timeout=120) for iid, f in futs}
    for rec in ds.records:
        assert served[rec.image_id] == by_img.get(rec.image_id, [])


def test_engine_multi_batch_export_picks_smallest_fitting(
    tiny_model_and_state, tiny_coco, tmp_path
):
    """With (1, 4) exported, a lone request runs the batch-1 artifact —
    pinned by replaying the exact preprocessing + conversion against the
    artifact directly."""
    from batchai_retinanet_horovod_coco_tpu.data.pipeline import (
        resize_for_bucket,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.detect import (
        detections_to_coco,
    )
    from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
        export_model,
        load_model,
    )
    from batchai_retinanet_horovod_coco_tpu.ops.nms import Detections
    from batchai_retinanet_horovod_coco_tpu.serve.batcher import (
        assemble_requests,
    )
    from batchai_retinanet_horovod_coco_tpu.serve.common import ServeRequest

    model, state = tiny_model_and_state
    ds = tiny_coco
    cfg = _detect_config()
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=(1, 4), config=cfg,
        label_to_cat_id=ds.label_to_cat_id,
        image_min_side=64, image_max_side=64,
    )
    engine = DetectEngine.from_export(str(tmp_path / "exp"))
    assert engine.batch_sizes((64, 64)) == [1, 4]
    assert engine.batch_size_for((64, 64), 1) == 1
    assert engine.batch_size_for((64, 64), 3) == 4

    img = _decode(ds, ds.records[0])
    with DetectionServer(
        engine, ServeConfig(max_delay_ms=5, preprocess_workers=1)
    ) as srv:
        got = srv.submit(img).result(timeout=120)
        assert srv.snapshot()["batches"] == 1

    # Expected: the b1 artifact through the same assembly + conversion.
    req = ServeRequest(0, None, None)
    resized, scale = resize_for_bucket(img, (64, 64), 64, 64)
    req.image, req.scale = resized, np.float32(scale)
    h, w = img.shape[:2]
    req.orig_wh = (w, h)
    assembled = assemble_requests([req], (64, 64), 1)
    loaded = load_model(str(tmp_path / "exp"))
    det = Detections(*loaded.fn(1, (64, 64))(assembled.images))
    import jax

    want = detections_to_coco(
        jax.device_get(det), np.array([0], np.int64), assembled.scales,
        assembled.valid, engine.label_to_cat_id, image_sizes={0: (w, h)},
    )
    for d in want:
        d.pop("image_id")
    assert got == want and got


def test_serve_cli_offline_mode(tiny_model_and_state, tiny_coco, tmp_path):
    """The serve CLI end-to-end in offline mode: export dir in, detections
    JSONL out, stats snapshot returned."""
    from batchai_retinanet_horovod_coco_tpu.evaluate.export import (
        export_model,
    )
    from batchai_retinanet_horovod_coco_tpu.serve import frontend

    model, state = tiny_model_and_state
    ds = tiny_coco
    export_model(
        state, model, str(tmp_path / "exp"), buckets=((64, 64),),
        batch_size=2, config=_detect_config(),
        label_to_cat_id=ds.label_to_cat_id,
        image_min_side=64, image_max_side=64,
    )
    img_dir = os.path.dirname(ds.image_path(ds.records[0]))
    out = tmp_path / "dets.jsonl"
    # Admission queue smaller than the directory: the offline client must
    # backpressure on sheds (drain in-flight, retry) and still process
    # every image.
    snap = frontend.main(
        ["--export-dir", str(tmp_path / "exp"),
         "--images", img_dir, "--output", str(out),
         "--serve-max-delay-ms", "20", "--serve-admission-queue", "2"]
    )
    assert snap["completed"] == len(ds.records)
    records = [json.loads(line) for line in out.read_text().splitlines()]
    assert len(records) == len(ds.records)
    assert all("detections" in r for r in records)
    assert sum(len(r["detections"]) for r in records) > 0


# ---- watchdog-coverage satellite -----------------------------------------


class TestAuditCoversServe:
    """scripts/audit_threads.py must cover serve/ (ISSUE 4 satellite)."""

    def _audit(self):
        sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
        try:
            import audit_threads
        finally:
            sys.path.pop(0)
        return audit_threads

    def test_serve_spawn_sites_are_covered(self):
        audit = self._audit()
        serve_dir = os.path.join(
            REPO_ROOT, "batchai_retinanet_horovod_coco_tpu", "serve"
        )
        violations = audit.audit_package(serve_dir)
        assert violations == []
        # ... and not vacuously: the audit must actually SEE the serve
        # spawn sites (engine dispatcher, router workers, batchers).
        import ast

        spawns = 0
        for fn in os.listdir(serve_dir):
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(serve_dir, fn)) as f:
                tree = ast.parse(f.read())
            spawns += sum(1 for _ in audit._spawn_calls(tree))
        assert spawns >= 3

    def test_audit_bites_on_unwatched_serve_spawn(self, tmp_path):
        audit = self._audit()
        bad = tmp_path / "rogue_serve_worker.py"
        bad.write_text(
            "import threading\n"
            "t = threading.Thread(target=print)\n"
            "t.start()\n"
        )
        violations = audit.audit_file(str(bad))
        assert len(violations) == 1
        assert "watchdog" in violations[0]["reason"]
