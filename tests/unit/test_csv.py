"""CSV dataset source (keras-retinanet CSVGenerator format parity).

Mirrors the reference's tests/preprocessing CSV tests (SURVEY.md §4): format
parsing, empty-image rows, and the validation errors (malformed rows, inverted
boxes, unknown/duplicate classes) — plus plug-compatibility with the bucketed
pipeline, which the reference exercised through its Generator base class.
"""

import numpy as np
import pytest
from PIL import Image

from batchai_retinanet_horovod_coco_tpu.data import (
    CsvDataset,
    PipelineConfig,
    build_pipeline,
)
from batchai_retinanet_horovod_coco_tpu.data.csv import read_classes


@pytest.fixture(scope="module")
def csv_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("csvds")
    rng = np.random.default_rng(0)
    for name, (w, h) in [("a.jpg", (64, 48)), ("b.jpg", (40, 80)), ("c.jpg", (32, 32))]:
        Image.fromarray(
            rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        ).save(root / name)
    (root / "classes.csv").write_text("cat,0\ndog,1\n")
    (root / "annotations.csv").write_text(
        "a.jpg,1,2,30,40,cat\n"
        "a.jpg,5,5,20,20,dog\n"
        "b.jpg,0,0,10,70,dog\n"
        "c.jpg,,,,,\n"
    )
    return root


def make_ds(root, **kw):
    return CsvDataset(
        str(root / "annotations.csv"), str(root / "classes.csv"), **kw
    )


def test_parse_basic(csv_root):
    ds = make_ds(csv_root)
    assert ds.num_classes == 2
    assert ds.class_names == ["cat", "dog"]
    # c.jpg has no annotations and keep_empty defaults False.
    assert [r.file_name for r in ds.records] == ["a.jpg", "b.jpg"]
    rec = ds.records[0]
    assert rec.width == 64 and rec.height == 48  # from the image header
    np.testing.assert_allclose(rec.boxes, [[1, 2, 30, 40], [5, 5, 20, 20]])
    np.testing.assert_array_equal(rec.labels, [0, 1])
    np.testing.assert_allclose(rec.areas, [(30 - 1) * (40 - 2), 15 * 15])


def test_keep_empty(csv_root):
    ds = make_ds(csv_root, keep_empty=True)
    assert [r.file_name for r in ds.records] == ["a.jpg", "b.jpg", "c.jpg"]
    empty = ds.records[-1]
    assert empty.boxes.shape == (0, 4) and empty.labels.shape == (0,)


def test_identity_category_mapping(csv_root):
    # CSV class ids ARE the contiguous labels (unlike COCO's sparse ids).
    ds = make_ds(csv_root)
    assert ds.label_to_cat_id == {0: 0, 1: 1}
    assert ds.cat_id_to_label == {0: 0, 1: 1}


@pytest.mark.parametrize(
    "bad_row, match",
    [
        ("a.jpg,1,2,3,cat", "expected"),  # wrong field count
        ("a.jpg,x,2,30,40,cat", "malformed x1"),
        ("a.jpg,30,2,1,40,cat", "x2 .* must be > x1"),
        ("a.jpg,1,40,30,2,cat", "y2 .* must be > y1"),
        ("a.jpg,1,2,30,40,bird", "unknown class"),
        ("a.jpg,nan,nan,nan,nan,cat", "malformed x1"),
        ("a.jpg,1,2,inf,40,cat", "malformed x2"),
    ],
)
def test_validation_errors(csv_root, tmp_path, bad_row, match):
    ann = tmp_path / "bad.csv"
    ann.write_text(bad_row + "\n")
    with pytest.raises(ValueError, match=match):
        CsvDataset(
            str(ann), str(csv_root / "classes.csv"),
            image_dir=str(csv_root),
        )


def test_class_file_errors(tmp_path):
    bad = tmp_path / "classes.csv"
    bad.write_text("cat,0\ncat,1\n")
    with pytest.raises(ValueError, match="duplicate class name"):
        read_classes(str(bad))
    bad.write_text("cat,0\ndog,0\n")
    with pytest.raises(ValueError, match="duplicate class id"):
        read_classes(str(bad))
    bad.write_text("cat,0\ndog,2\n")
    with pytest.raises(ValueError, match="contiguous"):
        read_classes(str(bad))
    bad.write_text("cat,0\ndog,1.5\n")
    with pytest.raises(ValueError, match="malformed class id"):
        read_classes(str(bad))


def test_pipeline_compatibility(csv_root):
    """The bucketed pipeline consumes a CsvDataset unchanged."""
    ds = make_ds(csv_root)
    batches = build_pipeline(
        ds,
        PipelineConfig(
            batch_size=2, buckets=((96, 96),), min_side=64, max_side=96,
            max_gt=10, num_workers=2, shuffle=False,
        ),
        train=False,
    )
    batch = next(iter(batches))
    assert batch.images.shape == (2, 96, 96, 3)
    assert batch.gt_boxes.shape == (2, 10, 4)
    # a.jpg (64x48) scales by min(64/48 rule, fit) — boxes scale with it.
    assert batch.gt_mask[0].sum() == 2


def test_underscore_literals_rejected(csv_root, tmp_path):
    # Python allows digit-group underscores ('1_0' == 10); a CSV containing
    # one is a typo and must be rejected, for class ids and coordinates both.
    bad = tmp_path / "classes.csv"
    bad.write_text("cat,1_0\n")
    with pytest.raises(ValueError, match="malformed class id"):
        read_classes(str(bad))
    ann = tmp_path / "bad.csv"
    ann.write_text("a.jpg,1_0,2,30,40,cat\n")
    with pytest.raises(ValueError, match="malformed x1"):
        CsvDataset(str(ann), str(csv_root / "classes.csv"),
                   image_dir=str(csv_root))


def test_error_reports_physical_line_number(csv_root, tmp_path):
    # A quoted field spanning two physical lines: the error on the NEXT
    # record must cite the physical file line (3), not the record index (2).
    ann = tmp_path / "multiline.csv"
    ann.write_text('"a\nb.jpg",1,2,30,40,cat\nc.jpg,x,2,30,40,cat\n')
    with pytest.raises(ValueError, match="line 3"):
        CsvDataset(str(ann), str(csv_root / "classes.csv"),
                   image_dir=str(csv_root))
