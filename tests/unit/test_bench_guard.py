"""bench.py's TPU-outage contract (VERDICT r5 missing #1 / weak #1).

``BENCH_r05.json`` was an unparseable rc-1 traceback because the tunnel
died at capture time.  The contract now: a persistent UNAVAILABLE (or a
hung backend init — the probe runs in a subprocess precisely because init
can hang, not raise) produces ONE structured JSON line carrying the
committed last-known-good rate, with the distinct exit code 75
(EX_TEMPFAIL); real errors keep propagating as rc 1.
"""

import json
import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
)

import bench  # noqa: E402

_UNAVAILABLE_MSG = (
    "RuntimeError: Unable to initialize backend 'axon': "
    "UNAVAILABLE: TPU backend setup/compile error"
)


@pytest.fixture
def fast_probe_env(monkeypatch):
    """Bounded, sleep-free probe for tests."""
    monkeypatch.setenv("BENCH_PROBE_ATTEMPTS", "2")
    monkeypatch.setenv("BENCH_PROBE_BACKOFF_S", "0")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "5")


class TestUnreachableClassification:
    @pytest.mark.parametrize("mode", ["train", "eval", "serve"])
    def test_persistent_unavailable_emits_one_line_and_exit_75(
        self, mode, fast_probe_env, monkeypatch, capsys
    ):
        monkeypatch.setattr(bench, "_probe_once", lambda t: _UNAVAILABLE_MSG)
        with pytest.raises(SystemExit) as exc:
            bench.main(["--mode", mode])
        assert exc.value.code == bench.EXIT_TPU_UNREACHABLE == 75

        lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
        assert len(lines) == 1, "exactly ONE structured line, no traceback"
        rec = json.loads(lines[0])
        assert rec["error"] == "tpu_unreachable"
        assert rec["mode"] == mode
        assert rec["phase"] == "probe"
        assert rec["attempts"] == 2
        assert "UNAVAILABLE" in rec["last_error"]
        assert rec["exit_code"] == 75
        # The committed rate travels with the outage record, labeled stale.
        lkg = rec["last_known_good"]
        assert lkg is not None
        assert lkg["value"] > 0
        assert "NOT a fresh measurement" in lkg["note"]
        assert lkg["source"] == {
            "eval": "EVALBENCH.json",
            "serve": "SERVEBENCH.json",
        }.get(mode, "BUCKETBENCH.json")

    def test_probe_hang_classified_via_subprocess_timeout(
        self, fast_probe_env, monkeypatch, capsys
    ):
        # A dead tunnel HANGS init; _probe_once reports the bounded timeout.
        monkeypatch.setattr(
            bench, "_probe_once",
            lambda t: f"probe timed out after {t:.0f}s (backend init hang)",
        )
        with pytest.raises(SystemExit) as exc:
            bench.main([])
        assert exc.value.code == 75
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["error"] == "tpu_unreachable"
        assert "timed out" in rec["last_error"]

    def test_midrun_unavailable_classified(
        self, fast_probe_env, monkeypatch, capsys
    ):
        # Probe passes; the tunnel dies during the run.  Still classified.
        monkeypatch.setattr(bench, "_probe_once", lambda t: None)

        def dies(*a, **k):
            raise RuntimeError(_UNAVAILABLE_MSG)

        monkeypatch.setattr(bench, "run_train_mode", dies)
        with pytest.raises(SystemExit) as exc:
            bench.main([])
        assert exc.value.code == 75
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["phase"] == "mid-run"

    def test_real_errors_still_propagate(
        self, fast_probe_env, monkeypatch, capsys
    ):
        """OOM and ordinary bugs must NOT be classified as outages."""
        monkeypatch.setattr(bench, "_probe_once", lambda t: None)

        def oom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory in HBM")

        monkeypatch.setattr(bench, "run_train_mode", oom)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            bench.main([])

    def test_classifier_is_narrow(self):
        assert bench.is_unavailable_error(RuntimeError(_UNAVAILABLE_MSG))
        assert bench.is_unavailable_error("DEADLINE_EXCEEDED: poll")
        assert not bench.is_unavailable_error(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory")
        )
        assert not bench.is_unavailable_error(ValueError("shape mismatch"))

    def test_classifier_walks_the_exception_chain(self):
        """The r05 crash class: jax re-wraps the backend-init UNAVAILABLE
        RuntimeError (traceback filtering / lazy-dispatch shims), so the
        marker text sits one link down the __cause__/__context__ chain.
        Any chained backend-init outage classifies; a chain of ordinary
        errors stays narrow."""
        try:
            try:
                raise RuntimeError(_UNAVAILABLE_MSG)
            except RuntimeError as inner:
                raise ValueError("jax-filtered rewrap") from inner
        except ValueError as wrapped:
            assert bench.is_unavailable_error(wrapped)
        try:
            try:
                raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory")
            except RuntimeError:
                raise ValueError("secondary failure")
        except ValueError as wrapped:
            assert not bench.is_unavailable_error(wrapped)

    def test_chain_wrapped_midrun_unavailable_exits_75(
        self, fast_probe_env, monkeypatch, capsys
    ):
        monkeypatch.setattr(bench, "_probe_once", lambda t: None)

        def dies_wrapped(*a, **k):
            try:
                raise RuntimeError(_UNAVAILABLE_MSG)
            except RuntimeError as inner:
                raise ValueError("deferred dispatch rewrap") from inner

        monkeypatch.setattr(bench, "run_train_mode", dies_wrapped)
        with pytest.raises(SystemExit) as exc:
            bench.main([])
        assert exc.value.code == 75
        rec = json.loads(capsys.readouterr().out.strip())
        assert rec["phase"] == "mid-run"
        assert rec["last_known_good"] is not None


class TestInjectedBackendInitOutage:
    """ISSUE 6 acceptance: a backend shim that dies with the init
    UNAVAILABLE RuntimeError at the first lazy dispatch (the exact
    BENCH_r05 environment) must exit 75 with the structured line + the
    committed last-known-good — in WHATEVER phase it surfaces, import
    included — never an rc-1 traceback."""

    def test_injected_backend_init_unavailable_exits_75(self, tmp_path):
        import subprocess

        (tmp_path / "usercustomize.py").write_text(
            "import os\n"
            "if os.environ.get('FAKE_BACKEND_DOWN') == '1':\n"
            "    from jax._src import xla_bridge\n"
            "    def _boom(*a, **k):\n"
            "        raise RuntimeError(\n"
            "            \"Unable to initialize backend 'axon': UNAVAILABLE: \"\n"
            "            'TPU backend setup/compile error (Unavailable). '\n"
            "            \"(set JAX_PLATFORMS='' to automatically choose an \"\n"
            "            'available backend)')\n"
            "    xla_bridge.get_backend = _boom\n"
            "    xla_bridge.backends = _boom\n"
        )
        repo = os.path.dirname(os.path.abspath(bench.__file__))
        env = dict(
            os.environ,
            PYTHONPATH=str(tmp_path),
            FAKE_BACKEND_DOWN="1",
            BENCH_PROBE="0",  # probe inherits the shim; skip to reach the run
            BENCH_SWEEP="0",
        )
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py")],
            capture_output=True, text=True, timeout=240, env=env, cwd=repo,
        )
        assert r.returncode == 75, (r.returncode, r.stdout, r.stderr[-2000:])
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        rec = json.loads(lines[-1])
        assert rec["error"] == "tpu_unreachable"
        assert rec["mode"] == "train"
        assert rec["phase"] in ("import", "mid-run")
        assert "UNAVAILABLE" in rec["last_error"]
        lkg = rec["last_known_good"]
        assert lkg is not None and lkg["value"] > 0
        assert lkg["source"] == "BUCKETBENCH.json"


class TestProbeRetries:
    def test_probe_retries_until_success(self, fast_probe_env, monkeypatch):
        results = iter([_UNAVAILABLE_MSG, None])
        monkeypatch.setattr(bench, "_probe_once", lambda t: next(results))
        attempts, err = bench.probe_device()
        assert (attempts, err) == (2, None)

    def test_probe_exhausts_attempts(self, fast_probe_env, monkeypatch):
        calls = []

        def failing(t):
            calls.append(t)
            return _UNAVAILABLE_MSG

        monkeypatch.setattr(bench, "_probe_once", failing)
        attempts, err = bench.probe_device()
        assert attempts == 2 and len(calls) == 2
        assert "UNAVAILABLE" in err

    def test_real_probe_succeeds_on_cpu(self):
        """The actual subprocess probe against this box's default backend
        (CPU under the test env) — the zero-mock sanity leg."""
        err = bench._probe_once(timeout_s=120)
        assert err is None


class TestTrainBenchCheckDeviceGuard:
    def test_cpu_fallback_passes_with_note_against_legacy_artifact(
        self, capsys
    ):
        """BUCKETBENCH.json predates the device_kind field (a chip capture
        by provenance): a CPU-fallback session must report the class
        mismatch instead of misclassifying itself as a regression."""
        rc = bench.check_against_committed(0.1, "cpu")
        out = capsys.readouterr().out
        assert rc == 0
        assert "not comparable" in out

    def test_accelerator_run_still_compares_against_legacy_artifact(
        self, capsys
    ):
        """A non-CPU run keeps the full floor comparison (the driver's
        TPU-attached environment must keep its tripwire teeth)."""
        rc = bench.check_against_committed(0.1, "TPU v5 lite")
        out = capsys.readouterr().out
        assert rc == 1
        assert "REGRESSION" in out

    def test_no_device_given_keeps_legacy_behavior(self, capsys):
        assert bench.check_against_committed(0.1) == 1
        assert "REGRESSION" in capsys.readouterr().out


class TestEvalBenchCheck:
    def test_device_mismatch_passes_with_note(self, capsys):
        if not os.path.exists(
            os.path.join(os.path.dirname(os.path.abspath(bench.__file__)),
                         "EVALBENCH.json")
        ):
            pytest.skip("EVALBENCH.json not committed yet")
        rc = bench.check_eval_against_committed(1.0, "some-future-chip")
        out = capsys.readouterr().out
        assert rc == 0
        assert "not comparable" in out

    def test_regression_fails_on_matching_device(self, capsys):
        path = os.path.join(
            os.path.dirname(os.path.abspath(bench.__file__)), "EVALBENCH.json"
        )
        if not os.path.exists(path):
            pytest.skip("EVALBENCH.json not committed yet")
        with open(path) as f:
            committed = json.load(f)
        kind = committed["device_kind"]
        value = float(committed["value"])
        assert bench.check_eval_against_committed(value * 0.995, kind) == 0
        assert bench.check_eval_against_committed(value * 0.95, kind) == 1
        out = capsys.readouterr().out
        assert "ok" in out and "REGRESSION" in out


class TestServeBenchCheck:
    """servebench-check (ISSUE 4): the committed SERVEBENCH.json flagship
    closed-loop rate minus the noise band is the floor, with the same
    device-class guard as bench-check/evalbench-check."""

    def _committed(self):
        path = os.path.join(
            os.path.dirname(os.path.abspath(bench.__file__)),
            "SERVEBENCH.json",
        )
        with open(path) as f:
            return json.load(f)

    def test_device_mismatch_passes_with_note(self, capsys):
        rc = bench.check_serve_against_committed(1.0, "some-future-chip")
        out = capsys.readouterr().out
        assert rc == 0
        assert "not comparable" in out

    def test_floor_band_on_matching_device(self, capsys):
        committed = self._committed()
        kind = committed["device_kind"]
        value = float(committed["value"])
        assert bench.check_serve_against_committed(value * 0.995, kind) == 0
        assert bench.check_serve_against_committed(value * 0.95, kind) == 1
        out = capsys.readouterr().out
        assert "ok" in out and "REGRESSION" in out

    def test_committed_artifact_schema(self):
        """The committed capture must carry the fields the check and the
        RUNBOOK read: device_kind, per-bucket ceiling ratio, overload
        evidence that bounded queues shed."""
        committed = self._committed()
        assert committed["metric"] == "serve_images_per_sec_per_chip"
        assert committed["device_kind"]
        assert committed["value"] > 0
        flagship = committed["per_bucket"][
            f"{bench.BUCKET[0]}x{bench.BUCKET[1]}"
        ]
        assert flagship["detect_ceiling_imgs_per_sec"] > 0
        assert 0 < flagship["vs_ceiling"] <= 1.5
        overload = flagship["overload"]
        assert overload["shed_at_submit"] > 0
        assert overload["resolved"] == overload["accepted"]
        assert overload["sheds_instead_of_queueing"] is True

    def test_committed_continuous_record_holds_the_contract(self):
        """ISSUE 14: the committed continuous-vs-deadline leg must show
        occupancy strictly above deadline-only under the same seeded
        schedule, p99 no worse than the band, and — when the live leg
        was captured — bit-identity true."""
        cont = self._committed().get("continuous")
        assert cont, "SERVEBENCH.json has no continuous record"
        assert cont["engine"] == "stub"  # device-independent comparison
        assert (
            cont["continuous"]["occupancy_mean"]
            > cont["deadline"]["occupancy_mean"]
        )
        assert cont["p99_ratio"] <= 1.25
        if cont.get("e2e"):
            assert cont["e2e"]["bit_identical"] is True

    def test_continuous_check_bites_on_occupancy_regression(self, capsys):
        fresh = {
            "engine": "stub",
            "deadline": {"occupancy_mean": 0.8, "p99_ms": 100.0},
            "continuous": {"occupancy_mean": 0.7, "p99_ms": 100.0},
            "p99_ratio": 1.0,
        }
        assert bench.check_continuous_against_committed(fresh) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_continuous_check_bites_on_p99_band(self, capsys):
        fresh = {
            "engine": "stub",
            "deadline": {"occupancy_mean": 0.6, "p99_ms": 100.0},
            "continuous": {"occupancy_mean": 0.8, "p99_ms": 200.0},
            "p99_ratio": 2.0,
        }
        assert bench.check_continuous_against_committed(fresh) == 1
        out = capsys.readouterr().out
        assert "p99 ratio" in out and "REGRESSION" in out

    def test_continuous_check_bites_on_bit_identity(self, capsys):
        fresh = {
            "engine": "stub",
            "deadline": {"occupancy_mean": 0.6, "p99_ms": 100.0},
            "continuous": {"occupancy_mean": 0.8, "p99_ms": 100.0},
            "p99_ratio": 1.0,
            "e2e": {"bit_identical": False},
        }
        assert bench.check_continuous_against_committed(fresh) == 1
        assert "diverged" in capsys.readouterr().out

    def test_committed_autoscale_record_holds_the_contract(self):
        """ISSUE 19: the committed autoscale leg must show the fleet
        grew under the spike, drained back to min when the day quieted,
        and no request was ever dropped across scaling."""
        scale = self._committed().get("autoscale")
        assert scale, "SERVEBENCH.json has no autoscale record"
        assert scale["engine"] == "stub"  # device-independent comparison
        assert scale["dropped"] == 0
        assert scale["scaled_up"] >= 1 and scale["scaled_down"] >= 1
        assert scale["peak_replicas"] >= 2
        assert scale["final_replicas"] == scale["min_replicas"]
        # Trajectory evidence: offered load monotone, replica count
        # actually moved (the control loop lived through the day).
        traj = scale["trajectory"]
        assert len({int(s[2]) for s in traj}) >= 2

    def _scale_fresh(self, **over):
        fresh = {
            "engine": "stub", "requests": 240, "completed": 240,
            "shed": 0, "dropped": 0, "p99_ms": 150.0, "scaled_up": 2,
            "scaled_down": 2, "peak_replicas": 3, "final_replicas": 1,
            "min_replicas": 1, "max_replicas": 3,
        }
        fresh.update(over)
        return fresh

    def test_autoscale_check_bites_on_dropped_requests(self, capsys):
        fresh = self._scale_fresh(dropped=3)
        assert bench.check_autoscale_against_committed(fresh) == 1
        assert "never resolved" in capsys.readouterr().out

    def test_autoscale_check_bites_on_dead_control_loop(self, capsys):
        fresh = self._scale_fresh(scaled_up=0, peak_replicas=1)
        assert bench.check_autoscale_against_committed(fresh) == 1
        assert "never scaled up" in capsys.readouterr().out

    def test_autoscale_check_bites_on_stuck_fleet(self, capsys):
        fresh = self._scale_fresh(final_replicas=3)
        assert bench.check_autoscale_against_committed(fresh) == 1
        assert "never returned to min" in capsys.readouterr().out

    def test_autoscale_check_bites_on_p99_band(self, capsys):
        committed = self._committed()["autoscale"]
        fresh = self._scale_fresh(
            p99_ms=committed["p99_ms"] * 10.0
        )
        assert bench.check_autoscale_against_committed(fresh) == 1
        assert "latency not held" in capsys.readouterr().out

    def test_autoscale_check_passes_on_healthy_fresh(self, capsys):
        fresh = self._scale_fresh()
        assert bench.check_autoscale_against_committed(fresh) == 0
        assert "zero dropped: ok" in capsys.readouterr().out
