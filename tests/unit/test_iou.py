import numpy as np

from batchai_retinanet_horovod_coco_tpu.ops.iou import pairwise_iou


def brute_force_iou(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    out = np.zeros((len(a), len(b)), dtype=np.float64)
    for i, bi in enumerate(a):
        for j, bj in enumerate(b):
            ix1 = max(bi[0], bj[0])
            iy1 = max(bi[1], bj[1])
            ix2 = min(bi[2], bj[2])
            iy2 = min(bi[3], bj[3])
            iw = max(ix2 - ix1, 0.0)
            ih = max(iy2 - iy1, 0.0)
            inter = iw * ih
            area_i = max(bi[2] - bi[0], 0) * max(bi[3] - bi[1], 0)
            area_j = max(bj[2] - bj[0], 0) * max(bj[3] - bj[1], 0)
            union = area_i + area_j - inter
            out[i, j] = inter / union if union > 0 else 0.0
    return out


def test_iou_matches_brute_force():
    rng = np.random.default_rng(0)
    xy = rng.uniform(0, 100, size=(40, 2))
    wh = rng.uniform(1, 50, size=(40, 2))
    a = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
    xy = rng.uniform(0, 100, size=(17, 2))
    wh = rng.uniform(1, 50, size=(17, 2))
    b = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
    got = np.asarray(pairwise_iou(a, b))
    np.testing.assert_allclose(got, brute_force_iou(a, b), atol=1e-5)


def test_iou_exact_values():
    a = np.array([[0, 0, 10, 10]], dtype=np.float32)
    b = np.array(
        [[0, 0, 10, 10], [5, 5, 15, 15], [10, 10, 20, 20], [20, 20, 30, 30]],
        dtype=np.float32,
    )
    got = np.asarray(pairwise_iou(a, b))[0]
    np.testing.assert_allclose(got, [1.0, 25.0 / 175.0, 0.0, 0.0], atol=1e-6)


def test_degenerate_boxes_zero_iou():
    a = np.array([[5, 5, 5, 5], [3, 3, 2, 2]], dtype=np.float32)  # degenerate
    b = np.array([[0, 0, 10, 10]], dtype=np.float32)
    got = np.asarray(pairwise_iou(a, b))
    np.testing.assert_allclose(got, 0.0)
