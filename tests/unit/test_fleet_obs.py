"""Fleet-scope observability (ISSUE 15): cross-process request tracing,
metrics federation, merged fleet traces, fleet SLO rules.

Families:

- header propagation end-to-end: a trace id POSTed at the fleet edge is
  carried through ``HttpReplica`` to real replica frontends, tagged onto
  their ``serve_request`` spans, and echoed back on every response;
- metrics federation: each replica's Prometheus exposition round-trips
  through the fleet scrape replica-labeled with EQUAL values, plus the
  derived fleet aggregates the SLO monitor evaluates;
- merged fleet trace: valid Chrome schema with disjoint per-replica
  tracks, and a shed-then-redispatched request's ``serve_request`` spans
  landing on BOTH replicas under ONE trace id;
- fleet SLO: the availability-floor rule fires EXACTLY ONCE on an
  injected replica stall (injectable clock — no sleeps);
- the ``obs/analyze --fleet`` report: per-replica decomposition, the
  event timeline, and a verdict naming the killed replica.
"""

from __future__ import annotations

import json
import queue
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry, trace
from batchai_retinanet_horovod_coco_tpu.obs.analyze.report import (
    analyze_fleet_dir,
    validate_report,
)
from batchai_retinanet_horovod_coco_tpu.obs.telemetry import (
    parse_exposition_samples,
)
from batchai_retinanet_horovod_coco_tpu.serve import (
    DetectionServer,
    FleetConfig,
    FleetRouter,
    HttpReplica,
    LocalReplica,
    ServeConfig,
    serve_http,
)
from batchai_retinanet_horovod_coco_tpu.serve.fleet import (
    CLOSED,
    serve_fleet_http,
)
from batchai_retinanet_horovod_coco_tpu.serve.stub import (
    EXPECTED_DETECTIONS,
    StubDetectEngine,
)
from batchai_retinanet_horovod_coco_tpu.utils.backoff import BackoffPolicy

IMG = np.zeros((64, 64, 3), np.uint8)

EXACT_BACKOFF = BackoffPolicy(
    max_tries=1_000_000, base_s=1.0, multiplier=2.0, ceiling_s=8.0,
    jitter=0.0,
)


@pytest.fixture(autouse=True)
def _obs_state():
    telemetry.reset()
    trace.reset()
    yield
    telemetry.reset()
    trace.reset()


def make_server(rid: str, **cfg) -> DetectionServer:
    cfg.setdefault("max_delay_ms", 10)
    cfg.setdefault("preprocess_workers", 1)
    return DetectionServer(
        StubDetectEngine(), ServeConfig(**cfg), replica_id=rid
    )


def make_router(replicas, **cfg) -> FleetRouter:
    cfg.setdefault("probe_backoff", EXACT_BACKOFF)
    cfg.setdefault("poll_interval_s", 0.05)
    return FleetRouter(replicas, FleetConfig(**cfg), auto_poll=False)


def _png_bytes() -> bytes:
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(IMG).save(buf, "PNG")
    return buf.getvalue()


def _post(url: str, data: bytes, headers: dict | None = None):
    """(status, headers, payload dict) — HTTP errors are data here."""
    req = urllib.request.Request(url, data=data, method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read().decode())


def _serve_request_spans() -> list[dict]:
    return [
        e
        for e in trace.snapshot_events()
        if e.get("ph") == "X" and e.get("name") == "serve_request"
    ]


# ---- header propagation end-to-end (two real HTTP replicas) --------------


class TestHeaderPropagation:
    def test_trace_id_flows_edge_to_replicas_and_back(self, tmp_path):
        trace.configure(str(tmp_path))
        servers = [make_server("prop-r0"), make_server("prop-r1")]
        httpds, threads = [], []
        try:
            for srv in servers:
                httpd = serve_http(srv)
                # watchdog: test-local HTTP listener, bounded by shutdown.
                t = threading.Thread(target=httpd.serve_forever, daemon=True)
                t.start()
                httpds.append(httpd)
                threads.append(t)
            replicas = [
                HttpReplica(
                    f"http://{h.server_address[0]}:{h.server_address[1]}",
                    replica_id=srv.replica_id,
                )
                for h, srv in zip(httpds, servers)
            ]
            router = make_router(replicas)
            fleet_httpd = serve_fleet_http(router)
            # watchdog: test-local HTTP listener, bounded by shutdown.
            ft = threading.Thread(
                target=fleet_httpd.serve_forever, daemon=True
            )
            ft.start()
            base = (
                f"http://{fleet_httpd.server_address[0]}:"
                f"{fleet_httpd.server_address[1]}"
            )
            try:
                # A client-supplied id round-trips: response header AND
                # JSON field echo it verbatim.
                code, headers, payload = _post(
                    f"{base}/detect", _png_bytes(),
                    {trace.TRACE_HEADER: "client-trace-1"},
                )
                assert code == 200
                assert payload["detections"] == EXPECTED_DETECTIONS
                assert payload["trace_id"] == "client-trace-1"
                assert headers.get(trace.TRACE_HEADER) == "client-trace-1"
                # No header: the fleet edge mints one and still echoes.
                code, headers, payload = _post(
                    f"{base}/detect", _png_bytes()
                )
                assert code == 200
                minted = payload["trace_id"]
                assert minted and headers.get(trace.TRACE_HEADER) == minted
                # The replica frontends (same process here) tagged their
                # serve_request spans with the propagated ids.
                spans = _serve_request_spans()
                tagged = {
                    (e["args"].get("trace"), e["args"].get("replica"))
                    for e in spans
                    if e.get("args", {}).get("trace")
                }
                assert any(t == "client-trace-1" for t, _ in tagged)
                assert any(t == minted for t, _ in tagged)
                # Replica frontends echo directly too (satellite: clients
                # of a single replica correlate without the fleet).
                rep_base = replicas[0].base_url
                code, headers, payload = _post(
                    f"{rep_base}/detect", _png_bytes(),
                    {trace.TRACE_HEADER: "direct-1"},
                )
                assert code == 200
                assert payload["trace_id"] == "direct-1"
                assert headers.get(trace.TRACE_HEADER) == "direct-1"
            finally:
                fleet_httpd.shutdown()
                fleet_httpd.server_close()
                router.close()
        finally:
            for httpd in httpds:
                httpd.shutdown()
                httpd.server_close()
            for srv in servers:
                srv.close(drain=False)

    def test_error_responses_echo_the_trace_id(self):
        srv = make_server("prop-err")
        try:
            httpd = serve_http(srv)
            # watchdog: test-local HTTP listener, bounded by shutdown.
            t = threading.Thread(target=httpd.serve_forever, daemon=True)
            t.start()
            base = (
                f"http://{httpd.server_address[0]}:"
                f"{httpd.server_address[1]}"
            )
            try:
                code, headers, payload = _post(
                    f"{base}/detect", b"garbage",
                    {trace.TRACE_HEADER: "bad-input-1"},
                )
                assert code == 400
                assert payload["reason"] == "decode_error"
                assert payload["trace_id"] == "bad-input-1"
                assert headers.get(trace.TRACE_HEADER) == "bad-input-1"
            finally:
                httpd.shutdown()
                httpd.server_close()
        finally:
            srv.close(drain=False)


# ---- metrics federation --------------------------------------------------


class TestFederation:
    def test_federated_scrape_round_trips_each_replica_registry(self):
        servers = [make_server("fed-r0"), make_server("fed-r1")]
        replicas = [LocalReplica(s) for s in servers]
        router = make_router(replicas)
        try:
            for _ in range(3):
                assert (
                    router.detect(IMG, timeout_s=20) == EXPECTED_DETECTIONS
                )
            # Freeze each replica's exposition so the equality below is
            # exact (live registries move between scrapes — ages, new
            # requests); the round-trip under test is parse → re-label →
            # re-expose, not clock stability.
            frozen = {}
            for rep in replicas:
                text = rep.metrics_text()
                frozen[rep.replica_id] = text
                rep.metrics_text = (lambda t=text: t)  # type: ignore
            router.scrape_metrics_once()
            fleet_types, fleet_samples = parse_exposition_samples(
                router.telemetry.prometheus_text()
            )
            fleet_by_key = {
                (name, tuple(sorted(labels.items()))): value
                for name, labels, value in fleet_samples
            }
            for rid, text in frozen.items():
                _types, samples = parse_exposition_samples(text)
                assert samples, f"replica {rid} exposed nothing"
                for name, labels, value in samples:
                    key = (
                        name,
                        tuple(sorted({**labels, "replica": rid}.items())),
                    )
                    assert key in fleet_by_key, (
                        f"federated /metrics lost {name}{labels} of {rid}"
                    )
                    assert fleet_by_key[key] == pytest.approx(value), (
                        f"federated value drifted for {name}{labels}"
                    )
            # Derived aggregates: worst federated p99 + fleet availability
            # land in the SAME snapshot the SLO monitor evaluates.
            snap = router.federated_snapshot()
            p99s = [
                v
                for (name, labels), v in fleet_by_key.items()
                if name == "serve_request_latency_ms"
                and ("quantile", "0.99") in labels
            ]
            assert snap["fleet_federated_p99_ms"] == pytest.approx(
                max(p99s)
            )
            assert snap["fleet_availability"] == 1.0
            for rid in frozen:
                assert (
                    snap[
                        "serve_requests_completed_total"
                        f'{{replica="{rid}"}}'
                    ]
                    >= 1.0
                )
        finally:
            router.close()
            for s in servers:
                s.close(drain=False)

    def test_closed_local_replica_drops_from_federation(self):
        """A closed in-process server's registry object outlives it —
        its frozen exposition must DROP like a dead HTTP replica's."""
        servers = [make_server("fed-c0"), make_server("fed-c1")]
        replicas = [LocalReplica(s) for s in servers]
        router = make_router(replicas)
        try:
            router.scrape_metrics_once()
            assert set(router.status()["federated_replicas"]) == {
                "fed-c0", "fed-c1",
            }
            servers[0].close(drain=False)
            router.scrape_metrics_once()
            assert router.status()["federated_replicas"] == ["fed-c1"]
        finally:
            router.close()
            for s in servers:
                s.close(drain=False)

    def test_failed_scrape_drops_the_replica_not_the_sweep(self):
        servers = [make_server("fed-a"), make_server("fed-b")]
        replicas = [LocalReplica(s) for s in servers]
        router = make_router(replicas)
        try:
            router.scrape_metrics_once()
            assert set(router.status()["federated_replicas"]) == {
                "fed-a", "fed-b",
            }
            replicas[0].metrics_text = lambda: None  # type: ignore
            router.scrape_metrics_once()
            # Stale series DROP; the healthy replica keeps federating.
            assert router.status()["federated_replicas"] == ["fed-b"]
        finally:
            router.close()
            for s in servers:
                s.close(drain=False)


# ---- merged fleet trace --------------------------------------------------


def _fragment(pid: int, label: str, spans: list[tuple]) -> dict:
    events = [
        {
            "ph": "M", "name": "process_name", "pid": pid,
            "args": {"name": f"p?:{label} (pid {pid})"},
        }
    ]
    for tid, name, ts_us, dur_us, args in spans:
        events.append(
            {
                "ph": "X", "cat": "obs", "name": name, "ts": ts_us,
                "dur": dur_us, "pid": pid, "tid": tid, "args": args,
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms", "otherData": {}}


class TestMergedFleetTrace:
    def test_merge_is_valid_chrome_schema_with_disjoint_tracks(
        self, tmp_path
    ):
        frags = {
            "trace-run1-replica-0-111.json": _fragment(
                111, "replica-0",
                [(1, "serve_request", 1000, 500,
                  {"id": 0, "replica": "replica-0", "trace": "t1"})],
            ),
            "trace-run1-replica-1-222.json": _fragment(
                222, "replica-1",
                [(1, "serve_request", 1600, 400,
                  {"id": 0, "replica": "replica-1", "trace": "t1"})],
            ),
        }
        for name, doc in frags.items():
            (tmp_path / name).write_text(json.dumps(doc))
        out = trace.merge_traces(str(tmp_path))
        with open(out) as f:
            merged = json.load(f)
        events = merged["traceEvents"]
        assert isinstance(events, list) and events
        for e in events:  # Chrome schema: every event has ph/name/pid
            assert {"ph", "name", "pid"} <= set(e)
            if e["ph"] == "X":
                assert isinstance(e["ts"], int) and isinstance(
                    e["dur"], int
                )
        tracks = {
            rid: {
                (e["pid"], e["tid"])
                for e in events
                if e.get("ph") == "X"
                and (e.get("args") or {}).get("replica") == rid
            }
            for rid in ("replica-0", "replica-1")
        }
        assert tracks["replica-0"] and tracks["replica-1"]
        assert not (tracks["replica-0"] & tracks["replica-1"])

    def test_redispatched_request_spans_both_replicas_one_trace(
        self, tmp_path
    ):
        """A shed on replica A re-dispatches to B: BOTH serve_request
        spans carry the one trace id, the fleet_request span wraps them,
        and the re-dispatch instant names the trace."""
        trace.configure(str(tmp_path))
        servers = [make_server("red-a"), make_server("red-b")]
        replicas = [LocalReplica(s) for s in servers]
        router = make_router(replicas)
        try:
            # Force replica A's admission full (shed with a recorded
            # span) and make the pick order deterministic A-then-B.
            full = queue.Queue(maxsize=1)
            full.put_nowait(object())
            servers[0]._admission = full
            states = list(router._states)

            def pick(exclude):
                for st in states:
                    if id(st) not in exclude and st.state == CLOSED:
                        return st
                return None

            router._pick = pick  # type: ignore
            dets = router.detect(
                IMG, timeout_s=20, trace_id="t-redispatch"
            )
            assert dets == EXPECTED_DETECTIONS
            events = trace.snapshot_events()
            tagged = {
                e["args"]["replica"]
                for e in events
                if e.get("ph") == "X"
                and e.get("name") == "serve_request"
                and (e.get("args") or {}).get("trace") == "t-redispatch"
            }
            assert tagged == {"red-a", "red-b"}
            fleet_spans = [
                e
                for e in events
                if e.get("ph") == "X" and e.get("name") == "fleet_request"
            ]
            assert any(
                (e.get("args") or {}).get("trace") == "t-redispatch"
                for e in fleet_spans
            )
            redis = [
                e
                for e in events
                if e.get("ph") == "i"
                and e.get("name") == "fleet_redispatch"
            ]
            assert len(redis) == 1
            assert redis[0]["args"]["trace"] == "t-redispatch"
            assert redis[0]["args"]["replica_id"] == "red-b"
            # The flow chain (s → t → f) under the same id makes the hop
            # followable in Perfetto.
            flow_phases = {
                e["ph"]
                for e in events
                if e.get("cat") == "obs.flow"
                and e.get("id") == "t-redispatch"
            }
            assert {"s", "t", "f"} <= flow_phases
        finally:
            router.close()
            for s in servers:
                s.close(drain=False)


# ---- fleet SLO -----------------------------------------------------------


class ScriptedReplica:
    """A replica handle with scriptable health (the test_fleet fake,
    trimmed): 503-with-stall when unhealthy."""

    version = "v1"

    def __init__(self, replica_id: str):
        self.replica_id = replica_id
        self.healthy = True

    def healthz(self):
        if not self.healthy:
            return 503, {"status": "stalled", "component": "serve-dispatch"}
        return 200, {
            "status": "ok",
            "load": {
                "replica_id": self.replica_id,
                "version": self.version,
                "inflight": 0,
                "admission_qsize": 0,
                "admission_capacity": 8,
                "p99_ms": 50.0,
                "accepting": True,
            },
        }

    def detect(self, payload, timeout_s=None, trace_id=None):
        return EXPECTED_DETECTIONS

    def drain(self, timeout_s=5.0):
        pass

    def close(self):
        pass


class TestFleetSlo:
    def test_availability_rule_fires_exactly_once_per_stall(self):
        a, b = ScriptedReplica("slo-a"), ScriptedReplica("slo-b")
        router = make_router([a, b])
        mon = slo.SloMonitor(
            router.telemetry, [slo.fleet_availability_rule()]
        )
        try:
            router.poll_once(now=0.0)
            assert mon.check_once(now=0.0) == []
            # Injected stall: a's healthz flips 503 → breaker opens on
            # the next poll → availability 0.5 < 0.999.
            a.healthy = False
            router.poll_once(now=1.0)
            fired = mon.check_once(now=1.0)
            assert [v["rule"] for v in fired] == ["fleet-availability"]
            assert fired[0]["value"] == 0.5
            # The latch: the continuing breach never re-fires.
            for t in (2.0, 3.0, 4.0):
                router.poll_once(now=t)
                assert mon.check_once(now=t) == []
            # Heal: the half-open probe readmits (backoff base 1s), the
            # breach clears, still exactly one violation total.
            a.healthy = True
            router.poll_once(now=10.0)
            assert router.status()["replicas"][0]["state"] == "closed"
            assert mon.check_once(now=10.0) == []
            assert len(mon.violations) == 1
        finally:
            mon.stop()
            router.close()


# ---- the fleet perf report -----------------------------------------------


def _instant(pid, tid, name, ts_us, args):
    return {
        "ph": "i", "cat": "obs", "name": name, "ts": ts_us, "s": "t",
        "pid": pid, "tid": tid, "args": args,
    }


class TestFleetReport:
    def _build_obs_dir(self, d):
        events = []
        for pid, label in ((10, "fleet"), (11, "replica-0"),
                           (12, "replica-1")):
            events.append({
                "ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": f"p?:{label} (pid {pid})"},
            })
        # replica-0 served t1 before dying; t2 re-dispatched onto
        # replica-1 (spans on both tracks, one id).
        for pid, rid, ts, tr in (
            (11, "replica-0", 1_000_000, "t1"),
            (11, "replica-0", 1_200_000, "t2"),
            (12, "replica-1", 1_400_000, "t2"),
            (12, "replica-1", 1_600_000, "t3"),
        ):
            events.append({
                "ph": "X", "cat": "obs", "name": "serve_request",
                "ts": ts, "dur": 100_000, "pid": pid, "tid": 1,
                "args": {"id": 1, "replica": rid, "trace": tr},
            })
        events.append(_instant(10, 1, "fleet_replica_died", 1_300_000,
                               {"replica_id": "replica-0", "rc": -9}))
        events.append(_instant(10, 1, "fleet_breaker_open", 1_310_000,
                               {"replica_id": "replica-0",
                                "reason": "unreachable"}))
        events.append(_instant(10, 1, "fleet_redispatch", 1_390_000,
                               {"replica_id": "replica-1", "attempt": 1,
                                "trace": "t2"}))
        events.append(_instant(10, 1, "fleet_replica_respawned",
                               1_700_000, {"replica_id": "replica-0"}))
        events.append(_instant(10, 1, "fleet_breaker_close", 1_800_000,
                               {"replica_id": "replica-0"}))
        events.append(_instant(10, 1, "slo_violation", 1_320_000,
                               {"rule": "fleet-availability",
                                "metric": "fleet_availability",
                                "value": 0.5, "threshold": 0.999,
                                "sustained_s": 0.0}))
        (d / "trace.json").write_text(json.dumps(
            {"traceEvents": events, "otherData": {}}
        ))
        (d / "FLEET_METRICS.json").write_text(json.dumps({
            "replicas": {
                "replica-0": {"types": {}, "samples": [
                    ["serve_requests_completed_total", {}, 2.0],
                    ["serve_request_latency_ms", {"quantile": "0.99"},
                     120.0],
                ]},
                "replica-1": {"types": {}, "samples": [
                    ["serve_requests_completed_total", {}, 2.0],
                    ["serve_shed_total", {"reason": "x"}, 1.0],
                    ["serve_request_latency_ms", {"quantile": "0.99"},
                     80.0],
                ]},
            },
            "snapshot": {}, "status": {},
        }))

    def test_fleet_report_names_the_killed_replica(self, tmp_path):
        self._build_obs_dir(tmp_path)
        report = analyze_fleet_dir(str(tmp_path))
        assert validate_report(report) == []
        fleet = report["fleet"]
        assert fleet["available"]
        assert set(fleet["replicas"]) == {"replica-0", "replica-1"}
        r0 = fleet["replicas"]["replica-0"]
        assert r0["requests"] == 2
        assert r0["federated"]["p99_ms"] == 120.0
        shares = [
            fleet["replicas"][r]["routing_share"]
            for r in ("replica-0", "replica-1")
        ]
        assert sum(shares) == pytest.approx(1.0)
        assert fleet["redispatched_traces"] == {
            "count": 1, "sample": ["t2"],
        }
        kinds = [e["event"] for e in fleet["timeline"]]
        assert "fleet_replica_died" in kinds
        assert "fleet_breaker_close" in kinds
        names = [b["name"] for b in report["bottlenecks"]]
        # Declared SLO breach first, then the fleet verdict NAMING the
        # killed replica, then inferred bottlenecks.
        assert names[0] == "slo:fleet-availability"
        assert names[1] == "fleet:unavailable_replica:replica-0"
        ranks = [b["rank"] for b in report["bottlenecks"]]
        assert ranks == list(range(1, len(ranks) + 1))

    def test_shared_process_stage_time_is_not_multiply_attributed(self):
        """In-process fleets share one pid across replicas: stage spans
        (no replica arg) must NOT be credited to every replica — they
        are skipped and flagged instead of overcounted N×."""
        from batchai_retinanet_horovod_coco_tpu.obs.analyze.report import (
            _fleet_section,
        )

        events = [
            {
                "ph": "M", "name": "process_name", "pid": 5,
                "args": {"name": "p?:serve (pid 5)"},
            },
            {
                "ph": "X", "cat": "obs", "name": "serve_dispatch",
                "ts": 1_000_000, "dur": 50_000, "pid": 5, "tid": 1,
                "args": {},
            },
        ]
        for rid, ts in (("in-a", 1_000_000), ("in-b", 1_200_000)):
            events.append({
                "ph": "X", "cat": "obs", "name": "serve_request",
                "ts": ts, "dur": 100_000, "pid": 5, "tid": 1,
                "args": {"id": 1, "replica": rid},
            })
        sec = _fleet_section(events, None)
        for rid in ("in-a", "in-b"):
            entry = sec["replicas"][rid]
            assert entry.get("stages_shared_process") is True
            assert "stages_s" not in entry

    def test_fleet_report_without_metrics_file_still_works(self, tmp_path):
        self._build_obs_dir(tmp_path)
        (tmp_path / "FLEET_METRICS.json").unlink()
        report = analyze_fleet_dir(str(tmp_path))
        assert validate_report(report) == []
        assert report["source"]["fleet_metrics"] is False
        assert report["fleet"]["replicas"]["replica-0"]["requests"] == 2
        assert any(
            b["name"] == "fleet:unavailable_replica:replica-0"
            for b in report["bottlenecks"]
        )
