"""Launch-layer command generation (SURVEY.md W3/W4 parity).

The reference's cluster/job specs were JSON checked into the repo; here the
equivalent artifact is the generated gcloud argv, asserted exactly.
"""

import shlex
import subprocess
import sys

from batchai_retinanet_horovod_coco_tpu.launch import (
    TPUClusterConfig,
    create_command,
    delete_command,
    status_command,
    submit_command,
)
from batchai_retinanet_horovod_coco_tpu.launch.cluster import main


class TestCommands:
    def test_create_tpu_vm(self):
        cfg = TPUClusterConfig(
            name="ret", zone="us-east5-b", accelerator="v5litepod-8",
            runtime_version="rt",
        )
        assert create_command(cfg) == [
            "gcloud", "compute", "tpus", "tpu-vm", "create", "ret",
            "--zone=us-east5-b", "--accelerator-type=v5litepod-8",
            "--version=rt",
        ]

    def test_create_queued_spot_project(self):
        cfg = TPUClusterConfig(
            name="ret", project="proj", accelerator="v5litepod-256",
            runtime_version="rt", spot=True, queued=True,
        )
        cmd = create_command(cfg)
        assert cmd[:6] == [
            "gcloud", "compute", "tpus", "queued-resources", "create", "ret",
        ]
        assert "--project=proj" in cmd
        assert "--node-id=ret-0" in cmd
        assert "--accelerator-type=v5litepod-256" in cmd
        assert "--spot" in cmd

    def test_delete_and_status(self):
        import dataclasses

        cfg = TPUClusterConfig(name="ret")
        assert delete_command(cfg)[4:] == ["delete", "ret", "--quiet",
                                           f"--zone={cfg.zone}"]
        assert status_command(cfg)[4] == "describe"
        queued = dataclasses.replace(cfg, queued=True)
        assert delete_command(queued)[3] == "queued-resources"

    def test_submit_runs_same_binary_on_all_workers(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["--preset", "pod", "coco", "/mnt/coco"])
        assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "ret"]
        assert "--worker=all" in cmd
        command = cmd[-1]
        assert command.startswith("--command=")
        # The whole W4 job spec: same train.py + --distributed-auto + all
        # devices; no mpirun, no hostfile, no processCount.
        assert "python train.py --preset pod coco /mnt/coco " \
               "--distributed-auto --num-devices 0" in command
        assert "mpirun" not in command

    def test_submit_quotes_args(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["coco", "/path with space"])
        assert shlex.quote("/path with space") in cmd[-1]

    def test_submit_quotes_workdir(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["coco", "/d"], workdir="shared data/repo")
        assert "cd 'shared data/repo' &&" in cmd[-1]

    def test_submit_targets_queued_node(self):
        import dataclasses

        cfg = dataclasses.replace(TPUClusterConfig(name="ret"), queued=True)
        # Queued create names the node 'ret-0'; submit must ssh THAT node.
        assert submit_command(cfg, ["coco", "/d"])[5] == "ret-0"


class TestCLI:
    def test_dry_run_prints_command(self, capsys):
        rc = main(["create", "--name", "x", "--accelerator", "v5litepod-8",
                   "--dry-run"])
        assert rc == 0
        out = capsys.readouterr().out.strip()
        assert out.startswith("gcloud compute tpus tpu-vm create x")

    def test_submit_passthrough_after_dashdash(self, capsys):
        rc = main(["submit", "--name", "x", "--dry-run", "--",
                   "--preset", "pod", "coco", "/mnt/coco"])
        assert rc == 0
        assert "--preset pod coco /mnt/coco" in capsys.readouterr().out

    def test_typo_flag_errors_instead_of_silently_dropping(self):
        import pytest

        with pytest.raises(SystemExit) as e:
            main(["create", "--name", "x", "--acclerator", "v5litepod-8",
                  "--dry-run"])
        assert e.value.code == 2  # argparse usage error

    def test_train_args_rejected_for_non_submit(self):
        import pytest

        with pytest.raises(SystemExit) as e:
            main(["create", "--name", "x", "--dry-run", "--", "coco", "/d"])
        assert e.value.code == 2

    def test_module_entrypoint(self):
        out = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.launch.cluster",
             "status", "--name", "y", "--dry-run"],
            capture_output=True, timeout=120,
        )
        assert out.returncode == 0
        assert b"describe y" in out.stdout


class TestElasticWorldResume:
    """ISSUE 11: rejoin-from-checkpoint at a NEW world size — the cluster
    story the launch layer exists for.  A ZeRO (--shard-weight-update) run
    checkpointed on a 4-device virtual mesh restores onto 2- and 8-device
    meshes with optimizer state equal to the gathered (unsharded)
    reference, and run_training actually CONTINUES there."""

    def _setup(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from batchai_retinanet_horovod_coco_tpu.data.pipeline import Batch
        from batchai_retinanet_horovod_coco_tpu.models import (
            RetinaNetConfig,
            build_retinanet,
        )
        from batchai_retinanet_horovod_coco_tpu.train import (
            create_train_state,
        )

        model = build_retinanet(
            RetinaNetConfig(
                num_classes=3, backbone="resnet_test", fpn_channels=16,
                head_width=16, head_depth=1, dtype=jnp.float32,
            )
        )
        tx = optax.sgd(1e-3, momentum=0.9)

        def fresh_state():
            import jax

            return create_train_state(
                model, tx, (1, 64, 64, 3), jax.random.key(0),
                init_opt_state=False,
            )

        def stream():
            rng = np.random.default_rng(0)
            images = rng.normal(0, 1, (8, 64, 64, 3)).astype(np.float32)
            gt = np.tile(
                np.array([[8.0, 8.0, 40.0, 40.0]], np.float32), (8, 1, 1)
            )
            while True:
                yield Batch(
                    images=images, gt_boxes=gt,
                    gt_labels=np.ones((8, 1), np.int32),
                    gt_mask=np.ones((8, 1), bool),
                    image_ids=np.arange(8, dtype=np.int64),
                    scales=np.ones((8,), np.float32),
                    valid=np.ones((8,), bool),
                )

        return model, tx, fresh_state, stream

    def _sharded_state(self, fresh_state, tx, mesh):
        import jax

        from batchai_retinanet_horovod_coco_tpu.parallel import (
            init_sharded_opt_state,
            replicated_sharding,
        )

        state = fresh_state()
        params = jax.device_put(state.params, replicated_sharding(mesh))
        return state.replace(
            params=params,
            opt_state=init_sharded_opt_state(tx, params, mesh),
        )

    def test_zero_ckpt_world4_to_2_and_8(self, tmp_path):
        import jax
        import numpy as np

        from batchai_retinanet_horovod_coco_tpu.parallel import make_mesh
        from batchai_retinanet_horovod_coco_tpu.train.loop import (
            LoopConfig,
            run_training,
        )
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
            read_manifest,
        )

        model, tx, fresh_state, stream = self._setup()
        ckpt_dir = str(tmp_path / "ckpt")

        # World 4: two ZeRO steps, checkpoint every step.
        mesh4 = make_mesh(4)
        run_training(
            model, self._sharded_state(fresh_state, tx, mesh4), stream(), 3,
            LoopConfig(
                total_steps=2, log_every=100, checkpoint_every=1,
                checkpoint_dir=ckpt_dir,
            ),
            mesh=mesh4, shard_weight_update=True,
        )
        manifest = read_manifest(ckpt_dir)
        assert manifest["step"] == 2
        assert manifest["zero_world_size"] == 4

        # The gathered (unsharded) reference: restore into a REPLICATED
        # template — logical, world-free.
        repl_template = fresh_state()
        repl_template = repl_template.replace(
            opt_state=tx.init(repl_template.params)
        )
        reference = CheckpointManager(ckpt_dir).restore(repl_template)

        for world in (2, 8):
            mesh = make_mesh(world)
            template = self._sharded_state(fresh_state, tx, mesh)
            restored = CheckpointManager(ckpt_dir).restore(template)
            # Optimizer state == the gathered reference, re-laid for this
            # world: unpad each flat leaf back to logical and compare.
            def unpad(flat, like):
                flat = np.asarray(flat)
                if flat.ndim != 1 or np.shape(like) == flat.shape:
                    return flat
                return flat[: np.asarray(like).size].reshape(np.shape(like))

            jax.tree.map(
                lambda got, ref: np.testing.assert_array_equal(
                    unpad(got, ref), np.asarray(ref)
                ),
                restored.opt_state,
                reference.opt_state,
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_array_equal(
                    np.asarray(a), np.asarray(b)
                ),
                restored.params,
                reference.params,
            )

            # And the loop actually TRAINS there: resume (restore happens
            # inside run_training) and take one more step at this world.
            out = run_training(
                model, self._sharded_state(fresh_state, tx, mesh),
                stream(), 3,
                LoopConfig(
                    total_steps=3, log_every=1, checkpoint_every=1,
                    checkpoint_dir=ckpt_dir, max_to_keep=10,
                ),
                mesh=mesh, shard_weight_update=True,
            )
            assert int(out.step) == 3
            # Un-pin the world-3 save so the next world resumes from the
            # same step-2 snapshot.
            import shutil

            shutil.rmtree(
                str(tmp_path / "ckpt" / "ckpt-3"), ignore_errors=True
            )
