"""Launch-layer command generation (SURVEY.md W3/W4 parity).

The reference's cluster/job specs were JSON checked into the repo; here the
equivalent artifact is the generated gcloud argv, asserted exactly.
"""

import shlex
import subprocess
import sys

from batchai_retinanet_horovod_coco_tpu.launch import (
    TPUClusterConfig,
    create_command,
    delete_command,
    status_command,
    submit_command,
)
from batchai_retinanet_horovod_coco_tpu.launch.cluster import main


class TestCommands:
    def test_create_tpu_vm(self):
        cfg = TPUClusterConfig(
            name="ret", zone="us-east5-b", accelerator="v5litepod-8",
            runtime_version="rt",
        )
        assert create_command(cfg) == [
            "gcloud", "compute", "tpus", "tpu-vm", "create", "ret",
            "--zone=us-east5-b", "--accelerator-type=v5litepod-8",
            "--version=rt",
        ]

    def test_create_queued_spot_project(self):
        cfg = TPUClusterConfig(
            name="ret", project="proj", accelerator="v5litepod-256",
            runtime_version="rt", spot=True, queued=True,
        )
        cmd = create_command(cfg)
        assert cmd[:6] == [
            "gcloud", "compute", "tpus", "queued-resources", "create", "ret",
        ]
        assert "--project=proj" in cmd
        assert "--node-id=ret-0" in cmd
        assert "--accelerator-type=v5litepod-256" in cmd
        assert "--spot" in cmd

    def test_delete_and_status(self):
        import dataclasses

        cfg = TPUClusterConfig(name="ret")
        assert delete_command(cfg)[4:] == ["delete", "ret", "--quiet",
                                           f"--zone={cfg.zone}"]
        assert status_command(cfg)[4] == "describe"
        queued = dataclasses.replace(cfg, queued=True)
        assert delete_command(queued)[3] == "queued-resources"

    def test_submit_runs_same_binary_on_all_workers(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["--preset", "pod", "coco", "/mnt/coco"])
        assert cmd[:6] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh", "ret"]
        assert "--worker=all" in cmd
        command = cmd[-1]
        assert command.startswith("--command=")
        # The whole W4 job spec: same train.py + --distributed-auto + all
        # devices; no mpirun, no hostfile, no processCount.
        assert "python train.py --preset pod coco /mnt/coco " \
               "--distributed-auto --num-devices 0" in command
        assert "mpirun" not in command

    def test_submit_quotes_args(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["coco", "/path with space"])
        assert shlex.quote("/path with space") in cmd[-1]

    def test_submit_quotes_workdir(self):
        cfg = TPUClusterConfig(name="ret")
        cmd = submit_command(cfg, ["coco", "/d"], workdir="shared data/repo")
        assert "cd 'shared data/repo' &&" in cmd[-1]

    def test_submit_targets_queued_node(self):
        import dataclasses

        cfg = dataclasses.replace(TPUClusterConfig(name="ret"), queued=True)
        # Queued create names the node 'ret-0'; submit must ssh THAT node.
        assert submit_command(cfg, ["coco", "/d"])[5] == "ret-0"


class TestCLI:
    def test_dry_run_prints_command(self, capsys):
        rc = main(["create", "--name", "x", "--accelerator", "v5litepod-8",
                   "--dry-run"])
        assert rc == 0
        out = capsys.readouterr().out.strip()
        assert out.startswith("gcloud compute tpus tpu-vm create x")

    def test_submit_passthrough_after_dashdash(self, capsys):
        rc = main(["submit", "--name", "x", "--dry-run", "--",
                   "--preset", "pod", "coco", "/mnt/coco"])
        assert rc == 0
        assert "--preset pod coco /mnt/coco" in capsys.readouterr().out

    def test_typo_flag_errors_instead_of_silently_dropping(self):
        import pytest

        with pytest.raises(SystemExit) as e:
            main(["create", "--name", "x", "--acclerator", "v5litepod-8",
                  "--dry-run"])
        assert e.value.code == 2  # argparse usage error

    def test_train_args_rejected_for_non_submit(self):
        import pytest

        with pytest.raises(SystemExit) as e:
            main(["create", "--name", "x", "--dry-run", "--", "coco", "/d"])
        assert e.value.code == 2

    def test_module_entrypoint(self):
        out = subprocess.run(
            [sys.executable, "-m",
             "batchai_retinanet_horovod_coco_tpu.launch.cluster",
             "status", "--name", "y", "--dry-run"],
            capture_output=True, timeout=120,
        )
        assert out.returncode == 0
        assert b"describe y" in out.stdout
