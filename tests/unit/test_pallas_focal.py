"""Pallas fused focal kernel vs the jnp implementation (interpret mode).

The kernel must match ``losses.focal_loss_compact`` semantics exactly:
implicit one-hot from integer labels, ignore-state masking, and the closed
form gradient vs jax.grad of the jnp path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu import losses as L
from batchai_retinanet_horovod_coco_tpu.ops.pallas import focal as pf


def _jnp_per_image_sums(logits, labels, state, alpha=0.25, gamma=2.0):
    """Reference: per-image focal sums via the dense jnp path."""
    K = logits.shape[-1]
    targets = (
        (state == 1)[..., None]
        & (labels[..., None] == jnp.arange(K, dtype=jnp.int32))
    ).astype(jnp.float32)
    x = logits.astype(jnp.float32)
    p = jax.nn.sigmoid(x)
    bce = jax.nn.softplus(x) - x * targets
    p_t = p * targets + (1 - p) * (1 - targets)
    alpha_t = alpha * targets + (1 - alpha) * (1 - targets)
    loss = alpha_t * (1 - p_t) ** gamma * bce
    loss = loss * (state != -1)[..., None]
    return jnp.sum(loss, axis=(-2, -1))


def _random_case(B=2, A=300, K=7, seed=0):
    rng = np.random.default_rng(seed)
    logits = rng.normal(0, 3, (B, A, K)).astype(np.float32)
    labels = rng.integers(0, K, (B, A)).astype(np.int32)
    state = rng.choice([-1, 0, 1], (B, A), p=[0.2, 0.7, 0.1]).astype(np.int32)
    return jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(state)


@pytest.mark.parametrize("seed", [0, 1])
def test_forward_matches_jnp(seed):
    logits, labels, state = _random_case(seed=seed)
    got = pf.focal_loss_per_image_sums(logits, labels, state, interpret=True)
    want = _jnp_per_image_sums(logits, labels, state)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_forward_tail_masking():
    """A not divisible by either tile: out-of-range rows contribute nothing."""
    logits, labels, state = _random_case(A=pf.FWD_TILE_A + 37, seed=2)
    got = pf.focal_loss_per_image_sums(logits, labels, state, interpret=True)
    want = _jnp_per_image_sums(logits, labels, state)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)


def test_gradient_tail_masking():
    """A not divisible by the backward tile: no gradient for padded rows."""
    logits, labels, state = _random_case(B=1, A=pf.BWD_TILE_A + 37, seed=6)
    g_pallas = jax.grad(
        lambda x: jnp.sum(
            pf.focal_loss_per_image_sums(x, labels, state, interpret=True)
        )
    )(logits)
    g_jnp = jax.grad(lambda x: jnp.sum(_jnp_per_image_sums(x, labels, state)))(
        logits
    )
    np.testing.assert_allclose(
        np.asarray(g_pallas), np.asarray(g_jnp), rtol=1e-4, atol=1e-6
    )


def test_gradient_matches_autodiff():
    logits, labels, state = _random_case(seed=3)

    def f_pallas(x):
        return jnp.sum(
            pf.focal_loss_per_image_sums(x, labels, state, interpret=True)
            * jnp.asarray([1.0, -0.5])
        )

    def f_jnp(x):
        return jnp.sum(_jnp_per_image_sums(x, labels, state) * jnp.asarray([1.0, -0.5]))

    g_pallas = jax.grad(f_pallas)(logits)
    g_jnp = jax.grad(f_jnp)(logits)
    np.testing.assert_allclose(
        np.asarray(g_pallas), np.asarray(g_jnp), rtol=1e-4, atol=1e-6
    )


def test_matches_focal_loss_compact_normalized():
    """Kernel sums + outside normalization == focal_loss_compact."""
    logits, labels, state = _random_case(seed=4)
    sums = pf.focal_loss_per_image_sums(logits, labels, state, interpret=True)
    num_pos = jnp.sum((state == 1).astype(jnp.float32), axis=-1)
    got = jnp.mean(sums / jnp.maximum(num_pos, 1.0))
    want = L.focal_loss_compact(logits, labels, state)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)


def test_config_branch_matches_default_path():
    """LossConfig(pallas_focal=True) wiring == the jnp path, rank 3 and 2."""
    logits, labels, state = _random_case(seed=7)
    cfg = L.LossConfig(pallas_focal=True, pallas_interpret=True)
    got = L.focal_loss_compact(logits, labels, state, cfg)
    want = L.focal_loss_compact(logits, labels, state)
    np.testing.assert_allclose(float(got), float(want), rtol=1e-5)
    # Unbatched (A, K) input — the kernel wrapper adds/flattens leading dims.
    got2 = L.focal_loss_compact(logits[0], labels[0], state[0], cfg)
    want2 = L.focal_loss_compact(logits[0], labels[0], state[0])
    np.testing.assert_allclose(float(got2), float(want2), rtol=1e-5)


def test_bf16_logits():
    logits, labels, state = _random_case(seed=5)
    got = pf.focal_loss_per_image_sums(
        logits.astype(jnp.bfloat16), labels, state, interpret=True
    )
    want = _jnp_per_image_sums(logits.astype(jnp.bfloat16), labels, state)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-2)
