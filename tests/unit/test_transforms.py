"""Random affine + photometric augmentation (data/transforms.py).

Oracle style per SURVEY.md §4: exact-value assertions on tiny hand-built
fixtures — identity transforms, pure flips, exact 90-degree rotations —
mirroring keras-retinanet's tests/utils/test_transform.py coverage.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.data.transforms import (
    TransformConfig,
    apply_random_transform,
    random_transform_matrix,
    transform_boxes,
    warp_image,
)

IDENTITY = TransformConfig(
    rotation=(0, 0),
    translation=(0, 0),
    shear=(0, 0),
    scaling=(1, 1),
    flip_x_prob=0.0,
    flip_y_prob=0.0,
    brightness=(0, 0),
    contrast=(1, 1),
    saturation=(1, 1),
)


def test_identity_transform_is_noop():
    rng = np.random.default_rng(0)
    image = rng.integers(0, 255, (32, 48, 3), dtype=np.uint8)
    boxes = np.array([[4.0, 6.0, 20.0, 28.0]], np.float32)
    labels = np.array([2], np.int32)
    out_img, out_boxes, out_labels = apply_random_transform(
        image, boxes, labels, IDENTITY, rng
    )
    np.testing.assert_array_equal(out_img, image)
    np.testing.assert_allclose(out_boxes, boxes, atol=1e-5)
    np.testing.assert_array_equal(out_labels, labels)


def test_flip_x_matches_manual_flip():
    cfg = TransformConfig(
        rotation=(0, 0), translation=(0, 0), shear=(0, 0), scaling=(1, 1),
        flip_x_prob=1.0, flip_y_prob=0.0,
        brightness=(0, 0), contrast=(1, 1), saturation=(1, 1),
    )
    rng = np.random.default_rng(1)
    h, w = 16, 24
    m = random_transform_matrix(cfg, rng, h, w)
    boxes = np.array([[2.0, 3.0, 10.0, 12.0]], np.float32)
    out, keep = transform_boxes(boxes, m, h, w)
    assert keep.all()
    np.testing.assert_allclose(out, [[w - 10.0, 3.0, w - 2.0, 12.0]], atol=1e-5)

    image = np.zeros((h, w, 3), np.uint8)
    image[:, :4] = 255  # left stripe
    flipped = warp_image(image, m)
    # Stripe moves to the right edge (allow 1px interpolation slack).
    assert flipped[:, -2:].mean() > 200
    assert flipped[:, :2].mean() < 50


def test_rotation_90deg_box_mapping():
    """Exact 90° rotation about the center of a square image."""
    cfg = TransformConfig(
        rotation=(np.pi / 2, np.pi / 2), translation=(0, 0), shear=(0, 0),
        scaling=(1, 1), flip_x_prob=0.0, flip_y_prob=0.0,
    )
    h = w = 20
    m = random_transform_matrix(cfg, np.random.default_rng(0), h, w)
    # Point (15, 10) — right of center — rotates to (10, 15) (below center).
    p = m @ np.array([15.0, 10.0, 1.0])
    np.testing.assert_allclose(p[:2], [10.0, 15.0], atol=1e-6)
    boxes = np.array([[12.0, 8.0, 18.0, 12.0]], np.float32)
    out, keep = transform_boxes(boxes, m, h, w)
    assert keep.all()
    np.testing.assert_allclose(out, [[8.0, 12.0, 12.0, 18.0]], atol=1e-5)


def test_degenerate_boxes_dropped():
    # Translate far right: the box is pushed outside and clips to nothing.
    m = np.array([[1.0, 0.0, 100.0], [0.0, 1.0, 0.0], [0.0, 0.0, 1.0]])
    boxes = np.array([[2.0, 2.0, 8.0, 8.0]], np.float32)
    out, keep = transform_boxes(boxes, m, 20, 20)
    assert not keep.any()

    rng = np.random.default_rng(0)
    cfg = TransformConfig(
        rotation=(0, 0), translation=(5.0, 5.0), shear=(0, 0), scaling=(1, 1),
        flip_x_prob=0.0, brightness=(0, 0), contrast=(1, 1), saturation=(1, 1),
    )
    image = np.zeros((20, 20, 3), np.uint8)
    _, out_boxes, out_labels = apply_random_transform(
        image, boxes, np.array([1], np.int32), cfg, rng
    )
    assert len(out_boxes) == 0 and len(out_labels) == 0


def test_photometric_stays_uint8_in_range():
    rng = np.random.default_rng(3)
    cfg = TransformConfig(
        rotation=(0, 0), translation=(0, 0), shear=(0, 0), scaling=(1, 1),
        flip_x_prob=0.0, brightness=(0.5, 0.5), contrast=(2.0, 2.0),
        saturation=(1.5, 1.5),
    )
    image = rng.integers(0, 255, (16, 16, 3), dtype=np.uint8)
    out, _, _ = apply_random_transform(
        image, np.zeros((0, 4), np.float32), np.zeros((0,), np.int32), cfg, rng
    )
    assert out.dtype == np.uint8
    assert out.max() <= 255 and out.min() >= 0
    assert out.mean() > image.mean()  # +0.5 brightness dominates


def test_transform_is_deterministic_given_rng():
    cfg = TransformConfig()
    img = np.random.default_rng(5).integers(0, 255, (24, 24, 3), dtype=np.uint8)
    boxes = np.array([[4.0, 4.0, 16.0, 16.0]], np.float32)
    labels = np.array([0], np.int32)
    a = apply_random_transform(img, boxes, labels, cfg, np.random.default_rng(7))
    b = apply_random_transform(img, boxes, labels, cfg, np.random.default_rng(7))
    np.testing.assert_array_equal(a[0], b[0])
    np.testing.assert_allclose(a[1], b[1])


def test_pipeline_with_transform_config(tmp_path):
    """End-to-end: augmented pipeline yields valid batches inside the bucket."""
    from batchai_retinanet_horovod_coco_tpu.data import (
        CocoDataset,
        PipelineConfig,
        build_pipeline,
        make_synthetic_coco,
    )

    ann = make_synthetic_coco(str(tmp_path), num_images=6, num_classes=2, seed=4)
    ds = CocoDataset(ann, image_dir=f"{tmp_path}/train")
    cfg = PipelineConfig(
        batch_size=2,
        buckets=((320, 320),),
        min_side=300,
        max_side=320,
        max_gt=8,
        transform=TransformConfig(),
        num_workers=2,
        seed=0,
    )
    batch = next(build_pipeline(ds, cfg, train=True))
    assert batch.images.shape == (2, 320, 320, 3)
    if batch.gt_mask.any():
        valid = batch.gt_boxes[batch.gt_mask]
        assert np.all(valid >= -1e-3) and np.all(valid <= 320 + 1e-3)
        assert np.all(valid[:, 2] > valid[:, 0])
