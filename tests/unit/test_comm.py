"""comm/ subsystem (ISSUE 13): bucketed EF compression on the 8-dev mesh.

The claims, in dependency order:

1. plan/bytes — bucketing is deterministic, n-independent, packs small
   leaves, and the int8 plan's bytes-on-wire is <= 0.65x exact;
2. bucketed int8 pmean == exact pmean within the derived per-block
   tolerance (quantization AFTER the exact f32 reduce);
3. error feedback telescopes: a constant gradient is BIT-exact after
   the residual is applied on step 2 (controlled values on the exact
   float grid);
4. EF state survives the PR-10 checkpoint round-trip at a DIFFERENT
   world size (reshard like opt_state), and a policy/layout mismatch
   resets it to zero with one structured ef_reset event instead of
   refusing the restore;
5. overlap-on == overlap-off (same quantizer, different schedule);
6. ZeRO + compression parity vs the gathered exact reference (the
   lifted exclusivity);
7. the collective-safety lint rule bites on an unguarded comm/
   collective wrapper under a rank conditional;
8. with compression off the compiled train step is byte-identical
   (lowered-HLO text + metric key-set) to the comm-free step;
9. the ef_residual_spike SLO rule fires exactly once on an injected
   saturation spike, and the CLI alias maps with one structured
   deprecation warning.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from batchai_retinanet_horovod_coco_tpu.comm import (
    CommConfig,
    init_comm_state,
    plan_buckets,
    reduce_tree,
    state_partition_specs,
)
from batchai_retinanet_horovod_coco_tpu.parallel import (
    init_sharded_opt_state,
    make_mesh,
)
from batchai_retinanet_horovod_coco_tpu.parallel.mesh import DATA_AXIS
from batchai_retinanet_horovod_coco_tpu.parallel.shmap import shard_map
from batchai_retinanet_horovod_coco_tpu.train import make_train_step
from batchai_retinanet_horovod_coco_tpu.train.state import TrainState

N = 8
HW = (64, 64)


def make_batch(batch=8):
    rng = np.random.default_rng(3)
    return {
        "images": jnp.asarray(
            rng.normal(0, 1, (batch, *HW, 3)).astype(np.float32)
        ),
        "gt_boxes": jnp.asarray(
            np.tile(
                np.array([[8.0, 8.0, 40.0, 40.0]], np.float32),
                (batch, 1, 1),
            )
        ),
        "gt_labels": jnp.ones((batch, 1), jnp.int32),
        "gt_mask": jnp.ones((batch, 1), bool),
    }


def _with_comm_state(state, config, zero=False):
    return state.replace(
        comm_state={
            k: jnp.asarray(v)
            for k, v in init_comm_state(
                state.params, config, N, zero=zero
            ).items()
        }
    )


def _reduce_on_mesh(tree, config, comm_state=None, steps=1):
    """Run ``reduce_tree`` ``steps`` times on per-device data; returns
    (reduced, exact pmean, final comm state).  ``tree`` leaves carry a
    leading (N,) device axis; the same values feed every step."""
    mesh = make_mesh(N)
    plan = plan_buckets(jax.tree.map(lambda a: a[0], tree), config)
    comm_state = comm_state or {
        k: jnp.asarray(v)
        for k, v in init_comm_state(
            jax.tree.map(lambda a: a[0], tree), config, N
        ).items()
    }
    res_spec = state_partition_specs(comm_state)

    @jax.jit
    @lambda f: shard_map(
        f,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), res_spec),
        out_specs=(P(), P(), res_spec),
        check_vma=False,
    )
    def run(x, res):
        per_dev = jax.tree.map(lambda a: a[0], x)
        out = None
        for _ in range(steps):
            out, res, _sat = reduce_tree(
                per_dev, res, plan, config, DATA_AXIS, N
            )
        exact = jax.tree.map(lambda a: lax.pmean(a, DATA_AXIS), per_dev)
        return out, exact, res

    return run(tree, comm_state)


# ---------------------------------------------------------------------------
# 1. plan / bytes
# ---------------------------------------------------------------------------


class TestPlan:
    def test_small_leaves_ride_inside_buckets(self):
        """The old per-leaf _MIN_QUANTIZE_SIZE blind spot is gone: tiny
        leaves pack into the same bucket as large ones and quantize."""
        tree = {
            "backbone": {
                "w": np.zeros((64, 513), np.float32),
                "bias": np.zeros((7,), np.float32),  # old path: skipped
            }
        }
        plan = plan_buckets(tree, CommConfig(compress="int8"))
        assert len(plan.buckets) == 1
        bucket = plan.buckets[0]
        assert bucket.mode == "int8"
        assert {l.path for l in bucket.leaves} == {
            "['backbone']['bias']", "['backbone']['w']",
        }

    def test_undersized_bucket_stays_exact(self):
        tree = {"head": {"b": np.zeros((128,), np.float32)}}
        plan = plan_buckets(tree, CommConfig(compress="int8"))
        assert [b.mode for b in plan.buckets] == ["exact"]

    def test_bucket_assignment_is_world_size_independent(self):
        """EF checkpoints reshard across world sizes, so the bucket
        composition must not depend on n (only chunk shapes do)."""
        tree = {
            "backbone": {"w": np.zeros((40000,), np.float32)},
            "fpn": {"w": np.zeros((20000,), np.float32)},
        }
        cfg = CommConfig(compress="int8")
        plan = plan_buckets(tree, cfg)
        keys_by_n = {
            n: sorted(init_comm_state(tree, cfg, n)) for n in (2, 4, 8)
        }
        assert keys_by_n[2] == keys_by_n[4] == keys_by_n[8]
        assert [
            (b.key, tuple(l.path for l in b.leaves)) for b in plan.buckets
        ] == [
            (b.key, tuple(l.path for l in b.leaves))
            for b in plan_buckets(tree, cfg).buckets
        ]

    def test_int8_bytes_ratio_clears_the_claim(self, tiny_model_and_state):
        _, state = tiny_model_and_state
        plan = plan_buckets(state.params, CommConfig(compress="int8"))
        ratio = plan.compressed_bytes(N) / plan.exact_bytes(N)
        assert ratio <= 0.65, f"bytes ratio {ratio:.3f} > 0.65"

    def test_stage_mode_override(self):
        tree = {
            "backbone": {"w": np.zeros((40000,), np.float32)},
            "cls_head": {"w": np.zeros((40000,), np.float32)},
        }
        plan = plan_buckets(
            tree,
            CommConfig(compress="int8", stage_modes=(("heads", "bf16"),)),
        )
        modes = {b.stage: b.mode for b in plan.buckets}
        assert modes == {"backbone": "int8", "heads": "bf16"}

    def test_mode_none_means_exact_never_int8(self):
        """Overlap-without-compression (and a per-stage "none" opt-out)
        must keep the EXACT wire format — "none" falling through to the
        quantizer would silently quantize gradients the config promised
        to leave alone (review-round finding)."""
        tree = {"backbone": {"w": np.zeros((40000,), np.float32)}}
        overlap_only = plan_buckets(
            tree, CommConfig(compress="none", overlap=True)
        )
        assert [b.mode for b in overlap_only.buckets] == ["exact"]
        assert overlap_only.compressed_bytes(N) == overlap_only.exact_bytes(N)
        opt_out = plan_buckets(
            {"backbone": {"w": np.zeros((40000,), np.float32)},
             "cls_head": {"w": np.zeros((40000,), np.float32)}},
            CommConfig(compress="int8", stage_modes=(("heads", "none"),)),
        )
        assert {b.stage: b.mode for b in opt_out.buckets} == {
            "backbone": "int8", "heads": "exact",
        }

    def test_zero_quant_elems_uses_per_leaf_chunks(self):
        """The ZeRO saturation denominator counts the concat of PER-LEAF
        padded chunks (what zero_gather_updates actually quantizes), not
        the bucket-level chunk — sizes indivisible by n differ."""
        tree = {
            "backbone": {
                "a": np.zeros((10001,), np.float32),
                "b": np.zeros((10003,), np.float32),
            }
        }
        plan = plan_buckets(tree, CommConfig(compress="int8"))
        dp = plan.quant_elems(8)
        zero = plan.quant_elems(8, zero=True)
        assert dp == -(-20004 // 8)
        assert zero == -(-10001 // 8) + -(-10003 // 8)
        assert zero > dp


# ---------------------------------------------------------------------------
# 2. bucketed int8 pmean vs exact (the derived bound)
# ---------------------------------------------------------------------------


class TestBucketedPmean:
    def test_matches_exact_within_bound(self):
        rng = np.random.default_rng(0)
        tree = {
            "backbone": {
                "w": jnp.asarray(
                    rng.normal(0, 0.1, (N, 64, 513)).astype(np.float32)
                ),
                "bias": jnp.asarray(
                    rng.normal(0, 0.1, (N, 33)).astype(np.float32)
                ),
            }
        }
        q, exact, _ = _reduce_on_mesh(tree, CommConfig(compress="int8"))
        for key in ("w", "bias"):
            e = np.asarray(exact["backbone"][key])
            a = np.asarray(q["backbone"][key])
            # Derived tolerance: one symmetric rounding of the ALREADY
            # reduced value, <= max|block| / 254 per element; the global
            # max bounds every block max.
            bound = np.abs(np.asarray(exact["backbone"]["w"])).max() / 254.0
            np.testing.assert_allclose(a, e, atol=float(bound) + 1e-7)

    def test_outlier_blast_radius_is_one_block(self):
        cfg = CommConfig(compress="int8")
        rng = np.random.default_rng(5)
        shard_len = 8 * cfg.block
        big = rng.normal(0, 1e-3, (N, N * shard_len)).astype(np.float32)
        for s in range(N):
            big[:, s * shard_len] = 1e3  # one outlier per device shard
        q, exact, _ = _reduce_on_mesh({"w": jnp.asarray(big)}, cfg)
        q_np, e_np = np.asarray(q["w"]), np.asarray(exact["w"])
        mask = np.ones_like(e_np, dtype=bool)
        for s in range(N):
            mask[s * shard_len : s * shard_len + cfg.block] = False
        rel = np.abs(q_np[mask] - e_np[mask]) / np.maximum(
            np.abs(e_np[mask]), 1e-12
        )
        assert np.median(rel) < 0.05
        assert np.count_nonzero(q_np[mask]) > 0.95 * mask.sum()

    def test_non_finite_gradients_surface_as_nan(self):
        rng = np.random.default_rng(2)
        big = rng.normal(0, 0.1, (N, 16, 1024)).astype(np.float32)
        big[3, 5, 100] = np.inf
        q, _, _ = _reduce_on_mesh(
            {"w": jnp.asarray(big)}, CommConfig(compress="int8")
        )
        assert not np.isfinite(np.asarray(q["w"])).all()

    def test_bf16_mode_reduces(self):
        rng = np.random.default_rng(7)
        big = rng.normal(0, 0.1, (N, 9000)).astype(np.float32)
        q, exact, _ = _reduce_on_mesh(
            {"w": jnp.asarray(big)}, CommConfig(compress="bf16")
        )
        e = np.asarray(exact["w"])
        np.testing.assert_allclose(
            np.asarray(q["w"]), e, atol=np.abs(e).max() / 128.0
        )


# ---------------------------------------------------------------------------
# 3. error feedback: constant gradient bit-exact after step 2
# ---------------------------------------------------------------------------


def test_error_feedback_constant_gradient_bit_exact_on_step_2():
    """Controlled values on the exact float grid: every block carries a
    127.0 pin (scale = 1.0 exactly) and 0.5 elsewhere.  Step 1 rounds
    0.5 -> 0 (half-to-even) and banks the 0.5 residual; step 2 sees
    0.5 + 0.5 = 1.0, which quantizes exactly — so the CUMULATIVE applied
    gradient equals the exact sum bit-for-bit and the residual returns
    to zero.  The telescoping identity, on values where every float op
    is exact."""
    cfg = CommConfig(compress="int8")
    size = 8192  # one int8 bucket (32 KB), chunk 1024 = 2 blocks/device
    v = np.full((size,), 0.5, np.float32)
    v[:: cfg.block] = 127.0  # a scale pin in every block of every shard
    tree = {"w": jnp.asarray(np.tile(v, (N, 1)))}

    mesh = make_mesh(N)
    plan = plan_buckets({"w": v}, cfg)
    cs = {
        k: jnp.asarray(val)
        for k, val in init_comm_state({"w": v}, cfg, N).items()
    }
    res_spec = state_partition_specs(cs)

    @jax.jit
    @lambda f: shard_map(
        f,
        mesh=mesh,
        in_specs=(P(DATA_AXIS), res_spec),
        out_specs=(P(), P(), res_spec),
        check_vma=False,
    )
    def two_steps(x, res):
        per_dev = jax.tree.map(lambda a: a[0], x)
        out1, res, _ = reduce_tree(per_dev, res, plan, cfg, DATA_AXIS, N)
        out2, res, _ = reduce_tree(per_dev, res, plan, cfg, DATA_AXIS, N)
        return out1, out2, res

    out1, out2, res = two_steps(tree, cs)
    applied = np.asarray(out1["w"]) + np.asarray(out2["w"])
    np.testing.assert_array_equal(applied, 2.0 * v)  # BIT-exact
    np.testing.assert_array_equal(  # residual telescoped back to zero
        np.asarray(res["heads.0"]), np.zeros((res["heads.0"].size,), np.float32)
    )
    # And step 1 alone is NOT exact (the residual was real).
    assert not np.array_equal(np.asarray(out1["w"]), v)


# ---------------------------------------------------------------------------
# 4. checkpoint round-trip: reshard like opt_state + the ef_reset path
# ---------------------------------------------------------------------------


class _SinkSpy:
    def __init__(self):
        self.events = []

    def event(self, kind, **fields):
        self.events.append((kind, fields))


def _tiny_state(comm_state):
    params = {"w": np.arange(6, dtype=np.float32)}
    tx = optax.sgd(1e-2)
    return TrainState(
        step=np.zeros((), np.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
        tx=tx,
        comm_state=comm_state,
    )


class TestCheckpointElasticity:
    def test_ef_state_reshards_across_world_sizes(self, tmp_path):
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        # Logical EF content: 100 elements + world-8 zero padding.
        logical = np.arange(1, 101, dtype=np.float32) / 7.0
        world8 = np.zeros((8 * 13,), np.float32)  # 8 * ceil(100/8) = 104
        world8[:100] = logical
        saved_state = _tiny_state({"backbone.0": world8})
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        assert mgr.save(saved_state, step=5, force=True)

        # Restore into a WORLD-4 template: 4 * ceil(100/4) = 100 (the
        # padding truncates — legal iff all-zero, the ZeRO invariant).
        template = _tiny_state({"backbone.0": np.zeros((100,), np.float32)})
        restored = CheckpointManager(str(tmp_path)).restore(template)
        np.testing.assert_array_equal(
            restored.comm_state["backbone.0"], logical
        )
        # And back up to a WORLD-16 template (zero-pad).
        t16 = _tiny_state({"backbone.0": np.zeros((16 * 7,), np.float32)})
        r16 = CheckpointManager(str(tmp_path)).restore(t16)
        np.testing.assert_array_equal(
            r16.comm_state["backbone.0"][:100], logical
        )
        np.testing.assert_array_equal(
            r16.comm_state["backbone.0"][100:], 0.0
        )

    def test_missing_ef_state_zeroes_with_one_ef_reset_event(
        self, tmp_path, capsys
    ):
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        # Uncompressed checkpoint (no comm leaves) ...
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        assert mgr.save(_tiny_state(()), step=3, force=True)
        # ... restored into a run WITH compression: zeros + ONE event,
        # never a refusal.
        sink = _SinkSpy()
        template = _tiny_state({"backbone.0": np.ones((24,), np.float32)})
        restored = CheckpointManager(str(tmp_path), sink=sink).restore(
            template
        )
        np.testing.assert_array_equal(
            restored.comm_state["backbone.0"], np.zeros((24,), np.float32)
        )
        resets = [e for e in sink.events if e[0] == "ef_reset"]
        assert len(resets) == 1
        err = capsys.readouterr().err
        assert sum(1 for l in err.splitlines() if '"ef_reset"' in l) == 1

    def test_dropped_ef_state_is_tolerated(self, tmp_path):
        """Compressed checkpoint restored WITHOUT compression: the comm
        leaves are dropped (with the same ef_reset record), and the
        params/optimizer restore is untouched."""
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        mgr = CheckpointManager(str(tmp_path), async_save=False)
        assert mgr.save(
            _tiny_state({"backbone.0": np.ones((24,), np.float32)}),
            step=3, force=True,
        )
        restored = CheckpointManager(str(tmp_path)).restore(_tiny_state(()))
        assert restored.comm_state == ()
        np.testing.assert_array_equal(
            restored.params["w"], np.arange(6, dtype=np.float32)
        )

    def test_bucket_layout_change_zeroes_instead_of_refusing(
        self, tmp_path
    ):
        """A comm key that survives a bucket-layout change but SHRINKS
        (real residual content would be dropped) zeroes with one
        ef_reset instead of refusing the restore — EF residuals are
        advisory state; only params/optimizer mismatches refuse
        (review-round finding)."""
        from batchai_retinanet_horovod_coco_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        full = np.ones((24,), np.float32)  # no zero tail at all
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        assert mgr.save(_tiny_state({"backbone.0": full}), step=1, force=True)
        sink = _SinkSpy()
        template = _tiny_state({"backbone.0": np.zeros((12,), np.float32)})
        restored = CheckpointManager(str(tmp_path), sink=sink).restore(
            template
        )
        np.testing.assert_array_equal(
            restored.comm_state["backbone.0"], np.zeros((12,), np.float32)
        )
        assert [e[0] for e in sink.events] == ["ef_reset"]
        # The params restore is untouched by the comm degrade.
        np.testing.assert_array_equal(
            restored.params["w"], np.arange(6, dtype=np.float32)
        )


def test_overlap_only_reduce_is_bitwise_exact():
    """--comm-overlap without --comm-compress: the reduce must be the
    exact pmean values (only the schedule moves)."""
    rng = np.random.default_rng(11)
    tree = {
        "backbone": {
            "w": jnp.asarray(rng.normal(0, 0.1, (N, 40000)).astype(np.float32))
        }
    }
    q, exact, _ = _reduce_on_mesh(
        tree, CommConfig(compress="none", overlap=True)
    )
    np.testing.assert_array_equal(
        np.asarray(q["backbone"]["w"]), np.asarray(exact["backbone"]["w"])
    )


def test_zero_gather_tolerates_missing_ef_state():
    """ZeRO + an EF-enabled policy with NO initialized comm state (the
    deprecated alias's default TrainState.comm_state == ()) must degrade
    to stateless quantization, not crash with a KeyError at trace time
    (review-round finding — the deleted quantized×ZeRO exclusivity
    guard's replacement contract)."""
    from batchai_retinanet_horovod_coco_tpu.comm import zero_gather_updates
    from batchai_retinanet_horovod_coco_tpu.parallel.zero import (
        _local_shard,
    )

    cfg = CommConfig(compress="int8")  # error_feedback=True by default
    assert cfg.needs_state
    rng = np.random.default_rng(13)
    params = {
        "backbone": {
            "w": jnp.asarray(rng.normal(0, 0.1, (40000,)).astype(np.float32))
        }
    }
    updates_full = jax.tree.map(lambda p: -0.01 * jnp.ones_like(p), params)
    plan = plan_buckets(params, cfg)
    mesh = make_mesh(N)

    @jax.jit
    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P(), P()), out_specs=(P(), P()),
        check_vma=False,
    )
    def run(p, upd_full):
        idx = jax.lax.axis_index(DATA_AXIS)
        upd = jax.tree.map(lambda u: _local_shard(u, N, idx), upd_full)
        new_p, new_res, _sat = zero_gather_updates(
            upd, p, {}, plan, cfg, DATA_AXIS, N
        )
        assert new_res == {}  # stateless degrade, structure preserved
        return new_p, jnp.zeros(())

    new_p, _ = run(params, updates_full)
    expect = params["backbone"]["w"] - 0.01
    np.testing.assert_allclose(
        np.asarray(new_p["backbone"]["w"]), np.asarray(expect), atol=1e-3
    )


# ---------------------------------------------------------------------------
# 5/6/8. full train-step flavors (fixture model, one batch)
# ---------------------------------------------------------------------------


class TestTrainStepFlavors:
    def test_overlap_matches_fused_and_single_device(
        self, tiny_model_and_state
    ):
        model, state = tiny_model_and_state
        batch = make_batch()
        mesh = make_mesh(N)
        cfg_fused = CommConfig(compress="int8")
        cfg_overlap = CommConfig(compress="int8", overlap=True)

        single = make_train_step(model, HW, 3, mesh=None, donate_state=False)
        s_new, s_metrics = single(state, batch)

        fused_state = _with_comm_state(state, cfg_fused)
        fused = make_train_step(
            model, HW, 3, mesh=mesh, comm=cfg_fused, donate_state=False
        )
        f_new, f_metrics = fused(fused_state, batch)

        over_state = _with_comm_state(state, cfg_overlap)
        over = make_train_step(
            model, HW, 3, mesh=mesh, comm=cfg_overlap, donate_state=False
        )
        o_new, o_metrics = over(over_state, batch)

        # (5) overlap == fused: same quantizer, different schedule.
        np.testing.assert_allclose(
            float(o_metrics["loss"]), float(f_metrics["loss"]), rtol=1e-6
        )
        for a, b in zip(
            jax.tree.leaves(o_new.params), jax.tree.leaves(f_new.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6
            )
        for k in o_new.comm_state:
            np.testing.assert_allclose(
                np.asarray(o_new.comm_state[k]),
                np.asarray(f_new.comm_state[k]),
                atol=1e-7,
            )
        # Compressed step tracks the exact single-device update within
        # the one-rounding bound.
        np.testing.assert_allclose(
            float(f_metrics["loss"]), float(s_metrics["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(f_new.params), jax.tree.leaves(s_new.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-3
            )
        # EF health metrics present and sane.
        for m in (f_metrics, o_metrics):
            assert float(m["ef_residual_norm"]) > 0
            assert 0.0 <= float(m["ef_saturation"]) <= 1.0
            assert float(m["comm_compressed_bytes"]) > 0

    def test_zero_plus_compression_matches_gathered_reference(
        self, tiny_model_and_state
    ):
        model, state = tiny_model_and_state
        batch = make_batch()
        mesh = make_mesh(N)
        cfg = CommConfig(compress="int8")

        single = make_train_step(model, HW, 3, mesh=None, donate_state=False)
        s_new, s_metrics = single(state, batch)

        zstate = state.replace(
            opt_state=init_sharded_opt_state(state.tx, state.params, mesh)
        )
        zstate = _with_comm_state(zstate, cfg, zero=True)
        zstep = make_train_step(
            model, HW, 3, mesh=mesh, shard_weight_update=True, comm=cfg,
            donate_state=False,
        )
        z_new, z_metrics = zstep(zstate, batch)
        np.testing.assert_allclose(
            float(z_metrics["loss"]), float(s_metrics["loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree.leaves(z_new.params), jax.tree.leaves(s_new.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-3
            )
        assert float(z_metrics["ef_residual_norm"]) >= 0
        assert float(z_metrics["comm_compressed_bytes"]) > 0
        # The params must stay bitwise REPLICATED (every device applied
        # the identical dequantized update).
        for leaf in jax.tree.leaves(z_new.params):
            assert bool(
                jnp.all(jnp.isfinite(jnp.asarray(leaf)))
            )

    def test_compression_off_is_byte_identical(self, tiny_model_and_state):
        """The acceptance gate: comm=None and comm=CommConfig("none")
        lower to the SAME HLO text, and the metric key-set is the
        pre-ISSUE-13 vocabulary (the PR-9 numerics-gate technique)."""
        model, state = tiny_model_and_state
        batch = make_batch()
        mesh = make_mesh(N)
        base = make_train_step(model, HW, 3, mesh=mesh, donate_state=False)
        off = make_train_step(
            model, HW, 3, mesh=mesh, comm=CommConfig(compress="none"),
            donate_state=False,
        )
        text_a = base.lower(state, batch).as_text()
        text_b = off.lower(state, batch).as_text()
        assert text_a == text_b
        new_state, metrics = base(state, batch)
        assert set(metrics) == {
            "loss", "cls_loss", "box_loss", "num_pos", "grad_norm",
            "param_norm",
        }


# ---------------------------------------------------------------------------
# 7. lint: rank-guarded comm collective
# ---------------------------------------------------------------------------


def test_lint_bites_on_rank_guarded_comm_collective():
    from tests.unit.test_lint import run_rule

    result = run_rule(
        """
        import jax

        from batchai_retinanet_horovod_coco_tpu.comm import compress

        def step(grads, comm_state, plan, cfg):
            if jax.process_index() == 0:
                grads, comm_state, _ = compress.reduce_tree(
                    grads, comm_state, plan, cfg, "data", 8
                )
            return grads
        """,
        "collective-safety",
    )
    assert len(result.findings) == 1
    assert "reduce_tree" in result.findings[0].message

    clean = run_rule(
        """
        from batchai_retinanet_horovod_coco_tpu.comm import compress

        def step(grads, comm_state, plan, cfg):
            return compress.reduce_tree(
                grads, comm_state, plan, cfg, "data", 8
            )
        """,
        "collective-safety",
    )
    assert clean.findings == []


# ---------------------------------------------------------------------------
# 9. SLO rule + CLI mapping
# ---------------------------------------------------------------------------


def test_ef_residual_spike_fires_exactly_once():
    from batchai_retinanet_horovod_coco_tpu.obs import slo, telemetry

    telemetry.enable()  # Gauge.set is gated on the global enable
    try:
        registry = telemetry.Registry()
        gauge = registry.gauge("train_ef_residual", "test")
        monitor = slo.SloMonitor(
            registry, [slo.ef_residual_spike(factor=10.0)],
            poll_interval=999,
        )
        # Healthy baseline (min_baseline samples) ...
        for i in range(6):
            gauge.set(1.0 + 0.01 * i)
            assert monitor.check_once(now=float(i)) == []
        # ... injected saturation spike: fires EXACTLY once and stays
        # latched through the sustained breach.
        gauge.set(100.0)
        fired = monitor.check_once(now=10.0)
        assert [v["rule"] for v in fired] == ["ef_residual_spike"]
        assert monitor.check_once(now=11.0) == []
        assert monitor.check_once(now=12.0) == []
    finally:
        telemetry.disable()


def test_ef_rule_silent_without_compression_gauge():
    from batchai_retinanet_horovod_coco_tpu.obs import slo
    from batchai_retinanet_horovod_coco_tpu.obs.telemetry import Registry

    monitor = slo.SloMonitor(
        Registry(), [slo.ef_residual_spike()], poll_interval=999
    )
    for i in range(10):
        assert monitor.check_once(now=float(i)) == []


class TestCliMapping:
    def _args(self, **kw):
        import argparse

        defaults = dict(
            comm_compress="none", comm_overlap=False, comm_bucket_mb=4.0,
            comm_no_error_feedback=False, quantized_allreduce=False,
        )
        defaults.update(kw)
        return argparse.Namespace(**defaults)

    def test_none_maps_to_no_config(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        assert make_comm_config(self._args()) is None

    def test_flags_map_to_config(self):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        cfg = make_comm_config(
            self._args(comm_compress="int8", comm_overlap=True,
                       comm_bucket_mb=2.0)
        )
        assert cfg == CommConfig(
            compress="int8", overlap=True, bucket_mb=2.0
        )

    def test_deprecated_alias_maps_with_one_structured_warning(
        self, capsys
    ):
        from batchai_retinanet_horovod_coco_tpu.utils.cli import (
            make_comm_config,
        )

        cfg = make_comm_config(self._args(quantized_allreduce=True))
        assert cfg is not None and cfg.compress == "int8"
        err = capsys.readouterr().err
        warnings = [
            json.loads(l) for l in err.splitlines()
            if '"deprecated_flag"' in l
        ]
        assert len(warnings) == 1
        assert warnings[0]["flag"] == "--quantized-allreduce"
        assert "int8" in warnings[0]["mapped_to"]


def test_record_comm_feeds_gauges_and_counter():
    from batchai_retinanet_horovod_coco_tpu.obs import telemetry

    telemetry.reset()
    telemetry.enable()
    try:
        telemetry.record_comm(
            ef_residual=0.25, ef_saturation=0.01,
            compressed_bytes=1000.0, steps=20,
        )
        snap = telemetry.default().snapshot()
        assert snap["train_ef_residual"] == 0.25
        assert snap["train_ef_saturation"] == 0.01
        assert snap["train_comm_compressed_bytes_total"] == 20000.0
        # Disabled: the record site is a single bool check, no mutation.
        telemetry.reset()
        telemetry.record_comm(ef_residual=9.9, compressed_bytes=1.0)
        assert "train_ef_residual" not in telemetry.default().snapshot()
    finally:
        telemetry.reset()
