import math

import numpy as np

from batchai_retinanet_horovod_coco_tpu.ops.anchors import (
    AnchorConfig,
    anchors_for_image_shape,
    generate_base_anchors,
)


def test_base_anchor_count_and_areas():
    cfg = AnchorConfig()
    base = generate_base_anchors(32, cfg.ratios, cfg.scales)
    assert base.shape == (9, 4)
    # Every anchor is centered at the origin.
    centers = (base[:, :2] + base[:, 2:]) / 2.0
    np.testing.assert_allclose(centers, 0.0, atol=1e-4)
    # Areas: (size*scale)^2 for each scale, repeated per ratio.
    areas = (base[:, 2] - base[:, 0]) * (base[:, 3] - base[:, 1])
    expected = np.array([(32 * s) ** 2 for s in cfg.scales] * 3)
    np.testing.assert_allclose(areas, expected, rtol=1e-5)


def test_base_anchor_aspect_ratios():
    cfg = AnchorConfig()
    base = generate_base_anchors(64, cfg.ratios, cfg.scales)
    w = base[:, 2] - base[:, 0]
    h = base[:, 3] - base[:, 1]
    ratios = h / w
    expected = np.repeat(np.array(cfg.ratios), len(cfg.scales))
    np.testing.assert_allclose(ratios, expected, rtol=1e-5)


def test_anchor_grid_hand_computed():
    """2x2 P3 grid on a 16x16 image: shift centers at stride*(i+0.5)."""
    cfg = AnchorConfig(levels=(3,), strides=(8,), sizes=(32,), ratios=(1.0,), scales=(1.0,))
    anchors = anchors_for_image_shape((16, 16), cfg)
    assert anchors.shape == (4, 4)
    centers = (anchors[:, :2] + anchors[:, 2:]) / 2.0
    expected_centers = np.array(
        [[4.0, 4.0], [12.0, 4.0], [4.0, 12.0], [12.0, 12.0]]
    )
    np.testing.assert_allclose(centers, expected_centers, atol=1e-4)
    # All boxes are 32x32.
    np.testing.assert_allclose(anchors[:, 2] - anchors[:, 0], 32.0)


def test_total_anchor_count_800_1333():
    cfg = AnchorConfig()
    anchors = anchors_for_image_shape((800, 1344), cfg)
    expected = 0
    for stride in cfg.strides:
        fh = math.ceil(800 / stride)
        fw = math.ceil(1344 / stride)
        expected += fh * fw * 9
    assert anchors.shape == (expected, 4)
    # ~200k anchors for the flagship bucket, plausibility per SURVEY.md 3.3.
    assert 90_000 < expected < 250_000


def test_anchor_cache_identity():
    a = anchors_for_image_shape((256, 256))
    b = anchors_for_image_shape((256, 256))
    assert a is b  # lru_cache returns the same array: free at step time


def test_cached_anchors_are_readonly():
    a = anchors_for_image_shape((128, 128))
    import pytest as _pytest

    with _pytest.raises(ValueError):
        a[0, 0] = 5.0
