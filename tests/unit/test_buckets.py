"""Bucket routing contracts (VERDICT r4 weak #4).

The bench's weighted-mix arithmetic keys COCO shares by aspect class;
these tests tie that keying to the pipeline's ACTUAL routing
(``bucket_for_source`` = resize rule + rounding + ``pick_bucket``), so a
bucket-list change that de-syncs the weighted bench number from reality
fails here instead of silently skewing BENCH artifacts.

The exhaustive scan is also what exposed (round 5) that the former
third 1088x1088 "mid" bucket was unreachable: every resized image has
min dim <= lo and max dim <= hi, so one of the two orientation buckets
always fits — the phantom bucket cost a dead multi-minute compile per
run and a 4% phantom share.
"""

import os
import sys

import pytest

# repo root, derived from this file's own path (the suite must run
# from any checkout location, not just /root/repo)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from batchai_retinanet_horovod_coco_tpu.data.pipeline import (  # noqa: E402
    bucket_for_source,
    default_buckets,
)

FLAGSHIP = (800, 1333)


def _aspect_class(hw):
    h, w = hw
    return "landscape" if h < w else ("portrait" if h > w else "square")


def _source_grid():
    """Source sizes covering the COCO range plus adversarial extremes
    (tiny, huge, near-square both ways, pathological aspect ratios)."""
    sizes = [
        (h, w)
        for h in range(40, 1500, 97)
        for w in range(40, 1500, 89)
    ]
    sizes += [
        (500, 500), (640, 480), (480, 640), (639, 640), (640, 639),
        (1, 10000), (10000, 1), (3000, 3000), (16, 16), (801, 800),
        (800, 801),
    ]
    return sizes


def test_every_bucket_is_reachable():
    """Anti-dead-bucket contract: each bucket the pipeline compiles a
    program for must be the routing target of SOME source size — a
    bucket no image can reach is pure compile-time waste (the round-5
    finding this test pins)."""
    buckets = default_buckets(*FLAGSHIP)
    hit = {
        bucket_for_source(h, w, *FLAGSHIP, buckets)
        for h, w in _source_grid()
    }
    assert hit == set(buckets), (
        f"unreachable bucket(s): {set(buckets) - hit}"
    )


def test_routing_matches_bench_aspect_class_keying():
    """bench.py pairs each bucket with a COCO share via the bucket's
    aspect class (landscape/portrait); the pipeline must actually route
    landscape AND square sources to the landscape bucket and portrait
    sources to the portrait bucket, for every source size."""
    buckets = default_buckets(*FLAGSHIP)
    for h, w in _source_grid():
        target = bucket_for_source(h, w, *FLAGSHIP, buckets)
        want = "portrait" if h > w else "landscape"
        assert _aspect_class(target) == want, (
            f"source {h}x{w} ({_aspect_class((h, w))}) routed to "
            f"{target} ({_aspect_class(target)}), bench keys its share "
            f"as {want}"
        )


def test_bench_sweep_buckets_cover_pipeline_buckets():
    """bench.sweep_buckets' (bucket, share) pairs: same bucket list as
    the pipeline, every share keyed to the class the routing scan above
    validates, shares summing to 1."""
    bench = pytest.importorskip("bench")

    pairs = bench.sweep_buckets()
    assert [b for b, _ in pairs] == list(default_buckets(*FLAGSHIP))
    assert abs(sum(s for _, s in pairs) - 1.0) < 1e-9
    for b, share in pairs:
        assert share == bench._MIX_SHARES[_aspect_class(b)]


def test_debug_buckets_shares_agree_with_pick_bucket(tmp_path):
    """`debug.py buckets` (the operator's exact-share tool) and the
    pipeline's own router must produce identical shares for the same
    annotation metadata — the bench's re-derive-exactly instruction
    assumes they agree."""
    import json

    import debug

    dims = [(640, 480), (640, 480), (640, 480), (480, 640), (500, 500)]
    blob = {
        "categories": [{"id": 1, "name": "thing"}],
        "images": [
            {"id": i, "file_name": f"{i}.jpg", "width": w, "height": h}
            for i, (h, w) in enumerate(dims)
        ],
        "annotations": [
            {"id": i, "image_id": i, "category_id": 1,
             "bbox": [1, 1, 10, 10], "area": 100, "iscrowd": 0}
            for i in range(len(dims))
        ],
    }
    ann = tmp_path / "instances.json"
    with open(ann, "w") as f:
        json.dump(blob, f)

    shares = debug.bucket_shares(str(ann), *FLAGSHIP)

    buckets = default_buckets(*FLAGSHIP)
    expect = {f"{b[0]}x{b[1]}": 0 for b in buckets}
    for h, w in dims:
        b = bucket_for_source(h, w, *FLAGSHIP, buckets)
        expect[f"{b[0]}x{b[1]}"] += 1
    assert {k: v["count"] for k, v in shares.items()} == expect
    # Concrete flagship-config expectation for these (h, w) dims: the
    # three 640x480 portraits -> 1344x800; the 480x640 landscape and
    # 500x500 square -> 800x1344.
    assert expect == {"800x1344": 2, "1344x800": 3}
