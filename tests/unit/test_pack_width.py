"""Width-packed stage2 (models/resnet.py pack_width) is math-identical.

The packed path re-expresses every stage2 op on a (B, H, W/2, 2C) layout
with block-structured kernels; its defining property is exact equivalence
to the plain path UNDER THE SAME PARAMS.  These tests build both variants,
initialize one, and run the other with the identical tree — possible only
because PackedConv / Packed*Norm declare canonical param shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.models.resnet import (
    ResNet,
    _pack_kernel_1x1,
    _pack_kernel_3x3,
    _pack_w,
    _unpack_w,
)

HW = (32, 48)  # stage2 width 12: even, exercises several packed columns


def _build(pack, norm_kind):
    return ResNet(
        stage_sizes=(2, 1, 1, 1),
        norm_kind=norm_kind,
        dtype=jnp.float32,  # f32 so the comparison tolerance can be tight
        stem="conv",
        pack_width=pack,
    )


def _input(seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(0, 1, (2, *HW, 3)).astype(np.float32))


def test_pack_roundtrip():
    x = _input()
    np.testing.assert_array_equal(np.asarray(_unpack_w(_pack_w(x))), np.asarray(x))


def test_packed_kernels_shapes():
    k1 = jnp.ones((1, 1, 4, 6))
    k3 = jnp.ones((3, 3, 4, 6))
    assert _pack_kernel_1x1(k1).shape == (1, 1, 8, 12)
    assert _pack_kernel_3x3(k3).shape == (3, 3, 8, 12)


@pytest.mark.parametrize("norm_kind", ["gn", "frozen_bn", "bn"])
def test_packed_forward_matches_plain(norm_kind):
    x = _input()
    plain, packed = _build(False, norm_kind), _build(True, norm_kind)
    variables = plain.init(jax.random.key(0), x)
    # Same tree structure/shapes — the checkpoint-compatibility contract.
    packed_vars = packed.init(jax.random.key(0), x)
    assert jax.tree.structure(variables) == jax.tree.structure(packed_vars)
    jax.tree.map(lambda a, b: (a.shape == b.shape) or (_ for _ in ()).throw(
        AssertionError(f"{a.shape} != {b.shape}")), variables, packed_vars)

    for train in (False, True):
        kw = {}
        if norm_kind == "bn" and train:
            kw["mutable"] = ["batch_stats"]
        out_p = plain.apply(variables, x, train=train, **kw)
        out_q = packed.apply(variables, x, train=train, **kw)
        if kw:
            (out_p, bs_p), (out_q, bs_q) = out_p, out_q
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
                ),
                bs_p,
                bs_q,
            )
        # Train-mode bn gets an order of magnitude more absolute slack:
        # its batch statistics are live reductions whose order differs
        # between the packed (W/2, 2C) and plain layouts, and the variance
        # rsqrt amplifies that reordering — measured 6.9e-4 max-abs on 4 of
        # 8192 elements with XLA 0.4.37's scheduling (gn / frozen_bn, whose
        # normalizers carry no batch reduction, stay at the tight bound).
        tol = 1e-3 if (norm_kind == "bn" and train) else 1e-4
        for key in ("c3", "c4", "c5"):
            np.testing.assert_allclose(
                np.asarray(out_q[key]),
                np.asarray(out_p[key]),
                rtol=1e-4,
                atol=tol,
                err_msg=f"{norm_kind} train={train} {key}",
            )


def test_odd_stage2_width_rejected():
    x = jnp.zeros((1, 32, 36, 3))  # stage2 width ceil(36/4) = 9, odd
    model = _build(True, "gn")
    with pytest.raises(ValueError, match="even stage2 width"):
        model.init(jax.random.key(0), x)


@pytest.mark.slow
def test_grads_match_plain():
    """Autodiff through the kernel repack must produce the PLAIN gradients
    (the structurally-zero blocks' cotangents drop in the gather transpose).

    Slow tier: ~40 s of compile (round-4 timing report) for a retired-by-
    default lever (pack_width is a measured-negative config on v5e); the
    forward equivalence tests keep its correctness pinned in fast."""
    x = _input(1)
    plain, packed = _build(False, "gn"), _build(True, "gn")
    variables = plain.init(jax.random.key(0), x)

    def loss(params, model):
        out = model.apply({"params": params}, x, train=True)
        return sum(jnp.sum(o * o) for o in out.values())

    g_p = jax.grad(loss)(variables["params"], plain)
    g_q = jax.grad(loss)(variables["params"], packed)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3
        ),
        g_p,
        g_q,
    )
