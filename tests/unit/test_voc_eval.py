"""PASCAL-VOC mAP oracle vs hand-computed fixtures.

Mirrors the semantics of keras-retinanet's ``utils/eval.py::evaluate`` /
``callbacks/eval.py::Evaluate`` (SURVEY.md M13): greedy score-ordered
matching, one claim per gt box, all-point interpolated AP, classes without
annotations excluded from the mean.
"""

import numpy as np
import pytest

from batchai_retinanet_horovod_coco_tpu.evaluate import (
    compute_ap,
    evaluate_detections_voc,
)


def gt_ann(img, cat, box, iscrowd=0):
    x1, y1, x2, y2 = box
    return {
        "image_id": img,
        "category_id": cat,
        "bbox": [x1, y1, x2 - x1, y2 - y1],
        "iscrowd": iscrowd,
    }


def det(img, cat, box, score):
    x1, y1, x2, y2 = box
    return {
        "image_id": img,
        "category_id": cat,
        "bbox": [x1, y1, x2 - x1, y2 - y1],
        "score": score,
    }


class TestComputeAp:
    def test_perfect(self):
        assert compute_ap(np.array([0.5, 1.0]), np.array([1.0, 1.0])) == 1.0

    def test_no_recall(self):
        assert compute_ap(np.array([0.0]), np.array([0.0])) == 0.0

    def test_hand_computed(self):
        # tp sequence [1, 0, 1] over 2 gts: recall [.5,.5,1], prec [1,.5,2/3].
        # Envelope over recall steps: 0→.5 at p=1, .5→1 at p=2/3.
        ap = compute_ap(
            np.array([0.5, 0.5, 1.0]), np.array([1.0, 0.5, 2 / 3])
        )
        assert ap == pytest.approx(0.5 * 1.0 + 0.5 * 2 / 3)


class TestEvaluateVoc:
    def test_perfect_single_class(self):
        gts = [gt_ann(0, 0, (0, 0, 10, 10)), gt_ann(1, 0, (5, 5, 20, 20))]
        dts = [
            det(0, 0, (0, 0, 10, 10), 0.9),
            det(1, 0, (5, 5, 20, 20), 0.8),
        ]
        out = evaluate_detections_voc(gts, dts)
        assert out["voc_mAP"] == pytest.approx(1.0)
        assert out["voc_AP_0"] == pytest.approx(1.0)

    def test_fp_between_tps(self):
        gts = [gt_ann(0, 0, (0, 0, 10, 10)), gt_ann(0, 0, (50, 50, 60, 60))]
        dts = [
            det(0, 0, (0, 0, 10, 10), 0.9),     # TP
            det(0, 0, (100, 100, 110, 110), 0.8),  # FP (no overlap)
            det(0, 0, (50, 50, 60, 60), 0.7),   # TP
        ]
        out = evaluate_detections_voc(gts, dts)
        assert out["voc_mAP"] == pytest.approx(0.5 + 0.5 * 2 / 3)

    def test_double_detection_is_fp(self):
        gts = [gt_ann(0, 0, (0, 0, 10, 10))]
        dts = [
            det(0, 0, (0, 0, 10, 10), 0.9),
            det(0, 0, (0, 0, 10, 10), 0.8),  # same gt already claimed
        ]
        out = evaluate_detections_voc(gts, dts)
        # recall [1,1], precision [1,.5] → AP 1.0 (envelope at recall step).
        assert out["voc_mAP"] == pytest.approx(1.0)

    def test_iou_threshold(self):
        gts = [gt_ann(0, 0, (0, 0, 10, 10))]
        # IoU = 50/150 = 1/3 against the gt.
        dts = [det(0, 0, (5, 0, 15, 10), 0.9)]
        assert evaluate_detections_voc(gts, dts)["voc_mAP"] == 0.0
        out = evaluate_detections_voc(gts, dts, iou_threshold=0.3)
        assert out["voc_mAP"] == pytest.approx(1.0)

    def test_empty_class_excluded_from_mean(self):
        gts = [gt_ann(0, 0, (0, 0, 10, 10))]  # class 1 has no gt
        dts = [
            det(0, 0, (0, 0, 10, 10), 0.9),
            det(0, 1, (0, 0, 10, 10), 0.9),  # detection of gt-less class
        ]
        out = evaluate_detections_voc(gts, dts)
        assert out["voc_mAP"] == pytest.approx(1.0)
        assert "voc_AP_1" not in out

    def test_weighted_average(self):
        # class 0: 1 gt, found (AP 1); class 1: 3 gts, none found (AP 0).
        gts = [gt_ann(0, 0, (0, 0, 10, 10))] + [
            gt_ann(0, 1, (i * 20, 0, i * 20 + 10, 10)) for i in range(3)
        ]
        dts = [det(0, 0, (0, 0, 10, 10), 0.9)]
        assert evaluate_detections_voc(gts, dts)["voc_mAP"] == pytest.approx(0.5)
        out = evaluate_detections_voc(gts, dts, weighted_average=True)
        assert out["voc_mAP"] == pytest.approx(0.25)

    def test_crowd_skipped(self):
        gts = [
            gt_ann(0, 0, (0, 0, 10, 10)),
            gt_ann(0, 0, (50, 50, 60, 60), iscrowd=1),
        ]
        dts = [det(0, 0, (0, 0, 10, 10), 0.9)]
        # The crowd gt neither counts as an annotation nor absorbs matches.
        assert evaluate_detections_voc(gts, dts)["voc_mAP"] == pytest.approx(1.0)

    def test_detection_on_ignore_region_is_not_fp(self):
        """VOC difficult semantics: a hit on an ignore box is neither TP
        nor FP (data/pascal_voc.py routes difficult objects here)."""
        gts = [
            gt_ann(0, 0, (0, 0, 10, 10)),
            gt_ann(0, 0, (50, 50, 60, 60), iscrowd=1),  # difficult/ignore
        ]
        dts = [
            det(0, 0, (50, 50, 60, 60), 0.95),  # on the ignore region
            det(0, 0, (0, 0, 10, 10), 0.9),     # TP on the real gt
        ]
        # The ignore hit must not deflate precision: AP stays 1.0.
        assert evaluate_detections_voc(gts, dts)["voc_mAP"] == pytest.approx(1.0)
        # A genuine miss elsewhere in the image is still an FP.
        dts_fp = [det(0, 0, (80, 80, 90, 90), 0.95),
                  det(0, 0, (0, 0, 10, 10), 0.9)]
        out = evaluate_detections_voc(gts, dts_fp)
        assert out["voc_mAP"] == pytest.approx(0.5)

    def test_duplicate_of_claimed_box_is_fp_despite_ignore_overlap(self):
        """Devkit assignment: the duplicate's max overlap is the CLAIMED
        real box, so it is an FP even though an ignore box also overlaps."""
        gts = [
            gt_ann(0, 0, (0, 0, 10, 10)),
            # Ignore box overlapping the real one (IoU with a det on the
            # real box = 5*10/(100+50-50) = 0.5 ≥ threshold).
            gt_ann(0, 0, (5, 0, 15, 10), iscrowd=1),
        ]
        dts = [
            det(0, 0, (0, 0, 10, 10), 0.9),  # TP, claims the real box
            det(0, 0, (0, 0, 10, 10), 0.8),  # duplicate → FP
        ]
        out = evaluate_detections_voc(gts, dts)
        # tp=[1,0], fp=[0,1]: recall [1,1], precision [1,.5] → AP 1.0 via
        # the envelope, but the duplicate MUST be an FP, which shows in a
        # second class... simpler: assert via precision by adding a second
        # real gt that stays unmatched (recall 0.5 path).
        assert out["voc_AP_0"] == pytest.approx(1.0)
        gts.append(gt_ann(1, 0, (0, 0, 10, 10)))  # unmatched gt, img 1
        out = evaluate_detections_voc(gts, dts)
        # recall=[.5,.5], precision=[1,.5] → AP = 0.5 (duplicate counted FP;
        # were it ignored, precision would stay 1 and AP would still be 0.5
        # — so ALSO check the winner-is-ignore case flips it):
        assert out["voc_AP_0"] == pytest.approx(0.5)
        # Detection sitting MORE on the ignore box than any real gt:
        dts_ign = [det(0, 0, (6, 0, 15, 10), 0.7)]
        out = evaluate_detections_voc(
            [gt_ann(0, 0, (0, 0, 10, 10)), gts[1]], dts_ign
        )
        # IoU vs real box = 4*10/(100+90-40)=0.267 < IoU vs ignore
        # (9*10/(90+100-90)=0.9) → neither TP nor FP → no FP recorded,
        # recall 0 → AP 0 but with zero precision damage (no fp).
        assert out["voc_AP_0"] == pytest.approx(0.0)

    def test_no_gt_at_all(self):
        assert evaluate_detections_voc([], [det(0, 0, (0, 0, 5, 5), 0.5)])[
            "voc_mAP"
        ] == 0.0
